"""Interposer: wraps the runtime's failure seams with a FaultPlan.

Wrapping, not forking: the live objects keep their classes and state;
their seam *methods* are replaced on the instance with chaos-aware
wrappers that consult the plan's rules and fall through to the original
bound method.  ``detach()`` restores every original, so a cluster can be
un-chaosed mid-test.

Seams (the ones the tentpole names):

* transport — ``InProcTransport.send`` (fabric-wide) / ``TcpTransport.send``
  (per silo): drop, delay, duplicate, reorder; plus scripted partitions and
  per-silo network stalls (both sides of the cut dropped).
* storage   — ``StorageProvider.write_state`` on every registered provider:
  fail (raises ChaosInjectedError) or slow.
* membership — ``InMemoryMembershipTable.update_row``: injected
  CasConflictError (the table's own conflict type, so the oracle's CAS
  retry discipline is what gets exercised).
* engine    — ``TensorEngine.send_batch``: corrupt a seeded fraction of
  slab rows with NaN (float columns) or near-overflow values (int
  columns) before they enter the tick pipeline.

First matching rule wins per event — order rules accordingly.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from orleans_tpu.chaos.plan import (
    ChaosInjectedError,
    FaultPlan,
    FaultTrace,
    _RuleState,
)


def _ambient_trace_id() -> Optional[str]:
    """Trace id of the request whose turn/task the seam fired inside
    (storage writes and engine injections run under the caller's ambient
    RequestContext; orleans_tpu/spans.py).  Tagging faults with it maps
    an injected fault to the exact request it hit."""
    from orleans_tpu.spans import current_trace
    t = current_trace()
    return t.get("trace_id") if t else None


def _message_trace_id(msg: Any) -> Optional[str]:
    from orleans_tpu.spans import trace_id_of
    return trace_id_of(msg)


class Interposer:

    def __init__(self, plan: FaultPlan, trace: Optional[FaultTrace] = None,
                 telemetry=None) -> None:
        self.plan = plan
        self.trace = trace if trace is not None \
            else FaultTrace(telemetry=telemetry)
        self._states: Dict[str, _RuleState] = {
            r.name: _RuleState(r, plan.seed) for r in plan.rules}
        self._originals: List[Tuple[Any, str, Any]] = []
        self._wrapped: set = set()
        # (listener list, callback) pairs for runtime-event mirrors
        # (breaker transitions, dead-letter records) — removed on detach
        self._listeners: List[Tuple[list, Any]] = []
        # scripted topology faults
        self.partition_groups: Optional[List[set]] = None
        self.stalled: set = set()
        # one-slot park buffer for the reorder action
        self._parked: List[Tuple[Any, tuple]] = []
        self.counters: Dict[str, int] = {
            "transport_seen": 0, "transport_dropped": 0,
            "transport_delayed": 0, "transport_duplicated": 0,
            "transport_reordered": 0, "partition_dropped": 0,
            "stall_dropped": 0,
            "storage_seen": 0, "storage_failed": 0, "storage_slowed": 0,
            "membership_seen": 0, "membership_conflicted": 0,
            "engine_seen": 0, "engine_corrupted": 0,
        }

    # ---- rule machinery ---------------------------------------------------

    def rule_state(self, name: str) -> _RuleState:
        return self._states[name]

    def set_rule_enabled(self, name: str, enabled: bool) -> None:
        self._states[name].enabled = enabled

    def _decide(self, seam: str, ctx: Any):
        """First firing rule wins: returns (rule, match_index) or None."""
        for state in self._states.values():
            if state.rule.seam != seam:
                continue
            idx = state.decide(ctx)
            if idx is not None:
                return state.rule, idx
        return None

    def _record_rule(self, rule, idx: int, detail: Dict[str, Any]) -> None:
        self.trace.record(
            "rule", rule.name, rule.seam, rule.action, detail,
            sig=(("rule", rule.name, rule.action, idx)
                 if rule.pinned else None))

    # ---- scripted topology -----------------------------------------------

    def set_partition(self, groups: List[set]) -> None:
        self.partition_groups = [set(g) for g in groups]

    def heal_partition(self) -> None:
        self.partition_groups = None

    def stall_silo(self, address) -> None:
        self.stalled.add(address)

    def unstall_silo(self, address) -> None:
        self.stalled.discard(address)

    def _cut(self, sender, target) -> Optional[str]:
        """Is the (sender → target) edge severed by a partition/stall?"""
        if sender in self.stalled or target in self.stalled:
            return "stall"
        if self.partition_groups is not None:
            for group in self.partition_groups:
                if sender in group:
                    return None if target in group else "partition"
            # sender in no group (e.g. a silo started mid-partition):
            # isolate it from every grouped silo
            for group in self.partition_groups:
                if target in group:
                    return "partition"
        return None

    # ---- attach / detach --------------------------------------------------

    def _wrap(self, obj: Any, attr: str, wrapper) -> None:
        key = (id(obj), attr)
        if key in self._wrapped:
            return
        self._wrapped.add(key)
        original = getattr(obj, attr)
        self._originals.append((obj, attr, original))
        setattr(obj, attr, wrapper(original))

    def detach(self) -> None:
        """Restore every wrapped seam."""
        for obj, attr, original in reversed(self._originals):
            setattr(obj, attr, original)
        self._originals.clear()
        self._wrapped.clear()
        for listeners, cb in self._listeners:
            if cb in listeners:
                listeners.remove(cb)
        self._listeners.clear()

    def attach_cluster(self, cluster) -> None:
        """Wire every seam of a TestingCluster-shaped object."""
        from orleans_tpu.runtime.transport import InProcTransport
        if isinstance(cluster.fabric, InProcTransport):
            self.attach_inproc_fabric(cluster.fabric)
        self.attach_membership_table(cluster.table)
        for silo in cluster.silos:
            self.attach_silo(silo)

    def attach_silo(self, silo) -> None:
        """Per-silo seams (storage, engine, tcp transport).  Idempotent —
        call again for silos started mid-run."""
        for name, provider in silo.storage_providers.items():
            self.attach_storage(provider, name)
        if silo.tensor_engine is not None:
            self.attach_engine(silo.tensor_engine)
        transport = getattr(silo, "_bound_transport", None)
        inner = getattr(transport, "transport", None)
        if inner is not None and hasattr(inner, "send"):  # TcpBoundTransport
            self.attach_tcp_transport(inner)
        self.attach_resilience(silo)

    def attach_resilience(self, silo) -> None:
        """Mirror the containment plane's runtime events into the trace:
        circuit-breaker transitions and dead-letter records.  Recorded
        with ``sig=None`` — like unpinned rules, their exact counts ride
        timing-dependent traffic, so they are evidence in the trace but
        excluded from the reproducibility signature.  Idempotent."""
        board = getattr(silo, "breakers", None)
        if board is not None \
                and ("breaker", id(board)) not in self._wrapped:
            self._wrapped.add(("breaker", id(board)))

            def on_breaker(target, old, new, reason, _name=silo.name):
                self.trace.record(
                    "runtime", f"breaker.{_name}", "breaker", new,
                    {"silo": _name, "target": str(target), "from": old,
                     "reason": reason})

            board.on_transition.append(on_breaker)
            self._listeners.append((board.on_transition, on_breaker))
        ring = getattr(silo, "dead_letters", None)
        if ring is not None \
                and ("dead_letters", id(ring)) not in self._wrapped:
            self._wrapped.add(("dead_letters", id(ring)))

            def on_dead_letter(entry, _name=silo.name):
                self.trace.record(
                    "runtime", f"dead_letter.{_name}", "dead_letter",
                    entry["reason"],
                    {"silo": _name, "detail": entry["detail"],
                     "method": entry["method"],
                     "trace_id": entry.get("trace_id")})

            ring.on_record.append(on_dead_letter)
            self._listeners.append((ring.on_record, on_dead_letter))

    # ---- transport seam ---------------------------------------------------

    def attach_inproc_fabric(self, fabric) -> None:
        self._wrap(fabric, "send", lambda original:
                   lambda sender, msg, _o=original:
                   self._transport_send(_o, sender, msg))

    def attach_tcp_transport(self, transport) -> None:
        sender = transport.silo.address
        self._wrap(transport, "send", lambda original:
                   lambda msg, _o=original, _s=sender:
                   self._transport_send(_o, _s, msg, tcp=True))

    def _transport_send(self, original, sender, msg, tcp: bool = False):
        self.counters["transport_seen"] += 1

        def forward(m):
            # re-checked at CALL time, not decision time: a delayed or
            # reorder-parked message fires from a timer, and a partition
            # or stall imposed in the meantime must sever it too
            cut_now = self._cut(sender, m.target_silo)
            if cut_now is not None:
                self.counters[f"{cut_now}_dropped"] += 1
                return None
            return original(m) if tcp else original(sender, m)

        cut = self._cut(sender, msg.target_silo)
        if cut is not None:
            self.counters[f"{cut}_dropped"] += 1
            return None
        hit = self._decide("transport", msg)
        if hit is None:
            if self._parked:
                # a reorder previously parked a message: let this one pass
                # first, then flush the parked one behind it
                parked, self._parked = self._parked, []
                forward(msg)
                for fwd, m in parked:
                    fwd(m)
                return None
            return forward(msg)
        rule, idx = hit
        detail = {"target": msg.target_silo,
                  "method": getattr(msg, "method_name", None),
                  "trace_id": _message_trace_id(msg)}
        self._record_rule(rule, idx, detail)
        if rule.action == "drop":
            self.counters["transport_dropped"] += 1
            return None
        if rule.action == "delay":
            self.counters["transport_delayed"] += 1
            asyncio.get_running_loop().call_later(rule.delay, forward, msg)
            return None
        if rule.action == "duplicate":
            self.counters["transport_duplicated"] += 1
            forward(msg)
            return forward(msg)
        # reorder: park this message; it flushes behind the next passing
        # message (or after rule.delay, whichever comes first — the timer
        # guarantees a lone parked message still arrives)
        self.counters["transport_reordered"] += 1
        entry = (forward, msg)
        self._parked.append(entry)

        def flush_fallback() -> None:
            if entry in self._parked:
                self._parked.remove(entry)
                forward(msg)

        asyncio.get_running_loop().call_later(rule.delay, flush_fallback)
        return None

    # ---- storage seam -----------------------------------------------------

    def attach_storage(self, provider, name: str = "?") -> None:
        self._wrap(provider, "write_state", lambda original:
                   lambda grain_type, grain_id, state, _o=original, _n=name:
                   self._storage_write(_o, _n, grain_type, grain_id, state))

    async def _storage_write(self, original, provider_name: str,
                             grain_type: str, grain_id, state):
        self.counters["storage_seen"] += 1
        hit = self._decide("storage", (provider_name, grain_type, grain_id))
        if hit is None:
            return await original(grain_type, grain_id, state)
        rule, idx = hit
        self._record_rule(rule, idx, {"provider": provider_name,
                                      "grain_type": grain_type,
                                      "grain_id": grain_id,
                                      "trace_id": _ambient_trace_id()})
        if rule.action == "fail":
            self.counters["storage_failed"] += 1
            raise ChaosInjectedError(
                f"chaos[{rule.name}]: injected storage write failure for "
                f"{grain_type}/{grain_id}")
        self.counters["storage_slowed"] += 1
        await asyncio.sleep(rule.delay)
        return await original(grain_type, grain_id, state)

    # ---- membership seam --------------------------------------------------

    def attach_membership_table(self, table) -> None:
        self._wrap(table, "update_row", lambda original:
                   lambda entry, etag, table_version, _o=original:
                   self._membership_update(_o, entry, etag, table_version))

    async def _membership_update(self, original, entry, etag, table_version):
        from orleans_tpu.runtime.membership import CasConflictError
        self.counters["membership_seen"] += 1
        hit = self._decide("membership", entry)
        if hit is None:
            return await original(entry, etag, table_version)
        rule, idx = hit
        self._record_rule(rule, idx, {"silo": entry.silo,
                                      "status": entry.status.value})
        self.counters["membership_conflicted"] += 1
        raise CasConflictError(
            f"chaos[{rule.name}]: injected CAS conflict on {entry.silo}")

    # ---- engine seam -------------------------------------------------------

    def attach_engine(self, engine) -> None:
        self._wrap(engine, "send_batch", lambda original:
                   lambda interface, method, keys, args, want_results=False,
                   _o=original:
                   self._engine_send(_o, interface, method, keys, args,
                                     want_results))

    def _engine_send(self, original, interface, method, keys, args,
                     want_results: bool):
        self.counters["engine_seen"] += 1
        type_name = interface if isinstance(interface, str) \
            else interface.__name__
        hit = self._decide("engine", (type_name, method))
        if hit is not None:
            rule, idx = hit
            corrupted, n_rows = self._corrupt(rule, keys, args)
            detail = {"type": type_name, "method": method,
                      "corrupted_rows": n_rows,
                      "trace_id": _ambient_trace_id()}
            if n_rows:
                self.counters["engine_corrupted"] += 1
                args = corrupted
            else:
                # honest evidence for replay: the rule fired but the slab
                # had no eligible columns — no data was actually poisoned
                detail["note"] = "no eligible columns"
            self._record_rule(rule, idx, detail)
        return original(interface, method, keys, args,
                        want_results=want_results)

    def _corrupt(self, rule, keys, args) -> Tuple[Any, int]:
        """Copy-and-corrupt a seeded fraction of slab rows: NaN into float
        columns (corrupt_nan) or near-max values into integer columns
        (corrupt_overflow).  The caller's arrays are never mutated.
        Returns (corrupted_args, rows_actually_poisoned) — 0 when no
        column was eligible, so the trace can stay honest.  The row draw
        happens unconditionally to keep the rule's RNG stream aligned
        with its matched-event sequence."""
        import jax

        n = len(keys)
        if n == 0:
            return args, 0
        state = self._states[rule.name]
        k = max(1, int(n * rule.corrupt_fraction))
        rows = np.asarray(sorted(state.rng.sample(range(n), min(k, n))))
        touched = {"any": False}

        def poison(leaf):
            a = np.array(leaf)  # host copy (also detaches device arrays)
            if a.ndim == 0 or a.shape[0] != n:
                return leaf  # scalar / non-row-aligned column: leave it
            if rule.action == "corrupt_nan" \
                    and np.issubdtype(a.dtype, np.floating):
                a[rows] = np.nan
                touched["any"] = True
                return a
            if rule.action == "corrupt_overflow" \
                    and np.issubdtype(a.dtype, np.integer):
                a[rows] = np.iinfo(a.dtype).max - 1
                touched["any"] = True
                return a
            return leaf

        out = jax.tree_util.tree_map(poison, args)
        return out, (len(rows) if touched["any"] else 0)

    # ---- reporting ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        return {
            "counters": dict(self.counters),
            "rules": {name: {"matched": s.matched, "fired": s.fired,
                             "enabled": s.enabled}
                      for name, s in self._states.items()},
            "partitioned": self.partition_groups is not None,
            "stalled": [str(s) for s in self.stalled],
        }
