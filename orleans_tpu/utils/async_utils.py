"""Async building blocks used across the runtime.

Parity: the reference's async utility suite (reference: src/Orleans/Async/
AsyncExecutorWithRetries.cs, AsyncPipeline.cs, AsyncLock.cs,
AsyncSerialExecutor.cs, AsyncBatchedContinuationQueue.cs,
MultiTaskCompletionSource.cs).  The reference builds these on TPL tasks and
interlocked primitives; here they ride the single asyncio event loop the
host control plane runs on, so the lock-free dances collapse into plain
awaits — same contracts, far less machinery.
"""

from __future__ import annotations

import asyncio
import random
import time
from collections import deque
from typing import Any, Awaitable, Callable, Deque, List, Optional, Tuple

INFINITE_RETRIES = -1


def spawn_in_fresh_context(coro) -> "asyncio.Task":
    """Schedule ``coro`` as a task running in a FRESH contextvars.Context —
    background loops (pulling agents, cache maintainers, reminder firings)
    must not inherit the ambient grain-call context of whoever happened to
    start them.  ``loop.create_task(..., context=...)`` only exists on
    Python 3.11+; on 3.10 the task snapshots the context active at
    construction, so constructing it inside ``Context().run`` is the
    equivalent."""
    import contextvars
    loop = asyncio.get_running_loop()
    try:
        return loop.create_task(coro, context=contextvars.Context())
    except TypeError:  # Python < 3.11: no context kwarg
        return contextvars.Context().run(loop.create_task, coro)


class FixedBackoff:
    """(reference: FixedBackoff in AsyncExecutorWithRetries.cs)"""

    def __init__(self, delay: float) -> None:
        self.delay = delay

    def next(self, attempt: int) -> float:
        return self.delay


class ExponentialBackoff:
    """Exponential backoff with decorrelated jitter
    (reference: ExponentialBackoff in AsyncExecutorWithRetries.cs)."""

    def __init__(self, min_delay: float = 0.05, max_delay: float = 5.0,
                 step: float = 2.0) -> None:
        if min_delay <= 0 or max_delay < min_delay or step < 1.0:
            raise ValueError("invalid backoff parameters")
        self.min_delay = min_delay
        self.max_delay = max_delay
        self.step = step

    def next(self, attempt: int) -> float:
        high = min(self.max_delay, self.min_delay * (self.step ** attempt))
        return random.uniform(self.min_delay, high)


async def execute_with_retries(
    fn: Callable[[int], Awaitable[Any]],
    max_retries: int = 3,
    retry_filter: Optional[Callable[[BaseException, int], bool]] = None,
    max_execution_time: Optional[float] = None,
    backoff: Optional[Any] = None,
    success_filter: Optional[Callable[[Any, int], bool]] = None,
) -> Any:
    """Run ``fn(attempt)`` with retry policy.

    Retries on exceptions passing ``retry_filter`` and on results failing
    ``success_filter``, up to ``max_retries`` (−1 = infinite), bounded by
    ``max_execution_time`` wall seconds, sleeping ``backoff.next(attempt)``
    between tries (reference: AsyncExecutorWithRetries.ExecuteWithRetries).
    """
    start = time.monotonic()
    attempt = 0
    while True:
        if max_execution_time is not None and \
                time.monotonic() - start > max_execution_time:
            raise TimeoutError(
                f"retries exceeded max_execution_time={max_execution_time}s "
                f"after {attempt} attempts")
        try:
            result = await fn(attempt)
        except asyncio.CancelledError:
            raise
        except BaseException as exc:
            keep = retry_filter(exc, attempt) if retry_filter else True
            exhausted = max_retries != INFINITE_RETRIES \
                and attempt >= max_retries
            if not keep or exhausted:
                raise
        else:
            if success_filter is None or success_filter(result, attempt):
                return result
            if max_retries != INFINITE_RETRIES and attempt >= max_retries:
                return result
        attempt += 1
        if backoff is not None:
            await asyncio.sleep(backoff.next(attempt))


class AsyncLock:
    """FIFO async mutex usable as ``async with`` (reference: AsyncLock.cs).

    asyncio.Lock already guarantees FIFO wakeup on one loop; this wrapper
    exists for API parity and for lock-scoped helpers.
    """

    def __init__(self) -> None:
        self._lock = asyncio.Lock()

    async def __aenter__(self) -> "AsyncLock":
        await self._lock.acquire()
        return self

    async def __aexit__(self, *exc) -> None:
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()


class AsyncSerialExecutor:
    """Serializes submitted async closures: no two run concurrently, FIFO
    order, each caller awaits its own closure's result (reference:
    AsyncSerialExecutor.cs — used inside reentrant grains to run selected
    sections non-reentrantly)."""

    def __init__(self) -> None:
        self._queue: Deque[Tuple[asyncio.Future, Callable[[], Awaitable[Any]]]] = deque()
        self._pumping = False

    def submit(self, fn: Callable[[], Awaitable[Any]]) -> "asyncio.Future[Any]":
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._queue.append((fut, fn))
        if not self._pumping:
            self._pumping = True
            asyncio.get_running_loop().create_task(self._pump())
        return fut

    async def execute(self, fn: Callable[[], Awaitable[Any]]) -> Any:
        return await self.submit(fn)

    async def _pump(self) -> None:
        try:
            while self._queue:
                fut, fn = self._queue.popleft()
                if fut.cancelled():
                    continue
                try:
                    result = await fn()
                except asyncio.CancelledError:
                    fut.cancel()
                    raise
                except BaseException as exc:
                    if not fut.done():
                        fut.set_exception(exc)
                else:
                    if not fut.done():
                        fut.set_result(result)
        finally:
            self._pumping = False
            if self._queue:  # raced with a submit during the last await
                self._pumping = True
                asyncio.get_running_loop().create_task(self._pump())


class AsyncPipeline:
    """Bounded-concurrency task pipeline: ``add`` blocks (asynchronously)
    once ``capacity`` tasks are in flight — backpressure for load
    generators (reference: AsyncPipeline.cs, DEFAULT_CAPACITY=10)."""

    DEFAULT_CAPACITY = 10

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._running: set = set()
        self._errors: List[BaseException] = []

    @property
    def count(self) -> int:
        return len(self._running)

    async def add(self, aw: Awaitable[Any]) -> None:
        while len(self._running) >= self.capacity:
            done, self._running = await asyncio.wait(
                self._running, return_when=asyncio.FIRST_COMPLETED)
            self._collect(done)
        task = asyncio.ensure_future(aw)
        self._running.add(task)

    async def wait(self) -> None:
        """Drain the pipeline; re-raises the first captured failure
        (reference: AsyncPipeline.Wait propagating faulted tasks)."""
        if self._running:
            done, _ = await asyncio.wait(self._running)
            self._running = set()
            self._collect(done)
        if self._errors:
            raise self._errors[0]

    def _collect(self, done) -> None:
        for t in done:
            if t.cancelled():
                continue
            exc = t.exception()
            if exc is not None:
                self._errors.append(exc)


class MultiCompletionSource:
    """A countdown future: resolves when ``set_one_result`` has been called
    ``count`` times; fails fast on ``set_exception``
    (reference: MultiTaskCompletionSource.cs)."""

    def __init__(self, count: int) -> None:
        if count <= 0:
            raise ValueError("count must be > 0")
        self._remaining = count
        self._future: asyncio.Future = \
            asyncio.get_event_loop().create_future()

    @property
    def task(self) -> "asyncio.Future[None]":
        return self._future

    def set_one_result(self) -> None:
        if self._remaining <= 0:
            raise RuntimeError("set_one_result called more times than count")
        self._remaining -= 1
        if self._remaining == 0 and not self._future.done():
            self._future.set_result(None)

    def set_exception(self, exc: BaseException) -> None:
        if not self._future.done():
            self._future.set_exception(exc)


class BatchedContinuationQueue:
    """Coalesces many tiny completions into periodic batched callbacks —
    the host-path analog of the reference's vectorized continuation queue
    (reference: AsyncBatchedContinuationQueue.cs, which flushes on a count
    or time gate).  Used to amortize per-message bookkeeping the same way
    the tensor engine amortizes per-message dispatch."""

    def __init__(self, flush_count: int = 256,
                 flush_interval: float = 0.001) -> None:
        self.flush_count = flush_count
        self.flush_interval = flush_interval
        self._items: List[Any] = []
        self._callbacks: List[Callable[[List[Any]], None]] = []
        self._timer: Optional[asyncio.TimerHandle] = None

    def on_flush(self, cb: Callable[[List[Any]], None]) -> None:
        self._callbacks.append(cb)

    def enqueue(self, item: Any) -> None:
        self._items.append(item)
        if len(self._items) >= self.flush_count:
            self.flush()
        elif self._timer is None:
            loop = asyncio.get_event_loop()
            self._timer = loop.call_later(self.flush_interval, self.flush)

    def flush(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._items:
            return
        batch, self._items = self._items, []
        for cb in self._callbacks:
            cb(batch)
