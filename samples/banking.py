"""Banking sample — the durable-state workload riding the device journal.

The scenario class the durable state plane opens (ROADMAP item 5:
banking / inventory / game state — anything where a crash must not lose
acknowledged writes): every account is a vector-grain row holding an
INTEGER balance, deposits and transfers arrive as batched commands, and
the ingress sites are JOURNALED (``engine.register_journal``) — each
tick's command batch appends to the device journal ring in one op, seals
into durable segments, and fold-replays after a crash.  Integer folds
are exactly associative, so restored state is BIT-exact against the
host oracle at the acknowledged horizon — the property the durability
bench and chaos tier assert.

Transfers exercise the interesting recovery path: the debit executes at
the ingress site and the credit is an EMIT to the destination account —
on replay the handler re-emits, so the downstream leg is reconstructed
by re-execution, never separately journaled (the event-sourcing shape:
journal the commands, fold the effects).

Parity thread: the host path's ``event_sourcing.py`` JournaledGrain
(reference: OrleansEventSourcing, JournaledGrain.cs:34) commits one
storage write per raised event; this is the same contract — state is a
fold over a durable event log — at per-tick batch granularity.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from orleans_tpu.core.grain import batched_method
from orleans_tpu.tensor import (
    Batch,
    Emit,
    VectorGrain,
    field,
    seg_sum,
    vector_grain,
)


@vector_grain
class AccountGrain(VectorGrain):
    """One bank account per row — integer state only (bit-exactness is
    the durability contract's currency)."""

    balance = field(jnp.int32, 0)
    credits = field(jnp.int32, 0)     # deposits + received transfers
    debits = field(jnp.int32, 0)      # sent transfers

    @batched_method
    @staticmethod
    def deposit(state, batch: Batch, n_rows: int):
        rows, args = batch.rows, batch.args
        live = (rows >= 0).astype(jnp.int32)
        return {
            **state,
            "balance": state["balance"]
            + seg_sum(args["amount"], rows, n_rows),
            "credits": state["credits"] + seg_sum(live, rows, n_rows),
        }, None, ()

    @batched_method
    @staticmethod
    def transfer(state, batch: Batch, n_rows: int):
        """Debit the source row, credit the destination via an emit —
        the two-leg command whose second leg recovery reconstructs by
        re-execution."""
        rows, args = batch.rows, batch.args
        live = (rows >= 0).astype(jnp.int32)
        state = {
            **state,
            "balance": state["balance"]
            - seg_sum(args["amount"], rows, n_rows),
            "debits": state["debits"] + seg_sum(live, rows, n_rows),
        }
        emit = Emit(interface="AccountGrain", method="credit",
                    keys=args["dst"],
                    args={"amount": args["amount"]},
                    mask=batch.mask)
        return state, None, (emit,)

    @batched_method
    @staticmethod
    def credit(state, batch: Batch, n_rows: int):
        rows, args = batch.rows, batch.args
        live = (rows >= 0).astype(jnp.int32)
        return {
            **state,
            "balance": state["balance"]
            + seg_sum(args["amount"], rows, n_rows),
            "credits": state["credits"] + seg_sum(live, rows, n_rows),
        }, None, ()


class BankOracle:
    """Host replay oracle: numpy fold of the SAME commands, applied in
    the same per-tick grouping.  ``expect()`` renders the per-key state
    the restored arena must equal bit-for-bit at any command prefix."""

    def __init__(self, n_accounts: int) -> None:
        self.n = n_accounts
        self.balance = np.zeros(n_accounts, dtype=np.int64)
        self.credits = np.zeros(n_accounts, dtype=np.int64)
        self.debits = np.zeros(n_accounts, dtype=np.int64)

    def apply(self, event: Dict) -> None:
        keys = event["keys"]
        if event["method"] == "deposit":
            np.add.at(self.balance, keys, event["amount"])
            np.add.at(self.credits, keys, 1)
        elif event["method"] == "transfer":
            np.add.at(self.balance, keys, -event["amount"])
            np.add.at(self.debits, keys, 1)
            np.add.at(self.balance, event["dst"], event["amount"])
            np.add.at(self.credits, event["dst"], 1)
        else:
            raise ValueError(event["method"])

    def expect(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        k = np.asarray(keys)
        return {"balance": self.balance[k].astype(np.int32),
                "credits": self.credits[k].astype(np.int32),
                "debits": self.debits[k].astype(np.int32)}

    def total(self) -> int:
        """Conservation invariant: transfers move, deposits mint — the
        cluster-wide balance equals total deposited."""
        return int(self.balance.sum())


def make_events(n_accounts: int, n_ticks: int, lanes: int,
                seed: int = 0, transfer_every: int = 3
                ) -> List[Dict]:
    """Deterministic command stream: one batch per tick, every
    ``transfer_every``-th a transfer batch, the rest deposits."""
    rng = np.random.default_rng(seed)
    events = []
    for t in range(n_ticks):
        keys = rng.integers(0, n_accounts, lanes).astype(np.int64)
        amount = rng.integers(1, 50, lanes).astype(np.int32)
        if transfer_every > 0 and t % transfer_every == transfer_every - 1:
            events.append({"method": "transfer", "keys": keys,
                           "amount": amount,
                           "dst": rng.integers(0, n_accounts, lanes)
                           .astype(np.int32)})
        else:
            events.append({"method": "deposit", "keys": keys,
                           "amount": amount})
    return events


def register_banking_journal(engine) -> None:
    """Journal the two INGRESS sites.  ``credit`` is deliberately not
    journaled — it is reachable only as a transfer's emit, and replay
    reconstructs it by re-executing the transfer."""
    engine.register_journal("AccountGrain", "deposit")
    # transfer's ``dst`` leaf holds emit-destination keys of the same
    # type — naming it lets fused fold-replay pre-activate the union
    # instead of rolling back on cold credit targets
    engine.register_journal("AccountGrain", "transfer",
                            emit_key_args=("dst",))


async def run_banking_load(engine, events: List[Dict],
                           oracle: Optional[BankOracle] = None,
                           ticks_per_event: int = 1) -> Dict:
    """Drive the command stream, one batch per tick (the journal's
    per-tick grouping contract), folding the oracle in step."""
    import time
    t0 = time.perf_counter()
    for ev in events:
        args = {"amount": ev["amount"]}
        if ev["method"] == "transfer":
            args["dst"] = ev["dst"]
        engine.send_batch("AccountGrain", ev["method"], ev["keys"], args)
        for _ in range(ticks_per_event):
            engine.run_tick()
        if oracle is not None:
            oracle.apply(ev)
    await engine.flush()
    return {"events": len(events),
            "lanes": int(sum(len(e["keys"]) for e in events)),
            "seconds": time.perf_counter() - t0}


def read_accounts(engine, keys: np.ndarray) -> Dict[str, np.ndarray]:
    """Per-key state gathered from the arena (host view)."""
    arena = engine.arena_for("AccountGrain")
    rows, found = arena.lookup_rows(np.asarray(keys, dtype=np.int64))
    assert found.all(), "unactivated account probed"
    out = {}
    for name in ("balance", "credits", "debits"):
        out[name] = np.asarray(arena.state[name])[rows]
    return out
