"""SQL (sqlite) table storage provider.

Parity: reference SQL storage provider (reference: src/OrleansSQLUtils/
Storage/Provider/SqlStorageProvider.cs:13 + the OrleansGrainState table DDL
in CreateOrleansTables_SqlServer.sql) — grain state rows keyed by
(grain type, grain id) with optimistic-concurrency etags.  SQLite stands in
for SQL Server/MySQL; the schema and the etag CAS discipline are the same
shape, so a real backend is a connection-string swap.
"""

from __future__ import annotations

import asyncio
import sqlite3
from typing import Any, Dict, Optional

from orleans_tpu.codec import default_manager as codec
from orleans_tpu.ids import GrainId
from orleans_tpu.runtime.storage import (
    GrainState,
    InconsistentStateError,
    StorageProvider,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS grain_state (
    grain_type TEXT NOT NULL,
    grain_key  TEXT NOT NULL,
    etag       INTEGER NOT NULL,
    data       BLOB,
    PRIMARY KEY (grain_type, grain_key)
)
"""


class SqliteStorage(StorageProvider):
    """``path=":memory:"`` gives a per-provider in-memory database (tests);
    a file path gives durable storage shared across silo restarts."""

    def __init__(self, path: str = ":memory:") -> None:
        self.path = path
        self._conn = sqlite3.connect(path)
        self._conn.execute(_SCHEMA)
        self._conn.commit()

    async def close(self) -> None:
        self._conn.close()

    # sqlite calls are sub-ms; they run inline on the loop the same way the
    # reference's ADO.NET calls run on the thread pool behind one await
    async def read_state(self, grain_type: str, grain_id: GrainId,
                         state: GrainState) -> None:
        row = self._conn.execute(
            "SELECT etag, data FROM grain_state "
            "WHERE grain_type=? AND grain_key=?",
            (grain_type, str(grain_id))).fetchone()
        if row is None:
            state.record_exists = False
            state.etag = None
            return
        etag, blob = row
        state.data = codec.deserialize(blob)
        state.etag = str(etag)
        state.record_exists = True

    async def write_state(self, grain_type: str, grain_id: GrainId,
                          state: GrainState) -> None:
        key = (grain_type, str(grain_id))
        blob = codec.serialize(state.data)
        cur = self._conn.cursor()
        if state.etag is None:
            # insert-if-absent (CAS on non-existence)
            try:
                cur.execute(
                    "INSERT INTO grain_state "
                    "(grain_type, grain_key, etag, data) VALUES (?,?,1,?)",
                    (*key, blob))
            except sqlite3.IntegrityError:
                row = cur.execute(
                    "SELECT etag FROM grain_state "
                    "WHERE grain_type=? AND grain_key=?", key).fetchone()
                raise InconsistentStateError(
                    str(row[0]) if row else None, None)
            self._conn.commit()
            state.etag = "1"
        else:
            cur.execute(
                "UPDATE grain_state SET etag=etag+1, data=? "
                "WHERE grain_type=? AND grain_key=? AND etag=?",
                (blob, *key, int(state.etag)))
            if cur.rowcount == 0:
                row = cur.execute(
                    "SELECT etag FROM grain_state "
                    "WHERE grain_type=? AND grain_key=?", key).fetchone()
                raise InconsistentStateError(
                    str(row[0]) if row else None, state.etag)
            self._conn.commit()
            state.etag = str(int(state.etag) + 1)
        state.record_exists = True

    async def clear_state(self, grain_type: str, grain_id: GrainId,
                          state: GrainState) -> None:
        key = (grain_type, str(grain_id))
        cur = self._conn.cursor()
        if state.etag is None:
            row = cur.execute(
                "SELECT etag FROM grain_state "
                "WHERE grain_type=? AND grain_key=?", key).fetchone()
            if row is not None:
                raise InconsistentStateError(str(row[0]), None)
            return
        cur.execute(
            "DELETE FROM grain_state "
            "WHERE grain_type=? AND grain_key=? AND etag=?",
            (*key, int(state.etag)))
        if cur.rowcount == 0:
            row = cur.execute(
                "SELECT etag FROM grain_state "
                "WHERE grain_type=? AND grain_key=?", key).fetchone()
            raise InconsistentStateError(
                str(row[0]) if row else None, state.etag)
        self._conn.commit()
        state.etag = None
        state.record_exists = False
        state.data = None
