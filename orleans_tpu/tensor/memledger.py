"""DeviceMemoryLedger: byte-accurate HBM accounting by owner.

The arena IS the heap of this runtime — state columns, directory
mirrors, use clocks, pending-batch slabs and the latency-ledger
histogram are the device allocations a silo makes — yet until now the
only memory number anywhere was whatever ``device.memory_stats()``
happened to say, with no attribution.  This ledger walks the engine's
own references and accounts every byte to an owner:

* ``arena.<type>.state`` — state columns (per-field detail in the
  ``arenas`` section, since "which FIELD is fat" is the actionable
  number when a grain type outgrows its budget);
* ``arena.<type>.clocks`` — the device use clock;
* ``arena.<type>.mirror`` — device directory mirrors (sorted / dense /
  wide), the replicated routing state;
* ``pending_batches`` — device-resident leaves of queued
  ``PendingBatch``es (emit slabs awaiting their tick);
* ``latency_ledger`` — the PR 6 on-device histogram;
* ``autofuse_chain`` — pre-run state buffers pinned by the auto-fuser's
  rollback snapshot (counted only while they differ from the live
  columns — before the first window runs they alias the live state).

Free-list slack (bytes of column storage attributable to freed rows) and
fragmentation ride the per-arena detail: slack is *reusable* capacity,
not an extra allocation, so it overlays the state bytes rather than
adding to the total.

Where the backend exposes ``device.memory_stats()`` (TPU), the snapshot
reconciles self-accounting against ``bytes_in_use`` and derives a
**headroom** ratio the ShedController consumes (memory pressure floors
the shed level, the same discipline as the watchdog stall floor).  On
backends that return ``None`` (CPU) the ledger degrades to pure
self-accounting — no warnings, headroom unknown (tests pin this under
``JAX_PLATFORMS=cpu``).

Everything is host-side attribute walking over buffers the engine
already holds: no device work, no transfers, no allocation beyond the
snapshot dict.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


def _dev_bytes(x: Any) -> int:
    """Bytes of a device-resident array (0 for host/np/scalars)."""
    import jax
    if isinstance(x, jax.Array):
        return int(x.nbytes)
    return 0


def _host_bytes(x: Any) -> int:
    return int(x.nbytes) if isinstance(x, np.ndarray) else 0


class DeviceMemoryLedger:
    """Per-engine HBM accounting (see module docstring)."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self.peak_bytes = 0          # peak self-accounted total observed
        self.snapshots_taken = 0

    # -- device stats (guarded: CPU backends return None) --------------------

    def _devices(self) -> List[Any]:
        eng = self.engine
        if eng.mesh is not None:
            return list(eng.mesh.devices.flat)
        try:
            import jax
            return [jax.devices()[0]]
        except Exception:  # noqa: BLE001 — no backend, no stats
            return []

    def device_stats(self) -> Optional[Dict[str, int]]:
        """Aggregated ``memory_stats()`` over the engine's devices, or
        None when the backend exposes nothing (CPU) — the degrade path
        is silent by contract (no warnings; self-accounting stands)."""
        per_dev = []
        for d in self._devices():
            fn = getattr(d, "memory_stats", None)
            if fn is None:
                continue
            try:
                s = fn()
            except Exception:  # noqa: BLE001 — a backend without the
                s = None       # query must not break the snapshot
            if s:
                per_dev.append(s)
        if not per_dev:
            return None
        out: Dict[str, int] = {"devices": len(per_dev)}
        for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use",
                    "bytes_reserved"):
            vals = [s[key] for s in per_dev if key in s]
            if vals:
                out[key] = int(sum(vals))
        return out

    # -- self accounting -----------------------------------------------------

    @staticmethod
    def _row_bytes(arena) -> int:
        """Bytes one arena row occupies across its state columns."""
        total = 0
        for f in arena.info.state_fields.values():
            n = 1
            for d in f.shape:
                n *= d
            total += n * np.dtype(f.dtype).itemsize
        return total

    def _arena_detail(self, name: str, arena) -> Dict[str, Any]:
        fields = {fname: _dev_bytes(col)
                  for fname, col in arena.state.items()}
        mirror = sum(_dev_bytes(m) for m in (
            arena._dev_sorted_keys, arena._dev_sorted_rows,
            arena._dev_dense))
        if arena._dev_wide is not None:
            mirror += sum(_dev_bytes(p) for p in arena._dev_wide)
        free_rows = sum(len(f) for f in arena._free)
        return {
            "capacity": arena.capacity,
            "live_rows": arena.live_count,
            "state_bytes": sum(fields.values()),
            "fields": fields,
            "clock_bytes": _dev_bytes(arena.last_use_dev),
            "mirror_bytes": mirror,
            "free_rows": free_rows,
            # slack: column bytes currently attributable to freed rows —
            # reusable in place, an overlay of state_bytes (not added to
            # the owner totals)
            "slack_bytes": free_rows * self._row_bytes(arena),
            "fragmentation": round(arena.fragmentation(), 4),
        }

    def _pending(self) -> Dict[str, int]:
        import jax
        dev = host = batches = 0
        for queue in self.engine.queues.values():
            for b in queue:
                batches += 1
                leaves = list(jax.tree_util.tree_leaves(b.args))
                leaves += [b.rows, b.keys_dev, b.mask]
                if b.keys_wide is not None:
                    leaves += list(b.keys_wide)
                for leaf in leaves:
                    if leaf is None:
                        continue
                    dev += _dev_bytes(leaf)
                    host += _host_bytes(leaf)
                host += _host_bytes(b.keys_host)
        return {"batches": batches, "device_bytes": dev,
                "host_bytes": host}

    def _autofuse_chain_bytes(self) -> int:
        """Rollback-snapshot buffers the auto-fuser pins: counted only
        when they are NOT the live columns (post-window the live state is
        a fresh buffer; pre-window the snapshot aliases it)."""
        fuser = getattr(self.engine, "autofuser", None)
        snap = getattr(fuser, "_chain_snapshot", None) if fuser else None
        if not snap:
            return 0
        total = 0
        for name, cols in snap.items():
            arena = self.engine.arenas.get(name)
            live = arena.state if arena is not None else {}
            for fname, col in cols.items():
                if live.get(fname) is not col:
                    total += _dev_bytes(col)
        return total

    def snapshot(self) -> Dict[str, Any]:
        """The full accounting: owners, per-arena detail, device
        reconciliation, headroom.  Cheap enough for every
        ``engine.snapshot()`` — pure host attribute walks."""
        eng = self.engine
        owners: Dict[str, int] = {}
        arenas: Dict[str, Any] = {}
        for name, arena in eng.arenas.items():
            detail = self._arena_detail(name, arena)
            arenas[name] = detail
            owners[f"arena.{name}.state"] = detail["state_bytes"]
            owners[f"arena.{name}.clocks"] = detail["clock_bytes"]
            if detail["mirror_bytes"]:
                owners[f"arena.{name}.mirror"] = detail["mirror_bytes"]
        pending = self._pending()
        if pending["device_bytes"]:
            owners["pending_batches"] = pending["device_bytes"]
        ledger_hist = getattr(eng.ledger, "_hist", None)
        if ledger_hist is not None:
            owners["latency_ledger"] = _dev_bytes(ledger_hist)
        chain = self._autofuse_chain_bytes()
        if chain:
            owners["autofuse_chain"] = chain
        total = sum(owners.values())
        self.peak_bytes = max(self.peak_bytes, total)
        self.snapshots_taken += 1
        device = self.device_stats()
        headroom = None
        if device is not None and device.get("bytes_limit"):
            headroom = round(
                1.0 - device.get("bytes_in_use", 0)
                / device["bytes_limit"], 4)
        out: Dict[str, Any] = {
            "total_self_bytes": total,
            "peak_self_bytes": self.peak_bytes,
            "owners": owners,
            "arenas": arenas,
            "pending": pending,
            # device reconciliation: None on backends without
            # memory_stats (CPU) — self-accounting stands alone
            "device": device,
            "headroom": headroom,
            "source": "device+self" if device is not None else "self",
        }
        if device is not None and device.get("bytes_in_use"):
            # accounted / in-use: <1 means allocations the ledger does
            # not own (XLA scratch, compiled programs); ~1 means the
            # ledger explains the heap
            out["accounted_ratio"] = round(
                total / device["bytes_in_use"], 4)
        return out

    def headroom(self) -> Optional[float]:
        """The shed-controller gauge: device HBM headroom in [0, 1], or
        None when the backend cannot say (CPU self-accounting has no
        denominator — the controller treats None as no-signal)."""
        device = self.device_stats()
        if device is None or not device.get("bytes_limit"):
            return None
        return 1.0 - device.get("bytes_in_use", 0) / device["bytes_limit"]
