"""The TPU data plane: batched, tick-based grain execution.

This package is the rebuild's answer to the reference's hot path — the
per-message Dispatcher/Scheduler traversal (reference: src/OrleansRuntime/
Core/Dispatcher.cs, Scheduler/OrleansTaskScheduler.cs).  Instead of routing
one message at a time through queues and threads, each tick:

1. collects the tick's messages into dense (dst_row, payload) tensors,
2. routes them to the owning state shard (host index + XLA collectives),
3. applies one vectorized state-transition kernel per (grain type, method)
   — ``segment_sum``/gather-scatter fan-in on the MXU/VPU,
4. emits next-tick messages and host-bound responses.

Grain identity, the directory, persistence and RPC surfaces are shared with
the host path: a vector grain is still a grain.
"""

from orleans_tpu.tensor.vector_grain import (
    Batch,
    Emit,
    VectorGrain,
    field,
    seg_max,
    seg_mean,
    seg_sum,
    scatter_rows,
    vector_grain,
)
from orleans_tpu.tensor.engine import TensorEngine
from orleans_tpu.tensor.fanout import DeviceFanout, FanoutOverflowError
from orleans_tpu.tensor.fused import FusedTickProgram
from orleans_tpu.tensor.streams_plane import DeviceSubscriptions
from orleans_tpu.tensor.memledger import DeviceMemoryLedger
from orleans_tpu.tensor.profiler import (
    COMPILE_CAUSES,
    CompileTracker,
    TickPhaseProfiler,
)
from orleans_tpu.tensor.persistence import (
    FileVectorStore,
    MemoryVectorStore,
    StorageProviderVectorStore,
    VectorStore,
)
from orleans_tpu.tensor.checkpoint import (
    CheckpointPlane,
    FileSnapshotStore,
    MemorySnapshotStore,
    SnapshotStore,
)

__all__ = [
    "CheckpointPlane",
    "FileSnapshotStore",
    "MemorySnapshotStore",
    "SnapshotStore",
    "FileVectorStore",
    "MemoryVectorStore",
    "StorageProviderVectorStore",
    "VectorStore",
    "Batch",
    "Emit",
    "VectorGrain",
    "field",
    "seg_sum",
    "seg_max",
    "seg_mean",
    "scatter_rows",
    "vector_grain",
    "TensorEngine",
    "DeviceFanout",
    "DeviceSubscriptions",
    "FanoutOverflowError",
    "FusedTickProgram",
    "DeviceMemoryLedger",
    "TickPhaseProfiler",
    "CompileTracker",
    "COMPILE_CAUSES",
]
