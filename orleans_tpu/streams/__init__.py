"""Streams: the pub/sub programming model and its runtime.

Parity: reference streams core (reference: src/Orleans/Streams/ — 65 files:
IAsyncStream.cs:36, StreamImpl.cs:35, StreamConsumer.cs:32,
StreamPubSubImpl.cs:31, ImplicitStreamSubscriberTable.cs:32) and streams
runtime (reference: src/OrleansRuntime/Streams/ —
PersistentStreamPullingManager.cs:35, PersistentStreamPullingAgent.cs:34,
HashRingBasedStreamQueueMapper.cs:30, QueueBalancer/*).

Two provider families, as in the reference:

* SimpleMessageStreamProvider — direct grain-to-grain fan-out, no queue
  (reference: SimpleMessageStreamProvider.cs:31).
* PersistentStreamProvider — queue-backed: producers enqueue, per-queue
  pulling agents on the queue's ring-owner silo deliver to subscribers
  (reference: PersistentStreamProvider.cs:58).
"""

from orleans_tpu.streams.core import (
    StreamId,
    StreamSubscriptionHandle,
    implicit_stream_subscription,
)
from orleans_tpu.streams.simple import SimpleMessageStreamProvider
from orleans_tpu.streams.persistent import (
    InMemoryQueueAdapter,
    PersistentStreamProvider,
    QueueMessage,
    TensorSinkBinding,
)

__all__ = [
    "StreamId",
    "StreamSubscriptionHandle",
    "implicit_stream_subscription",
    "SimpleMessageStreamProvider",
    "PersistentStreamProvider",
    "InMemoryQueueAdapter",
    "QueueMessage",
    "TensorSinkBinding",
]
