"""ShardExchange: on-device cross-shard message routing over the mesh.

The arena is mesh-sharded (the directory's consistent-hash assignment IS
the shard-block map — arena.py, runtime/ring.py), but until now a batch's
scatter into rows owned by OTHER shards was left to XLA's implicit
collectives: every `state.at[rows].set` over a sharded column turns into
unstructured gather/scatter communication, re-planned per kernel.  This
module makes the cross-shard hop an EXPLICIT, structured exchange — the
device analog of the cross-silo slab path (tensor/router.py), so the
8-device mesh runs as one logical cluster with host transport reserved
for true cross-process hops:

1. **bucket** — each shard classifies its slice of the batch by
   destination shard (``rows // shard_capacity``; identical to the
   directory's `shard_of_keys` hash by construction — the agreement is
   property-tested) and packs messages into a ``[n_shards, cap]`` send
   buffer;
2. **exchange** — ONE ``lax.all_to_all`` over the mesh axis moves every
   bucket to its owner (inside the compiled program: the fused window
   threads this through its ``lax.scan``);
3. **fold** — the received lanes carry rows that are all shard-local, so
   the existing step kernel's scatter/segment-sum applies them without
   further communication.

**Occupancy-sized buckets** (the perf contract): ``cap`` is NOT a
worst-case bound.  Every exchange measures the per-destination bucket
demand on device (``need`` — the true lane count wanting each bucket,
overflow included) and a per-(type, method) estimator quantizes the
observed peak onto a small ladder ({2^k} ∪ {3·2^(k-1)}, ≤33% overshoot,
O(log) rungs): caps GROW immediately when demand overflows (the parked
redelivery below is the correctness net while the estimate lags) and
SHRINK only after ``exchange_shrink_patience`` calm drains, so steady
traffic never churns compiles.  A site whose measured demand is zero
plans ``cap == 0`` and the exchange short-circuits to a classification
pass — no sort, no all_to_all, output width == input width — which is
also what a host-side shard-ALIGNED batch (``align_plan``) gets by
construction.  Before measurement lands, ``plan`` falls back to the old
worst-case formula (``pad_quantum`` / ``capacity_factor``), so the first
dispatch is always safe.

Exactness across the bounded buckets: a lane that does not fit its
bucket (``cap`` overflow under skew, or ANY cross lane while the
estimate says 0) is never silently lost — the send side computes a
per-lane ``dropped`` mask, the engine parks it like an optimistic
miss-check, and the dropped lanes re-deliver next tick through the
exact same path with their ORIGINAL ``inject_tick`` stamp (the latency
ledger therefore includes the redelivery wait, same contract as the
miss path).  Inside a fused window the dropped count folds into the
window's miss counter instead: a nonzero count fails ``verify()`` and
the auto-fuser rolls back and replays unfused — transparency never
costs exactness.

Ordering caveat (same as host-batch padding): the exchange permutes lane
order within a (type, method) batch.  Delivery SETS are preserved
exactly; handlers that resolve duplicate-row writes by lane order
(``scatter_rows`` with duplicate destinations) are order-sensitive and
should combine with ``seg_*`` instead — the contract vector_grain.py
already states for fan-in.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec

#: estimator site key: the (type_name, method) a batch executes as —
#: caps are per-site because a source leg and its emit leg can have
#: wildly different cross-shard demand (an aligned injection has none;
#: its fan-in delivery carries the workload's whole cross ratio)
Site = Tuple[str, str]


def pow2ceil(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


def classify_lanes(rows, mask, shard_capacity: int, L: int, n: int):
    """THE destination-classification algebra, shared by every path
    that asks "which lanes are home?" (the structured per-shard body,
    the cap-0 fast paths, and the disengaged probe): inputs are the
    PADDED global (or per-shard) lanes; returns ``(valid, dest, local,
    cross)``.  ``chunk`` is position // L — identical to the shard_map
    split by construction.  Kept free of reductions so the lean in-scan
    caller pays nothing it did not ask for; demand wants
    ``demand_per_dest`` on top."""
    m_pad = rows.shape[0]
    chunk = jnp.arange(m_pad, dtype=jnp.int32) // L
    valid = mask & (rows >= 0)
    dest = jnp.where(valid, rows // shard_capacity, n)
    local = valid & (dest == chunk)
    cross = valid & ~local
    return valid, dest, local, cross


def demand_per_dest(cross, dest, n: int):
    """Per-destination-shard lane demand (int32[n]) — the occupancy
    estimator's input; a global count when computed outside shard_map
    (an upper bound on the per-(src,dst) bucket need — growth-safe,
    refined by the next measured structured drain)."""
    return jax.ops.segment_sum(
        cross.astype(jnp.int32), jnp.where(cross, dest, n),
        num_segments=n + 1)[:n]


def ladder_ceil(n: int) -> int:
    """Smallest ladder rung ≥ n, rungs {2^k} ∪ {3·2^(k-1)}
    (1, 2, 3, 4, 6, 8, 12, 16, 24, ...): ≤33% overshoot where pow2
    pays up to 100%, still O(log) distinct values so the compile set
    under varying demand stays bounded.  0 maps to 0."""
    n = int(n)
    if n <= 0:
        return 0
    p = pow2ceil(n)
    three = 3 * (p // 4)
    return three if three >= n else p


class _SiteEstimator:
    """Measured bucket demand for one (type, method) exchange site.

    Tracks the per-destination-shard demand peak and grants a quantized
    cap: growth is immediate (an undersized grant only costs a parked
    redelivery, but staying undersized would cost one EVERY tick);
    shrink waits for ``patience`` consecutive calm observations below
    half the grant, so a noisy steady state never flaps compiles."""

    def __init__(self, n_shards: int) -> None:
        self.n_shards = n_shards
        self.peak = np.zeros(n_shards, np.int64)    # all-time, for gauges
        self._window = np.zeros(n_shards, np.int64)  # since last decision
        self._obs = 0
        self.grant: Optional[int] = None
        self.observations = 0
        # per-DESTINATION formulation: one rung per destination (send
        # segments) + one receive rung over the worst shard's total
        # inbound.  Decided on its own window so the scalar grant's
        # schedule (and the tests pinning it) is untouched.
        self.grants: Optional[np.ndarray] = None    # int64[n] send caps
        self.recv_grant: Optional[int] = None       # inbound rung
        self.peak_inbound = np.zeros(n_shards, np.int64)
        self.last_need = np.zeros(n_shards, np.int64)
        self._window_pd = np.zeros(n_shards, np.int64)
        self._window_in = np.zeros(n_shards, np.int64)
        self._obs_pd = 0

    @staticmethod
    def _rungs(vec: np.ndarray, headroom: float) -> np.ndarray:
        return np.array([ladder_ceil(int(np.ceil(float(v) * headroom)))
                         if v > 0 else 0 for v in vec], np.int64)

    def observe(self, need: np.ndarray, headroom: float,
                patience: int, inbound: Optional[np.ndarray] = None
                ) -> Tuple[bool, bool]:
        """Fold one drained need vector; returns (legacy grant changed,
        per-dest grants changed) — the caller bumps the exchange's plan
        version for the planes the configured mode can actually bake
        (a per-dest-only rung move must NOT re-trace a "never" run).
        ``need`` is the per-destination demand maxed over source shards
        (sizes the per-dest send caps); ``inbound`` is the same demand
        SUMMED over sources — each destination's total inbound, which
        sizes the receive rung.  Legacy [n]-tail drains pass only
        ``need``: it then stands in for the inbound too (exact for
        globally counted tails, an upper bound otherwise)."""
        need = np.asarray(need, np.int64)
        inb = need if inbound is None else np.asarray(inbound, np.int64)
        self.peak = np.maximum(self.peak, need)
        self.peak_inbound = np.maximum(self.peak_inbound, inb)
        self.last_need = need
        self._window = np.maximum(self._window, need)
        self._obs += 1
        self.observations += 1
        changed = False
        want = ladder_ceil(int(np.ceil(float(need.max()) * headroom))) \
            if need.max() > 0 else 0
        if self.grant is None or want > self.grant:
            self.grant = want
            self._window = np.zeros(self.n_shards, np.int64)
            self._obs = 0
            changed = True
        elif self._obs >= max(1, int(patience)):
            calm = ladder_ceil(int(np.ceil(float(self._window.max())
                                           * headroom)))
            self._window = np.zeros(self.n_shards, np.int64)
            self._obs = 0
            if calm < self.grant:
                self.grant = calm
                changed = True
        # per-destination grants: any rung grows immediately; shrink
        # waits for a full calm window (same discipline, vectorized)
        self._window_pd = np.maximum(self._window_pd, need)
        self._window_in = np.maximum(self._window_in, inb)
        self._obs_pd += 1
        changed_pd = False
        want_pd = self._rungs(need, headroom)
        want_r = ladder_ceil(int(np.ceil(float(inb.max()) * headroom))) \
            if inb.max() > 0 else 0
        if self.grants is None or (want_pd > self.grants).any() \
                or want_r > self.recv_grant:
            self.grants = want_pd if self.grants is None \
                else np.maximum(self.grants, want_pd)
            self.recv_grant = want_r if self.recv_grant is None \
                else max(self.recv_grant, want_r)
            self._window_pd = np.zeros(self.n_shards, np.int64)
            self._window_in = np.zeros(self.n_shards, np.int64)
            self._obs_pd = 0
            changed_pd = True
        elif self._obs_pd >= max(1, int(patience)):
            calm_pd = self._rungs(self._window_pd, headroom)
            calm_r = ladder_ceil(int(np.ceil(
                float(self._window_in.max()) * headroom))) \
                if self._window_in.max() > 0 else 0
            self._window_pd = np.zeros(self.n_shards, np.int64)
            self._window_in = np.zeros(self.n_shards, np.int64)
            self._obs_pd = 0
            if (calm_pd < self.grants).any() or calm_r < self.recv_grant:
                self.grants = np.minimum(self.grants, calm_pd)
                self.recv_grant = min(self.recv_grant, calm_r)
                changed_pd = True
        return changed, changed_pd

    def snapshot(self) -> Dict[str, Any]:
        return {"grant": self.grant,
                "grants": None if self.grants is None
                else self.grants.tolist(),
                "recv_grant": self.recv_grant,
                "peak_need": self.peak.tolist(),
                "peak_inbound": self.peak_inbound.tolist(),
                "observations": self.observations}


class ShardExchange:
    """Per-engine exchange plane: builds and caches the jitted exchange
    programs (one per (batch size, cap, shard layout) — batch sizes are
    stable in steady state and cap moves on the quantized ladder only)
    and holds the device-side stat accumulators the engine drains at
    quiescence."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self.mesh = engine.mesh
        self.axis = engine.config.mesh_axis
        self.n_shards = engine.n_shards
        self._platform = str(
            np.asarray(self.mesh.devices).flat[0].platform)
        # disengaged-probe pacing, PER SITE: one measure-only
        # classification per exchange_probe_interval occurrences of
        # each (type, method) — a single global clock would alias with
        # the deterministic per-tick group rotation and could leave a
        # site permanently unsampled
        self._probe_clocks: Dict[Site, int] = {}
        # cumulative stats (folded from device at drain points)
        self.exchanges_run = 0
        self.cross_shard_msgs = 0
        self.delivered_msgs = 0
        self.dropped_msgs = 0
        self.redeliveries = 0
        self.exchange_seconds = 0.0
        # bucket utilization: logical input lanes vs the padded output
        # lanes every downstream kernel pays for — THE number the
        # occupancy sizing moves (worst-case caps ran this at ~0.12)
        self.live_lanes = 0
        self.padded_lanes = 0
        # overlap: wall time pre-dispatched exchanges spent running
        # under other work before their consuming group needed them
        self.overlap_seconds = 0.0
        self.overlap_hits = 0
        # pre-dispatched exchanges that went stale before consumption
        # (their counters were never folded — the inline recompute's
        # were, so dispatch telemetry counts each logical batch once)
        self.pre_discards = 0
        # occupancy-sized caps: per-site estimators + a version the
        # fused plan signature watches (any grant move re-traces, cause
        # bucket_growth — never a silent per-tick recompile)
        self.estimators: Dict[Site, _SiteEstimator] = {}
        self.cap_version = 0
        self._jit_cache: Dict[Tuple[int, int, int, int], Any] = {}
        #: global widths THIS plane produced (exchange outputs, aligned
        #: layouts): only these keep their exact per-shard split in
        #: plan() — an organic batch that merely happens to be
        #: n-divisible still quantizes onto the ladder, so the compile
        #: set stays O(log) under drifting sizes.  Bounded: derived
        #: from ladder L x ladder cap combinations.
        self._transport_widths: set = set()
        #: shapes already compiled with SOME cap — a new cap for a seen
        #: (L, shard_capacity, leaves) is a re-quantization, recorded
        self._seen_shapes: Dict[Tuple[int, int, int], set] = {}
        # trace capture: fused builds drain this to account in-window
        # exchange shapes for the utilization counters
        self.trace_log: List[Tuple[Site, int, int]] = []

    def adopt_stats(self, prev: "Optional[ShardExchange]") -> None:
        """Carry cumulative counters AND the demand estimators across a
        mesh reshard when the shard count is unchanged (the engine
        rebuilds the exchange; the perf trajectory must not reset).  A
        reshard to a DIFFERENT shard count invalidates the per-dest
        vectors — estimators restart from the safe fallback plan."""
        if prev is None:
            return
        self.exchanges_run = prev.exchanges_run
        self.cross_shard_msgs = prev.cross_shard_msgs
        self.delivered_msgs = prev.delivered_msgs
        self.dropped_msgs = prev.dropped_msgs
        self.redeliveries = prev.redeliveries
        self.exchange_seconds = prev.exchange_seconds
        self.live_lanes = prev.live_lanes
        self.padded_lanes = prev.padded_lanes
        self.overlap_seconds = prev.overlap_seconds
        self.overlap_hits = prev.overlap_hits
        self.pre_discards = prev.pre_discards
        self.cap_version = prev.cap_version + 1
        if prev.n_shards == self.n_shards:
            self.estimators = prev.estimators
            self._transport_widths = set(prev._transport_widths)

    # -- engagement (structured vs identity) --------------------------------

    def engaged(self) -> bool:
        """Whether the STRUCTURED formulation (bucket + all_to_all)
        runs at all.  "auto" engages it only over a real accelerator
        interconnect: on a host-virtual mesh every collective is a
        synchronized memcpy inside one process, so the structured
        region costs strictly more than the implicit-collective
        scatter it replaces (measured at every width — the multichip
        bench's exchange_attribution).  Disengaged, the exchange is
        IDENTITY: delivery rides the same implicit collectives as
        exchange-off (unconditionally exact), and the sampled probe
        keeps the demand estimators + cross-traffic counters honest."""
        mode = getattr(self.engine.config, "exchange_structured", "auto")
        if mode == "always":
            return True
        if mode == "never":
            return False
        return self._platform != "cpu"

    def note_transport_width(self, w: int) -> None:
        """Register a global width this plane produced (exchange output
        or aligned layout) — plan() keeps such widths' exact per-shard
        split instead of re-quantizing them."""
        self._transport_widths.add(int(w))

    def probe_scale(self, site: Site, interval: int) -> int:
        """Advance the site's probe clock; 0 = this occurrence is not
        probed, otherwise the SAMPLING SCALE for the measure-only
        classification — the number of occurrences (inclusive) the
        probe stands in for, so every occurrence is covered by exactly
        one probe's scale window and the folded counters stay exact-in-
        expectation even for short runs.  A site's FIRST occurrence
        always probes (scale 1): telemetry and the demand estimate
        exist from the start instead of after interval-1 silent
        groups."""
        pending = self._probe_clocks.get(site)
        if pending is None:
            self._probe_clocks[site] = 0
            return 1
        pending += 1
        if pending >= max(1, interval):
            self._probe_clocks[site] = 0
            return pending
        self._probe_clocks[site] = pending
        return 0

    def _probe(self, arena, rows, mask, site: Site) -> Any:
        """Measure-only classification for a disengaged exchange: one
        async jit returning the int32[3+2n] stats vector (cross, 0,
        valid, per-dest demand twice — the global count is both an
        upper bound on the per-src need and the exact total inbound) —
        the batch itself is untouched and delivers through the normal
        path, so the parked check must never redeliver
        (measure_only)."""
        n = self.n_shards
        m = int(rows.shape[0])
        shard_capacity = int(arena.shard_capacity)
        L = m // n if m in self._transport_widths and m % n == 0 \
            else ladder_ceil(-(-m // n))
        key = ("probe", L, shard_capacity)
        fn = self._jit_cache.get(key)
        if fn is None:
            m_pad = n * L

            def call(rows, mask):
                def pad(x, fill):
                    if x.shape[0] == m_pad:
                        return x
                    return jnp.pad(x, [(0, m_pad - x.shape[0])],
                                   constant_values=fill)
                rows_p = pad(jnp.asarray(rows, jnp.int32), -1)
                mask_p = pad(jnp.asarray(mask, bool), False)
                valid, dest, _local, cross = classify_lanes(
                    rows_p, mask_p, shard_capacity, L, n)
                # probe semantics: cross lanes DELIVER (through the
                # implicit-collective path) — counted as cross traffic,
                # never as drops
                g = demand_per_dest(cross, dest, n)
                return jnp.concatenate([jnp.stack([
                    jnp.sum(cross.astype(jnp.int32)),
                    jnp.int32(0),
                    jnp.sum(valid.astype(jnp.int32)),
                ]), g, g])
            fn = jax.jit(call)
            self._jit_cache[key] = fn
        return fn(jnp.asarray(rows), mask)

    # -- occupancy-sized planning -------------------------------------------

    def observe_need(self, site: Site, need: np.ndarray,
                     inbound: Optional[np.ndarray] = None) -> None:
        """Fold one drained per-destination demand vector for a site.
        A [2n] vector (max-half ‖ sum-half) may arrive as one array in
        ``need``; it is split here so every drain path can stay
        width-agnostic."""
        cfg = self.engine.config
        need = np.asarray(need)
        n = self.n_shards
        if inbound is None and need.shape[0] == 2 * n:
            need, inbound = need[:n], need[n:]
        est = self.estimators.get(site)
        if est is None:
            est = self.estimators[site] = _SiteEstimator(self.n_shards)
        changed, changed_pd = est.observe(
            need, cfg.exchange_headroom,
            cfg.exchange_shrink_patience, inbound=inbound)
        # a per-dest-only rung move is invisible to a "never" run's
        # baked plans — bumping the version there would re-trace every
        # fused window for a vector no plan consumes (the estimator
        # keeps tracking either way: gauges + a later mode flip)
        if changed or (changed_pd and getattr(
                cfg, "exchange_per_dest", "auto") != "never"):
            self.cap_version += 1
            rec = self.engine._span_recorder()
            if rec is not None:
                # a grant move is the exchange's re-trace trigger
                # (fused plans re-bake on cap_version): one timeline
                # episode per rung move, annotated with the new caps
                rec.plane_span(
                    "exchange", f"grant growth {site}",
                    site=str(site), cap_version=self.cap_version,
                    grant=int(est.grant or 0),
                    recv_grant=int(est.recv_grant or 0),
                    peak_need=int(np.asarray(need).max(initial=0)))

    def grant_for(self, site: Optional[Site]) -> Optional[int]:
        if site is None or not self.engine.config.exchange_occupancy_sizing:
            return None
        est = self.estimators.get(site)
        return None if est is None else est.grant

    def grants_for(self, site: Optional[Site]
                   ) -> Optional[Tuple[np.ndarray, int]]:
        """The per-destination grant vector + receive rung for a
        measured site, or None (unmeasured / sizing off)."""
        if site is None or not self.engine.config.exchange_occupancy_sizing:
            return None
        est = self.estimators.get(site)
        if est is None or est.grants is None:
            return None
        return est.grants, int(est.recv_grant or 0)

    def plan(self, m: int, site: Optional[Site] = None
             ) -> Tuple[int, int]:
        """(per-shard lanes L, per-(src,dst) bucket cap) for an m-lane
        batch.  Both ladder-quantized so the compile set under varying
        batch sizes/demand is O(log n); cap is clamped to L (a bucket
        can never need more than one shard's whole slice).  A site with
        a measured grant uses it; an unmeasured site falls back to the
        worst-case formula (``pad_quantum`` floor × ``capacity_factor``
        skew allowance) so the first dispatch never drops avoidably.
        (Host-ALIGNED batches never reach plan(): the fused build skips
        the exchange for them entirely — fused.py `_apply_group`.)"""
        n = self.n_shards
        cfg = self.engine.config
        # a width THIS plane produced keeps its exact per-shard split:
        # it is a transport shape (the n·W output of an upstream
        # exchange) or an aligned layout (n·La) — re-quantizing would
        # shift every lane out of its home chunk and re-cross traffic
        # that is already placed.  Such widths are static per window /
        # key set AND registered (`_transport_widths`), so they carry
        # no compile-churn pressure; every other size — including
        # organic batches that merely happen to be n-divisible —
        # quantizes onto the ladder, keeping the compile set O(log)
        # under drifting population.
        L = m // n if m in self._transport_widths and m % n == 0 \
            else ladder_ceil(-(-m // n))
        grant = self.grant_for(site)
        if grant is not None:
            return L, min(L, grant)
        cap = min(L, pow2ceil(max(
            int(cfg.exchange_pad_quantum),
            int(L / n * cfg.exchange_capacity_factor))))
        return L, cap

    def plan_ex(self, m: int, site: Optional[Site] = None):
        """The mode-selecting plan: ``("legacy", L, cap, None)`` or
        ``("perdest", L, cap, (caps_tuple, R))``.  The per-destination
        formulation replaces the ``n·cap`` send/receive layout with
        per-dest send segments (width ``sum(caps)``) and one receive
        rung ``R`` sized by the worst shard's total inbound —
        ``exchange_per_dest="auto"`` engages it only when that is
        strictly narrower than the legacy layout for the measured site,
        so symmetric demand keeps the exact legacy plan."""
        L, cap = self.plan(m, site=site)
        mode = getattr(self.engine.config, "exchange_per_dest", "auto")
        if mode == "never":
            return ("legacy", L, cap, None)
        pd = self.grants_for(site)
        if pd is None:
            return ("legacy", L, cap, None)
        grants, recv = pd
        caps = np.minimum(grants, L).astype(np.int64)
        if caps.max() == 0 or cap == 0:
            # no measured cross demand: the legacy cap-0 fast path is
            # already the narrowest possible program
            return ("legacy", L, cap, None)
        n = self.n_shards
        R = max(1, ladder_ceil(min(int(recv), n * L)))
        S = int(caps.sum())
        if mode != "always" and S + R >= 2 * n * cap:
            return ("legacy", L, cap, None)
        return ("perdest", L, cap,
                (tuple(int(c) for c in caps), R))

    def plan_signature(self, sites) -> Tuple:
        """What a fused window's baked exchange plans depend on: the
        occupancy toggle, the fallback knobs, and the current grant per
        site the window exchanges.  prepare() re-traces when this moves
        (cause ``bucket_growth`` — re-quantization is attributed, never
        a silent recompile)."""
        cfg = self.engine.config
        mode = getattr(cfg, "exchange_per_dest", "auto")

        def pd_sig(s):
            # a "never" run bakes only legacy plans: the per-dest
            # vector must not churn its signature
            if mode == "never":
                return None
            pd = self.grants_for(s)
            return None if pd is None else (tuple(pd[0].tolist()), pd[1])
        return (self.engaged(),
                bool(cfg.exchange_occupancy_sizing),
                mode,
                int(cfg.exchange_pad_quantum),
                float(cfg.exchange_capacity_factor),
                tuple((s, self.grant_for(s), pd_sig(s))
                      for s in sorted(sites)))

    # -- host-side shard alignment ------------------------------------------

    def align_plan(self, rows_np: np.ndarray, shard_capacity: int,
                   quantum: int = 16) -> Optional[Dict[str, Any]]:
        """Pack a KNOWN row set home-shard-local on the host: lanes are
        permuted so shard s's slice of the padded batch holds only rows
        s owns — the fused build then SKIPS the exchange for this
        source entirely (zero sort, zero all_to_all, zero
        classification; staleness re-traces through the generation/
        epoch discipline before the packing can rot).  Returns None
        when any row is invalid (callers keep the dynamic path).

        ``take`` is the gather map from aligned lane → original lane
        (-1 = padding); per-shard width La is quantized to ``quantum``
        multiples (alignment is static per key set, so there is no
        compile-churn pressure pushing it to pow2 — a tighter pad wins
        downstream width)."""
        rows_np = np.asarray(rows_np)
        if rows_np.ndim != 1 or rows_np.size == 0 or (rows_np < 0).any():
            return None
        n = self.n_shards
        dest = rows_np // int(shard_capacity)
        if (dest >= n).any():
            return None
        counts = np.bincount(dest, minlength=n)
        La = max(quantum, -(-int(counts.max()) // quantum) * quantum)
        take = np.full(n * La, -1, np.int64)
        order = np.argsort(dest, kind="stable")
        off = 0
        for s in range(n):
            lanes = order[off:off + counts[s]]
            take[s * La:s * La + len(lanes)] = lanes
            off += counts[s]
        rows_aligned = np.where(take >= 0, rows_np[np.clip(take, 0, None)],
                                -1).astype(np.int32)
        return {"L": La, "m": int(rows_np.size),
                "take": take.astype(np.int32),
                "rows": rows_aligned}

    # -- the per-shard program (pure jax; traced into jit or a fused scan) ---

    def _traced(self, rows, leaves: List[Any], mask, shard_capacity: int,
                L: int, cap: int):
        """The exchange body at padded size ``n * L``: returns
        ``(recv_rows, recv_leaves, recv_mask, dropped, stats)`` where
        ``dropped`` is a bool[n*L] mask in INPUT lane order (slice back
        to m) and ``stats`` is an int32[3 + n]: (cross_shard, dropped,
        delivered) summed over shards followed by the per-destination
        bucket demand maxed over shards — the estimator's input.

        ``cap == 0`` is the packed fast path: classification only (one
        compare + masks), cross lanes drop into redelivery, and the
        output width equals the input width — an aligned or all-local
        batch pays nothing for having the exchange in its program."""
        from jax.experimental.shard_map import shard_map

        n = self.n_shards
        axis = self.axis
        m_pad = n * L
        # output lanes per shard: EXACT — local slice + the received
        # buckets, no rung padding.  A downstream exchange (the emit leg
        # of this batch) sees a global width divisible by n and keeps
        # the per-shard split as-is (plan()'s n-divisible rule), so the
        # re-slice stays aligned with THIS exchange's shard boundaries
        # by construction — the accounting test pins it.
        W = L + n * cap

        def pad_to(x, fill):
            if x.shape[0] == m_pad:
                return x
            widths = [(0, m_pad - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, widths, constant_values=fill)

        rows = pad_to(jnp.asarray(rows, jnp.int32), -1)
        mask = pad_to(jnp.asarray(mask, bool), False)
        leaves = [pad_to(jnp.asarray(x), 0) for x in leaves]

        if cap == 0:
            # packed fast path, WITHOUT shard_map: a zero-cap site has
            # no buckets and no all_to_all, so the classification is
            # plain elementwise algebra GSPMD partitions natively —
            # on op-count-bound virtual meshes the shard_map wrapper
            # itself is the dominant cost of an empty exchange.  Local
            # lanes deliver in place; any cross lane (the estimate says
            # there are none) drops into redelivery; the demand vector
            # is the GLOBAL per-destination count — an upper bound on
            # the per-(src,dst) bucket demand, so a traffic shift grows
            # the cap at least far enough (the next measured drain
            # refines it downward).
            _valid, dest, local, cross = classify_lanes(
                rows, mask, shard_capacity, L, n)
            # cap-0 semantics: cross lanes DROP into redelivery
            # (stats[1]) — the estimate said there were none.  The
            # demand tail is GLOBAL, so it serves as both halves of the
            # [2n] tail: an upper bound on the per-src need and the
            # exact total inbound.
            g = demand_per_dest(cross, dest, n)
            stats = jnp.concatenate([jnp.stack([
                jnp.int32(0),
                jnp.sum(cross.astype(jnp.int32)),
                jnp.sum(local.astype(jnp.int32)),
            ]), g, g])
            recv_rows = jnp.where(local, rows, -1)
            return recv_rows, leaves, local, cross, stats

        def per_shard(rows_l, mask_l, *leaves_l):
            my = jax.lax.axis_index(axis)
            valid = mask_l & (rows_l >= 0)
            # destination shard straight from the row-block layout — the
            # same function as the directory's shard_of_keys (arena rows
            # are allocated in the key's home block; property-tested)
            dest = jnp.where(valid, rows_l // shard_capacity, n)
            # lanes already home stay IN PLACE (first L output lanes):
            # the all_to_all carries only cross-shard traffic, so its
            # volume — and the bucket pressure `cap` must absorb —
            # scales with the cross-shard ratio, not the batch size
            local = valid & (dest == my)
            cross = valid & ~local
            # per-destination bucket demand (overflow INCLUDED): the
            # occupancy signal the estimator sizes future caps from —
            # here per SOURCE shard (reduced by max outside shard_map)
            need = demand_per_dest(cross, dest, n)
            sdest_in = jnp.where(cross, dest, n)
            order = jnp.argsort(sdest_in)  # ties keep relative order
            sdest = sdest_in[order]
            start = jnp.searchsorted(sdest,
                                     jnp.arange(n, dtype=sdest.dtype))
            pos = jnp.arange(L) - start[jnp.clip(sdest, 0, n - 1)]
            fits = (sdest < n) & (pos < cap)
            # out-of-range slot + mode="drop": invalid/overflow lanes
            # scatter nowhere
            slot = jnp.where(fits, sdest * cap + pos, n * cap)
            send_rows = jnp.full(n * cap, -1, jnp.int32) \
                .at[slot].set(rows_l[order], mode="drop")

            def bucket(leaf):
                s = leaf[order]
                out = jnp.zeros((n * cap,) + s.shape[1:], s.dtype)
                return out.at[slot].set(s, mode="drop")

            send_leaves = [bucket(x) for x in leaves_l]

            def a2a(x):
                r = jax.lax.all_to_all(
                    x.reshape((n, cap) + x.shape[1:]), axis,
                    split_axis=0, concat_axis=0)
                return r.reshape((n * cap,) + x.shape[2:])

            recv_rows = jnp.concatenate(
                [jnp.where(local, rows_l, -1), a2a(send_rows)])
            recv_leaves = [
                jnp.concatenate([x, a2a(s)])
                for x, s in zip(leaves_l, send_leaves)]
            recv_mask = recv_rows >= 0
            # dropped mask back in input lane order
            dropped_sorted = (sdest < n) & (pos >= cap)
            dropped_l = jnp.zeros(L, bool).at[order].set(dropped_sorted)
            n_dropped = jnp.sum(dropped_sorted.astype(jnp.int32))
            stats = jnp.concatenate([jnp.stack([
                jnp.sum(cross.astype(jnp.int32)),
                n_dropped,
                jnp.sum(valid.astype(jnp.int32)) - n_dropped,
            ]), need])[None, :]  # [1, 3 + n]: per-shard, reduced outside
            return (recv_rows, recv_mask, dropped_l, stats, *recv_leaves)

        P = PartitionSpec
        sharded = P(axis)
        out_specs = (sharded, sharded, sharded, sharded) \
            + (sharded,) * len(leaves)
        fn = shard_map(per_shard, mesh=self.mesh,
                       in_specs=(sharded, sharded) + (sharded,) * len(leaves),
                       out_specs=out_specs, check_rep=False)
        recv_rows, recv_mask, dropped, stats, *recv_leaves = fn(
            rows, mask, *leaves)
        # counts SUM across shards; the per-dest demand reduces BOTH
        # ways into the [2n] tail — MAX over sources (the per-(src,dst)
        # bucket cap must cover the worst src) and SUM over sources
        # (each destination's total inbound, sizing the per-dest
        # formulation's receive rung)
        stats = jnp.concatenate([jnp.sum(stats[:, :3], axis=0),
                                 jnp.max(stats[:, 3:], axis=0),
                                 jnp.sum(stats[:, 3:], axis=0)])
        return recv_rows, recv_leaves, recv_mask, dropped, stats

    def _traced_perdest(self, rows, leaves: List[Any], mask,
                        shard_capacity: int, L: int,
                        caps: Tuple[int, ...], R: int):
        """The per-DESTINATION exchange body: same contract as
        ``_traced`` (``(recv_rows, recv_leaves, recv_mask, dropped,
        stats[3+2n])``), different layout.  Each shard packs its cross
        lanes into per-destination send segments at static offsets
        (width ``S = sum(caps)`` instead of ``n * cap`` — one hot
        destination no longer sizes every lane's buckets), the segments
        move with one ``all_gather`` alongside an ``[n, n]`` fill
        matrix, and each shard compacts its inbound lanes to the single
        receive rung ``R`` with a searchsorted over the fill prefix
        sums + one gather per leaf (no sort).  Receive overflow (total
        inbound past ``R``) is computed on the SENDER from the same
        fill prefix ranks the receiver takes lanes in, so an overflow
        lane parks into the standard redelivery net instead of being
        silently truncated."""
        from jax.experimental.shard_map import shard_map

        n = self.n_shards
        axis = self.axis
        m_pad = n * L
        caps_arr = np.asarray(caps, np.int32)
        offs_arr = np.concatenate([[0], np.cumsum(caps_arr)[:-1]]) \
            .astype(np.int32)
        S = int(caps_arr.sum())

        def pad_to(x, fill):
            if x.shape[0] == m_pad:
                return x
            widths = [(0, m_pad - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, widths, constant_values=fill)

        rows = pad_to(jnp.asarray(rows, jnp.int32), -1)
        mask = pad_to(jnp.asarray(mask, bool), False)
        leaves = [pad_to(jnp.asarray(x), 0) for x in leaves]

        def per_shard(rows_l, mask_l, *leaves_l):
            my = jax.lax.axis_index(axis)
            valid = mask_l & (rows_l >= 0)
            dest = jnp.where(valid, rows_l // shard_capacity, n)
            local = valid & (dest == my)
            cross = valid & ~local
            need = demand_per_dest(cross, dest, n)
            sdest_in = jnp.where(cross, dest, n)
            order = jnp.argsort(sdest_in)  # ties keep relative order
            sdest = sdest_in[order]
            start = jnp.searchsorted(sdest,
                                     jnp.arange(n, dtype=sdest.dtype))
            pos = jnp.arange(L) - start[jnp.clip(sdest, 0, n - 1)]
            caps_v = jnp.asarray(caps_arr)
            offs_v = jnp.asarray(offs_arr)
            sdest_c = jnp.clip(sdest, 0, n - 1)
            fits = (sdest < n) & (pos < caps_v[sdest_c])
            slot = jnp.where(fits, offs_v[sdest_c] + pos, S)
            send_rows = jnp.full(S, -1, jnp.int32) \
                .at[slot].set(rows_l[order], mode="drop")

            def segment(leaf):
                s = leaf[order]
                out = jnp.zeros((S,) + s.shape[1:], s.dtype)
                return out.at[slot].set(s, mode="drop")

            send_leaves = [segment(x) for x in leaves_l]
            # fill matrix: lanes each source actually packed per dest
            fills_row = jnp.minimum(need, caps_v)
            fills = jax.lax.all_gather(fills_row, axis)      # [n, n]
            g_rows = jax.lax.all_gather(send_rows, axis)     # [n, S]
            g_leaves = [jax.lax.all_gather(s, axis)
                        for s in send_leaves]
            # receive compaction to R lanes, src-major order: output
            # position j maps through the inbound prefix sums to
            # (source shard, offset within its segment for me)
            mine = fills[:, my]
            cum = jnp.cumsum(mine)
            total_in = cum[n - 1]
            j = jnp.arange(R)
            src = jnp.searchsorted(cum, j, side="right")
            src_c = jnp.clip(src, 0, n - 1)
            within = j - (cum[src_c] - mine[src_c])
            live = j < total_in
            lane = jnp.clip(offs_v[my] + within, 0, max(S - 1, 0))
            recv_rows_x = jnp.where(live, g_rows[src_c, lane], -1)

            def compact(g):
                out = g[src_c, lane]
                shape = (R,) + (1,) * (out.ndim - 1)
                return jnp.where(live.reshape(shape), out,
                                 jnp.zeros((), out.dtype))

            recv_leaves_x = [compact(g) for g in g_leaves]
            # sender-side receive-overflow: the global rank of a sent
            # lane in the receiver's src-major take order
            before = jnp.cumsum(fills, axis=0) - fills       # excl. src
            rank = before[my][sdest_c] + pos
            recv_drop = fits & (rank >= R)
            dropped_sorted = ((sdest < n) & ~fits) | recv_drop
            dropped_l = jnp.zeros(L, bool).at[order].set(dropped_sorted)
            n_dropped = jnp.sum(dropped_sorted.astype(jnp.int32))
            recv_rows = jnp.concatenate(
                [jnp.where(local, rows_l, -1), recv_rows_x])
            recv_leaves = [
                jnp.concatenate([x, rx])
                for x, rx in zip(leaves_l, recv_leaves_x)]
            recv_mask = recv_rows >= 0
            stats = jnp.concatenate([jnp.stack([
                jnp.sum(cross.astype(jnp.int32)),
                n_dropped,
                jnp.sum(valid.astype(jnp.int32)) - n_dropped,
            ]), need])[None, :]  # [1, 3 + n]: reduced outside
            return (recv_rows, recv_mask, dropped_l, stats, *recv_leaves)

        P = PartitionSpec
        sharded = P(axis)
        out_specs = (sharded, sharded, sharded, sharded) \
            + (sharded,) * len(leaves)
        fn = shard_map(per_shard, mesh=self.mesh,
                       in_specs=(sharded, sharded) + (sharded,) * len(leaves),
                       out_specs=out_specs, check_rep=False)
        recv_rows, recv_mask, dropped, stats, *recv_leaves = fn(
            rows, mask, *leaves)
        stats = jnp.concatenate([jnp.sum(stats[:, :3], axis=0),
                                 jnp.max(stats[:, 3:], axis=0),
                                 jnp.sum(stats[:, 3:], axis=0)])
        return recv_rows, recv_leaves, recv_mask, dropped, stats

    # -- fused-path entry (called under an active trace) ---------------------

    def apply_traced(self, site: Site, shard_capacity: int, rows, args: Any,
                     mask):
        """Exchange inside a fused window trace: returns
        ``(rows2, args2, mask2, dropped_count, need)`` — the dropped
        count folds into the window's device-side miss counter so a
        capacity overflow fails ``verify()`` (rollback + unfused replay)
        instead of losing lanes, and ``need`` (int32[2n]: per-dest
        demand maxed over sources ‖ summed over sources) rides the
        window's xneed accumulator so steady fused traffic keeps the
        site's occupancy estimate honest in BOTH directions.  A group
        whose args are not lane-aligned (slab-style handlers consuming a
        whole buffer per tick, e.g. the twitter dispatcher) passes
        through untouched — permuting rows away from such args would
        break the handler's row↔buffer correspondence."""
        m = rows.shape[0]
        n = self.n_shards
        if not exchangeable_args(args, m):
            return rows, args, mask, jnp.int32(0), \
                jnp.zeros(2 * n, jnp.int32)
        mode, L, cap, pd = self.plan_ex(m, site=site)
        if cap == 0:
            # LEAN in-scan fast path: classification + the miss count,
            # nothing else — the per-tick demand reductions of the full
            # stats vector are cross-device collectives inside the
            # scan, measured as the entire residual cost of an empty
            # exchange on op-count-bound meshes.  A traffic shift here
            # fails verify() (dropped ≠ 0), the rollback's unfused
            # replay re-delivers, and ITS drained stats grow the cap —
            # the estimator's slow feedback half; the fused fast path
            # never pays for a possibility that isn't happening.
            m_pad = n * L

            def pad(x, fill):
                if x.shape[0] == m_pad:
                    return x
                widths = [(0, m_pad - x.shape[0])] + \
                    [(0, 0)] * (x.ndim - 1)
                return jnp.pad(x, widths, constant_values=fill)

            rows_p = pad(jnp.asarray(rows, jnp.int32), -1)
            mask_p = pad(jnp.asarray(mask, bool), False)
            args_p = jax.tree_util.tree_map(
                lambda a: a if jnp.ndim(a) == 0
                else pad(jnp.asarray(a), 0), args)
            _valid, _dest, local, cross = classify_lanes(
                rows_p, mask_p, shard_capacity, L, n)
            dropped = jnp.sum(cross.astype(jnp.int32))
            self.trace_log.append((site, int(m), m_pad))
            self.note_transport_width(m_pad)
            return (jnp.where(local, rows_p, -1), args_p, local,
                    dropped, jnp.zeros(2 * n, jnp.int32))
        leaves, treedef, scalar_ix = _split_leaves(args, m)
        if mode == "perdest":
            caps, R = pd
            rows2, leaves2, mask2, _dropped, stats = self._traced_perdest(
                rows, leaves, mask, shard_capacity, L, caps, R)
        else:
            rows2, leaves2, mask2, _dropped, stats = self._traced(
                rows, leaves, mask, shard_capacity, L, cap)
        args2 = _join_leaves(treedef, scalar_ix, leaves2)
        self.trace_log.append((site, int(m), int(rows2.shape[0])))
        self.note_transport_width(int(rows2.shape[0]))
        return rows2, args2, mask2, stats[1], stats[3:]

    # -- unfused-path entry (jitted dispatch; stats parked on device) --------

    def dispatch(self, arena, rows, args: Any, mask,
                 site: Optional[Site] = None,
                 defer_stats: bool = False):
        """One async exchange dispatch for an unfused batch.  Returns
        ``(rows2, args2, mask2, dropped_mask, stats)`` with the dropped
        mask and the int32[3+2n] stats still ON DEVICE — the engine parks
        them (like a miss-check) and reads everything in one batched
        transfer at the next quiescence point.  ``defer_stats`` (the
        round-start pre-dispatch) appends a run-cost tuple to the
        return and folds NO counters — the consumer calls
        ``fold_dispatch`` on use or drops the result (stale), so a
        logical batch counts exactly once either way."""
        t0 = time.perf_counter()
        m = int(rows.shape[0])
        shard_capacity = int(arena.shard_capacity)
        mode, L, cap, pd = self.plan_ex(m, site=site)
        leaves, treedef, scalar_ix = _split_leaves(args, m)
        if mode == "perdest":
            caps, R = pd
            key = (L, ("pd", caps, R), shard_capacity, len(leaves))
            cap_label = f"pd{sum(caps)}r{R}"
        else:
            key = (L, cap, shard_capacity, len(leaves))
            cap_label = str(cap)
        fn = self._jit_cache.get(key)
        if fn is None:
            if mode == "perdest":
                def call(rows, mask, *leaves):
                    return self._traced_perdest(
                        rows, list(leaves), mask, shard_capacity,
                        L, caps, R)
            else:
                def call(rows, mask, *leaves):
                    return self._traced(rows, list(leaves), mask,
                                        shard_capacity, L, cap)
            fn = jax.jit(call)
            self._jit_cache[key] = fn
            shape = (L, shard_capacity, len(leaves))
            seen = self._seen_shapes.setdefault(shape, set())
            if seen:
                # same batch shape, new cap: the occupancy estimate
                # re-quantized the bucket — attribute the recompile
                # (tensor/profiler.py churn taxonomy) so a flapping
                # estimate can never hide as organic shape churn
                from orleans_tpu.tensor.profiler import CAUSE_BUCKET_GROWTH
                self.engine.compile_tracker.record(
                    CAUSE_BUCKET_GROWTH,
                    key=f"exchange[{L}]cap{sorted(seen)[-1]}"
                        f"->{cap_label}",
                    tick=self.engine.tick_number)
            seen.add(cap_label)
        rows2, leaves2, mask2, dropped, stats = fn(
            jnp.asarray(rows), mask, *leaves)
        args2 = _join_leaves(treedef, scalar_ix, leaves2)
        self.note_transport_width(int(rows2.shape[0]))
        if defer_stats:
            # pre-dispatch path: the consumer folds the run counters
            # (or discards them with the result — a stale pre-exchange
            # must not double-count the inline recompute's batch)
            return rows2, args2, mask2, dropped[:m], stats, \
                (m, int(rows2.shape[0]), time.perf_counter() - t0)
        self.exchanges_run += 1
        self.live_lanes += m
        self.padded_lanes += int(rows2.shape[0])
        self.exchange_seconds += time.perf_counter() - t0
        return rows2, args2, mask2, dropped[:m], stats

    def fold_dispatch(self, run_cost: Tuple[int, int, float]) -> None:
        """Fold a deferred pre-dispatch's run counters at consumption
        (see ``dispatch(defer_stats=True)``)."""
        m, padded, dt = run_cost
        self.exchanges_run += 1
        self.live_lanes += m
        self.padded_lanes += padded
        self.exchange_seconds += dt

    def fold_stats(self, stats_host: np.ndarray,
                   site: Optional[Site] = None,
                   scale: int = 1) -> None:
        """Accumulate one drained [3 + n] or [3 + 2n] stats vector; the
        demand tail feeds the site's occupancy estimator (a [2n] tail
        splits into max-half ‖ sum-half inside ``observe_need``).
        ``scale > 1`` marks a
        SAMPLED disengaged-mode probe (1-in-scale groups measured):
        count stats multiply up to stay an unbiased estimate comparable
        with engaged-mode exact totals; the demand tail is a peak, not
        a sum, and never scales."""
        self.cross_shard_msgs += int(stats_host[0]) * scale
        self.dropped_msgs += int(stats_host[1]) * scale
        self.delivered_msgs += int(stats_host[2]) * scale
        if site is not None and len(stats_host) > 3:
            self.observe_need(site, np.asarray(stats_host[3:]))

    def fold_fused_shapes(self, shapes, n_ticks: int) -> None:
        """Account a fused window run's in-window exchanges (shapes were
        captured at trace time): utilization + run counters, no device
        traffic."""
        for _site, m_in, m_out in shapes:
            self.exchanges_run += n_ticks
            self.live_lanes += m_in * n_ticks
            self.padded_lanes += m_out * n_ticks

    def note_overlap(self, seconds: float) -> None:
        self.overlap_seconds += max(0.0, seconds)
        self.overlap_hits += 1

    def utilization(self) -> float:
        """Live input lanes over padded output lanes — how much of the
        width every post-exchange kernel pays for is real traffic."""
        return self.live_lanes / self.padded_lanes \
            if self.padded_lanes else 1.0

    def cap_gauges(self) -> Dict[int, int]:
        """Per-destination-shard occupancy-sized cap (the ladder rung
        the measured peak demand for that shard quantizes to, maxed
        over sites) — the ``route.exchange_cap{shard}`` gauge."""
        cfg = self.engine.config
        out = {s: 0 for s in range(self.n_shards)}
        for est in self.estimators.values():
            for s in range(self.n_shards):
                rung = ladder_ceil(int(np.ceil(
                    float(est.peak[s]) * cfg.exchange_headroom)))
                out[s] = max(out[s], rung)
        return out

    def cap_util_gauges(self) -> Dict[int, float]:
        """Steady-state utilization of the per-destination grants: the
        LAST drained demand over the current grant per destination,
        maxed over sites — the ``route.exchange_cap_util{shard}``
        gauge.  1.0 means the grant is exactly full; a persistently
        low column is padding every lane pays for."""
        out = {s: 0.0 for s in range(self.n_shards)}
        for est in self.estimators.values():
            if est.grants is None:
                continue
            for s in range(self.n_shards):
                if est.grants[s] > 0:
                    util = float(est.last_need[s]) / float(est.grants[s])
                    out[s] = max(out[s], round(util, 4))
        return out

    def snapshot(self) -> Dict[str, Any]:
        return {
            "n_shards": self.n_shards,
            "exchanges_run": self.exchanges_run,
            "cross_shard_msgs": self.cross_shard_msgs,
            "delivered_msgs": self.delivered_msgs,
            "dropped_msgs": self.dropped_msgs,
            "redeliveries": self.redeliveries,
            "exchange_seconds": round(self.exchange_seconds, 6),
            "compiled_programs": len(self._jit_cache),
            "bucket_utilization": round(self.utilization(), 4),
            "overlap_seconds": round(self.overlap_seconds, 6),
            "overlap_hits": self.overlap_hits,
            "pre_discards": self.pre_discards,
            "cap_version": self.cap_version,
            "sites": {f"{t}.{m}": est.snapshot()
                      for (t, m), est in self.estimators.items()},
        }


def exchangeable_args(args: Any, m: int) -> bool:
    """True when every non-scalar arg leaf is lane-aligned ([m, ...]) —
    the precondition for permuting lanes.  Slab-style handlers (args
    consumed as a whole buffer, not per lane) fail this and keep the
    legacy path."""
    return all(np.ndim(leaf) == 0 or np.shape(leaf)[0] == m
               for leaf in jax.tree_util.tree_leaves(args))


def _split_leaves(args: Any, m: int):
    """Flatten an args pytree into (exchangeable [m, ...] leaves,
    treedef, scalar positions).  Scalar leaves broadcast in the kernels
    and are uniform across lanes, so they bypass the exchange."""
    flat, treedef = jax.tree_util.tree_flatten(args)
    leaves: List[Any] = []
    scalar_ix: Dict[int, Any] = {}
    for i, leaf in enumerate(flat):
        if np.ndim(leaf) == 0:
            scalar_ix[i] = leaf
        else:
            if np.shape(leaf)[0] != m:
                raise ValueError(
                    f"exchange: arg leaf {i} has leading dim "
                    f"{np.shape(leaf)[0]}, batch has {m} lanes")
            leaves.append(leaf)
    return leaves, treedef, scalar_ix


def _join_leaves(treedef, scalar_ix: Dict[int, Any],
                 leaves: List[Any]) -> Any:
    flat: List[Any] = []
    it = iter(leaves)
    for i in range(treedef.num_leaves):
        flat.append(scalar_ix[i] if i in scalar_ix else next(it))
    return jax.tree_util.tree_unflatten(treedef, flat)
