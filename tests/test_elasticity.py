"""Elasticity: joining silos, directory healing, single-activation under
topology change (reference analogs: SilosStopTests.cs, directory handoff
suites)."""

import asyncio

from orleans_tpu.core.grain import grain_id_for
from orleans_tpu.testing import TestingCluster

from tests.fixture_grains import ICounterGrain


def hosts_of(cluster, gid):
    return [s for s in cluster.silos if s.catalog.directory.by_grain.get(gid)]


def test_join_preserves_single_activation(run):
    """A joining silo takes over ring ranges; existing activations must
    keep their single-activation guarantee (directory heal replaces the
    reference's partition split handoff)."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(0)
            refs = [factory.get_grain(ICounterGrain, i) for i in range(30)]
            await asyncio.gather(*(r.add(1) for r in refs))

            await cluster.start_additional_silo()
            await cluster.wait_for_liveness_convergence()
            await asyncio.sleep(0.3)  # let the heal pass run

            # calls keep hitting the same activations: counters stay linear
            values = await asyncio.gather(*(r.add(1) for r in refs))
            assert values == [2] * 30, values
            for i in range(30):
                gid = grain_id_for(ICounterGrain, i)
                assert len(hosts_of(cluster, gid)) == 1, f"grain {i} duplicated"
        finally:
            await cluster.stop()

    run(main())


def test_dead_silo_entries_heal_to_successor(run):
    """After a hard kill, directory ranges owned by the dead silo move to
    survivors and hosted activations re-register — the merge half of the
    reference's handoff (GrainDirectoryHandoffManager.cs:141)."""

    async def main():
        cluster = await TestingCluster(n_silos=3).start()
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(0)
            refs = [factory.get_grain(ICounterGrain, i) for i in range(30)]
            await asyncio.gather(*(r.add(1) for r in refs))

            victim = cluster.silos[2]
            cluster.kill_silo(victim)
            deadline = asyncio.get_running_loop().time() + 10
            while any(victim.address in s.active_silos()
                      for s in cluster.silos):
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.1)
            await asyncio.sleep(0.3)  # heal pass

            values = await asyncio.gather(*(r.add(1) for r in refs))
            assert len(values) == 30
            for i in range(30):
                gid = grain_id_for(ICounterGrain, i)
                assert len(hosts_of(cluster, gid)) == 1, f"grain {i} duplicated"
        finally:
            await cluster.stop()

    run(main())


def test_scale_out_scale_in_cycle(run):
    async def main():
        cluster = await TestingCluster(n_silos=1).start()
        try:
            factory = cluster.attach_client(0)
            refs = [factory.get_grain(ICounterGrain, i) for i in range(10)]
            await asyncio.gather(*(r.add(1) for r in refs))
            # scale out to 3
            await cluster.start_additional_silo()
            await cluster.start_additional_silo()
            await cluster.wait_for_liveness_convergence()
            await asyncio.sleep(0.3)
            await asyncio.gather(*(r.add(1) for r in refs))
            # scale back in (graceful)
            await cluster.stop_silo(cluster.silos[2])
            await cluster.stop_silo(cluster.silos[1])
            await cluster.wait_for_liveness_convergence()
            values = await asyncio.gather(*(r.add(1) for r in refs))
            # grains that moved lose unsaved in-memory count (no storage
            # write) — but every call must succeed and the count per grain
            # is consistent with exactly-one-activation semantics
            assert all(v >= 1 for v in values)
            assert cluster.total_activations() == 10
        finally:
            await cluster.stop()

    run(main())
