"""Closed-loop rebalance: the actuator for the attribution plane.

PR 10 built exactly the input a rebalancer needs — the HotSet contract,
``skew.*`` per-shard traffic shares, ``slo.*`` burn rates — and until
now a human read the dashboard and nothing acted: a Zipf hot spot pins
one shard while the rest of the mesh idles.  This module closes the
loop (ROADMAP item 2; reference analog: Orleans' placement + ring
rebalance over the virtual-actor directory, MSR-TR-2014-41):

* ``RebalancePlanner`` — the PURE decision core: per interval it judges
  each arena's per-shard traffic shares against the trigger (hysteresis
  so a one-interval blip never moves grains, cooldown so a move wave's
  effect lands in the telemetry before re-judging, a per-interval move
  budget so placement churn is bounded) and plans which hot grains
  leave the burning shard for the coolest ones.  No engine, no silo —
  the unit tests drive it with synthetic HotSet/skew fixtures.
* ``RebalanceController`` — the wiring: diffs the attribution plane's
  cumulative telemetry into interval signals, resolves hot keys to
  their CURRENT shard, applies planned moves through the batched
  live-migration primitive (``engine.migrate_keys`` — one columnar
  gather/scatter per wave, never per-grain Python), and optionally
  moves hot grains to a less-loaded PEER silo (the cross-silo leg,
  tensor/router.py placement overrides + state-slab push) when this
  silo's SLO burns and the gossiped load reports show remote capacity.

Every decision is counted (``rebalance.*`` catalog rows) and kept in a
bounded decision ring for the dashboard/flight recorder.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = [
    "ArenaSignals",
    "Move",
    "Replicate",
    "RebalancePlanner",
    "RebalanceController",
    "interval_latency_burn",
]


@dataclass
class ArenaSignals:
    """One arena's interval telemetry, as the planner consumes it."""

    arena: str
    n_shards: int
    # traffic per shard THIS interval (cumulative diffs, clamped >= 0)
    interval_shard_msgs: np.ndarray
    # hot-set entries with their key's CURRENT shard resolved:
    # [{"key", "msgs", "share", "shard"}] sorted hottest-first
    hot: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class Move:
    """One planned wave: ``keys[i]`` migrates to ``dst_shards[i]``."""

    arena: str
    keys: np.ndarray
    dst_shards: np.ndarray
    src_shard: int
    share: float          # the burning shard's interval share
    trigger: float        # the effective trigger it beat
    reason: str


@dataclass
class Replicate:
    """One planned hot-grain promotion: a grain too hot for ANY single
    shard (its share alone clears ``replicate_share`` — migrating it
    would just relocate the burn) spreads to ``k`` replica rows.  The
    controller applies it through ``engine.replicate_key`` when the
    grain's traffic-bearing methods are declared commutative, else
    falls back to migrating the grain to ``fallback_dst``."""

    arena: str
    key: int
    k: int
    src_shard: int
    fallback_dst: int     # coolest shard, for the non-commutative case
    share: float          # the burning shard's interval share
    grain_share: float    # the grain's own share of arena traffic
    reason: str


class RebalancePlanner:
    """The pure decision core (see module docstring).  State held
    between ``plan`` calls: consecutive-over-trigger counts (hysteresis)
    and post-move cooldowns, both per arena."""

    def __init__(self, cfg) -> None:
        self.cfg = cfg
        self._over: Dict[str, int] = {}
        self._cooldown: Dict[str, int] = {}
        self.intervals = 0
        self.moves_planned = 0
        self.skipped_idle = 0
        self.skipped_below_trigger = 0
        self.skipped_hysteresis = 0
        self.skipped_cooldown = 0
        self.skipped_no_candidates = 0
        self.replications_planned = 0
        self.hot_grain_blocked = 0
        # the replication decisions of the LAST plan() call (the moves
        # are the return value; these ride alongside so the signature
        # the tests pin stays put)
        self.pending_replications: List[Replicate] = []

    def effective_trigger(self, n_shards: int, slo_burn: float) -> float:
        """The share that arms a move: the configured trigger, halved
        while the latency SLO burns (milder skew justifies acting when
        the budget is already bleeding), floored at 1.25x the uniform
        share so a balanced mesh can never read as burning."""
        trigger = self.cfg.trigger_share
        if slo_burn > self.cfg.slo_burn_trigger:
            trigger = trigger / 2.0
        return max(1.25 / max(1, n_shards), trigger)

    def plan(self, signals: List[ArenaSignals],
             slo_burn: float = 0.0) -> List[Move]:
        self.intervals += 1
        moves: List[Move] = []
        self.pending_replications = []
        for sig in signals:
            if sig.n_shards <= 1:
                continue
            total = int(sig.interval_shard_msgs.sum())
            if total < self.cfg.min_interval_msgs:
                # idle interval: no judgement, hysteresis DISARMS (skew
                # over noise traffic is meaningless)
                self._over[sig.arena] = 0
                self.skipped_idle += 1
                continue
            shares = sig.interval_shard_msgs / float(total)
            burning = int(np.argmax(shares))
            share = float(shares[burning])
            trigger = self.effective_trigger(sig.n_shards, slo_burn)
            if share <= trigger:
                self._over[sig.arena] = 0
                self.skipped_below_trigger += 1
                continue
            cd = self._cooldown.get(sig.arena, 0)
            if cd > 0:
                # cooling down after a wave: the moved traffic needs an
                # interval or two to show in the telemetry — re-judging
                # now would thrash (hysteresis stays ARMED: sustained
                # skew resumes acting the moment the cooldown ends)
                self._cooldown[sig.arena] = cd - 1
                self.skipped_cooldown += 1
                continue
            over = self._over.get(sig.arena, 0) + 1
            self._over[sig.arena] = over
            if over < self.cfg.hysteresis_intervals:
                self.skipped_hysteresis += 1
                continue
            # two escalating levers: a grain whose OWN share clears
            # replicate_share is too hot for any single shard —
            # migrating it just relocates the burn, so it goes to the
            # replication lever; the rest migrate as before
            budget = max(0, int(self.cfg.move_budget))
            rep_share = float(getattr(self.cfg, "replicate_share",
                                      0.0) or 0.0)
            hot_here = [h for h in sig.hot
                        if h.get("shard") == burning]
            rep_cands = [h for h in hot_here
                         if h.get("share", 0.0) >= rep_share] \
                if rep_share > 0 else []
            rep_keys = {int(h["key"]) for h in rep_cands}
            movers = [h for h in hot_here
                      if h.get("share", 0.0) >= self.cfg.min_grain_share
                      and int(h["key"]) not in rep_keys]
            movers = movers[:budget]
            if not movers and not rep_cands:
                if hot_here and rep_share > 0:
                    # BUGFIX: a burning shard whose heat rides one grain
                    # below the mover floor used to idle here FOREVER —
                    # hysteresis armed, no candidates, no action, every
                    # interval.  Count it and route the hottest grain to
                    # the replication lever instead of spinning.
                    self.hot_grain_blocked += 1
                    rep_cands = hot_here[:1]
                else:
                    self.skipped_no_candidates += 1
                    continue
            coolest = int(np.argmin(shares))
            for h in rep_cands[:budget]:
                self.pending_replications.append(Replicate(
                    arena=sig.arena,
                    key=int(h["key"]),
                    k=max(2, min(int(self.cfg.max_replicas),
                                 sig.n_shards)),
                    src_shard=burning,
                    fallback_dst=coolest,
                    share=share,
                    grain_share=float(h.get("share", 0.0)),
                    reason=f"grain {int(h['key'])} share "
                           f"{float(h.get('share', 0.0)):.3f} on burning "
                           f"shard {burning} (shard share {share:.3f}) — "
                           f"beyond the single-shard ceiling"))
                self.replications_planned += 1
            if not movers:
                self._over[sig.arena] = 0
                self._cooldown[sig.arena] = self.cfg.cooldown_intervals
                continue
            # destinations: greedy share-aware packing — each mover
            # (hottest first) lands on the destination with the least
            # ACCUMULATED load (background interval share + already-
            # assigned movers' shares).  Share-blind round-robin would
            # re-concentrate the hot ranks (hottest + every wrap-around
            # mate on one shard) and mint a new hot spot; the exchange
            # cap is sized by the MAX per-destination demand, so the
            # packing's max is what recovery is bounded by.
            order = [int(s) for s in np.argsort(shares, kind="stable")
                     if int(s) != burning]
            load = {s: float(shares[s]) for s in order}
            dst = []
            for h in movers:
                s = min(order, key=lambda x: load[x])
                dst.append(s)
                load[s] += max(0.0, float(h.get("share", 0.0)))
            dst = np.asarray(dst, dtype=np.int64)
            moves.append(Move(
                arena=sig.arena,
                keys=np.array([int(h["key"]) for h in movers],
                              dtype=np.int64),
                dst_shards=dst,
                src_shard=burning,
                share=share,
                trigger=trigger,
                reason=f"shard {burning} interval share "
                       f"{share:.3f} > trigger {trigger:.3f} for "
                       f"{over} intervals"))
            self.moves_planned += 1
            self._over[sig.arena] = 0
            self._cooldown[sig.arena] = self.cfg.cooldown_intervals
        return moves

    def snapshot(self) -> Dict[str, int]:
        return {
            "intervals": self.intervals,
            "moves_planned": self.moves_planned,
            "skipped_idle": self.skipped_idle,
            "skipped_below_trigger": self.skipped_below_trigger,
            "skipped_hysteresis": self.skipped_hysteresis,
            "skipped_cooldown": self.skipped_cooldown,
            "skipped_no_candidates": self.skipped_no_candidates,
            "replications_planned": self.replications_planned,
            "hot_grain_blocked": self.hot_grain_blocked,
        }


def interval_latency_burn(engine, error_budget: float,
                          prev_counts: Optional[np.ndarray],
                          spt: Optional[float] = None) -> tuple:
    """Latency-SLO burn over an INTERVAL of the device ledger (the
    silo's ``_publish_slo`` judges the cumulative distribution; the
    controller must react to what happened SINCE its last decision, so
    it diffs the bucket counts).  Returns ``(burn, counts)`` where
    ``counts`` is the cumulative array to pass back next interval.
    ``spt`` overrides the ticks→seconds clock (the bench passes the
    interval's own seconds-per-tick so one segment's burn is judged at
    that segment's pace, not the run-cumulative mean).  Burn 0.0 when
    there is no budget, no ledger, or no traffic."""
    from orleans_tpu.metrics import bucket_bounds
    budget = engine.config.target_tick_latency
    if budget <= 0 or not engine.ledger.enabled or not engine.ticks_run:
        return 0.0, prev_counts
    counts = np.asarray(engine.ledger.fetch_counts())
    delta = counts
    if prev_counts is not None and prev_counts.shape == counts.shape:
        delta = np.maximum(counts - prev_counts, 0)
    window = int(delta.sum())
    if window == 0 or error_budget <= 0:
        return 0.0, counts
    if spt is None:
        spt = engine.tick_seconds / engine.ticks_run
    if spt <= 0:
        return 0.0, counts
    bounds = bucket_bounds(1.0, engine.ledger.n_buckets)
    over_buckets = [k for k, (lo, _hi) in enumerate(bounds)
                    if lo * spt > budget]
    over = int(delta[:, over_buckets].sum()) if over_buckets else 0
    return over / window / error_budget, counts


class RebalanceController:
    """Wires the planner to a live engine (and optionally its silo —
    the cross-silo leg and the ``rebalance.*`` publication need one;
    the shard leg runs engine-only, which is how the bench drives it).
    """

    def __init__(self, silo=None, engine=None, config=None) -> None:
        self.silo = silo
        self.engine = engine if engine is not None \
            else (silo.tensor_engine if silo is not None else None)
        if self.engine is None:
            raise ValueError("RebalanceController needs an engine")
        self.cfg = config if config is not None \
            else silo.config.rebalance
        self.planner = RebalancePlanner(self.cfg)
        # cumulative baselines diffed into interval signals
        self._prev_shard_msgs: Dict[str, np.ndarray] = {}
        self._prev_ledger_counts: Optional[np.ndarray] = None
        # acted-on accounting (the planner counts decisions; these count
        # what actually happened to the arena)
        self.moves_applied = 0
        self.grains_moved = 0
        self.replications_applied = 0
        self.demotions_applied = 0
        self.replica_fallback_moves = 0
        # per replicated grain: consecutive below-demote_share intervals
        # + the cumulative-msgs baseline diffed into interval shares
        # (attribution hot shares are lifetime-cumulative — a grain that
        # was once hot would otherwise never read as cooled)
        self._replica_cool: Dict[tuple, int] = {}
        self._replica_prev_msgs: Dict[tuple, int] = {}
        self.cross_silo_moves = 0
        self.cross_silo_grains = 0
        self.last_trigger_share = 0.0
        self.last_slo_burn = 0.0
        self.last_move_pause_s = 0.0
        self.max_move_pause_s = 0.0
        self.decisions: deque = deque(maxlen=64)
        self._task: Optional[asyncio.Task] = None

    # -- signal collection --------------------------------------------------

    def _signals(self) -> List[ArenaSignals]:
        eng = self.engine
        att = eng.attribution
        if not att.enabled:
            return []
        snap = att.snapshot(cache=True)
        signals: List[ArenaSignals] = []
        for name, a in snap["arenas"].items():
            arena = eng.arenas.get(name)
            if arena is None or arena.n_shards <= 1:
                continue
            cum = np.asarray(a["shard_msgs"], dtype=np.int64)
            prev = self._prev_shard_msgs.get(name)
            # clamped diff: retirement (eviction/migration moves counts
            # from the live column to the per-key mirror) and reshard
            # folds shrink the cumulative sums — a negative delta is
            # accounting motion, not negative traffic
            delta = np.maximum(cum - prev, 0) \
                if prev is not None and prev.shape == cum.shape else cum
            self._prev_shard_msgs[name] = cum
            hot = []
            if len(a["hot"]):
                keys = np.array([int(h["key"]) for h in a["hot"]],
                                dtype=np.int64)
                rows, found = arena.lookup_rows(keys)
                shards = rows.astype(np.int64) // arena.shard_capacity
                for h, s, ok in zip(a["hot"], shards.tolist(),
                                    found.tolist()):
                    if ok:
                        hot.append({**h, "shard": int(s)})
            signals.append(ArenaSignals(
                arena=name, n_shards=arena.n_shards,
                interval_shard_msgs=delta, hot=hot))
        return signals

    def _slo_burn(self) -> float:
        mc = self.silo.config.metrics if self.silo is not None \
            else self.engine.metrics_config
        burn, self._prev_ledger_counts = interval_latency_burn(
            self.engine, mc.slo_latency_error_budget,
            self._prev_ledger_counts)
        self.last_slo_burn = burn
        return burn

    # -- one decision interval ----------------------------------------------

    async def run_once(self) -> int:
        """One closed-loop interval: read signals, plan, act.  Returns
        grains moved (shard leg + cross-silo leg)."""
        signals = self._signals()
        burn = self._slo_burn()
        moves = self.planner.plan(signals, slo_burn=burn)
        reps = list(self.planner.pending_replications)
        moved_total = 0
        for mv in moves:
            t0 = time.perf_counter()
            moved = self.engine.migrate_keys(mv.arena, mv.keys,
                                            mv.dst_shards)
            pause = time.perf_counter() - t0
            self.last_move_pause_s = pause
            self.max_move_pause_s = max(self.max_move_pause_s, pause)
            self.last_trigger_share = mv.share
            if moved:
                self.moves_applied += 1
                self.grains_moved += moved
                moved_total += moved
            self.decisions.append({
                "t": time.time(), "leg": "shard", "arena": mv.arena,
                "src_shard": mv.src_shard, "grains": moved,
                "share": round(mv.share, 4),
                "trigger": round(mv.trigger, 4),
                "pause_s": round(pause, 6), "reason": mv.reason})
            rec = self.engine._span_recorder()
            if rec is not None:
                # one timeline episode per rebalance decision, carrying
                # the planner's own evidence (share vs trigger)
                rec.plane_span("rebalance", f"move {mv.arena}",
                               duration=pause, grains=moved,
                               src_shard=mv.src_shard,
                               share=round(mv.share, 4),
                               trigger=round(mv.trigger, 4),
                               reason=mv.reason)
        moved_total += self._apply_replications(reps)
        self._maybe_demote(signals)
        if self.cfg.cross_silo and self.silo is not None:
            moved_total += await self._cross_silo_leg(burn)
        return moved_total

    # -- hot-grain replication lever ----------------------------------------

    def _replicable(self, arena_name: str) -> bool:
        """True when the grain TYPE's state is safe to replicate: every
        method observed carrying traffic (the attribution plane's
        per-method slots; fallback when no slot data — every declared
        method) is declared ``@commutative``, so the replica fold is
        order-independent and exact."""
        arena = self.engine.arenas.get(arena_name)
        if arena is None or not arena.info.methods:
            return False
        infos = arena.info.methods
        att = self.engine.attribution
        active: List[str] = []
        if att.enabled:
            prefix = f"{arena_name}."
            active = [m[len(prefix):]
                      for m in att.snapshot(cache=True).get("methods", {})
                      if m.startswith(prefix)]
        names = [m for m in active if m in infos] or list(infos)
        return all(getattr(infos[m], "commutative", False)
                   for m in names)

    def _apply_replications(self, reps: List[Replicate]) -> int:
        """Apply the planner's promotion decisions: commutative grains
        promote through ``engine.replicate_key``; non-commutative ones
        fall back to a single-grain migration to the coolest shard (the
        old lever — the burn relocates, but at least off the burning
        shard).  Returns grains moved by the fallback leg."""
        moved_total = 0
        for rp in reps:
            t0 = time.perf_counter()
            if self._replicable(rp.arena):
                already = (rp.arena, rp.key) in self._replica_cool
                got = self.engine.replicate_key(rp.arena, rp.key, rp.k)
                pause = time.perf_counter() - t0
                if got and not already:
                    self.replications_applied += 1
                    self._replica_cool[(rp.arena, rp.key)] = 0
                    self.decisions.append({
                        "t": time.time(), "leg": "replicate",
                        "arena": rp.arena, "key": rp.key,
                        "replicas": got,
                        "grain_share": round(rp.grain_share, 4),
                        "share": round(rp.share, 4),
                        "pause_s": round(pause, 6),
                        "reason": rp.reason})
            else:
                moved = self.engine.migrate_keys(
                    rp.arena, np.array([rp.key], dtype=np.int64),
                    np.array([rp.fallback_dst], dtype=np.int64))
                pause = time.perf_counter() - t0
                if moved:
                    self.replica_fallback_moves += 1
                    self.grains_moved += moved
                    moved_total += moved
                self.decisions.append({
                    "t": time.time(), "leg": "replicate-fallback",
                    "arena": rp.arena, "key": rp.key,
                    "dst_shard": rp.fallback_dst, "grains": moved,
                    "pause_s": round(pause, 6),
                    "reason": "state not commutative — migrated instead"})
            self.last_move_pause_s = pause
            self.max_move_pause_s = max(self.max_move_pause_s, pause)
        return moved_total

    def _maybe_demote(self, signals: List[ArenaSignals]) -> int:
        """Cool-down sweep: a replicated grain whose INTERVAL share
        stays below ``demote_share`` for ``demote_patience`` consecutive
        intervals folds back to one row (promote/demote must not flap —
        the estimator's shrink-patience discipline).  The attribution
        hot list carries lifetime-cumulative msgs, so the interval share
        is the diff against last interval's baseline over the arena's
        interval total — a grain absent from the top-K reads as cold."""
        live = {(name, int(k))
                for name, a in self.engine.arenas.items()
                for k in a._replicas}
        if not live:
            self._replica_cool.clear()
            self._replica_prev_msgs.clear()
            return 0
        totals = {sig.arena: int(np.sum(sig.interval_shard_msgs))
                  for sig in signals}
        cum_msgs: Dict[tuple, int] = {}
        for sig in signals:
            for h in sig.hot:
                cum_msgs[(sig.arena, int(h["key"]))] = \
                    int(h.get("msgs", 0))
        demoted = 0
        for ident in sorted(live):
            prev = self._replica_prev_msgs.get(ident)
            cum = cum_msgs.get(ident, prev if prev is not None else 0)
            cum = max(cum, prev or 0)
            delta = cum - prev if prev is not None else cum
            self._replica_prev_msgs[ident] = cum
            total = totals.get(ident[0], 0)
            share = delta / total if total > 0 else 0.0
            if share < self.cfg.demote_share:
                streak = self._replica_cool.get(ident, 0) + 1
            else:
                streak = 0
            self._replica_cool[ident] = streak
            if streak >= max(1, int(self.cfg.demote_patience)):
                name, key = ident
                if self.engine.demote_key(name, key):
                    demoted += 1
                    self.demotions_applied += 1
                    self.decisions.append({
                        "t": time.time(), "leg": "demote",
                        "arena": name, "key": key,
                        "reason": f"share < {self.cfg.demote_share} for "
                                  f"{streak} intervals"})
                self._replica_cool.pop(ident, None)
                self._replica_prev_msgs.pop(ident, None)
        # grains demoted elsewhere (eviction, reshard): drop tracking
        for ident in list(self._replica_cool):
            if ident not in live:
                self._replica_cool.pop(ident)
                self._replica_prev_msgs.pop(ident, None)
        return demoted

    async def _cross_silo_leg(self, burn: float) -> int:
        """Move hot grains to a less-loaded PEER when this silo's SLO
        burns and the gossiped load reports (satellite: they carry
        arena occupancy + memory headroom) show remote capacity."""
        silo = self.silo
        router = silo.vector_router
        if router is None or not hasattr(router, "migrate_keys_out") \
                or burn <= self.cfg.slo_burn_trigger:
            return 0
        target = self._pick_peer()
        if target is None:
            return 0
        hot = silo.hot_set()
        if not hot:
            return 0
        moved = 0
        budget = max(0, int(self.cfg.move_budget))
        by_arena: Dict[str, List[int]] = {}
        for h in hot[:budget]:
            if h.get("share", 0.0) >= self.cfg.min_grain_share:
                by_arena.setdefault(h["arena"], []).append(int(h["key"]))
        for arena, keys in by_arena.items():
            t0 = time.perf_counter()
            n = await router.migrate_keys_out(
                arena, np.asarray(keys, dtype=np.int64), target)
            pause = time.perf_counter() - t0
            self.last_move_pause_s = pause
            self.max_move_pause_s = max(self.max_move_pause_s, pause)
            if n:
                self.cross_silo_moves += 1
                self.cross_silo_grains += n
                moved += n
            self.decisions.append({
                "t": time.time(), "leg": "silo", "arena": arena,
                "target": str(target), "grains": n,
                "burn": round(burn, 3), "pause_s": round(pause, 6)})
        return moved

    def _pick_peer(self) -> Optional[Any]:
        """Least-loaded live peer by reported arena occupancy ratio,
        skipping peers above the occupancy ceiling or with no capacity
        report yet (the load broadcast is the only channel — the
        controller never guesses about remote capacity)."""
        silo = self.silo
        lp = silo.load_publisher
        if lp is None:
            return None
        best, best_ratio = None, None
        for addr, st in lp.periodic_stats.items():
            if addr == silo.address or not silo.is_silo_alive(addr):
                continue
            if getattr(st, "is_standby", False):
                # an armed standby's emptiness is reserved for its
                # primary's arena at promotion — never a migration
                # target (standby placement awareness)
                continue
            occ = getattr(st, "arena_occupancy", None)
            if occ is None:
                continue
            live = sum(o.get("live", 0) for o in occ.values())
            cap = sum(o.get("capacity", 0) for o in occ.values())
            ratio = (live / cap) if cap else 0.0
            headroom = getattr(st, "memory_headroom", None)
            if ratio >= self.cfg.peer_occupancy_ceiling:
                continue
            if headroom is not None and headroom < 0.05:
                continue
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = addr, ratio
        return best

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._task is not None:
            return
        from orleans_tpu.utils.async_utils import spawn_in_fresh_context
        self._task = spawn_in_fresh_context(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(max(0.01, self.cfg.interval_s))
            try:
                if self.cfg.enabled:
                    await self.run_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad interval must
                # not kill the loop for the silo's life (the load
                # publisher's reasoning); the next interval re-reads
                # fresh signals
                if self.silo is not None:
                    self.silo.logger.warn(
                        "rebalance interval failed; retrying next "
                        "interval", code=2930)

    def snapshot(self) -> Dict[str, Any]:
        return {
            **self.planner.snapshot(),
            "moves_applied": self.moves_applied,
            "grains_moved": self.grains_moved,
            "replications_applied": self.replications_applied,
            "demotions_applied": self.demotions_applied,
            "replica_fallback_moves": self.replica_fallback_moves,
            "cross_silo_moves": self.cross_silo_moves,
            "cross_silo_grains": self.cross_silo_grains,
            "last_trigger_share": self.last_trigger_share,
            "last_slo_burn": self.last_slo_burn,
            "last_move_pause_s": self.last_move_pause_s,
            "max_move_pause_s": self.max_move_pause_s,
            "decisions": list(self.decisions),
        }
