"""WorkloadAttribution: device-resident hot-grain and skew accounting.

Why this exists (ROADMAP item 4's prerequisite): the observability stack
so far is entirely system-centric — the spans say *what* happened
(spans.py), the latency ledger says *how long* it took (ledger.py), the
profiler says *where the cost lives* (profiler.py) — but none of them
can say that ``ChirperAccount/42`` receives 30% of the traffic.  Load-
driven placement and live rebalance (PAPER.md: directory ring +
ActivationCountPlacementDirector) need exactly that *who* signal, and a
per-message host hook would burn the data plane to get it.  This module
accumulates the signal where the traffic lives, with the latency
ledger's discipline: fold inside the tick, one small d2h per snapshot,
never per message.

Three device-resident structures per engine:

* **per-row traffic counts** — one int32 column per arena (sharded like
  the state columns), scatter-added with each executing batch's
  destination rows (``segment_sum`` semantics: the applied-lane mask is
  combined inside the fold, so a masked redelivery lane never counts
  twice).  ``jax.lax.top_k`` over the column at snapshot time yields the
  candidate-row top-K ON DEVICE — only [K] rows + counts cross d2h.
* **a count-min sketch** — int32[depth, width] per arena, the same lanes
  hashed ``depth`` ways (pairwise-independent-ish multiply-shift mixes)
  into ``width`` buckets.  The sketch is the bounded-memory witness:
  its per-key estimate never undercounts, and the classical bound
  ``P[est > true + (e/width)·N] <= exp(-depth)`` prices the HotSet's
  ``confidence`` — the counts column can be evicted/remapped, the sketch
  keeps absorbing, and a reader knows exactly how much to trust it.
* **per-(type, method) slot counts** — int32[MAX_SLOTS] sharing the
  latency ledger's SlotRegistry, so traffic share per method costs one
  scatter-add in the same fold.

The fold is ONE jit dispatch per executing (type, method) group on the
unfused path, and it must cost ~nothing: a per-lane scatter per batch
measured ~50ns/lane on the CPU backend — 2.5x the whole tick at 20k
lanes, where the acceptance bar is <5%.  The unfused engine's steady
state saves us: an injector re-presents the SAME device (rows, mask)
arrays every tick (the identity the whole engine keys caching on), so
the fold memoizes a **dense delta plan** per (rows, mask) identity —
bincount of the valid lanes + the sketch's hashed delta, built once by
``_plan_kernel`` — and the steady-state dispatch is three vectorized
adds (``_apply_kernel``, donated in, async, no sync).  Device arrays
are immutable, so identity implies content; numpy inputs are never
memoized (hosts can mutate buffers in place — the PR 9 staging-memo
lesson).  A novel batch pays one scatter-shaped plan build, measured in
the bench oracle tier.  Inside fused windows the fold inlines into the
``lax.scan`` as the plain scatter (``fold_batch``) exactly like the
ledger hist — integer adds are exactly associative, so the two paths
are bit-identical — autofuse's AOT lower includes the accumulator
avals, windows return them undonated, and a rolled-back chain restores
the pre-chain arrays so the unfused replay re-records exactly once
(``snapshot_state``/``restore_state``, the ledger contract).

Eviction epochs: free-list deactivation frees rows without moving
survivors, and a freed slot may be *reused by a different grain* — a
per-row count that outlived its grain would misattribute.  The arena's
deactivation path therefore RETIRES victims through ``on_evict``: their
counts gather to a host-side ``{key: count}`` mirror (one small d2h per
eviction chunk, riding a path that is already host-synchronous) and the
rows zero on device before reuse.  Snapshots merge live + retired per
key, so per-grain totals survive eviction epochs bit-exactly.  Row moves
(growth/compaction) remap the column on device (``remap_rows``, the
``last_use_dev`` discipline); a mesh reshard folds to the host mirror
first (``fold_type`` — the compiled arrays are committed to the old
device set, same as ``ledger.relocate``).

The host half resolves candidate rows back to grain keys via the arena
mirror (``_key_of_row``) and publishes a **HotSet** — ``[(key, msgs,
share, sketch_est, confidence)]`` — plus per-arena skew gauges
(max-shard share, Gini over live rows, p99-to-mean) computed on device
at snapshot time.  ``silo.collect_metrics`` mirrors all of it into the
``hot.*``/``skew.*`` catalog rows, the load publisher broadcasts the
HotSet with its runtime statistics, and the dashboard renders the
hot-grains/skew rows — the signal ROADMAP item 4's rebalancer consumes
unchanged.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from orleans_tpu.tensor.arena import _pow2_pad
from orleans_tpu.tensor.ledger import MAX_SLOTS, SlotRegistry

#: multiply-shift seed per sketch depth (odd constants; depth is capped
#: by the seed count — 8 depths drive the failure probability to e^-8)
CMS_SEEDS = (0x9E3779B1, 0x85EBCA6B, 0xC2B2AE35, 0x27D4EB2F,
             0x165667B1, 0xD3A2646D, 0xFD7046C5, 0xB55A4F09)
MAX_CMS_DEPTH = len(CMS_SEEDS)


def pow2ceil(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


def cms_hash(rows, seeds, width: int):
    """[depth, m] sketch buckets of ``rows`` (device twin used by both
    the fold and the snapshot estimator — MUST stay consistent)."""
    u = rows.astype(jnp.uint32)[None, :] * seeds[:, None]
    u = u ^ (u >> 15)
    u = u * jnp.uint32(0x27D4EB2F)
    u = u ^ (u >> 13)
    return (u & jnp.uint32(width - 1)).astype(jnp.int32)


def fold_batch(counts, cms, slots, seeds, slot, rows, valid):
    """One batched attribution fold (traceable — the fused tick program
    inlines this inside its scan): combine the applied-lane mask (valid
    ∧ rows in range), scatter-add the lanes into (a) the per-row traffic
    column, (b) every sketch depth's hashed bucket, and (c) the
    (type, method) slot counter.  Invalid lanes add zero everywhere."""
    cap = counts.shape[0]
    rows = jnp.asarray(rows, jnp.int32)
    valid = jnp.asarray(valid, bool) & (rows >= 0) & (rows < cap)
    inc = valid.astype(jnp.int32)
    r = jnp.where(valid, rows, cap)  # out-of-range + mode="drop"
    counts = counts.at[r].add(inc, mode="drop")
    depth = cms.shape[0]
    h = cms_hash(rows, seeds, cms.shape[1])
    cms = cms.at[jnp.arange(depth, dtype=jnp.int32)[:, None], h].add(
        inc[None, :])
    slots = slots.at[slot].add(jnp.sum(inc))
    return counts, cms, slots


def fold_counts(counts, slots, slot, rows, valid, segments=None):
    """The scan-carry half of an in-window fold: per-row counts + the
    (type, method) slot counter, WITHOUT the sketch — the fused window
    folds the CMS once per window from the counts delta
    (``fold_cms_dense``), which removes a lane-sized sketch scatter
    from every scanned tick.  Integer adds commute, so the split is
    bit-identical to per-lane ``fold_batch`` calls.

    ``segments`` (a pull-mode delivery batch's row-aligned offsets,
    tensor/streams_plane.py) switches the counts fold to the same
    scatter-free cumulative-sum reduction the delivery handler uses."""
    inc_src = jnp.asarray(valid, bool)
    if segments is not None:
        inc = inc_src.astype(jnp.int32)
        z = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(inc)])
        counts = counts + (z[segments[1:]] - z[segments[:-1]])
        slots = slots.at[slot].add(jnp.sum(inc))
        return counts, slots
    cap = counts.shape[0]
    rows = jnp.asarray(rows, jnp.int32)
    valid = inc_src & (rows >= 0) & (rows < cap)
    inc = valid.astype(jnp.int32)
    r = jnp.where(valid, rows, cap)  # out-of-range + mode="drop"
    counts = counts.at[r].add(inc, mode="drop")
    slots = slots.at[slot].add(jnp.sum(inc))
    return counts, slots


def fold_cms_dense(cms, counts_delta, seeds):
    """Sketch fold from a DENSE per-row delta: one capacity-sized
    scatter covering any number of per-tick, per-group lane folds —
    the per-row sums land in exactly the hashed buckets ``fold_batch``
    would have scattered lane by lane (the hash is row-keyed and adds
    commute), so the result is bit-identical."""
    depth, width = cms.shape
    cap = counts_delta.shape[0]
    h = cms_hash(jnp.arange(cap, dtype=jnp.int32), seeds, width)
    return cms.at[jnp.arange(depth, dtype=jnp.int32)[:, None], h].add(
        counts_delta[None, :].astype(jnp.int32))


@partial(jax.jit, static_argnames=("cap", "width", "depth"))
def _plan_kernel(rows, valid, seeds, cap: int, width: int, depth: int):
    """Build one batch's dense delta plan: bincount of the valid lanes
    over the counts column's support + the sketch's hashed delta + the
    lane total.  Paid ONCE per (rows, mask) identity (injector steady
    state) or per call for novel batches — the scatters live here, off
    the steady-state hot path."""
    rows = jnp.asarray(rows, jnp.int32)
    valid = jnp.asarray(valid, bool) & (rows >= 0) & (rows < cap)
    inc = valid.astype(jnp.int32)
    r = jnp.where(valid, rows, cap)  # out-of-range lanes park at cap
    counts_delta = jnp.zeros(cap + 1, jnp.int32).at[r].add(inc)[:cap]
    h = cms_hash(rows, seeds, width)
    cms_delta = jnp.zeros((depth, width), jnp.int32).at[
        jnp.arange(depth, dtype=jnp.int32)[:, None], h].add(inc[None, :])
    return counts_delta, cms_delta, jnp.sum(inc)


@partial(jax.jit, donate_argnums=(0, 1, 2))
def _apply_coalesced(counts, cms, slots, counts_delta, cms_delta, slot,
                     n, k):
    """Flush a run of ``k`` host-proven folds of ONE plan: integer
    multiply-adds are exactly k repeated adds, so coalescing is
    bit-exact.  Donated accumulators (double-buffered in place — safe
    because fused windows never donate their attribution inputs, and no
    unfused fold can run mid-chain: any pattern break settles the chain
    first, flushing this buffer)."""
    return (counts + k * counts_delta, cms + k * cms_delta,
            slots.at[slot].add(k * n))


@partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _apply_checked_stack(counts, cms, slots, stale, plan_rows,
                         plan_valid, counts_delta, cms_delta, n, seeds,
                         slot, rows_stack, valid_stack, real):
    """Flush a stack of device-checked folds against ONE plan: emit
    batches' rows are jit program OUTPUTS — fresh buffers every tick
    even when the values never change — so no host-side identity can
    prove the plan applies.  The device proves it instead: one batched
    exact compare counts the matching occurrences (k·delta fast path),
    and each mismatched occurrence takes the full scatter fold inside a
    ``lax.scan`` step while bumping the stale counter the next snapshot
    reads to refresh the plan.  ``real`` masks the pow2 padding rows
    (no-ops on both paths).  Exactness is unconditional; only the cost
    depends on the guess."""
    rows_stack = jnp.asarray(rows_stack, jnp.int32)
    valid_stack = jnp.asarray(valid_stack, bool)
    matches = real \
        & jnp.all(rows_stack == plan_rows[None, :], axis=1) \
        & jnp.all(valid_stack == plan_valid[None, :], axis=1)
    km = jnp.sum(matches.astype(jnp.int32))
    counts = counts + km * counts_delta
    cms = cms + km * cms_delta
    slots = slots.at[slot].add(km * n)
    mismatch = real & ~matches

    def body(carry, x):
        c, s, sl, st = carry
        r, v, mm = x

        def miss(_):
            c2, s2, sl2 = fold_batch(c, s, sl, seeds, slot, r, v)
            return c2, s2, sl2, st + 1

        return jax.lax.cond(mm, miss, lambda _: (c, s, sl, st),
                            None), None

    (counts, cms, slots, stale), _ = jax.lax.scan(
        body, (counts, cms, slots, stale),
        (rows_stack, valid_stack, mismatch))
    return counts, cms, slots, stale


#: bound on the (rows, mask) → delta-plan memo (cleared wholesale past
#: it, the ones_mask cache discipline)
_MAX_PLANS = 128

#: buffered folds flushed per coalesced dispatch (the amortization
#: window: steady state pays one dispatch per _FLUSH_CAP folds instead
#: of one per executing group)
_FLUSH_CAP = 32


@partial(jax.jit, static_argnames=("k", "n_shards"))
def _snapshot_kernel(counts, cms, seeds, k: int, n_shards: int):
    """Device-side snapshot of one arena: candidate top-K, per-shard
    sums, and the skew gauges — everything reduced ON DEVICE so the d2h
    transfer is a handful of tiny arrays, never the counts column."""
    total = jnp.sum(counts)
    vals, rows = jax.lax.top_k(counts, k)
    shard = jnp.sum(counts.reshape(n_shards, -1), axis=1)
    s = jnp.sort(counts)
    nz = s > 0
    nnz = jnp.sum(nz)
    nnz_f = jnp.maximum(nnz, 1).astype(jnp.float32)
    # Gini over the LIVE (nonzero) rows: sorted ascending, the zeros
    # occupy ranks below every live row, so rank-within-nonzero is the
    # running cumsum of the nonzero mask
    rank = jnp.cumsum(nz.astype(jnp.int32))
    g = jnp.where(nz, (2.0 * rank - nnz_f - 1.0) * s.astype(jnp.float32),
                  0.0)
    total_f = jnp.maximum(total, 1).astype(jnp.float32)
    gini = jnp.sum(g) / (nnz_f * total_f)
    cap = counts.shape[0]
    pos = jnp.clip(cap - nnz + ((nnz - 1) * 99) // 100, 0, cap - 1)
    p99 = s[pos]
    mean_nz = total_f / nnz_f
    est = jnp.min(cms[jnp.arange(cms.shape[0], dtype=jnp.int32)[:, None],
                      cms_hash(rows, seeds, cms.shape[1])], axis=0)
    return vals, rows, shard, total, gini, p99, mean_nz, nnz, est


@jax.jit
def _gather_counts(counts, rows):
    """Small pow2-padded gather for eviction retirement / candidate
    cross-checks (the padding rows gather row 0; callers slice)."""
    return counts[jnp.clip(rows, 0, counts.shape[0] - 1)]


@jax.jit
def _zero_rows(counts, rows):
    return counts.at[rows].set(0, mode="drop")


class WorkloadAttribution:
    """Per-engine workload attribution plane (see module docstring).

    Accumulator lifecycle mirrors DeviceLatencyLedger: arrays are
    created lazily at the arena's current capacity, ride fused windows
    as undonated carry, snapshot/restore for rollback, and fold to host
    on reshard.  ``d2h_fetches`` counts snapshot transfers (the budget
    test pins one per snapshot call)."""

    def __init__(self, engine, enabled: bool = True, top_k: int = 16,
                 cms_depth: int = 4, cms_width: int = 8192,
                 slots: Optional[SlotRegistry] = None) -> None:
        self.engine = engine
        self.enabled = enabled
        self.top_k = max(1, int(top_k))
        self.cms_depth = max(1, min(int(cms_depth), MAX_CMS_DEPTH))
        self.cms_width = pow2ceil(max(16, int(cms_width)))
        self.slots = slots if slots is not None else SlotRegistry()
        self._counts: Dict[str, jnp.ndarray] = {}   # type → int32[capacity]
        self._cms: Dict[str, jnp.ndarray] = {}      # type → [depth, width]
        self._slot_counts: Optional[jnp.ndarray] = None  # int32[MAX_SLOTS]
        self._seeds: Optional[jnp.ndarray] = None
        # host mirror of counts RETIRED off the device column (eviction,
        # reshard): per type, grain key → messages.  Merged per key at
        # snapshot so totals survive eviction epochs bit-exactly.
        self._retired: Dict[str, Dict[int, int]] = {}
        self.records = 0
        self.d2h_fetches = 0
        self.retired_rows = 0
        self._retire_version = 0
        self._snap_cache: Optional[Tuple[Tuple[int, int], Dict]] = None
        # (type, method) → (anchor, mask, epoch, plan): the dense delta
        # plans; entries hold the anchoring arrays so a recycled id can
        # never alias a dead buffer, and plan = (rows, valid,
        # counts_delta, cms_delta, n) with the baked content the
        # checked kernel verifies on device
        self._plans: Dict[Tuple[str, str], Tuple] = {}
        self._stale: Optional[jnp.ndarray] = None  # device mismatch count
        self._last_stale = 0
        self._slot_dev: Dict[int, jnp.ndarray] = {}  # slot → device scalar
        # buffered (type, slot, plan, rows, mask, checked) folds —
        # flushed coalesced on the cap or before any accumulator read
        self._pending: List[Tuple] = []
        self.plan_hits = 0
        self.plan_checked = 0
        self.plan_builds = 0

    # -- configuration -------------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  top_k: Optional[int] = None,
                  cms_depth: Optional[int] = None,
                  cms_width: Optional[int] = None) -> None:
        """Live-reload surface (silo.update_config re-push).  Changing
        the sketch layout resets the accumulated sketch (its shape is
        part of every compiled fold signature); the counts columns and
        retired mirror survive a top_k/enable change untouched."""
        self.flush_folds()  # buffered folds assume the OLD layout
        if enabled is not None:
            self.enabled = enabled
        if top_k is not None:
            self.top_k = max(1, int(top_k))
        reshape = False
        if cms_depth is not None:
            d = max(1, min(int(cms_depth), MAX_CMS_DEPTH))
            reshape |= d != self.cms_depth
            self.cms_depth = d
        if cms_width is not None:
            w = pow2ceil(max(16, int(cms_width)))
            reshape |= w != self.cms_width
            self.cms_width = w
        if reshape:
            self._cms = {}
            self._seeds = None
            self._plans = {}  # plans bake the sketch layout
        self._snap_cache = None

    def build_signature(self) -> Tuple:
        """What a fused window bakes in: a change re-traces (cause
        config_toggle), the prepare() discipline the ledger set."""
        return (self.enabled, self.cms_depth, self.cms_width)

    def reset(self) -> None:
        """Zero everything (bench A/B segment boundaries)."""
        self._pending = []  # zeroed with the accumulators they target
        self._counts = {}
        self._cms = {}
        self._slot_counts = None
        self._retired = {}
        self._retire_version += 1
        self._snap_cache = None

    # -- accumulator access --------------------------------------------------

    def _seed_arr(self) -> jnp.ndarray:
        if self._seeds is None:
            seeds = jnp.asarray(
                np.asarray(CMS_SEEDS[:self.cms_depth], dtype=np.uint32))
            if isinstance(seeds, jax.core.Tracer):
                # created under an abstract trace (fused discovery):
                # trace-local — caching would leak (arena.device_index's
                # guard, applied to every lazy array here)
                return seeds
            self._seeds = seeds
        return self._seeds

    def counts_for(self, type_name: str) -> jnp.ndarray:
        col = self._counts.get(type_name)
        arena = self.engine.arenas.get(type_name)
        cap = arena.capacity if arena is not None \
            else self.engine.initial_capacity
        if col is None or col.shape[0] != cap:
            if col is not None:
                # capacity changed without a remap/fold hook firing
                # (direct arena surgery in tests): fold what we can
                self.fold_type(type_name)
            col = arena._dev_zeros_i32(cap) if arena is not None \
                else jnp.zeros(cap, jnp.int32)
            if isinstance(col, jax.core.Tracer):
                return col  # trace-local (see _seed_arr)
            self._counts[type_name] = col
        return col

    def cms_for(self, type_name: str) -> jnp.ndarray:
        sk = self._cms.get(type_name)
        if sk is None or isinstance(sk, np.ndarray):
            # a numpy entry is a relocated sketch (host-parked across a
            # mesh reshard) — re-upload on the current device set
            sk = jnp.asarray(sk) if sk is not None else \
                jnp.zeros((self.cms_depth, self.cms_width), jnp.int32)
            if isinstance(sk, jax.core.Tracer):
                return sk  # trace-local (see _seed_arr)
            self._cms[type_name] = sk
        return sk

    def _slot_arr(self) -> jnp.ndarray:
        if self._slot_counts is None or \
                isinstance(self._slot_counts, np.ndarray):
            slots = jnp.asarray(self._slot_counts) \
                if self._slot_counts is not None \
                else jnp.zeros(MAX_SLOTS, jnp.int32)
            if isinstance(slots, jax.core.Tracer):
                return slots  # trace-local (see _seed_arr)
            self._slot_counts = slots
        return self._slot_counts

    # -- hot path ------------------------------------------------------------

    def _stale_arr(self) -> jnp.ndarray:
        if self._stale is None:
            stale = jnp.zeros((), jnp.int32)
            if isinstance(stale, jax.core.Tracer):
                return stale  # trace-local (see _seed_arr)
            self._stale = stale
        return self._stale

    def _slot_scalar(self, slot: int) -> jnp.ndarray:
        """Device scalar per slot, cached — a per-fold ``jnp.int32``
        literal costs a small h2d on every dispatch (bounded: slots are
        capped at MAX_SLOTS)."""
        s = self._slot_dev.get(slot)
        if s is None:
            s = jnp.int32(slot)
            if isinstance(s, jax.core.Tracer):
                return s  # trace-local (see _seed_arr)
            self._slot_dev[slot] = s
        return s

    def record_group(self, arena, type_name: str, method: str,
                     rows, mask, ident=None) -> None:
        """One executing (type, method) group's fold — the engine's
        dispatch-phase accumulation point.  Steady state costs a host
        list append: the fold is BUFFERED (with its resolved delta
        plan) and flushed as coalesced device kernels on the buffer cap
        or before any read — integer adds commute, so k buffered folds
        of one plan land as one ``k·delta`` multiply-add, bit-exact.
        A plan is proven applicable one of two ways:

        * **host-proven** — the batch's anchor (``ident``: the stable
          ``keys_dev`` buffer, else ``rows`` itself on the injector
          fast path) is the SAME immutable device array the plan was
          built from, and for ident-anchored plans the arena's
          (generation, eviction_epoch, live_count) triple is unchanged
          so the key→row map cannot have moved.
        * **device-checked** — emit batches' rows are jit program
          outputs (fresh buffers every tick even at constant values):
          the flush kernel compares content on device and falls back
          to the full scatter fold in-kernel on mismatch, bumping a
          stale counter the next snapshot reads to refresh the plan.

        A novel batch builds its plan (the one scatter-shaped cost,
        measured in the bench oracle tier) at record time."""
        if not self.enabled:
            return
        slot = self.slots.slot_for(type_name, method)
        counts = self.counts_for(type_name)
        cms = self.cms_for(type_name)
        anchor = rows if ident is None else ident
        epoch = (arena.generation, arena.eviction_epoch,
                 arena.live_count) if arena is not None else None
        key = (type_name, method)
        entry = self._plans.get(key)
        plan = None
        checked = False
        if entry is not None:
            e_anchor, e_mask, e_epoch, e_plan = entry
            shapes_ok = (e_plan[2].shape[0] == counts.shape[0]
                         and e_plan[3].shape == cms.shape
                         and getattr(rows, "shape", None)
                         == e_plan[0].shape)
            if shapes_ok and e_anchor is anchor and e_mask is mask \
                    and (ident is None or e_epoch == epoch):
                plan = e_plan
                self.plan_hits += 1
            elif shapes_ok and isinstance(rows, jax.Array) \
                    and isinstance(mask, jax.Array):
                plan = e_plan
                checked = True
                self.plan_checked += 1
        if plan is None:
            rows_d = jnp.asarray(rows, jnp.int32)
            mask_d = jnp.asarray(mask, bool)
            delta = _plan_kernel(rows_d, mask_d, self._seed_arr(),
                                 cap=counts.shape[0],
                                 width=cms.shape[1],
                                 depth=cms.shape[0])
            plan = (rows_d, mask_d) + delta
            rows, mask = rows_d, mask_d
            self.plan_builds += 1
            if isinstance(anchor, jax.Array) \
                    and isinstance(mask, jax.Array):
                if len(self._plans) >= _MAX_PLANS:
                    self._plans.clear()
                self._plans[key] = (anchor, mask, epoch, plan)
        self._pending.append((type_name, slot, plan, rows, mask,
                              checked))
        self.records += 1
        self._snap_cache = None
        if len(self._pending) >= _FLUSH_CAP:
            self.flush_folds()

    def flush_folds(self) -> None:
        """Apply every buffered fold in coalesced device kernels: runs
        of one plan collapse to a single ``k·delta`` multiply-add
        (host-proven) or one stacked compare + per-mismatch scatter
        scan (device-checked).  Re-entrant safe (the buffer swaps out
        first); called on the buffer cap and before ANY read or
        row-lifecycle mutation of the accumulators."""
        if not self._pending:
            return
        if not jax.core.trace_state_clean():
            # under an ACTIVE trace (fused window trace, AOT lower,
            # discovery eval_shape) a jit call inlines into the outer
            # trace and returns TRACERS — storing those would poison
            # the accumulators for every later concrete call.  Defer:
            # the pre-run device_state_in / the next concrete read
            # flushes (traces only need avals, and shapes don't move).
            return
        pending, self._pending = self._pending, []
        groups: Dict[Tuple, List] = {}
        order: List[Tuple] = []
        for e in pending:
            key = (e[0], e[1], id(e[2]), e[5])
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(e)
        for key in order:
            entries = groups[key]
            type_name, slot, _pid, checked = key
            counts = self.counts_for(type_name)
            cms = self.cms_for(type_name)
            plan = entries[0][2]
            plan_rows, plan_valid, cdelta, sdelta, n = plan
            if cdelta.shape[0] != counts.shape[0] \
                    or sdelta.shape != cms.shape:
                # accumulator layout changed under the buffer (live
                # sketch reconfigure, direct arena surgery): replay
                # each fold from its retained ground-truth rows — the
                # rows are the truth in BOTH regimes, so a rebuilt plan
                # applies proven
                for e in entries:
                    d = _plan_kernel(
                        jnp.asarray(e[3], jnp.int32),
                        jnp.asarray(e[4], bool), self._seed_arr(),
                        cap=counts.shape[0], width=cms.shape[1],
                        depth=cms.shape[0])
                    counts, cms, slots = _apply_coalesced(
                        counts, cms, self._slot_arr(), d[0], d[1],
                        self._slot_scalar(slot), d[2], jnp.int32(1))
                    self._counts[type_name] = counts
                    self._cms[type_name] = cms
                    self._slot_counts = slots
                continue
            if checked:
                k = len(entries)
                pad = pow2ceil(k)
                rows_stack = jnp.stack(
                    [jnp.asarray(e[3], jnp.int32) for e in entries]
                    + [plan_rows] * (pad - k))
                valid_stack = jnp.stack(
                    [jnp.asarray(e[4], bool) for e in entries]
                    + [plan_valid] * (pad - k))
                real = jnp.asarray(
                    np.arange(pad) < k)
                counts, cms, slots, stale = _apply_checked_stack(
                    counts, cms, self._slot_arr(), self._stale_arr(),
                    plan_rows, plan_valid, cdelta, sdelta, n,
                    self._seed_arr(), self._slot_scalar(slot),
                    rows_stack, valid_stack, real)
                self._stale = stale
            else:
                counts, cms, slots = _apply_coalesced(
                    counts, cms, self._slot_arr(), cdelta, sdelta,
                    self._slot_scalar(slot), n,
                    jnp.int32(len(entries)))
            self._counts[type_name] = counts
            self._cms[type_name] = cms
            self._slot_counts = slots

    # -- fused-program integration -------------------------------------------

    def device_state_in(self, touched: List[str]) -> Dict[str, Any]:
        """The accumulator pytree handed INTO a fused window program
        (tensor/fused.py threads it through the scan; empty when the
        plane is disabled so the window signature stays stable)."""
        if not self.enabled:
            return {}
        self.flush_folds()  # the window must see every recorded fold
        return {"counts": {t: self.counts_for(t) for t in touched},
                "cms": {t: self.cms_for(t) for t in touched},
                "slots": self._slot_arr()}

    def device_state_out(self, attr: Dict[str, Any]) -> None:
        if not attr:
            return
        self._counts.update(attr["counts"])
        self._cms.update(attr["cms"])
        self._slot_counts = attr["slots"]
        self.records += 1
        self._snap_cache = None

    def snapshot_state(self) -> Tuple:
        """Rollback pin for the auto-fuser's verification chain: array
        references are safe to hold — fused windows never donate their
        attribution inputs, and no unfused fold can run mid-chain (the
        ledger's snapshot_state invariant)."""
        self.flush_folds()  # pin post-flush arrays; none recorded mid-chain
        return (dict(self._counts), dict(self._cms), self._slot_counts,
                {t: dict(d) for t, d in self._retired.items()},
                self.retired_rows)

    def restore_state(self, state: Tuple) -> None:
        """Undo every fold since ``snapshot_state`` — a rolled-back
        window's unfused replay re-records every message."""
        (self._counts, self._cms, self._slot_counts,
         self._retired, self.retired_rows) = state
        self._retire_version += 1
        self._snap_cache = None

    # -- row lifecycle hooks (arena calls these) -----------------------------

    def has_state(self, type_name: str) -> bool:
        return type_name in self._counts

    def on_evict(self, arena, victims: np.ndarray,
                 keys: np.ndarray) -> None:
        """Retire evicted rows' counts to the host mirror before their
        slots return to the free list (a reused slot must never inherit
        the evicted grain's traffic).  One small gather d2h per eviction
        chunk — the deactivation path is already host-synchronous."""
        self.flush_folds()  # retire POST-fold counts, not a stale column
        col = self._counts.get(arena.info.name)
        if col is None or len(victims) == 0:
            return
        idx = _pow2_pad(victims.astype(np.int32), 0)
        vals = np.asarray(_gather_counts(col, jnp.asarray(idx)))[
            :len(victims)]
        retired = self._retired.setdefault(arena.info.name, {})
        nz = vals > 0
        for k, v in zip(keys[nz].tolist(), vals[nz].tolist()):
            retired[k] = retired.get(k, 0) + int(v)
        self._counts[arena.info.name] = _zero_rows(
            col, jnp.asarray(_pow2_pad(
                victims.astype(np.int32), col.shape[0])))
        self.retired_rows += len(victims)
        self._retire_version += 1
        self._snap_cache = None

    def remap_rows(self, arena, old_rows: np.ndarray,
                   new_rows: np.ndarray, new_capacity: int) -> None:
        """Row move (growth/compaction): relocate the counts on device,
        the ``last_use_dev`` discipline — no transfer, keys keep their
        totals."""
        self.flush_folds()  # buffered folds target the OLD row layout:
        # applying them after the move would scatter into rows that are
        # now free or owned by other grains (the flush-before-any-
        # row-lifecycle-mutation rule on_evict/fold_type already follow)
        col = self._counts.get(arena.info.name)
        if col is None:
            return
        idx = jnp.asarray(old_rows, jnp.int32)
        dst = jnp.asarray(new_rows, jnp.int32)
        self._counts[arena.info.name] = \
            arena._dev_zeros_i32(new_capacity).at[dst].set(col[idx])
        self._snap_cache = None

    def fold_type(self, type_name: str, arena=None) -> None:
        """Fold one arena's device counts into the host retired mirror
        and drop the column (mesh reshard: the array is committed to the
        old device set — ledger.relocate's reasoning).  Idempotent."""
        self.flush_folds()
        col = self._counts.pop(type_name, None)
        if col is None:
            return
        arena = arena if arena is not None \
            else self.engine.arenas.get(type_name)
        if arena is None or arena.capacity != col.shape[0]:
            return  # keys unrecoverable; counts are lost (noted in stats)
        vals = np.asarray(jax.device_get(col))
        rows = np.nonzero(vals)[0]
        keys = arena._key_of_row[rows]
        live = keys >= 0
        retired = self._retired.setdefault(type_name, {})
        for k, v in zip(keys[live].tolist(), vals[rows[live]].tolist()):
            retired[k] = retired.get(k, 0) + int(v)
        self._retire_version += 1
        self._snap_cache = None

    def relocate(self) -> None:
        """Engine reshard: fold every arena's counts to host while the
        key→row mirrors still describe the old layout, and park the
        sketches/slot counters as host numpy — every device array here
        may be committed to the OLD device set (they ride fused-window
        outputs), and a mixed-device jit after a mesh change would
        reject them (ledger.relocate's reasoning).  The next fold
        re-uploads on the new device set; totals survive."""
        self.flush_folds()
        for name in list(self._counts):
            self.fold_type(name)
        for name, sk in list(self._cms.items()):
            if not isinstance(sk, np.ndarray):
                self._cms[name] = np.asarray(jax.device_get(sk))
        if self._slot_counts is not None \
                and not isinstance(self._slot_counts, np.ndarray):
            self._slot_counts = np.asarray(
                jax.device_get(self._slot_counts))
        # the delta plans and the stale counter are committed to the
        # old device set too; plans rebake from live batches, the
        # counter is advisory and restarts at zero
        self._plans = {}
        self._stale = None
        self._snap_cache = None

    # -- snapshots -----------------------------------------------------------

    def _confidence(self) -> float:
        return 1.0 - math.exp(-float(self.cms_depth))

    def snapshot(self, cache: bool = True) -> Dict[str, Any]:
        """The attribution snapshot: per-arena HotSet + skew gauges +
        per-method traffic, ONE batched ``device_get`` for all arenas'
        reduced outputs (d2h_fetches counts it; the transfer-budget test
        pins one per call).  ``cache=True`` reuses the last snapshot
        while no fold/retire has happened since — the load publisher's
        1s cadence must not turn snapshots into per-second device
        traffic on an idle silo."""
        self.flush_folds()
        key = (self.records, self._retire_version)
        if cache and self._snap_cache is not None \
                and self._snap_cache[0] == key:
            return self._snap_cache[1]
        pend: Dict[str, Any] = {}
        metas: Dict[str, Any] = {}
        for type_name, col in self._counts.items():
            arena = self.engine.arenas.get(type_name)
            if arena is None or arena.capacity != col.shape[0]:
                continue
            pend[type_name] = _snapshot_kernel(
                col, self.cms_for(type_name), self._seed_arr(),
                k=min(self.top_k, col.shape[0]), n_shards=arena.n_shards)
            metas[type_name] = arena
        if self._slot_counts is not None:
            pend["__slots__"] = self._slot_arr()
        if self._stale is not None:
            pend["__stale__"] = self._stale
        fetched = jax.device_get(pend) if pend else {}
        if pend:
            self.d2h_fetches += 1
        stale_now = int(fetched.get("__stale__", self._last_stale))
        if stale_now > self._last_stale:
            # checked applies mismatched since the last snapshot: the
            # baked plan content drifted from the live batches — drop
            # the plans so the next fold rebakes from current content
            self._plans.clear()
        self._last_stale = stale_now
        arenas: Dict[str, Any] = {}
        for type_name, arena in metas.items():
            vals, rows, shard, total, gini, p99, mean_nz, nnz, est = \
                fetched[type_name]
            retired = self._retired.get(type_name, {})
            cand: Dict[int, Dict[str, int]] = {}
            for v, r, e in zip(vals.tolist(), rows.tolist(), est.tolist()):
                if v <= 0:
                    continue
                k = int(arena._key_of_row[r])
                if k < 0:
                    continue  # freed between fold and snapshot
                cand[k] = {"msgs": int(v), "sketch": int(e)}
            # merge retired: candidates gain their retired history
            # (msgs AND sketch — the retired mirror is exact, so adding
            # it to the live-row CMS estimate keeps the published bound
            # one-sided even though the sketch hashed the OLD row); a
            # retired key that could displace the smallest candidate
            # joins (its live remainder cross-checked in one gather)
            for k, v in cand.items():
                if k in retired:
                    v["msgs"] += retired[k]
                    v["sketch"] += retired[k]
            if retired:
                # the floor only gates admission when the candidate set
                # is already full — with free top-K slots every retired
                # key joins (the evicted-but-hot grains are exactly the
                # ones an overloaded silo's rebalancer must see)
                floor = min((v["msgs"] for v in cand.values()), default=0) \
                    if len(cand) >= self.top_k else 0
                extra = [(k, c) for k, c in retired.items()
                         if k not in cand and c > floor]
                extra.sort(key=lambda kv: -kv[1])
                extra = extra[:self.top_k]
                if extra:
                    ekeys = np.asarray([k for k, _ in extra], np.int64)
                    erows, found = arena.lookup_rows(ekeys)
                    live_counts = np.zeros(len(extra), np.int64)
                    if found.any():
                        idx = _pow2_pad(
                            erows[found].astype(np.int32), 0)
                        live_counts[found] = np.asarray(_gather_counts(
                            self._counts[type_name],
                            jnp.asarray(idx)))[:int(found.sum())]
                        self.d2h_fetches += 1
                    for (k, c), lc in zip(extra, live_counts.tolist()):
                        cand[k] = {"msgs": int(c) + int(lc),
                                   "sketch": int(c) + int(lc)}
            retired_total = sum(retired.values())
            grand = int(total) + retired_total
            hot = sorted(cand.items(), key=lambda kv: -kv[1]["msgs"])
            hot = hot[:self.top_k]
            conf = self._confidence()
            # sketch_est clamps below at the exact count: a row move
            # (growth remap / compaction) strands the key's sketch
            # history in buckets hashed from the OLD row, so the raw
            # live-row estimate can undercount — the clamp keeps the
            # published one-sided bound true unconditionally
            hot_set = [{
                "key": k,
                "msgs": v["msgs"],
                "share": round(v["msgs"] / grand, 6) if grand else 0.0,
                "sketch_est": max(v["sketch"], v["msgs"]),
                "confidence": round(conf, 6),
            } for k, v in hot]
            shard_l = shard.tolist()
            arenas[type_name] = {
                "hot": hot_set,
                "total_msgs": grand,
                "live_msgs": int(total),
                "retired_msgs": retired_total,
                "topk_share": round(sum(h["msgs"] for h in hot_set)
                                    / grand, 6) if grand else 0.0,
                "skew": {
                    "max_shard_share": round(max(shard_l) / int(total), 6)
                    if int(total) else 0.0,
                    "gini": round(float(gini), 6),
                    "p99_to_mean": round(float(p99) / float(mean_nz), 4)
                    if float(mean_nz) else 0.0,
                    "hot_rows": int(nnz),
                },
                "shard_msgs": shard_l,
            }
        methods: Dict[str, int] = {}
        slots = fetched.get("__slots__")
        if slots is not None:
            for (t, m), s in self.slots.items():
                if int(slots[s]):
                    methods[f"{t}.{m}"] = int(slots[s])
        out = {
            "arenas": arenas,
            "methods": methods,
            "top_k": self.top_k,
            "sketch": {
                "depth": self.cms_depth,
                "width": self.cms_width,
                "epsilon": math.e / self.cms_width,
                "confidence": round(self._confidence(), 6),
            },
        }
        self._snap_cache = (key, out)
        return out

    def hot_set(self) -> List[Dict[str, Any]]:
        """The flattened HotSet contract for the load-publisher
        broadcast and the rebalancer: one entry per hot grain across all
        arenas, sorted by estimated message share."""
        if not self.enabled:
            return []
        snap = self.snapshot(cache=True)
        out = []
        for type_name, a in snap["arenas"].items():
            for h in a["hot"]:
                out.append({"arena": type_name, **h})
        out.sort(key=lambda h: -h["msgs"])
        return out[:self.top_k]

    def per_key_totals(self, type_name: str) -> Dict[int, int]:
        """EXACT per-grain totals, live + retired merged per key — the
        oracle-comparison surface (bench attribution tier, epoch
        bit-exactness tests).  Pays one full-column d2h; diagnostics
        only, never on the publish path."""
        self.flush_folds()
        out = {k: int(v)
               for k, v in self._retired.get(type_name, {}).items()}
        col = self._counts.get(type_name)
        arena = self.engine.arenas.get(type_name)
        if col is None or arena is None \
                or arena.capacity != col.shape[0]:
            return out
        vals = np.asarray(jax.device_get(col))
        self.d2h_fetches += 1
        rows = np.nonzero(vals)[0]
        keys = arena._key_of_row[rows]
        live = keys >= 0
        for k, v in zip(keys[live].tolist(), vals[rows[live]].tolist()):
            out[k] = out.get(k, 0) + int(v)
        return out

    def stats(self) -> Dict[str, Any]:
        """Cheap host-side plane health (no transfer)."""
        return {
            "enabled": self.enabled,
            "top_k": self.top_k,
            "cms_depth": self.cms_depth,
            "cms_width": self.cms_width,
            "tracked_arenas": len(self._counts),
            "records": self.records,
            "d2h_fetches": self.d2h_fetches,
            "retired_rows": self.retired_rows,
            "retired_keys": sum(len(d) for d in self._retired.values()),
            "plan_hits": self.plan_hits,
            "plan_checked": self.plan_checked,
            "plan_builds": self.plan_builds,
            "pending_folds": len(self._pending),
            "stale_folds": self._last_stale,
            "fold_compiles": fold_compiles(),
        }


def fold_compiles() -> int:
    """Compiled variants of the hot-path kernels (apply: one per
    accumulator layout; plan: one per batch shape ladder rung) — the
    compile-count half of the plane's cost contract, pinned by the
    budget test like the ledger's."""
    total = 0
    for kernel in (_apply_coalesced, _apply_checked_stack, _plan_kernel):
        size = getattr(kernel, "_cache_size", None)
        if size is None:
            continue
        try:
            total += int(size())
        except Exception:  # noqa: BLE001 — jax-version-specific API
            pass
    return total
