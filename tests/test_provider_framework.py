"""Provider framework: named-config loader, bootstrap providers, DI
startup hook, and the file-based table backend family.

Reference analogs: ProviderLoader.cs (named <Provider> blocks),
BootstrapProviderManager.cs, ConfigureStartupBuilder.cs:40 (DI), and the
interchangeable table backends (AzureBasedMembershipTable.cs:37 /
SqlMembershipTable.cs:34 — here: file-locked JSON vs sqlite).
"""

import asyncio

import pytest

from orleans_tpu import Grain, grain_interface
from orleans_tpu.core.grain import grain_class
from orleans_tpu.ids import GrainId
from orleans_tpu.plugins.file_tables import (
    FileMembershipTable,
    FileReminderTable,
)
from orleans_tpu.providers.loader import ProviderConfiguration, ProviderLoader
from orleans_tpu.runtime.reminders import ReminderEntry
from orleans_tpu.runtime.silo import Silo

from tests.test_plugins import _membership_contract


# ---------------------------------------------------------------------------
# file table backends run the SAME contract suite as sqlite/in-memory
# ---------------------------------------------------------------------------

def test_file_membership_table_contract(run, tmp_path):
    _membership_contract(run, FileMembershipTable(
        str(tmp_path / "members.json")))


def test_file_membership_table_survives_reopen(run, tmp_path):
    """A second table object over the same path (≈ another process) sees
    the rows and respects the CAS state."""

    async def go():
        from orleans_tpu.runtime.membership import (
            CasConflictError,
            MembershipEntry,
            SiloStatus,
        )
        from orleans_tpu.ids import SiloAddress

        path = str(tmp_path / "shared.json")
        t1 = FileMembershipTable(path)
        _, v = await t1.read_all()
        entry = MembershipEntry(silo=SiloAddress("h", 1, 1),
                                status=SiloStatus.ACTIVE)
        await t1.insert_row(entry, v)

        t2 = FileMembershipTable(path)  # fresh handle, same file
        snap, v2 = await t2.read_all()
        assert snap[entry.silo][0].status == SiloStatus.ACTIVE
        with pytest.raises(CasConflictError):
            await t2.insert_row(entry, v2)  # row exists
        entry.status = SiloStatus.DEAD
        await t2.update_row(entry, snap[entry.silo][1], v2)
        snap1, _ = await t1.read_all()
        assert snap1[entry.silo][0].status == SiloStatus.DEAD

    run(go())


def test_file_reminder_table_contract(run, tmp_path):
    async def go():
        path = str(tmp_path / "reminders.json")
        table = FileReminderTable(path)
        gid = GrainId.from_int(1234, 77)
        assert await table.read_row(gid, "r1") is None
        etag = await table.upsert_row(
            ReminderEntry(grain_id=gid, name="r1", start_at=1.0, period=2.0))
        row = await table.read_row(gid, "r1")
        assert row.etag == etag and row.period == 2.0
        etag2 = await table.upsert_row(
            ReminderEntry(grain_id=gid, name="r1", start_at=1.0, period=3.0))
        assert etag2 != etag
        assert not await table.remove_row(gid, "r1", etag)  # stale
        # reopen ≈ restart: etags are uuids, stale stays stale
        table2 = FileReminderTable(path)
        assert not await table2.remove_row(gid, "r1", etag)
        assert await table2.remove_row(gid, "r1", etag2)
        assert await table2.read_rows(gid) == []

    run(go())


def test_file_table_backed_cluster(run, tmp_path):
    """Two host-style silos cluster through the FILE membership table over
    TCP — the second backend family passes the same liveness path sqlite
    does."""

    async def main():
        from orleans_tpu.host import build_silo
        from tests.fixture_grains import ICounterGrain  # noqa: F401

        cfg = {"host": "127.0.0.1",
               "membership_file": str(tmp_path / "cluster.json"),
               "reminder_file": str(tmp_path / "reminders.json"),
               "storage": {"Default": {"kind": "memory"}},
               "silo": {"liveness": {
                   "probe_period": 0.1, "probe_timeout": 0.1,
                   "num_missed_probes_limit": 2,
                   "table_refresh_timeout": 0.2,
                   "iam_alive_table_publish": 0.5}}}
        a = build_silo({**cfg, "name": "file-a"})
        b = build_silo({**cfg, "name": "file-b"})
        await a.start()
        await b.start()
        try:
            deadline = asyncio.get_running_loop().time() + 10
            while not (len(a.active_silos()) == 2
                       and len(b.active_silos()) == 2):
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            factory = a.attach_client()
            from tests.fixture_grains import ICounterGrain
            results = await asyncio.gather(
                *(factory.get_grain(ICounterGrain, 9100 + i).add(1)
                  for i in range(8)))
            assert results == [1] * 8
        finally:
            await b.stop()
            await a.stop()

    run(main())


# ---------------------------------------------------------------------------
# provider loader + bootstrap + statistics + DI startup
# ---------------------------------------------------------------------------

def test_provider_loader_blocks(run, tmp_path):
    """Named blocks of every kind instantiate and register; bootstrap
    providers run at silo start with their config; statistics publishers
    report; dotted user types load (the reflective-load analog)."""

    async def main():
        from tests.fixture_startup import RecordingBootstrap

        RecordingBootstrap.initialized.clear()
        silo = Silo(name="provider-silo")
        loader = ProviderLoader()
        loader.load(silo, [
            {"kind": "storage", "type": "memory", "name": "Default"},
            {"kind": "storage", "type": "file", "name": "Files",
             "root": str(tmp_path / "files")},
            {"kind": "stream", "type": "simple", "name": "SMS"},
            {"kind": "bootstrap",
             "type": "tests.fixture_startup:RecordingBootstrap",
             "name": "warmup", "properties": {"level": 3}},
            {"kind": "statistics",
             "type": "orleans_tpu.plugins.stats_publisher:"
                     "LogStatisticsPublisher", "name": "log"},
        ])
        assert set(silo.storage_providers) == {"Default", "Files"}
        assert "SMS" in silo.stream_providers
        assert "warmup" in silo.bootstrap_providers
        assert "log" in silo.statistics_publishers

        await silo.start()
        try:
            assert RecordingBootstrap.initialized == [
                ("warmup", "provider-silo", {"level": 3})]
        finally:
            await silo.stop()

    run(main())


def test_provider_configuration_from_dict():
    cfg = ProviderConfiguration.from_dict(
        {"kind": "storage", "type": "sqlite", "name": "S",
         "path": "x.db", "properties": {"extra": 1}})
    assert cfg.properties == {"path": "x.db", "extra": 1}
    assert (cfg.kind, cfg.type, cfg.name) == ("storage", "sqlite", "S")


@grain_interface
class IServiceUser:
    async def mail(self, to: str) -> int: ...


@grain_class
class ServiceUserGrain(Grain, IServiceUser):
    async def mail(self, to: str) -> int:
        mailer = self.service("mailer")
        mailer.send(to, "hello")
        return len(mailer.sent)


def test_startup_hook_registers_services(run, tmp_path):
    """The host config's startup hook populates silo.services and grains
    resolve them via Grain.service() (the DI analog)."""

    async def main():
        from orleans_tpu.host import build_silo

        silo = build_silo({
            "name": "di-host", "host": "127.0.0.1",
            "storage": {"Default": {"kind": "memory"}},
            "startup": "tests.fixture_startup:configure",
        })
        await silo.start()
        try:
            assert silo.services["region"] == "test-region"
            factory = silo.attach_client()
            ref = factory.get_grain(IServiceUser, 1)
            assert await ref.mail("a@b") == 1
            assert await ref.mail("c@d") == 2
            assert silo.services["mailer"].sent[0] == ("a@b", "hello")
        finally:
            await silo.stop()

    run(main())


def test_live_config_reload(run):
    """update_config applies partial overrides to the RUNNING silo —
    nested sections mutate the live dataclasses, component-copied values
    are re-pushed, and subscribers fire (reference: OnConfigChange)."""

    async def main():
        silo = Silo(name="reload-silo")
        await silo.start()
        try:
            seen = []
            silo.on_config_change(lambda cfg: seen.append(
                cfg.messaging.response_timeout))

            assert silo.runtime_client.response_timeout == 30.0
            silo.update_config({
                "messaging": {"response_timeout": 7.5,
                              "deadlock_detection": False},
                "collection": {"default_age_limit": 123.0},
                "watchdog_period": 9.0,
                "name": "must-not-change",  # identity: ignored
            })
            assert silo.config.messaging.response_timeout == 7.5
            assert silo.runtime_client.response_timeout == 7.5
            assert silo.dispatcher.perform_deadlock_detection is False
            assert silo.catalog.age_limit == 123.0
            assert silo.watchdog.period == 9.0
            assert silo.name == "reload-silo"
            assert seen == [7.5]
        finally:
            await silo.stop()

    run(main())


def test_host_config_file_watch(run, tmp_path):
    """run_host live-applies silo-section edits to the config file."""

    async def main():
        import json

        from orleans_tpu.host import run_host

        path = tmp_path / "watched.json"
        path.write_text(json.dumps({
            "name": "watched", "host": "127.0.0.1",
            "silo": {"messaging": {"response_timeout": 30.0}}}))
        ev = asyncio.Event()
        captured = []
        task = asyncio.get_running_loop().create_task(
            run_host(json.loads(path.read_text()), shutdown=ev,
                     config_path=str(path), reload_poll=0.05,
                     on_started=captured.append))
        await asyncio.sleep(0.3)
        silo = captured[0]
        assert silo.runtime_client.response_timeout == 30.0

        # a malformed edit is rejected without killing the watcher ...
        path.write_text(json.dumps({
            "name": "watched", "host": "127.0.0.1", "silo": {"messaging": 5}}))
        await asyncio.sleep(0.3)
        assert silo.runtime_client.response_timeout == 30.0

        # ... and the next good edit still applies
        path.write_text(json.dumps({
            "name": "watched", "host": "127.0.0.1",
            "silo": {"messaging": {"response_timeout": 4.0}}}))
        deadline = asyncio.get_running_loop().time() + 5
        while silo.runtime_client.response_timeout != 4.0:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.05)
        assert silo.config.messaging.response_timeout == 4.0
        ev.set()
        await asyncio.wait_for(task, timeout=10.0)

    run(main())


def test_stream_provider_tensor_sinks_from_config(run, tmp_path):
    """The stream→tensor bridge binds from the provider config block
    (`tensor_sinks`), so a hosted silo gets slab injection with no code
    (hosting-exe path: host JSON → loader → bind_tensor_sink)."""

    async def main():
        import asyncio

        import numpy as np

        import tests.test_autofuse  # noqa: F401 — registers LwwGrain
        from orleans_tpu.streams.core import StreamId

        silo = Silo(name="sink-config-silo")
        loader = ProviderLoader()
        loader.load(silo, [
            {"kind": "stream", "type": "persistent_sqlite", "name": "pq",
             "path": str(tmp_path / "sink.db"), "queues": 1,
             "pull_period": 0.01,
             "tensor_sinks": {
                 "lww-events": {"interface": "LwwGrain",
                                "method": "put", "key_field": "key"}}},
        ])
        provider = silo.stream_providers["pq"]
        assert "lww-events" in provider.tensor_sinks

        # a provider type without pulling agents rejects the binding
        # loudly — misconfiguration must never silently drop the bridge
        with pytest.raises(ValueError, match="tensor_sinks"):
            ProviderLoader().load(Silo(name="bad-sink-silo"), [
                {"kind": "stream", "type": "simple", "name": "S",
                 "tensor_sinks": {"x": {"interface": "LwwGrain",
                                        "method": "put"}}}])

        await silo.start()
        try:
            sid = StreamId(provider="pq", namespace="lww-events", key=9)
            n = 32
            keys = np.arange(n, dtype=np.int64)
            await provider.produce(sid, [
                {"key": keys, "v": np.full(n, 4, np.int32)}])

            async def delivered():
                while sum(a.delivered
                          for a in provider.manager.agents.values()) < 1:
                    await asyncio.sleep(0.01)

            await asyncio.wait_for(delivered(), timeout=10)
            await silo.tensor_engine.flush()
            arena = silo.tensor_engine.arena_for("LwwGrain")
            rows = arena.resolve_rows(keys)
            np.testing.assert_array_equal(
                np.asarray(arena.state["count"])[rows], 1)
        finally:
            await silo.stop()

    run(main())


class _AsyncCloseStreamProvider:
    """User stream provider whose async close() releases resources
    acquired in __init__ — and which does NOT support tensor_sinks."""

    instances: list = []

    def __init__(self) -> None:
        self.resource_open = True
        type(self).instances.append(self)

    def init(self, silo, name: str) -> None:
        pass

    async def close(self) -> None:
        self.resource_open = False


def test_rejected_provider_async_close_runs_on_loop(run):
    """ADVICE regression: a provider rejected for unsupported
    tensor_sinks must have its async close() actually EXECUTED (scheduled
    on the running loop), not discarded — else __init__-acquired
    resources leak."""

    async def main():
        _AsyncCloseStreamProvider.instances.clear()
        with pytest.raises(ValueError, match="tensor_sinks"):
            ProviderLoader().load(Silo(name="close-sched-silo"), [
                {"kind": "stream",
                 "type": f"{_AsyncCloseStreamProvider.__module__}:"
                         f"{_AsyncCloseStreamProvider.__name__}",
                 "name": "S",
                 "tensor_sinks": {"x": {"interface": "LwwGrain",
                                        "method": "put"}}}])
        (instance,) = _AsyncCloseStreamProvider.instances
        # the close coroutine is scheduled, not awaited inline — give the
        # loop a beat to run it
        for _ in range(5):
            if not instance.resource_open:
                break
            await asyncio.sleep(0)
        assert not instance.resource_open, \
            "async close() never ran for the rejected provider"

    run(main())
