"""Chirper sample — power-law follower fan-out (the ragged-scatter
benchmark workload).

Parity: reference Samples/Chirper — ChirperAccount publishes a chirp and
forwards it to every follower, each of whom records it in a bounded
received-messages cache (reference:
Samples/Chirper/ChirperGrains/ChirperAccount.cs:129-156 PublishMessage →
Followers loop; NewChirp :261; AddFollower :235).  The follower network
(the sample's NetworkGenerator/NetworkLoader) is power-law: a few
celebrity accounts with huge follower counts, a long tail with few.

TPU-native shape: the follow graph is a device-resident CSR edge table
(``DeviceFanout``); a tick's publishes expand into one flat
(follower_key, chirp) tensor in a single jitted gather — the batched
equivalent of the per-follower RPC loop — and followers absorb the
fan-IN with segment reductions.  Power-law raggedness stresses exactly
what Presence's uniform fan-in does not: per-message emit widths that
vary by orders of magnitude.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from orleans_tpu.core.grain import batched_method
from orleans_tpu.tensor import (
    Batch,
    DeviceFanout,
    VectorGrain,
    field,
    seg_max,
    seg_sum,
    vector_grain,
)


@vector_grain
class ChirperAccount(VectorGrain):
    """Per-account state (reference: ChirperAccount.cs:40 — the publish
    and receive sides of one account grain)."""

    published = field(jnp.int32, 0)       # chirps this account published
    received = field(jnp.int32, 0)        # chirps received from followees
    last_chirp = field(jnp.int32, -1)     # newest chirp id seen
    checksum = field(jnp.float32, 0.0)    # delivery checksum (test oracle)

    @batched_method
    @staticmethod
    def publish(state, batch: Batch, n_rows: int):
        """Record the publish.  Follower fan-out happens through the
        engine-registered DeviceFanout (reference: PublishMessage's
        Followers loop, ChirperAccount.cs:145-156)."""
        rows = batch.rows
        ones = jnp.asarray(batch.mask, jnp.int32)
        return {
            **state,
            "published": state["published"] + seg_sum(ones, rows, n_rows),
        }

    @batched_method
    @staticmethod
    def new_chirp(state, batch: Batch, n_rows: int):
        """Absorb the fan-in from followed accounts (reference:
        ChirperAccount.NewChirp :261 — enqueue into the bounded
        RecentReceivedMessages cache)."""
        rows, args = batch.rows, batch.args
        ones = jnp.asarray(batch.mask, jnp.int32)
        chirp = jnp.asarray(args["chirp_id"], jnp.int32)
        return {
            **state,
            "received": state["received"] + seg_sum(ones, rows, n_rows),
            "last_chirp": jnp.maximum(state["last_chirp"],
                                      seg_max(jnp.where(batch.mask, chirp,
                                                        -1),
                                              rows, n_rows)),
            "checksum": state["checksum"]
            + seg_sum(jnp.where(batch.mask,
                                jnp.asarray(args["src_key"],
                                            jnp.float32) % 97.0,
                      0.0), rows, n_rows),
        }


def build_follow_graph(n_accounts: int, mean_followers: float = 20.0,
                       zipf_a: float = 1.6, seed: int = 0,
                       budget: Optional[int] = None) -> DeviceFanout:
    """Power-law follower network (the NetworkGenerator analog): account
    popularity ~ Zipf, so follower counts span orders of magnitude."""
    rng = np.random.default_rng(seed)
    # popularity weights ~ k^-a over a random permutation of accounts
    ranks = rng.permutation(n_accounts) + 1
    weights = ranks.astype(np.float64) ** (-zipf_a)
    weights /= weights.sum()
    n_edges = int(n_accounts * mean_followers)
    publishers = rng.choice(n_accounts, size=n_edges, p=weights)
    followers = rng.integers(0, n_accounts, size=n_edges)
    # drop self-follows and duplicate edges
    keep = publishers != followers
    edges = np.unique(
        np.stack([publishers[keep], followers[keep]], axis=1), axis=0)
    fanout = DeviceFanout(budget=budget or max(1 << 12, 2 * len(edges)))
    fanout.add_edges(edges[:, 0], edges[:, 1])
    return fanout


async def run_chirper_load(engine, n_accounts: int = 100_000,
                           mean_followers: float = 20.0,
                           n_ticks: int = 10, seed: int = 0,
                           fanout: Optional[DeviceFanout] = None,
                           measure_latency: bool = False
                           ) -> Dict[str, float]:
    """Every account publishes one chirp per tick; each chirp is delivered
    to all followers through the device fan-out.  Message accounting
    matches the reference's Chirper load: one publish RPC + one NewChirp
    per follower edge."""
    import jax as _jax

    if fanout is None:
        fanout = build_follow_graph(n_accounts, mean_followers, seed=seed)
    engine.register_fanout("ChirperAccount", "publish", fanout,
                           "ChirperAccount", "new_chirp")
    engine.arena_for("ChirperAccount").reserve(n_accounts)

    accounts = np.arange(n_accounts, dtype=np.int64)
    injector = engine.make_injector("ChirperAccount", "publish", accounts)
    chirp_ids = jnp.asarray(np.arange(n_accounts, dtype=np.int32))

    arena = engine.arena_for("ChirperAccount")
    tick_durations = []

    t0 = time.perf_counter()
    for t in range(n_ticks):
        tick_t0 = time.perf_counter()
        injector.inject({"chirp_id": chirp_ids + np.int32(t * n_accounts)})
        if measure_latency:
            await engine.flush()
            _jax.block_until_ready(arena.state["received"])
            tick_durations.append(time.perf_counter() - tick_t0)
        else:
            await engine.drain_queues()
    await engine.flush()
    _jax.block_until_ready(arena.state["received"])
    elapsed = time.perf_counter() - t0

    # one publish per account per tick + one delivery per follow edge
    messages = (n_accounts + fanout.edge_count) * n_ticks
    stats: Dict[str, float] = {
        "accounts": n_accounts,
        "edges": fanout.edge_count,
        "ticks": n_ticks,
        "seconds": elapsed,
        "messages": messages,
        "messages_per_sec": messages / elapsed,
        "mean_tick_seconds": elapsed / n_ticks,
    }
    if tick_durations:
        d = np.asarray(tick_durations)
        stats["tick_p50_seconds"] = float(np.percentile(d, 50))
        stats["tick_p99_seconds"] = float(np.percentile(d, 99))
    return stats


async def run_chirper_load_fused(engine, n_accounts: int = 100_000,
                                 mean_followers: float = 20.0,
                                 n_ticks: int = 10, window: int = 10,
                                 seed: int = 0,
                                 fanout: Optional[DeviceFanout] = None,
                                 measure_latency: bool = False
                                 ) -> Dict[str, float]:
    """Chirper through the FUSED tick path: publish kernel + CSR follower
    expansion + new_chirp fan-in compile into one program per window
    (tensor/fused.py; exactness via the device miss counter)."""
    import jax as _jax

    if fanout is None:
        fanout = build_follow_graph(n_accounts, mean_followers, seed=seed)
    engine.register_fanout("ChirperAccount", "publish", fanout,
                           "ChirperAccount", "new_chirp")
    accounts = np.arange(n_accounts, dtype=np.int64)
    engine.arena_for("ChirperAccount").reserve(n_accounts)
    engine.arena_for("ChirperAccount").resolve_rows(accounts)
    prog = engine.fuse_ticks("ChirperAccount", "publish", accounts)
    arena = engine.arena_for("ChirperAccount")

    from orleans_tpu.tensor.fused import plan_windows
    if measure_latency:
        window = 1
    window, n_windows, n_ticks = plan_windows(window, n_ticks)

    def stacked_for(base: int):
        # per-tick chirp ids: one scanned [T, m] leaf
        return {"chirp_id": (jnp.arange(window, dtype=jnp.int32)[:, None]
                             * np.int32(n_accounts)
                             + jnp.arange(n_accounts, dtype=jnp.int32)[None]
                             + np.int32(base * n_accounts))}

    prog.run(stacked_for(0))  # untimed warm window (compile)
    _jax.block_until_ready(arena.state["received"])

    # build every window's args BEFORE timing — eager construction is
    # host-side work the presence loader also excludes, so the two
    # workloads' latency numbers measure the same thing
    windows = [stacked_for(w + 1) for w in range(n_windows)]
    _jax.block_until_ready(windows)

    tick_durations = []
    t0 = time.perf_counter()
    for stacked in windows:
        w0 = time.perf_counter()
        prog.run(stacked)
        if measure_latency:
            _jax.block_until_ready(arena.state["received"])
            tick_durations.append(time.perf_counter() - w0)
    _jax.block_until_ready(arena.state["received"])
    elapsed = time.perf_counter() - t0
    assert prog.verify() == 0, "fused window touched unactivated grains"

    messages = (n_accounts + fanout.edge_count) * n_ticks
    stats: Dict[str, float] = {
        "accounts": n_accounts, "edges": fanout.edge_count,
        "ticks": n_ticks, "seconds": elapsed, "messages": messages,
        "messages_per_sec": messages / elapsed,
        "mean_tick_seconds": elapsed / n_ticks,
        "engine": "fused",
    }
    if tick_durations:
        d = np.asarray(tick_durations)
        stats["tick_p50_seconds"] = float(np.percentile(d, 50))
        stats["tick_p99_seconds"] = float(np.percentile(d, 99))
    return stats
