"""GrainFactory: typed references from (interface, key).

Parity: reference GrainFactory (reference: src/Orleans/GrainFactory.cs:40 —
GetGrain overloads :92-167, Cast :273).  The Cast operation is the
``as_interface`` method (re-typing a reference to another interface the
grain class implements).
"""

from __future__ import annotations

import uuid
from typing import Union

from orleans_tpu.core.grain import get_interface, grain_id_for
from orleans_tpu.core.reference import GrainReference
from orleans_tpu.ids import GrainId


class GrainFactory:

    def get_grain(self, interface, key: Union[int, str, uuid.UUID]
                  ) -> GrainReference:
        """(reference: GrainFactory.GetGrain<T>(key) :92-167)"""
        iface = get_interface(interface)
        grain_id = grain_id_for(interface, key)
        return GrainReference(grain_id, iface.interface_id)

    def get_grain_by_id(self, interface, grain_id: GrainId) -> GrainReference:
        iface = get_interface(interface)
        return GrainReference(grain_id, iface.interface_id)

    def as_interface(self, ref: GrainReference, interface) -> GrainReference:
        """Re-type a reference (reference: GrainFactory.Cast :273)."""
        iface = get_interface(interface)
        return GrainReference(ref.grain_id, iface.interface_id)


factory = GrainFactory()
