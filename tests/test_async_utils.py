"""Async utility suite (reference: TesterInternal AsyncSerialExecutorTests.cs
and the AsyncExecutorWithRetries contracts)."""

import asyncio

import pytest

from orleans_tpu.utils import (
    INFINITE_RETRIES,
    AsyncLock,
    AsyncPipeline,
    AsyncSerialExecutor,
    BatchedContinuationQueue,
    ExponentialBackoff,
    FixedBackoff,
    MultiCompletionSource,
    execute_with_retries,
)


def test_retries_succeeds_after_failures(run):
    calls = []

    async def main():
        async def flaky(attempt):
            calls.append(attempt)
            if attempt < 2:
                raise IOError("transient")
            return "ok"

        return await execute_with_retries(flaky, max_retries=5,
                                          backoff=FixedBackoff(0))

    assert run(main()) == "ok"
    assert calls == [0, 1, 2]


def test_retries_exhausted_raises(run):
    async def main():
        async def always_fails(attempt):
            raise IOError("perm")

        await execute_with_retries(always_fails, max_retries=2,
                                   backoff=FixedBackoff(0))

    with pytest.raises(IOError):
        run(main())


def test_retry_filter_stops_early(run):
    calls = []

    async def main():
        async def fails(attempt):
            calls.append(attempt)
            raise ValueError("fatal")

        await execute_with_retries(
            fails, max_retries=10,
            retry_filter=lambda exc, i: not isinstance(exc, ValueError))

    with pytest.raises(ValueError):
        run(main())
    assert calls == [0]


def test_success_filter_retries_on_bad_result(run):
    async def main():
        async def counter(attempt):
            return attempt

        return await execute_with_retries(
            counter, max_retries=10,
            success_filter=lambda r, i: r >= 3)

    assert run(main()) == 3


def test_max_execution_time(run):
    async def main():
        async def slow(attempt):
            await asyncio.sleep(0.02)
            raise IOError("again")

        await execute_with_retries(slow, max_retries=INFINITE_RETRIES,
                                   max_execution_time=0.05,
                                   backoff=FixedBackoff(0))

    with pytest.raises((TimeoutError, IOError)):
        run(main())


def test_exponential_backoff_bounds():
    b = ExponentialBackoff(min_delay=0.01, max_delay=1.0, step=2.0)
    for i in range(20):
        d = b.next(i)
        assert 0.01 <= d <= 1.0


def test_async_lock_mutual_exclusion(run):
    async def main():
        lock = AsyncLock()
        inside = 0
        max_inside = 0

        async def worker():
            nonlocal inside, max_inside
            async with lock:
                inside += 1
                max_inside = max(max_inside, inside)
                await asyncio.sleep(0.001)
                inside -= 1

        await asyncio.gather(*(worker() for _ in range(10)))
        return max_inside

    assert run(main()) == 1


def test_serial_executor_no_interleaving(run):
    """(reference: AsyncSerialExecutorTests — submitted closures never
    interleave and run FIFO)"""

    async def main():
        ex = AsyncSerialExecutor()
        order = []
        running = 0
        overlap = False

        async def job(i):
            nonlocal running, overlap
            running += 1
            if running > 1:
                overlap = True
            await asyncio.sleep(0.001)
            order.append(i)
            running -= 1
            return i

        results = await asyncio.gather(
            *(ex.execute(lambda i=i: job(i)) for i in range(8)))
        return overlap, order, results

    overlap, order, results = run(main())
    assert not overlap
    assert order == list(range(8))
    assert results == list(range(8))


def test_serial_executor_propagates_exceptions(run):
    async def main():
        ex = AsyncSerialExecutor()

        async def boom():
            raise RuntimeError("x")

        async def fine():
            return 42

        try:
            await ex.execute(boom)
        except RuntimeError:
            pass
        else:
            raise AssertionError("expected RuntimeError")
        return await ex.execute(fine)

    assert run(main()) == 42


def test_pipeline_enforces_capacity(run):
    async def main():
        pipe = AsyncPipeline(capacity=3)
        in_flight = 0
        peak = 0

        async def work():
            nonlocal in_flight, peak
            in_flight += 1
            peak = max(peak, in_flight)
            await asyncio.sleep(0.002)
            in_flight -= 1

        for _ in range(12):
            await pipe.add(work())
        await pipe.wait()
        return peak, pipe.count

    peak, count = run(main())
    assert peak <= 3
    assert count == 0


def test_pipeline_propagates_errors_on_wait(run):
    async def main():
        pipe = AsyncPipeline(capacity=2)

        async def bad():
            raise IOError("task failed")

        await pipe.add(bad())
        await pipe.wait()

    with pytest.raises(IOError):
        run(main())


def test_multi_completion_source(run):
    async def main():
        mcs = MultiCompletionSource(3)
        assert not mcs.task.done()
        mcs.set_one_result()
        mcs.set_one_result()
        assert not mcs.task.done()
        mcs.set_one_result()
        await mcs.task
        try:
            mcs.set_one_result()
        except RuntimeError:
            return True
        return False

    assert run(main())


def test_batched_continuation_queue_flushes_on_count_and_time(run):
    async def main():
        q = BatchedContinuationQueue(flush_count=4, flush_interval=0.01)
        batches = []
        q.on_flush(batches.append)
        for i in range(4):
            q.enqueue(i)
        assert batches == [[0, 1, 2, 3]]  # count gate flushed synchronously
        q.enqueue(99)
        await asyncio.sleep(0.05)          # time gate
        return batches

    assert run(main()) == [[0, 1, 2, 3], [99]]
