"""Durable state plane tests (tensor/checkpoint.py).

The contract under test, end to end:

* a FULL checkpoint is a consistent cut whose restore reconstructs
  per-key state AND row identity (key→row map, generation, eviction
  epoch, free-list high-water) exactly;
* incremental DELTAS select exactly the moved rows (attribution counts
  / clocks / key churn), never span a generation change, and compose
  with the full into the same bit-exact state;
* the device JOURNAL seals ingress batches into durable segments whose
  fold-replay reproduces an uninterrupted engine bit-for-bit at the
  acknowledged horizon — fused and unfused;
* a HARD KILL mid-traffic recovers inside the accounting invariant:
  zero acknowledged-write loss, bounded recovery time;
* the file stores are torn-write safe (tmp + fsync + atomic rename).
"""

import asyncio
import os

import jax.numpy as jnp
import numpy as np
import pytest

import samples.banking as banking
import samples.presence  # noqa: F401 — registers the presence grains
from orleans_tpu.config import TensorEngineConfig
from orleans_tpu.core.grain import batched_method, commutative
from orleans_tpu.tensor import (
    Batch,
    FileSnapshotStore,
    MemorySnapshotStore,
    MemoryVectorStore,
    TensorEngine,
    VectorGrain,
    field,
    seg_sum,
    vector_grain,
)
from orleans_tpu.tensor.vector_grain import scatter_add_rows, vector_type

pytestmark = pytest.mark.durability


def _engine(backing, **cfg_kw):
    cfg = TensorEngineConfig(tick_interval=0.0, auto_fusion_ticks=0,
                             **cfg_kw)
    return TensorEngine(config=cfg,
                        snapshot_store=MemorySnapshotStore(backing))


def _drive_presence(engine, keys, games, n_ticks, start=0):
    inj = engine.make_injector("PresenceGrain", "heartbeat", keys)
    for t in range(start, start + n_ticks):
        inj.inject({"game": games,
                    "score": np.ones(len(keys), np.float32),
                    "tick": np.int32(t + 1)})
        engine.run_tick()


def _arena_state(engine, type_name, keys):
    arena = engine.arena_for(type_name)
    rows, found = arena.lookup_rows(np.asarray(keys, dtype=np.int64))
    assert found.all()
    return {n: np.asarray(c)[rows] for n, c in arena.state.items()}


def test_full_checkpoint_restores_state_and_identity(run):
    """Kill after a sealed full checkpoint: per-key state, row ids,
    generation, eviction epoch and free-list high-water all equal the
    uninterrupted engine's."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        eng = _engine(backing)
        keys = np.arange(300, dtype=np.int64)
        games = (keys % 7).astype(np.int32)
        _drive_presence(eng, keys, games, 6)
        await eng.flush()
        # evict a slice so free lists + epoch are non-trivial
        arena = eng.arena_for("PresenceGrain")
        arena.evict_keys(keys[250:], write_back=False)
        gen0, epoch0 = arena.generation, arena.eviction_epoch
        eng.checkpointer.checkpoint_full()

        eng2 = _engine(backing)
        stats = await eng2.checkpointer.recover()
        assert stats["recovered"]
        a2 = eng2.arena_for("PresenceGrain")
        assert a2.generation == gen0
        assert a2.eviction_epoch == epoch0
        assert a2.live_count == arena.live_count
        assert np.array_equal(a2._key_of_row, arena._key_of_row)
        assert np.array_equal(np.asarray(a2._shard_next),
                              np.asarray(arena._shard_next))
        # free lists as SETS (LIFO order is not identity)
        for f1, f2 in zip(arena._free, a2._free):
            assert set(f1.tolist()) == set(f2.tolist())
        live = keys[:250]
        s1 = _arena_state(eng, "PresenceGrain", live)
        s2 = _arena_state(eng2, "PresenceGrain", live)
        for name in s1:
            assert np.array_equal(s1[name], s2[name]), name

    run(main())


def test_journal_fold_replay_bit_exact_vs_uninterrupted(run):
    """Hard kill with sealed journal tail: the recovered engine equals
    an uninterrupted oracle engine driven with exactly the acknowledged
    command prefix — bit-exact integer state, including the transfer
    emit leg reconstructed by re-execution."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        n_accounts = 200
        events = banking.make_events(n_accounts, 20, lanes=64, seed=7)
        eng = _engine(backing, journal_flush_every_ticks=3)
        banking.register_banking_journal(eng)
        eng.checkpointer.checkpoint_full()  # the base recovery point
        # drive WITHOUT a final flush: a flush is a quiesce and seals
        # the tail — the hard kill must land with ring lanes pending
        for ev in events:
            args = {"amount": ev["amount"]}
            if ev["method"] == "transfer":
                args["dst"] = ev["dst"]
            eng.send_batch("AccountGrain", ev["method"], ev["keys"],
                           args)
            eng.run_tick()
        site = eng.checkpointer.journal.sites[("AccountGrain",
                                               "deposit")]
        site_t = eng.checkpointer.journal.sites[("AccountGrain",
                                                 "transfer")]
        acked = (site.committed_lanes + site_t.committed_lanes) // 64
        assert 0 < acked < len(events)  # a real loss window
        # HARD KILL eng.  Oracle engine: uninterrupted, plane off,
        # driven with exactly the acknowledged prefix (seals are FIFO)
        oracle_eng = TensorEngine(config=TensorEngineConfig(
            tick_interval=0.0, auto_fusion_ticks=0))
        oracle = banking.BankOracle(n_accounts)
        await banking.run_banking_load(oracle_eng, events[:acked],
                                       oracle=oracle)
        eng2 = _engine(backing, journal_flush_every_ticks=4)
        stats = await eng2.checkpointer.recover()
        assert stats["replayed_lanes"] == acked * 64
        probe = np.arange(n_accounts, dtype=np.int64)
        # every account the oracle touched must exist + match; untouched
        # accounts must not be resident with nonzero state
        a2 = eng2.arena_for("AccountGrain")
        touched = np.unique(np.concatenate(
            [np.concatenate([e["keys"],
                             e.get("dst", np.empty(0, np.int64))])
             for e in events[:acked]])).astype(np.int64)
        got = banking.read_accounts(eng2, touched)
        want = oracle.expect(touched)
        for name in ("balance", "credits", "debits"):
            assert np.array_equal(got[name], want[name]), name
        # conservation: the restored total equals total minted
        rows_all, found_all = a2.lookup_rows(probe)
        total = int(np.asarray(a2.state["balance"])[
            rows_all[found_all]].sum())
        assert total == oracle.total()
        # and bit-exact vs the uninterrupted ENGINE too (not just the
        # numpy oracle): same fold order guarantees
        s1 = banking.read_accounts(oracle_eng, touched)
        for name in s1:
            assert np.array_equal(s1[name], got[name]), name

    run(main())


def test_delta_checkpoint_selects_moved_rows_and_composes(run):
    """Between checkpoints only touched rows re-write; full + delta
    compose into the same state a full-at-the-end would give."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        eng = _engine(backing)
        keys = np.arange(400, dtype=np.int64)
        games = (keys % 5).astype(np.int32)
        _drive_presence(eng, keys, games, 4)
        await eng.flush()
        eng.checkpointer.checkpoint_full()
        rows_before = eng.checkpointer.rows_written
        # touch only the first 50 keys
        hot = keys[:50]
        _drive_presence(eng, hot, games[:50], 3, start=10)
        await eng.flush()
        r = eng.checkpointer.checkpoint_delta()
        assert r["kind"] == "delta"
        delta_rows = eng.checkpointer.rows_written - rows_before
        # PresenceGrain dirty = 50 hot rows; GameGrain fan-in rows are
        # dirty too (5 games) — but never the cold 350
        assert 50 <= delta_rows <= 50 + 10
        eng2 = _engine(backing)
        await eng2.checkpointer.recover()
        for t in ("PresenceGrain", "GameGrain"):
            a1, a2 = eng.arena_for(t), eng2.arena_for(t)
            assert np.array_equal(a1._key_of_row, a2._key_of_row)
            ks = a1.keys()
            s1 = _arena_state(eng, t, ks)
            s2 = _arena_state(eng2, t, ks)
            for name in s1:
                assert np.array_equal(s1[name], s2[name]), (t, name)

    run(main())


def test_delta_exact_under_evict_and_slot_reuse(run):
    """The reused-row isolation case: evict a key between checkpoints,
    let a DIFFERENT key reuse its slot, delta, kill, restore — the new
    key owns the slot with its own state, the evicted key is gone, and
    row identity matches the live engine exactly."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        eng = _engine(backing)
        n = 120
        events = banking.make_events(n, 6, lanes=48, seed=3,
                                     transfer_every=0)
        banking.register_banking_journal(eng)
        oracle = banking.BankOracle(n)
        await banking.run_banking_load(eng, events, oracle=oracle)
        eng.checkpointer.checkpoint_full()
        arena = eng.arena_for("AccountGrain")
        victim = int(events[0]["keys"][0])
        victim_row = int(arena.lookup_rows(
            np.array([victim], np.int64))[0][0])
        arena.evict_keys(np.array([victim], np.int64), write_back=False)
        # a fresh key activates — LIFO free list hands it the slot
        newcomer = np.int64(n + 999)
        ev = {"method": "deposit",
              "keys": np.array([newcomer], np.int64),
              "amount": np.array([17], np.int32)}
        await banking.run_banking_load(eng, [ev])
        rows, found = arena.lookup_rows(np.array([newcomer]))
        assert found[0] and int(rows[0]) == victim_row  # slot reused
        eng.checkpointer.checkpoint_delta()
        eng2 = _engine(backing)
        await eng2.checkpointer.recover()
        a2 = eng2.arena_for("AccountGrain")
        assert np.array_equal(a2._key_of_row, arena._key_of_row)
        assert not a2.lookup_rows(np.array([victim], np.int64))[1][0]
        got = banking.read_accounts(eng2, np.array([newcomer]))
        assert int(got["balance"][0]) == 17
        assert int(got["credits"][0]) == 1

    run(main())


def test_generation_change_promotes_delta_to_full(run):
    """Row moves (growth) between checkpoints invalidate delta row ids
    — the plane must promote the next delta to a full."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        eng = _engine(backing)
        keys = np.arange(64, dtype=np.int64)
        games = (keys % 4).astype(np.int32)
        _drive_presence(eng, keys, games, 3)
        await eng.flush()
        eng.checkpointer.checkpoint_full()
        fulls0 = eng.checkpointer.full_snapshots
        # force growth: activate far past capacity
        more = np.arange(64, 3000, dtype=np.int64)
        eng.arena_for("PresenceGrain").resolve_rows(more, tick=5)
        r = eng.checkpointer.checkpoint_delta()
        assert r["kind"] == "full"
        assert eng.checkpointer.full_snapshots == fulls0 + 1
        assert eng.checkpointer.delta_snapshots == 0

    run(main())


def test_fused_run_recovers_bit_exact(run):
    """The journal rides auto-fused steady state: a fused engine's
    committed horizon restores bit-exact against an unfused oracle —
    the fused/unfused equivalence the whole engine is built on, now
    surviving a crash."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        n = 150
        rng = np.random.default_rng(11)
        keys = np.arange(n, dtype=np.int64)
        amounts = [rng.integers(1, 50, n).astype(np.int32)
                   for _ in range(24)]
        cfg = TensorEngineConfig(tick_interval=0.0, auto_fusion_ticks=4,
                                 auto_fusion_window=4,
                                 journal_flush_every_ticks=6)
        eng = TensorEngine(config=cfg,
                           snapshot_store=MemorySnapshotStore(backing))
        banking.register_banking_journal(eng)
        eng.checkpointer.checkpoint_full()
        inj = eng.make_injector("AccountGrain", "deposit", keys)
        for a in amounts:
            inj.inject({"amount": a})
            eng.run_tick()
        await eng.flush()
        assert eng.autofuser.snapshot()["windows_run"] > 0
        site = eng.checkpointer.journal.sites[("AccountGrain",
                                               "deposit")]
        acked = site.committed_lanes // n
        assert 0 < acked <= len(amounts)
        # HARD KILL.  Unfused oracle over the acknowledged prefix:
        oracle_eng = TensorEngine(config=TensorEngineConfig(
            tick_interval=0.0, auto_fusion_ticks=0))
        oinj = oracle_eng.make_injector("AccountGrain", "deposit", keys)
        for a in amounts[:acked]:
            oinj.inject({"amount": a})
            oracle_eng.run_tick()
        await oracle_eng.flush()
        eng2 = TensorEngine(config=cfg,
                            snapshot_store=MemorySnapshotStore(backing))
        await eng2.checkpointer.recover()
        s1 = banking.read_accounts(oracle_eng, keys)
        s2 = banking.read_accounts(eng2, keys)
        for name in s1:
            assert np.array_equal(s1[name], s2[name]), name

    run(main())


def test_journal_non_lane_device_leaf_appends_and_replays(run):
    """Review regression: an args leaf that is a DEVICE array whose
    first dimension differs from the batch's lane count (a per-batch
    constant, e.g. a lookup table) must append by reference like any
    device leaf — the old shape[0]==lanes guard dropped it into the
    scalar branch, crashing every send on the journaled site."""
    import jax.numpy as jnp

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        eng = _engine(backing)
        eng.register_journal("PresenceGrain", "heartbeat")
        eng.checkpointer.checkpoint_full()
        keys = np.arange(16, dtype=np.int64)
        games = jnp.asarray(np.zeros(16, np.int32))
        # "score" rides as a WIDER device constant is not presentable
        # through the real handler; instead exercise the journal path
        # directly with a mixed-width tree via a raw batch append
        from orleans_tpu.tensor.checkpoint import DeviceJournal

        class FakeBatch:
            keys_host = keys
            keys_dev = None
            inject_tick = 3
            args = {"game": games,                       # lane-aligned dev
                    "table": jnp.arange(7, dtype=jnp.int32),  # non-lane dev
                    "tick": np.int32(4)}                 # scalar

        eng.checkpointer.journal.append("PresenceGrain", "heartbeat",
                                        FakeBatch)
        eng.checkpointer.journal.flush()
        manifest = eng.checkpointer.store.read_manifest()
        seg = manifest["journal"]["PresenceGrain.heartbeat"]["segments"][-1]
        arrays, meta = eng.checkpointer.store.get_blob(seg["blob"])
        entries = DeviceJournal.decode_segment(arrays, meta)
        e = entries[-1]
        assert np.array_equal(e["keys"], keys)
        assert np.array_equal(e["args"]["game"], np.zeros(16, np.int32))
        assert np.array_equal(e["args"]["table"], np.arange(7))
        assert int(e["args"]["tick"]) == 4

    run(main())


def test_delta_restore_applies_recorded_use_clocks(run):
    """Review regression: a delta's meta records the FULL host use
    clock at its cut — restore must apply it, or rows hot at the crash
    keep the BASE snapshot's stale clocks and the first idle sweep
    after recovery evicts them as idle."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        eng = _engine(backing)
        keys = np.arange(100, dtype=np.int64)
        games = (keys % 4).astype(np.int32)
        _drive_presence(eng, keys, games, 3)
        await eng.flush()
        eng.checkpointer.checkpoint_full()
        arena = eng.arena_for("PresenceGrain")
        # advance the clock far past the base, touch a hot subset
        eng.tick_number += 500
        hot = keys[:20]
        arena.resolve_rows(hot, tick=eng.tick_number)
        r = eng.checkpointer.checkpoint_delta()
        assert r["kind"] == "delta"
        eng2 = _engine(backing)
        await eng2.checkpointer.recover()
        a2 = eng2.arena_for("PresenceGrain")
        hot_rows = arena.lookup_rows(hot)[0]
        assert np.array_equal(a2.last_use_tick[hot_rows],
                              arena.last_use_tick[hot_rows])
        assert int(a2.last_use_tick[hot_rows].min()) >= 500

    run(main())


def test_periodic_cadence_commits_under_live_traffic(run):
    """The on_tick cadence path: fulls + deltas + journal seals commit
    while traffic keeps flowing; the recovery-point age stays bounded
    by the delta cadence once the first full lands."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        eng = _engine(backing, ckpt_full_every_ticks=12,
                      ckpt_delta_every_ticks=4,
                      ckpt_pause_budget_s=0.002,
                      journal_flush_every_ticks=3)
        banking.register_banking_journal(eng)
        events = banking.make_events(100, 40, lanes=32, seed=5)
        await banking.run_banking_load(eng, events)
        ck = eng.checkpointer
        assert ck.full_snapshots >= 1
        assert ck.delta_snapshots >= 1
        assert ck.journal.segments_committed >= 1
        assert 0 <= ck.age_ticks() <= 3 * 12
        snap = eng.snapshot()["durability"]
        assert snap["enabled"] and snap["rows_written"] > 0

    run(main())


def test_journal_ring_overflow_seals_midtick_without_loss(run):
    """A full ring seals the open segment mid-append instead of
    dropping or erroring; every lane stays acknowledged-or-pending."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        eng = _engine(backing, journal_ring_lanes=128)
        banking.register_banking_journal(eng)
        events = banking.make_events(50, 10, lanes=48, seed=9,
                                     transfer_every=0)
        await banking.run_banking_load(eng, events)
        j = eng.checkpointer.journal
        assert j.ring_overflows > 0
        site = j.sites[("AccountGrain", "deposit")]
        assert site.appended_lanes == 10 * 48
        assert site.appended_lanes == site.committed_lanes \
            + site.segment_lanes

    run(main())


def test_file_snapshot_store_roundtrip_and_atomic_manifest(run, tmp_path):
    """The on-disk store: a full kill→recover round trip through real
    files, and a crash INSIDE a manifest commit leaves the previous
    recovery point readable (atomic replace)."""

    async def main():
        root = str(tmp_path / "snaps")
        eng = TensorEngine(config=TensorEngineConfig(
            tick_interval=0.0, auto_fusion_ticks=0),
            snapshot_store=FileSnapshotStore(root))
        banking.register_banking_journal(eng)
        events = banking.make_events(80, 8, lanes=32, seed=2)
        oracle = banking.BankOracle(80)
        await banking.run_banking_load(eng, events, oracle=oracle)
        eng.checkpointer.checkpoint_full()
        # crash mid-commit: os.replace raises before the swap — the
        # OLD manifest must stay intact and readable
        store = FileSnapshotStore(root)
        good = store.read_manifest()
        assert good is not None
        real_replace = os.replace

        def boom(src, dst):
            if dst.endswith("MANIFEST.json"):
                raise OSError("injected kill mid-commit")
            return real_replace(src, dst)

        os.replace = boom
        try:
            with pytest.raises(OSError):
                store.commit_manifest({"seq": 10**6, "recovery": None})
        finally:
            os.replace = real_replace
        assert store.read_manifest() == good
        eng2 = TensorEngine(config=TensorEngineConfig(
            tick_interval=0.0, auto_fusion_ticks=0),
            snapshot_store=FileSnapshotStore(root))
        stats = await eng2.checkpointer.recover()
        assert stats["recovered"]
        touched = np.unique(np.concatenate(
            [e["keys"] for e in events])).astype(np.int64)
        got = banking.read_accounts(eng2, touched)
        want = oracle.expect(touched)
        for name in ("balance", "credits", "debits"):
            assert np.array_equal(got[name], want[name]), name

    run(main())


def test_file_vector_store_torn_write_leaves_prior_record(tmp_path):
    """The FileVectorStore crash-safety regression: an exception thrown
    mid-columnar-write (the chaos storage seam's fault shape) leaves
    the previously committed record readable and no torn final path."""
    from orleans_tpu.tensor.persistence import FileVectorStore

    store = FileVectorStore(str(tmp_path / "rows"))
    keys = [1, 2, 3]
    cols = {"balance": np.array([10, 20, 30], np.int32)}
    store.write_many_columnar("Acct", keys, cols)
    calls = {"n": 0}
    real_savez = np.savez

    def flaky(f, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise OSError("injected kill mid-write")
        return real_savez(f, **kw)

    np.savez = flaky
    try:
        with pytest.raises(OSError):
            store.write_many_columnar(
                "Acct", keys,
                {"balance": np.array([11, 21, 31], np.int32)})
    finally:
        np.savez = real_savez
    out = store.read_many("Acct", keys)
    # key 1 committed the new value, key 2 kept the OLD one (never a
    # torn file), key 3 untouched by the interrupted pass
    assert int(out[1]["balance"]) == 11
    assert int(out[2]["balance"]) == 20
    assert int(out[3]["balance"]) == 30
    d = str(tmp_path / "rows" / "Acct")
    assert not [f for f in os.listdir(d) if f.endswith(".tmp")]


def test_durability_accounting_invariant_catches_missing_blob(run):
    """The chaos checker fails loudly when a manifest references a blob
    that is gone (the commit-order contract's tripwire)."""

    async def main():
        from orleans_tpu.chaos.invariants import (
            InvariantViolation,
            check_durability_accounting,
        )
        backing = MemorySnapshotStore.shared_backing()
        eng = _engine(backing)
        banking.register_banking_journal(eng)
        events = banking.make_events(40, 4, lanes=16, seed=1)
        await banking.run_banking_load(eng, events)
        eng.checkpointer.checkpoint_full()
        check_durability_accounting(eng)  # green
        manifest = eng.checkpointer.store.read_manifest()
        blob = manifest["recovery"]["full"]["arenas"][
            "AccountGrain"]["parts"][0]
        eng.checkpointer.store.delete_blob(blob)
        with pytest.raises(InvariantViolation):
            check_durability_accounting(eng)

    run(main())


def test_chaos_kill_scenario_green(run):
    """The seeded kill-mid-traffic scenario the chaos smoke runs: zero
    acknowledged loss, RTO met, journal replay + loss window both
    exercised."""

    async def main():
        from orleans_tpu.chaos.report import durability_kill_scenario

        report = await durability_kill_scenario(20260804)
        assert report["ok"]
        assert report["recovery"]["replayed_lanes"] > 0
        assert report["lost_unacknowledged_entries"] > 0

    run(main())


def test_silo_startup_recovery_and_graceful_stop(run):
    """The silo wiring: a graceful stop commits a terminal recovery
    point; a NEW silo over the same backing restores it during start()
    — before serving traffic — and reports the recovery stats."""

    async def main():
        from orleans_tpu.testing.cluster import TestingCluster

        backing = MemorySnapshotStore.shared_backing()

        def setup(silo):
            silo.tensor_engine.checkpointer.attach_store(
                MemorySnapshotStore(backing))
            banking.register_banking_journal(silo.tensor_engine)

        cluster = await TestingCluster(n_silos=1,
                                       silo_setup=setup).start()
        try:
            eng = cluster.silos[0].tensor_engine
            events = banking.make_events(60, 6, lanes=24, seed=4)
            oracle = banking.BankOracle(60)
            await banking.run_banking_load(eng, events, oracle=oracle)
        finally:
            await cluster.stop()  # graceful → terminal full snapshot
        cluster2 = await TestingCluster(n_silos=1,
                                        silo_setup=setup).start()
        try:
            silo = cluster2.silos[0]
            assert silo.last_recovery is not None
            assert silo.last_recovery["recovered"]
            touched = np.unique(np.concatenate(
                [np.concatenate([e["keys"],
                                 e.get("dst", np.empty(0, np.int64))])
                 for e in events])).astype(np.int64)
            got = banking.read_accounts(silo.tensor_engine, touched)
            want = oracle.expect(touched)
            for name in ("balance", "credits", "debits"):
                assert np.array_equal(got[name], want[name]), name
        finally:
            await cluster2.stop()

    run(main())


def test_silo_publishes_ckpt_and_journal_metrics(run):
    """Strict catalog publication: a plane-enabled silo's
    collect_metrics emits the ckpt.*/journal.* rows, and the dashboard
    renders the durability line from the merged snapshot."""

    async def main():
        from orleans_tpu.dashboard import render_text, view_from_snapshots
        from orleans_tpu.testing.cluster import TestingCluster

        backing = MemorySnapshotStore.shared_backing()

        def setup(silo):
            silo.tensor_engine.checkpointer.attach_store(
                MemorySnapshotStore(backing))
            banking.register_banking_journal(silo.tensor_engine)

        cluster = await TestingCluster(n_silos=1,
                                       silo_setup=setup).start()
        try:
            silo = cluster.silos[0]
            eng = silo.tensor_engine
            events = banking.make_events(50, 5, lanes=20, seed=6)
            await banking.run_banking_load(eng, events)
            eng.checkpointer.checkpoint_full()
            snap = silo.collect_metrics()
            assert snap["counters"]["ckpt.full_snapshots"][""] >= 1
            assert snap["counters"]["journal.segments"][""] >= 1
            assert "ckpt.age_ticks" in snap["gauges"]
            view = view_from_snapshots([snap])
            du = view["cluster"]["durability"]
            assert du["full_snapshots"] >= 1
            assert du["rows_written"] > 0
            text = render_text(view)
            assert "durability:" in text
        finally:
            await cluster.stop()

    run(main())


def test_perfgate_durability_family(tmp_path):
    """The durability perfgate family: artifact + baseline section are
    wired like every other plane's."""
    import json

    from orleans_tpu.perfgate import FAMILIES, run_gate

    assert "durability" in FAMILIES
    prefix, section, fallback = FAMILIES["durability"]
    assert fallback == "DURABILITY_BENCH.json"
    artifact = {"workload": "durability",
                "overhead": {"overhead_pct": 2.0},
                "kill_recovery": {"exact": True, "rto_met": True},
                "restore_scale": {"rows_per_sec": 1e6}}
    baseline = {section: {
        "durability_overhead_pct": {
            "path": "overhead.overhead_pct", "value": 5.0,
            "tolerance": 0.0, "direction": "lower"},
        "durability_kill_exact": {
            "path": "kill_recovery.exact", "value": 1.0,
            "direction": "flag"},
    }}
    bp = tmp_path / "PERF_BASELINE.json"
    bp.write_text(json.dumps(baseline))
    verdict = run_gate(str(bp), artifact=artifact, family="durability")
    assert verdict["status"] == "pass", verdict
    artifact["kill_recovery"]["exact"] = False
    verdict = run_gate(str(bp), artifact=artifact, family="durability")
    assert verdict["status"] == "fail"

    # repo baseline carries the seeded section
    repo_baseline = os.path.join(os.path.dirname(__file__), "..",
                                 "PERF_BASELINE.json")
    with open(repo_baseline) as f:
        data = json.load(f)
    assert "durability_metrics" in data, \
        "PERF_BASELINE.json must seed the durability family"

# ---------------------------------------------------------------------------
# fused fold-replay, composed recovery, warm standby (PR 18)
# ---------------------------------------------------------------------------


def _define_composed_grains():
    if vector_type("DuraCounter") is not None:
        return

    @vector_grain
    class DuraCounter(VectorGrain):
        # commutative so the grain is replicable mid-interval
        total = field(jnp.int32, 0)

        @batched_method
        @staticmethod
        @commutative
        def bump(state, batch: Batch, n_rows: int):
            return {**state,
                    "total": state["total"]
                    + seg_sum(batch.args["amount"], batch.rows,
                              n_rows)}, None, ()

    @vector_grain
    class DuraTimerProbe(VectorGrain):
        fires = field(jnp.int32, 0)

        @batched_method
        @staticmethod
        def receive_reminder(state, batch: Batch, n_rows: int):
            ones = jnp.where(batch.mask, 1, 0).astype(jnp.int32)
            return {"fires": scatter_add_rows(state["fires"],
                                              batch.rows, ones)}

        @batched_method
        @staticmethod
        def poke(state, batch: Batch, n_rows: int):
            return state


_define_composed_grains()


def _touched_keys(events):
    return np.unique(np.concatenate(
        [np.concatenate([e["keys"],
                         e.get("dst", np.empty(0, np.int64))])
         for e in events])).astype(np.int64)


def test_fused_fold_replay_matches_per_tick_and_oracle(run):
    """Fused fold-replay (stacked [T, m] windows through
    FusedTickProgram.replay — ONE compiled program per window of
    consecutive journaled ticks) is bit-exact vs BOTH the per-tick
    replay path and the uninterrupted oracle, including the transfer
    emit leg, and the fusion actually engages."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        n_accounts = 300
        events = banking.make_events(n_accounts, 30, lanes=64, seed=29)
        eng = _engine(backing, journal_flush_every_ticks=4)
        banking.register_banking_journal(eng)
        eng.checkpointer.checkpoint_full()
        for ev in events:
            args = {"amount": ev["amount"]}
            if ev["method"] == "transfer":
                args["dst"] = ev["dst"]
            eng.send_batch("AccountGrain", ev["method"], ev["keys"],
                           args)
            eng.run_tick()
        sites = eng.checkpointer.journal.sites
        acked = (sites[("AccountGrain", "deposit")].committed_lanes
                 + sites[("AccountGrain", "transfer")].committed_lanes
                 ) // 64
        assert 0 < acked < len(events)
        oracle = banking.BankOracle(n_accounts)
        for ev in events[:acked]:
            oracle.apply(ev)
        # HARD KILL → fused recovery (default window).  The restarted
        # process re-runs its app wiring first — registration carries
        # the emit_key_args hints the fused pre-activation needs.
        eng2 = _engine(backing, journal_flush_every_ticks=4)
        banking.register_banking_journal(eng2)
        stats2 = await eng2.checkpointer.recover()
        assert stats2["recovered"]
        assert stats2["replayed_lanes"] == acked * 64
        assert stats2["fused_windows"] > 0, \
            "fusion never engaged (every window fell back per-tick)"
        assert stats2["fused_lanes"] > 0
        # per-tick recovery over the SAME manifest: defer-re-anchor
        # left the recovery point untouched, so a second recovery
        # replays the identical tail
        eng3 = _engine(backing, journal_flush_every_ticks=4,
                       recover_fused_window=1)
        banking.register_banking_journal(eng3)
        stats3 = await eng3.checkpointer.recover()
        assert stats3["fused_windows"] == 0
        assert stats3["replayed_lanes"] == acked * 64
        touched = _touched_keys(events[:acked])
        want = oracle.expect(touched)
        got2 = banking.read_accounts(eng2, touched)
        got3 = banking.read_accounts(eng3, touched)
        for name in ("balance", "credits", "debits"):
            assert np.array_equal(got2[name], want[name]), name
            assert np.array_equal(got3[name], got2[name]), name

    run(main())


def test_composed_recovery_replication_pins_timers(run):
    """Restore identity under composition — a kill/recover spanning a
    promoted replication interval, migrated pins AND armed timers in
    ONE scenario: exact state vs the acknowledged-prefix oracle
    (replica folds exact), pins survive, timers fire exactly once."""

    async def main():
        backing = MemorySnapshotStore.shared_backing()
        cfg = dict(ckpt_full_every_ticks=10, ckpt_delta_every_ticks=5,
                   ckpt_pause_budget_s=0.002, journal_flush_every_ticks=3)
        eng = _engine(backing, **cfg)
        eng.n_shards = 4
        eng.register_journal("DuraCounter", "bump")
        rng = np.random.default_rng(23)
        keys = np.arange(96, dtype=np.int64)
        hot = 7
        # arm one-shots due AFTER the whole drive: they must survive
        # the kill ARMED and fire exactly once post-recovery
        tkeys = np.arange(32, dtype=np.int64)
        inj = eng.make_injector("DuraTimerProbe", "poke", tkeys)
        inj.inject({})
        eng.run_tick()
        due = eng.tick_number + 60
        eng.timers.arm_batch("DuraTimerProbe", tkeys,
                             np.full(32, due, np.int64), 0, "close")
        amounts_by_tick = []
        for t in range(25):
            amounts = rng.integers(1, 100, 96).astype(np.int32)
            amounts_by_tick.append(amounts)
            eng.send_batch("DuraCounter", "bump", keys,
                           {"amount": amounts})
            eng.run_tick()
            if t == 5:
                assert eng.replicate_key("DuraCounter", hot, 3) == 3
            if t == 9:
                movers = rng.choice(keys, 24, replace=False)
                eng.migrate_keys("DuraCounter", movers,
                                 rng.integers(0, 4, 24))
        await eng.flush()
        arena = eng.arenas["DuraCounter"]
        pins = dict(arena._shard_override)
        assert pins and arena._replicas, "scenario degenerate"
        site = eng.checkpointer.journal.sites[("DuraCounter", "bump")]
        acked = site.committed_lanes // 96
        assert 0 < acked < 25, "kill must land mid-cadence"
        oracle = np.zeros(96, dtype=np.int64)
        for amounts in amounts_by_tick[:acked]:
            oracle += amounts
        # HARD KILL → fresh engine over the same backing
        eng2 = _engine(backing, **cfg)
        eng2.n_shards = 4
        stats = await eng2.checkpointer.recover()
        assert stats["recovered"]
        # timers armed at the cut force the per-tick replay path
        assert stats["fused_windows"] == 0
        a2 = eng2.arenas["DuraCounter"]
        # replica folds exact: read through the fold-aware accessor
        got = np.array([int(a2.read_row(int(k))["total"])
                        for k in keys], dtype=np.int64)
        assert np.array_equal(got, oracle)
        # migration pins survive recovery
        assert a2._shard_override == pins
        # the armed set survived the kill; fires exactly once, on time
        assert eng2.timers.armed_total == 32
        while eng2.tick_number < due:
            eng2.run_tick()
        await eng2.flush()
        ta = eng2.arena_for("DuraTimerProbe")
        rows, found = ta.lookup_rows(tkeys)
        assert found.all()
        fires = np.asarray(ta.state["fires"])[rows]
        assert (fires == 1).all(), fires
        for _ in range(8):
            eng2.run_tick()
        await eng2.flush()
        fires = np.asarray(ta.state["fires"])[ta.lookup_rows(tkeys)[0]]
        assert (fires == 1).all(), "timer fired twice"

    run(main())


def test_standby_tails_promotes_and_fences(run):
    """Warm standby end to end: the tailer adopts the primary's
    committed fulls/deltas and stages sealed journal segments while
    traffic runs; promotion fences the store, replays ONLY the
    un-adopted tail, lands bit-exact at the acknowledged prefix; the
    old (merely partitioned) primary can never commit again, and the
    promoted standby serves and commits from there on."""

    async def main():
        from orleans_tpu.tensor.checkpoint import (
            FencedError,
            StandbyTailer,
        )
        backing = MemorySnapshotStore.shared_backing()
        n_accounts = 200
        events = banking.make_events(n_accounts, 24, lanes=64, seed=13)
        primary = _engine(backing, ckpt_full_every_ticks=8,
                          ckpt_delta_every_ticks=4,
                          ckpt_pause_budget_s=0.002,
                          journal_flush_every_ticks=3)
        banking.register_banking_journal(primary)
        standby_eng = TensorEngine(config=TensorEngineConfig(
            tick_interval=0.0, auto_fusion_ticks=0))
        banking.register_banking_journal(standby_eng)
        tailer = StandbyTailer(standby_eng,
                               MemorySnapshotStore(backing))
        for i, ev in enumerate(events):
            args = {"amount": ev["amount"]}
            if ev["method"] == "transfer":
                args["dst"] = ev["dst"]
            primary.send_batch("AccountGrain", ev["method"],
                               ev["keys"], args)
            primary.run_tick()
            if i % 4 == 3:
                tailer.poll()
        await primary.flush()
        assert tailer.lag_ticks() >= 0
        assert tailer.adopted_rows > 0, "standby never adopted a cut"
        sites = primary.checkpointer.journal.sites
        acked = (sites[("AccountGrain", "deposit")].committed_lanes
                 + sites[("AccountGrain", "transfer")].committed_lanes
                 ) // 64
        assert 0 < acked <= len(events)
        oracle = banking.BankOracle(n_accounts)
        for ev in events[:acked]:
            oracle.apply(ev)
        # HARD KILL the primary (the OBJECT stays alive to model a
        # partitioned zombie).  Promote the standby.
        res = await tailer.promote(owner="standby-1")
        assert res["promoted"] and tailer.promoted
        assert res["fence_epoch"] >= 1
        assert standby_eng.checkpointer.promotions == 1
        touched = _touched_keys(events[:acked])
        got = banking.read_accounts(standby_eng, touched)
        want = oracle.expect(touched)
        for name in ("balance", "credits", "debits"):
            assert np.array_equal(got[name], want[name]), name
        # zero acknowledged-write loss AND the old primary is fenced:
        # its next commit over the claimed store must refuse
        with pytest.raises(FencedError):
            primary.checkpointer.checkpoint_full()
        assert primary.checkpointer.fenced
        # the promoted standby serves and commits (it owns the fence)
        standby_eng.send_batch("AccountGrain", "deposit",
                               np.arange(8, dtype=np.int64),
                               {"amount": np.ones(8, np.int32)})
        standby_eng.run_tick()
        await standby_eng.flush()
        anchor = standby_eng.checkpointer.checkpoint_full()
        assert anchor["rows"] > 0

    run(main())
