"""GrainArena: the stacked state store for one vector grain type.

The arena is the tensor-path Catalog + ActivationDirectory (reference:
Catalog.cs:43, ActivationDirectory.cs:33): an activation is a *row*; the
host keeps the key→row index (the local directory partition) and the device
holds the state columns.  Row blocks are assigned to mesh shards by grain
key hash, so "which device owns this grain" is the same stable function the
silo ring uses — the directory IS the sharding map (BASELINE.json north
star).

Auto-activation: resolving an unseen key allocates a row in the key's home
shard block and initializes its columns from the declared field inits —
the batched analog of GetOrCreateActivation (reference: Catalog.cs:411).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from orleans_tpu.hashing import stable_hash_u64
from orleans_tpu.tensor.vector_grain import StateField, VectorGrainInfo


class ArenaFullError(RuntimeError):
    pass


def _hash_keys_u64(keys: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 matching hashing.stable_hash_u64, so host row
    assignment and any device-side bucketing agree."""
    x = keys.astype(np.uint64)
    x = x + np.uint64(0x9E3779B97F4A7C15)
    z = x
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


class GrainArena:

    def __init__(self, info: VectorGrainInfo, capacity: int = 1024,
                 n_shards: int = 1, sharding: Optional[Any] = None) -> None:
        self.info = info
        self.n_shards = max(1, n_shards)
        # capacity must divide evenly into shard blocks
        per_shard = max(1, -(-capacity // self.n_shards))
        self.shard_capacity = per_shard
        self.capacity = per_shard * self.n_shards
        self.sharding = sharding

        self.state: Dict[str, jnp.ndarray] = {}
        self._init_state_columns(self.capacity)
        # bumped whenever rows move (growth/repack); consumers holding
        # resolved row vectors must re-resolve on mismatch
        self.generation = 0

        # host-side directory partition: key → row
        self._key_of_row = np.full(self.capacity, -1, dtype=np.int64)
        self._shard_next = np.zeros(self.n_shards, dtype=np.int64)
        self._sorted_keys = np.empty(0, dtype=np.int64)
        self._sorted_rows = np.empty(0, dtype=np.int32)
        self._dirty = False
        self.live_count = 0
        self.last_use_tick = np.zeros(self.capacity, dtype=np.int64)

        # device-side directory mirror (int32 keys only — see device_resolve):
        # lets emit routing resolve key→row without any host round-trip,
        # which matters because d2h transfers are the slowest link.
        self._dev_sorted_keys: Optional[jnp.ndarray] = None
        self._dev_sorted_rows: Optional[jnp.ndarray] = None
        self._dev_index_stale = True

    # -- state columns ------------------------------------------------------

    def _make_column(self, f: StateField, capacity: int) -> jnp.ndarray:
        col = jnp.full((capacity, *f.shape), f.init, dtype=f.dtype)
        if self.sharding is not None:
            col = jax.device_put(col, self.sharding)
        return col

    def _init_state_columns(self, capacity: int) -> None:
        self.state = {name: self._make_column(f, capacity)
                      for name, f in self.info.state_fields.items()}

    # -- key → row resolution ----------------------------------------------

    def _rebuild_index(self) -> None:
        live = self._key_of_row >= 0
        rows = np.nonzero(live)[0].astype(np.int32)
        keys = self._key_of_row[rows]
        order = np.argsort(keys, kind="stable")
        self._sorted_keys = keys[order]
        self._sorted_rows = rows[order]
        self._dirty = False
        self._dev_index_stale = True

    # -- device-side directory mirror ---------------------------------------

    def device_index(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """The key→row map as device arrays (sorted int32 keys + rows).

        This is the 'directory == sharding map' realization: the same
        partition the host serves to the control plane is resident on the
        mesh, so batched routing (emits, injections) resolves destinations
        with a vectorized searchsorted instead of a host hop.  Keys wider
        than int32 fall back to the host path (hashed/string grain keys are
        rare on the hot path; int-keyed grains cover the benchmarks)."""
        if self._dirty:
            self._rebuild_index()
        if self._dev_index_stale or self._dev_sorted_keys is None:
            keys32 = self._sorted_keys.astype(np.int32)
            if np.any(keys32.astype(np.int64) != self._sorted_keys):
                raise OverflowError(
                    f"arena {self.info.name}: keys exceed int32; device "
                    f"routing unavailable (use host-side resolution)")
            # pad to capacity with the sentinel so the resolve kernel's
            # shapes only change on capacity growth (not per activation)
            pad = self.capacity - len(keys32)
            keys_padded = np.concatenate(
                [keys32, np.full(pad, 2**31 - 1, np.int32)])
            rows_padded = np.concatenate(
                [self._sorted_rows, np.full(pad, -1, np.int32)])
            dk = jnp.asarray(keys_padded)
            dr = jnp.asarray(rows_padded)
            if self.sharding is not None:
                # replicate the index: every shard routes locally
                from jax.sharding import NamedSharding, PartitionSpec
                repl = NamedSharding(self.sharding.mesh, PartitionSpec())
                dk = jax.device_put(dk, repl)
                dr = jax.device_put(dr, repl)
            self._dev_sorted_keys = dk
            self._dev_sorted_rows = dr
            self._dev_index_stale = False
        return self._dev_sorted_keys, self._dev_sorted_rows

    def lookup_rows(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized lookup; returns (rows int32, found bool)."""
        if self._dirty:
            self._rebuild_index()
        if len(self._sorted_keys) == 0:
            return (np.full(len(keys), -1, np.int32),
                    np.zeros(len(keys), bool))
        idx = np.searchsorted(self._sorted_keys, keys)
        idx = np.minimum(idx, len(self._sorted_keys) - 1)
        found = self._sorted_keys[idx] == keys
        rows = np.where(found, self._sorted_rows[idx], -1).astype(np.int32)
        return rows, found

    def resolve_rows(self, keys: np.ndarray, auto_activate: bool = True,
                     tick: int = 0) -> np.ndarray:
        """key→row with auto-activation of unseen keys
        (batched GetOrCreateActivation)."""
        keys = np.asarray(keys, dtype=np.int64)
        rows, found = self.lookup_rows(keys)
        if auto_activate and not found.all():
            missing = np.unique(keys[~found])
            self._activate_keys(missing)
            rows, found = self.lookup_rows(keys)
            if not found.all():
                raise ArenaFullError(
                    f"arena {self.info.name}: activation failed for "
                    f"{(~found).sum()} keys")
        self.last_use_tick[rows[rows >= 0]] = tick
        return rows

    def _activate_keys(self, keys: np.ndarray) -> None:
        shards = (_hash_keys_u64(keys) % np.uint64(self.n_shards)).astype(np.int64)
        # check capacity per shard; grow if any block would overflow
        counts = np.bincount(shards, minlength=self.n_shards)
        while np.any(self._shard_next + counts > self.shard_capacity):
            self._grow()
        for s in range(self.n_shards):
            ks = keys[shards == s]
            if len(ks) == 0:
                continue
            start = int(self._shard_next[s])
            base = s * self.shard_capacity
            rows = np.arange(start, start + len(ks)) + base
            self._key_of_row[rows] = ks
            self._shard_next[s] += len(ks)
        self.live_count += len(keys)
        self._dirty = True

    # -- growth -------------------------------------------------------------

    def _grow(self) -> None:
        """Double the per-shard block size, repacking rows so each shard's
        block stays contiguous (rows move; the key index is rebuilt —
        resharding is the same op at a bigger granularity)."""
        old_per = self.shard_capacity
        new_per = old_per * 2
        new_capacity = new_per * self.n_shards
        old_rows = np.nonzero(self._key_of_row >= 0)[0]
        old_shards = old_rows // old_per
        new_rows = (old_shards * new_per) + (old_rows % old_per)

        new_key_of_row = np.full(new_capacity, -1, dtype=np.int64)
        new_key_of_row[new_rows] = self._key_of_row[old_rows]
        new_last_use = np.zeros(new_capacity, dtype=np.int64)
        new_last_use[new_rows] = self.last_use_tick[old_rows]

        new_state: Dict[str, jnp.ndarray] = {}
        idx = jnp.asarray(old_rows, dtype=jnp.int32)
        dst = jnp.asarray(new_rows, dtype=jnp.int32)
        for name, f in self.info.state_fields.items():
            col = self._make_column(f, new_capacity)
            col = col.at[dst].set(self.state[name][idx])
            new_state[name] = col

        self.state = new_state
        self.shard_capacity = new_per
        self.capacity = new_capacity
        self._key_of_row = new_key_of_row
        self.last_use_tick = new_last_use
        self._dirty = True
        self.generation += 1

    def reserve(self, n: int) -> None:
        """Pre-size so ~n activations fit without growth mid-benchmark."""
        per_shard_target = -(-n // self.n_shards)
        while self.shard_capacity < per_shard_target * 2:
            self._grow()

    # -- host access (debug / persistence / host-path interop) --------------

    def read_row(self, key: int) -> Optional[Dict[str, np.ndarray]]:
        rows, found = self.lookup_rows(np.array([key], dtype=np.int64))
        if not found[0]:
            return None
        r = int(rows[0])
        return {name: np.asarray(col[r]) for name, col in self.state.items()}

    def keys(self) -> np.ndarray:
        return self._key_of_row[self._key_of_row >= 0]
