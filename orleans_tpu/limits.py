"""Limits + load shedding.

Parity: reference LimitManager (reference: src/Orleans/Configuration/
LimitManager.cs:34 — named LimitValue{soft,hard} lookups with defaults) and
the overload-driven load shedding fed by silo metrics (reference:
SiloPerformanceMetrics / NodeConfiguration LoadShedding settings, wired in
Silo.cs:257; queue-length overload checks ActivationData.CheckOverloaded
Catalog path :522 and GatewayTooBusy rejection).

The host runtime consults ``LimitManager`` for mailbox depth and client
connection limits; the tensor engine consults it for per-tick batch caps.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional


@dataclass(frozen=True)
class LimitValue:
    """(reference: LimitValue in LimitManager.cs)"""

    name: str
    soft_limit: int = 0
    hard_limit: int = 0

    @property
    def is_defined(self) -> bool:
        return self.soft_limit > 0 or self.hard_limit > 0


class LimitExceededError(Exception):
    """(reference: LimitExceededException)"""

    def __init__(self, name: str, current: int, limit: LimitValue,
                 context: str = ""):
        super().__init__(
            f"limit {name!r} exceeded: current={current} "
            f"soft={limit.soft_limit} hard={limit.hard_limit} {context}")
        self.limit_name = name
        self.current = current
        self.limit = limit


# Well-known limit names (reference: LimitNames in the reference config)
MAX_ENQUEUED_REQUESTS = "MaxEnqueuedRequests"
MAX_ENQUEUED_REQUESTS_STATELESS_WORKER = "MaxEnqueuedRequests_StatelessWorker"
MAX_PENDING_CLIENT_REQUESTS = "MaxPendingClientRequests"
MAX_TICK_BATCH_MESSAGES = "MaxTickBatchMessages"  # tensor-plane analog


class LimitManager:
    """Named soft/hard limit registry (reference: LimitManager.cs:34)."""

    def __init__(self, values: Optional[Dict[str, LimitValue]] = None) -> None:
        self._values: Dict[str, LimitValue] = dict(values or {})

    def add_limit(self, name: str, soft: int = 0, hard: int = 0) -> None:
        self._values[name] = LimitValue(name, soft, hard)

    def get_limit(self, name: str, default_soft: int = 0,
                  default_hard: int = 0) -> LimitValue:
        v = self._values.get(name)
        if v is not None:
            return v
        return LimitValue(name, default_soft, default_hard)

    def check(self, name: str, current: int, default_soft: int = 0,
              default_hard: int = 0, context: str = "",
              on_soft=None) -> None:
        """Raise on hard-limit breach; invoke ``on_soft`` (e.g. a warning
        logger) on soft-limit breach — the reference's pattern of
        warn-at-soft / reject-at-hard (ActivationData.CheckOverloaded)."""
        limit = self.get_limit(name, default_soft, default_hard)
        if limit.hard_limit > 0 and current > limit.hard_limit:
            raise LimitExceededError(name, current, limit, context)
        if limit.soft_limit > 0 and current > limit.soft_limit \
                and on_soft is not None:
            on_soft(name, current, limit)


class LoadSheddingGate:
    """CPU-style overload gate (reference: LoadSheddingEnabled /
    LoadSheddingLimit in NodeConfiguration, enforced at the gateway —
    overloaded silos reject new client work with GatewayTooBusy).

    The rebuild's load signal is queue pressure rather than Windows CPU
    counters: callers report a utilization-like scalar (e.g. pending
    messages / limit) and the gate trips above ``limit``.
    """

    def __init__(self, enabled: bool = False, limit: float = 0.95) -> None:
        self.enabled = enabled
        self.limit = limit
        self.latest_load: float = 0.0
        self.shed_count = 0

    def report_load(self, load: float) -> None:
        self.latest_load = load

    @property
    def is_overloaded(self) -> bool:
        return self.enabled and self.latest_load > self.limit

    def try_admit(self) -> bool:
        if self.is_overloaded:
            self.shed_count += 1
            return False
        return True
