"""Device-resident cross-shard routing (tensor/exchange.py).

Runs on the conftest-forced 8-device virtual CPU mesh and exercises the
REAL exchange path: bucket-by-destination-shard + lax.all_to_all inside
the compiled program, overflow redelivery with original inject stamps,
the fused-window threading, and the directory/arena agreement the whole
design rests on ("the directory IS the sharding map").
"""

import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from orleans_tpu.tensor import TensorEngine
from orleans_tpu.tensor.arena import shard_of_keys
from orleans_tpu.tensor.exchange import exchangeable_args, pow2ceil

from samples.routing import (
    SINK_BASE,
    RouteSink,     # noqa: F401 — registers the vector grains
    RouteSource,   # noqa: F401
    build_ratio_destinations,
    run_routing_load,
)

N_DEV = 8


def _mesh(n: int = N_DEV) -> Mesh:
    devices = jax.devices("cpu")
    assert len(devices) >= n, "conftest must force 8 host devices"
    return Mesh(np.array(devices[:n]), ("grains",))


def _engine(**kw) -> TensorEngine:
    e = TensorEngine(mesh=_mesh(), **kw)
    e.config.auto_fusion_ticks = 0  # tests opt in explicitly
    return e


def _sink_state(engine, n_sinks: int):
    arena = engine.arena_for("RouteSink")
    sinks = np.arange(SINK_BASE, SINK_BASE + n_sinks, dtype=np.int64)
    rows, found = arena.lookup_rows(sinks)
    assert found.all()
    return (np.asarray(arena.state["total"])[rows],
            np.asarray(arena.state["received"])[rows])


# ---------------------------------------------------------------------------
# exchange kernel unit level
# ---------------------------------------------------------------------------

def test_exchange_delivery_set_and_locality():
    """The exchange preserves the (row, payload) delivery multiset
    exactly (minus counted drops) and every received lane's row belongs
    to the shard block of the position it landed in."""
    engine = _engine(initial_capacity=16 * N_DEV)
    arena = engine.arena_for("RouteSink")
    arena.resolve_rows(np.arange(SINK_BASE, SINK_BASE + 100,
                                 dtype=np.int64))
    cap = arena.capacity
    rng = np.random.default_rng(0)
    m = 100
    rows = rng.integers(0, cap, m).astype(np.int32)
    mask = np.ones(m, bool)
    mask[::7] = False
    v = rng.integers(1, 9, m).astype(np.float32)
    r2, a2, m2, dropped, stats = engine.exchange.dispatch(
        arena, jnp.asarray(rows), {"v": jnp.asarray(v),
                                   "t": np.float32(3.0)},
        jnp.asarray(mask))
    r2h, vh, m2h, dh, sh = map(np.asarray, (r2, a2["v"], m2, dropped,
                                            stats))
    valid_in = mask & (rows >= 0)
    assert int(sh[2]) == int(valid_in.sum()) - int(dh.sum())
    sent = collections.Counter(
        zip(rows[valid_in & ~dh].tolist(),
            v[valid_in & ~dh].tolist()))
    got = collections.Counter(zip(r2h[m2h].tolist(), vh[m2h].tolist()))
    assert sent == got
    # locality: the received lane's row lives in the block of the shard
    # that received it — the step kernel's scatter is shard-local
    per_shard = len(r2h) // N_DEV
    pos_shard = np.arange(len(r2h)) // per_shard
    assert ((r2h[m2h] // arena.shard_capacity) == pos_shard[m2h]).all()
    # scalar leaves bypass the exchange untouched
    assert a2["t"] == np.float32(3.0)


def test_exchange_plan_pow2_and_clamp():
    engine = _engine(initial_capacity=16 * N_DEV)
    xch = engine.exchange
    for m in (1, 100, 4096, 100_000):
        L, cap = xch.plan(m)
        assert L == pow2ceil(-(-m // N_DEV))
        assert cap == pow2ceil(cap) and cap <= L
        assert cap >= min(L, engine.config.exchange_pad_quantum)


def test_slab_style_args_are_not_exchangeable():
    """Handlers consuming a whole buffer per tick (leaf leading dim !=
    lane count — the twitter dispatcher shape) must keep the legacy
    path: permuting rows away from the buffer would corrupt them."""
    assert exchangeable_args({"v": np.zeros(8), "s": np.float32(1)}, 8)
    assert not exchangeable_args({"slab": np.zeros(64)}, 8)


# ---------------------------------------------------------------------------
# engine integration: exactness across the ratio sweep
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("ratio", [0.0, 0.5, 0.9])
def test_routing_exact_vs_exchange_off(run, ratio):
    """Exchange ON must produce bit-identical sink state to the
    implicit-collective baseline at every cross-shard ratio (integer
    payloads through seg_sum: no float-order escape hatch)."""

    async def main():
        e_on = _engine(initial_capacity=1024)
        await run_routing_load(e_on, 512, 256, ratio, n_ticks=4)
        e_off = _engine(initial_capacity=1024)
        e_off.config.cross_shard_exchange = False
        await run_routing_load(e_off, 512, 256, ratio, n_ticks=4)
        t_on, r_on = _sink_state(e_on, 256)
        t_off, r_off = _sink_state(e_off, 256)
        np.testing.assert_array_equal(t_on, t_off)
        np.testing.assert_array_equal(r_on, r_off)
        assert r_on.sum() == 512 * 6  # warm (2) + timed (4) ticks
        xs = e_on.snapshot()["exchange"]
        assert xs["exchanges_run"] > 0 and xs["dropped_msgs"] == 0
        assert e_off.snapshot()["exchange"]["exchanges_run"] == 0
        if ratio > 0:
            assert xs["cross_shard_msgs"] > 0

    run(main())


def test_cross_shard_count_matches_constructed_ratio(run):
    """The stats the exchange reports reconcile with the analytically
    constructed traffic: sink deliveries cross shards exactly at the
    requested ratio (sources land on their own shard post-exchange, so
    the delivery leg's crossings are ratio * lanes per tick)."""

    async def main():
        n_src, n_sink, ratio, ticks = 512, 256, 0.5, 4
        e = _engine(initial_capacity=1024)
        await run_routing_load(e, n_src, n_sink, ratio, n_ticks=ticks,
                               warm_ticks=0)
        xs = e.snapshot()["exchange"]
        # two exchanged legs per tick: the source injection (whose
        # crossings depend on the injection layout) and the sink
        # delivery (whose crossings are EXACTLY the constructed ratio —
        # post-exchange, every emit lane sits on its source's home
        # shard).  The total is source-leg + ratio * lanes per tick.
        src = np.arange(n_src, dtype=np.int64)
        rows, _ = e.arena_for("RouteSource").lookup_rows(src)
        lane_shard = np.arange(n_src) // -(-n_src // N_DEV)
        src_cross = int((shard_of_keys(src, N_DEV) != lane_shard).sum())
        sink_cross = int(round(ratio * n_src))
        assert xs["cross_shard_msgs"] == (src_cross + sink_cross) * ticks
        assert xs["delivered_msgs"] == 2 * n_src * ticks
        assert xs["dropped_msgs"] == 0

    run(main())


# ---------------------------------------------------------------------------
# overflow redelivery + latency-ledger stamps
# ---------------------------------------------------------------------------

def test_overflow_redelivers_exactly_with_original_stamp(run):
    """Max-skew traffic (every message to ONE sink) with a deliberately
    tiny bucket: lanes overflow, redeliver over later ticks, and nothing
    is lost — and the device latency ledger records the redelivered
    lanes with their ORIGINAL inject stamp (nonzero tick deltas)."""

    async def main():
        e = _engine(initial_capacity=1024)
        e.config.exchange_pad_quantum = 2
        e.config.exchange_capacity_factor = 0.25
        src = np.arange(256, dtype=np.int64)
        e.arena_for("RouteSource").reserve(256)
        e.arena_for("RouteSink").reserve(64)
        e.arena_for("RouteSource").resolve_rows(src)
        e.arena_for("RouteSink").resolve_rows(
            np.arange(64, dtype=np.int64))
        inj = e.make_injector("RouteSource", "send", src)
        dst = jnp.asarray(np.zeros(256, np.int32))
        v = jnp.asarray(np.ones(256, np.float32))
        for t in range(3):
            inj.inject({"dst": dst, "v": v, "tick": np.int32(t)})
            await e.drain_queues()
        await e.flush()
        xs = e.snapshot()["exchange"]
        assert xs["dropped_msgs"] > 0 and xs["redeliveries"] > 0
        row = e.arena_for("RouteSink").read_row(0)
        assert int(row["received"]) == 256 * 3  # nothing lost
        led = e.ledger.snapshot()
        sink = led["RouteSink.recv"]
        assert sink["total"] == 256 * 3  # counted once each
        # redelivered lanes completed ticks after their stamp: buckets
        # beyond "same tick" must be populated
        assert sum(sink["counts"][1:]) > 0, sink

    run(main())


def test_checkpoint_defers_while_exchange_checks_parked(run):
    """Review-fix regression: a periodic checkpoint with exchange
    overflow redeliveries still parked would persist subscriber effects
    without their source update — the write defers one tick (the checks
    drain and requeue) and lands after the redeliveries apply."""
    from orleans_tpu.tensor import MemoryVectorStore
    from orleans_tpu.tensor.engine import _ExchangeCheck

    async def main():
        e = TensorEngine(mesh=_mesh(), initial_capacity=64,
                         store=MemoryVectorStore())
        e.config.auto_fusion_ticks = 0
        e.config.checkpoint_every_ticks = 1
        arena = e.arena_for("RouteSink")
        arena.resolve_rows(np.arange(SINK_BASE, SINK_BASE + 8,
                                     dtype=np.int64))
        e.tick_number = 5
        keys = jnp.asarray(
            np.arange(SINK_BASE, SINK_BASE + 4).astype(np.int32))
        e._exchange_checks.append(_ExchangeCheck(
            type_name="RouteSink", method="recv", keys=keys,
            args={"v": jnp.ones(4, jnp.float32),
                  "count": jnp.ones(4, jnp.int32)},
            dropped=jnp.asarray(np.array([True, False, False, False])),
            stats=jnp.asarray(np.array([1, 1, 3], np.int32)),
            inject_tick=2))
        assert e.maybe_periodic_checkpoint() == 0.0  # deferred
        assert not e._exchange_checks                # drained…
        redelivery = e.queues[("RouteSink", "recv")]
        assert redelivery and redelivery[0].inject_tick == 2  # …requeued
        await e.flush()  # redelivery applies (ticks checkpoint en route)
        assert e._last_checkpoint_tick > 0

    run(main())


def test_host_batch_not_misattributed_cross_shard(run):
    """Review-fix regression: a host-key batch for a method previously
    seen only through the exchange is organic traffic (host batches
    never exchange by design) — not a cross_shard toggle event."""

    async def main():
        e = _engine(initial_capacity=1024)
        await run_routing_load(e, 256, 128, 0.5, n_ticks=2,
                               warm_ticks=0)
        before = e.compile_tracker.by_cause.get("cross_shard", 0)
        e.send_batch("RouteSink", "recv",
                     np.arange(SINK_BASE, SINK_BASE + 16,
                               dtype=np.int64),
                     {"v": np.ones(16, np.float32),
                      "count": np.ones(16, np.int32)})
        await e.flush()
        assert e.compile_tracker.by_cause.get("cross_shard", 0) == before

    run(main())


def test_exchange_accounting_invariant(run):
    """The chaos-plane checker: parked checks drained at quiescence and
    counters internally consistent."""
    from orleans_tpu.chaos.invariants import check_exchange_accounting

    async def main():
        e = _engine(initial_capacity=1024)
        await run_routing_load(e, 256, 128, 0.5, n_ticks=3)
        report = check_exchange_accounting(e)
        assert report["ok"] and report["delivered_msgs"] > 0

    run(main())


# ---------------------------------------------------------------------------
# fused windows + autofuse
# ---------------------------------------------------------------------------

def test_fused_window_exchange_exact(run):
    """The exchange threads through the fused lax.scan: a fused run over
    the mesh matches the unfused exchange-off baseline exactly."""

    async def main():
        e_f = _engine(initial_capacity=1024)
        await run_routing_load(e_f, 512, 256, 0.5, n_ticks=4,
                               fused_window=2)
        e_off = _engine(initial_capacity=1024)
        e_off.config.cross_shard_exchange = False
        await run_routing_load(e_off, 512, 256, 0.5, n_ticks=4,
                               warm_ticks=2)
        t_f, r_f = _sink_state(e_f, 256)
        t_o, r_o = _sink_state(e_off, 256)
        np.testing.assert_array_equal(t_f, t_o)
        np.testing.assert_array_equal(r_f, r_o)

    run(main())


def test_fused_exchange_toggle_retraces_with_cause(run):
    """A live cross_shard_exchange toggle re-traces the fused program
    (cause config_toggle) instead of silently running the stale plan."""

    async def main():
        import jax.numpy as jnp

        e = _engine(initial_capacity=1024)
        src = np.arange(128, dtype=np.int64)
        e.arena_for("RouteSource").resolve_rows(src)
        e.arena_for("RouteSink").resolve_rows(
            np.arange(SINK_BASE, SINK_BASE + 64, dtype=np.int64))
        dst = build_ratio_destinations(
            src, np.arange(SINK_BASE, SINK_BASE + 64, dtype=np.int64),
            N_DEV, 0.5, seed=0)
        prog = e.fuse_ticks("RouteSource", "send", src)
        static = {"dst": jnp.asarray(dst.astype(np.int32)),
                  "v": jnp.ones(128, jnp.float32)}
        prog.run({"tick": jnp.arange(2, dtype=jnp.int32)},
                 static_args=static)
        assert prog.verify() == 0
        assert prog._exchange_on is True
        before = e.compile_tracker.by_cause.get("config_toggle", 0)
        e.config.cross_shard_exchange = False
        prog.run({"tick": jnp.arange(2, dtype=jnp.int32)},
                 static_args=static)
        assert prog.verify() == 0
        assert prog._exchange_on is False
        assert e.compile_tracker.by_cause["config_toggle"] == before + 1

    run(main())


def test_autofuse_engages_over_exchange(run):
    """Transparent auto-fusion on the mesh: the steady routing pattern
    engages, runs exchanged windows, and stays exact."""

    async def main():
        e = _engine(initial_capacity=1024)
        e.config.auto_fusion_ticks = 3
        e.config.auto_fusion_window = 4
        stats = await run_routing_load(e, 256, 128, 0.5, n_ticks=16,
                                       warm_ticks=0)
        assert e.autofuser.ticks_fused > 0, stats
        assert e.autofuser.windows_rolled_back == 0
        _t, received = _sink_state(e, 128)
        assert received.sum() == 256 * 16

    run(main())


# ---------------------------------------------------------------------------
# compile-cause + phase accounting
# ---------------------------------------------------------------------------

def test_live_toggle_records_cross_shard_cause(run):
    """Flipping the exchange re-specializes a seen (type, method, m)
    step — attributed as cause 'cross_shard', not organic shape churn."""

    async def main():
        e = _engine(initial_capacity=1024)
        e.config.cross_shard_exchange = False
        await run_routing_load(e, 256, 128, 0.5, n_ticks=2,
                               warm_ticks=0)
        assert e.compile_tracker.by_cause.get("cross_shard", 0) == 0
        e.config.cross_shard_exchange = True
        await run_routing_load(e, 256, 128, 0.5, n_ticks=2,
                               warm_ticks=0)
        assert e.compile_tracker.by_cause["cross_shard"] > 0

    run(main())


def test_exchange_phase_reconciles(run):
    """The exchange is its own tick phase; phase sums still reconcile
    with tick wall time (no double-counted stage)."""

    async def main():
        e = _engine(initial_capacity=1024)
        await run_routing_load(e, 256, 128, 0.5, n_ticks=4)
        prof = e.profiler
        assert prof.phase_seconds["exchange"] > 0.0
        assert prof.overrun_ticks == 0
        snap = prof.snapshot()
        assert "exchange" in snap["phase_seconds"]

    run(main())


# ---------------------------------------------------------------------------
# satellite: directory/arena agreement property test
# ---------------------------------------------------------------------------

def test_directory_arena_shard_agreement(run):
    """THE sharding-map claim, enforced: for random keys, the ring's
    device-granularity helper, the arena's row-block placement, and the
    exchange's rows//shard_capacity bucketing all agree — across
    growth (repack) and a mesh reshard."""
    from orleans_tpu.runtime.ring import device_shard_of_keys

    async def main():
        rng = np.random.default_rng(7)
        e = _engine(initial_capacity=2 * N_DEV)  # tiny: forces growth
        arena = e.arena_for("RouteSink")
        keys = np.unique(rng.integers(0, 2**31 - 2, 500,
                                      dtype=np.int64))

        def check(n_shards: int) -> None:
            rows, found = arena.lookup_rows(keys)
            assert found.all()
            got = rows // arena.shard_capacity
            want = shard_of_keys(keys, n_shards)
            np.testing.assert_array_equal(got, want)
            np.testing.assert_array_equal(
                want, device_shard_of_keys(keys, n_shards))

        arena.resolve_rows(keys[:50])   # initial block
        arena.resolve_rows(keys)        # forces several growths
        check(N_DEV)
        # growth again after more activations
        more = np.unique(rng.integers(2**20, 2**31 - 2, 1000,
                                      dtype=np.int64))
        arena.resolve_rows(more)
        check(N_DEV)
        # mesh reshard 8 → 4: same function at the new granularity
        await e.reshard(_mesh(4))
        check(4)

    run(main())


# ---------------------------------------------------------------------------
# satellite: chaos — mesh reshard mid-traffic × eviction epochs
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_mesh_reshard_mid_traffic(run):
    """The chaos scenario the issue names: reshard the mesh 8→4→8 while
    routing traffic flows, evict idle sinks mid-run (eviction epochs ×
    exchange), and assert the mesh invariants — single activation,
    home-block placement, exchange accounting, and exact end-to-end
    conservation (no message lost or doubled)."""
    from orleans_tpu.chaos.invariants import (
        check_exchange_accounting,
        check_mesh_single_activation,
    )
    from orleans_tpu.tensor import MemoryVectorStore

    async def main():
        store = MemoryVectorStore()
        e = TensorEngine(mesh=_mesh(), initial_capacity=1024,
                         store=store)
        e.config.auto_fusion_ticks = 0
        n_src, n_sink = 256, 128
        src = np.arange(n_src, dtype=np.int64)
        sinks = np.arange(SINK_BASE, SINK_BASE + n_sink, dtype=np.int64)
        dst = build_ratio_destinations(src, sinks, N_DEV, 0.5, seed=3)
        e.arena_for("RouteSource").resolve_rows(src)
        e.arena_for("RouteSink").resolve_rows(sinks)
        inj = e.make_injector("RouteSource", "send", src)
        dst_d = jnp.asarray(dst.astype(np.int32))
        v = jnp.asarray(np.ones(n_src, np.float32))
        ticks = 0

        async def burst(n: int) -> None:
            nonlocal ticks
            for _ in range(n):
                inj.inject({"dst": dst_d, "v": v,
                            "tick": np.int32(ticks)})
                await e.drain_queues()
                ticks += 1

        await burst(3)
        await e.reshard(_mesh(4))          # mid-traffic shrink
        inj = e.make_injector("RouteSource", "send", src)
        await burst(3)
        # eviction epoch churn: evict EVERYTHING idle (write-back to the
        # store), then keep routing — sinks re-activate from storage
        await e.flush()
        evicted = e.collect_idle(max_idle_ticks=0)
        assert evicted > 0
        await burst(3)
        await e.reshard(_mesh(N_DEV))      # grow back
        inj = e.make_injector("RouteSource", "send", src)
        await burst(3)
        await e.flush()

        check_mesh_single_activation(e)
        check_exchange_accounting(e)
        # sinks with no post-eviction traffic live only in the store —
        # re-activation loads their state back (Catalog stage-2 analog)
        e.arena_for("RouteSink").resolve_rows(sinks)
        check_mesh_single_activation(e)
        _total, received = _sink_state(e, n_sink)
        assert received.sum() == n_src * 12  # every tick, exactly once

    run(main())


# ---------------------------------------------------------------------------
# satellite: metrics + dashboard plumbing
# ---------------------------------------------------------------------------

def test_route_metrics_declared_and_dashboard_row():
    from orleans_tpu.dashboard import render_text, view_from_snapshots
    from orleans_tpu.metrics import CATALOG, MetricsRegistry

    for name in ("route.cross_shard_msgs", "route.delivered_msgs",
                 "route.exchange_dropped", "route.exchanges",
                 "route.exchange_s", "arena.shard_occupancy"):
        assert name in CATALOG, name
    reg = MetricsRegistry(source="s1")
    reg.apply("route.cross_shard_msgs", 100.0, None)
    reg.apply("route.delivered_msgs", 150.0, None)
    reg.apply("route.exchanges", 4.0, None)
    reg.apply("route.exchange_dropped", 2.0, None)
    reg.apply("route.exchange_s", 0.5, None)
    view = view_from_snapshots([reg.snapshot()])
    xs = view["cluster"]["cross_shard"]
    assert xs["exchanged_messages"] == 100
    assert xs["delivered_messages"] == 150
    assert xs["dropped_redelivered"] == 2
    assert "cross-shard (on device)" in render_text(view)


def test_shard_occupancy_gauge(run):
    async def main():
        e = _engine(initial_capacity=16 * N_DEV)
        arena = e.arena_for("RouteSink")
        arena.resolve_rows(np.arange(200, dtype=np.int64))
        occ = arena.shard_occupancy()
        assert occ.sum() == 200 and len(occ) == N_DEV
        expected = np.bincount(shard_of_keys(
            np.arange(200, dtype=np.int64), N_DEV), minlength=N_DEV)
        np.testing.assert_array_equal(occ, expected)

    run(main())


# ---------------------------------------------------------------------------
# satellite: perfgate multichip artifact family
# ---------------------------------------------------------------------------

def test_perfgate_multichip_family(tmp_path):
    import json

    from orleans_tpu.perfgate import newest_bench_artifact, run_gate

    # opaque legacy rounds are skipped, never treated as regression-free
    (tmp_path / "MULTICHIP_r05.json").write_text(json.dumps(
        {"n_devices": 8, "rc": 0, "ok": True, "tail": ""}))
    structured = {"workload": "multichip", "n_devices": 8,
                  "aggregate_msgs_per_sec": 1000.0,
                  "exchange": {"dropped_msgs": 0}}
    (tmp_path / "MULTICHIP_BENCH.json").write_text(
        json.dumps(structured))
    found = newest_bench_artifact(str(tmp_path), family="multichip")
    assert found is not None
    assert found[0].endswith("MULTICHIP_BENCH.json")

    baseline = {"source": "test",
                "multichip_metrics": {
                    "aggregate": {"path": "aggregate_msgs_per_sec",
                                  "value": 900.0, "tolerance": 0.3,
                                  "direction": "higher"},
                    "dropped": {"path": "exchange.dropped_msgs",
                                "value": 0.0, "tolerance": 0.0,
                                "direction": "lower"}}}
    bp = tmp_path / "PERF_BASELINE.json"
    bp.write_text(json.dumps(baseline))
    verdict = run_gate(str(bp), family="multichip")
    assert verdict["status"] == "pass", verdict
    # a driver-wrapper structured round outranks the bench fallback
    (tmp_path / "MULTICHIP_r06.json").write_text(json.dumps(
        {"parsed": {**structured, "aggregate_msgs_per_sec": 50.0}}))
    verdict = run_gate(str(bp), family="multichip")
    assert verdict["status"] == "fail"
    assert verdict["artifact"].endswith("MULTICHIP_r06.json")

    # the repo's own baseline declares the multichip family
    repo_baseline = json.loads(
        open("PERF_BASELINE.json").read())
    assert repo_baseline.get("multichip_metrics"), \
        "PERF_BASELINE.json must carry multichip tolerance bands"


@pytest.mark.slow
def test_multichip_bench_tier_publishes_contract(run):
    """The structured multichip tier at plumbing scale: the artifact
    carries the sweep, exactness at every ratio, per-shard balance, the
    A/B toggles, and an embedded perfgate verdict — the fields the
    driver's MULTICHIP rounds become trackable through.  Full smoke:
    ``python bench.py --workload multichip --smoke``."""
    import bench

    stats = run(bench._multichip_tier(smoke=False,
                                      sizes=(1024, 512, 4, 2)))
    assert stats["workload"] == "multichip"
    assert stats["exact_all_ratios"], stats["sweep"]
    assert set(stats["sweep"]) == {"r0", "r10", "r50", "r90"}
    for s in stats["sweep"].values():
        assert s["exact_vs_unfused_replay"]
        assert s["exchange_dropped"] == 0
        assert len(s["per_shard_sink_occupancy"]) == 8
    assert stats["sweep"]["r50"]["cross_shard_msgs"] > 0
    assert stats["aggregate_msgs_per_sec"] > 0
    assert "exchange_speedup_at_50" in stats
    assert stats["host_slab_reference"]["total_msgs_per_sec"] > 0
    assert stats["perfgate"]["family"] == "multichip"
