"""Admin/management surface: per-silo control target + cluster-wide
management grain.

Parity: reference SiloControl (a system target on every silo exposing
runtime stats, grain statistics, forced collection, directory ops —
reference: src/OrleansRuntime/Silo/SiloControl.cs:33) and ManagementGrain
(a normal grain that fans admin operations out to the SiloControl of each
selected silo — reference: src/OrleansRuntime/Core/ManagementGrain.cs:38).
The OrleansManager CLI drives this surface (orleans_tpu/manager.py;
reference: src/OrleansManager/Program.cs — grainstats, collect,
unregister, lookup).

TPU angle: grain statistics and forced collection cover BOTH planes —
host activations (catalog) and vector-grain arena rows (tensor engine),
so one admin surface manages the whole framework.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from orleans_tpu import Grain, grain_interface
from orleans_tpu.core.grain import grain_class
from orleans_tpu.ids import GrainId, SiloAddress


@dataclass
class SimpleGrainStatistic:
    """(reference: SimpleGrainStatistic — type/silo/activation count)"""

    grain_type: str
    silo: SiloAddress
    activation_count: int
    plane: str = "host"  # "host" (catalog) | "tensor" (arena rows)


@dataclass
class DetailedGrainReport:
    """(reference: DetailedGrainReport.cs)"""

    grain_id: GrainId
    silo: SiloAddress
    local_activations: List[str]
    directory_entry: Optional[str]
    is_directory_owner: bool


class SiloControl:
    """Per-silo admin system target (reference: SiloControl.cs:33)."""

    def __init__(self, silo) -> None:
        self.silo = silo

    async def ping(self, message: str = "") -> str:
        """(reference: SiloControl.Ping :46)"""
        return f"pong from {self.silo.address}"

    async def get_runtime_statistics(self):
        """(reference: GetRuntimeStatistics :101)"""
        from orleans_tpu.runtime.load_publisher import collect_silo_statistics
        return collect_silo_statistics(self.silo)

    async def get_activation_count(self) -> int:
        """(reference: GetActivationCount :134)"""
        return len(self.silo.catalog.directory)

    async def get_simple_grain_statistics(self) -> List[SimpleGrainStatistic]:
        """Per-type activation counts on this silo, both planes
        (reference: GetSimpleGrainStatistics :113)."""
        counts: Dict[str, int] = {}
        for act in self.silo.catalog.directory.all():
            counts[act.class_info.cls.__name__] = \
                counts.get(act.class_info.cls.__name__, 0) + 1
        stats = [SimpleGrainStatistic(t, self.silo.address, n)
                 for t, n in sorted(counts.items())]
        if self.silo.tensor_engine is not None:
            stats.extend(
                SimpleGrainStatistic(name, self.silo.address, a.live_count,
                                     plane="tensor")
                for name, a in sorted(self.silo.tensor_engine.arenas.items()))
        return stats

    async def force_activation_collection(self,
                                          age_limit: float = 0.0) -> int:
        """Collect idle host activations now; age_limit 0 = collect all
        idle (reference: ForceActivationCollection :89)."""
        return self.silo.catalog.collect_idle_activations(
            age_limit if age_limit > 0 else 0.0)

    async def force_tensor_collection(self, idle_ticks: int = 0) -> int:
        """Collect idle vector-grain rows now (the tensor-plane analog of
        forced collection)."""
        engine = self.silo.tensor_engine
        if engine is None:
            return 0
        return engine.collect_idle(idle_ticks)

    async def get_tensor_statistics(self) -> dict:
        """The tick engine's performance counters — throughput, TRUE
        latency percentiles, arena row counts (the tensor-plane analog of
        GetRuntimeStatistics; reference: SiloControl stats surface).
        Rows carry the silo address so operators can attribute a hot or
        stalled engine."""
        engine = self.silo.tensor_engine
        if engine is None:
            return {}
        return {"silo": str(self.silo.address), **engine.snapshot()}

    async def capture_profile(self, ticks: int = 8) -> dict:
        """Start a jax.profiler deep capture over the next ``ticks``
        engine ticks (tensor/profiler.py); returns the capture event
        record with the trace directory path.  The same record rides
        the flight-recorder dump, so an operator-triggered capture and
        a threshold-triggered one leave identical evidence."""
        return self.silo.capture_profile(ticks, reason="silo_control")

    async def get_detailed_grain_report(self, grain_id: GrainId
                                        ) -> DetailedGrainReport:
        """(reference: GetDetailedGrainReport :120)"""
        directory = self.silo.grain_directory
        entry = directory.partition.lookup(grain_id)
        return DetailedGrainReport(
            grain_id=grain_id,
            silo=self.silo.address,
            local_activations=[
                str(a.address)
                for a in self.silo.catalog.directory.activations_of(grain_id)],
            directory_entry=str(entry) if entry is not None else None,
            is_directory_owner=directory.owner_of(grain_id)
            == self.silo.address,
        )

    async def set_log_level(self, logger_name: str, level: int) -> bool:
        """(reference: SetLogLevel :69)"""
        import logging
        logging.getLogger(logger_name).setLevel(level)
        return True

    async def directory_lookup(self, grain_id: GrainId) -> Optional[str]:
        addr = await self.silo.grain_directory.full_lookup(grain_id)
        return str(addr) if addr is not None else None

    async def directory_unregister(self, grain_id: GrainId) -> bool:
        """Force-remove a directory registration (the OrleansManager
        'unregister' repair command — reference: Program.cs unregister)."""
        addr = self.silo.grain_directory.try_local_lookup(grain_id)
        if addr is None:
            addr = await self.silo.grain_directory.full_lookup(grain_id)
        if addr is None:
            return False
        await self.silo.grain_directory.unregister(addr)
        return True


# ---------------------------------------------------------------------------
# ManagementGrain: cluster-wide fan-out (reference: ManagementGrain.cs:38)
# ---------------------------------------------------------------------------

@grain_interface
class IManagementGrain:
    async def get_hosts(self, only_active: bool = True) -> dict: ...
    async def get_total_activation_count(self) -> int: ...
    async def get_simple_grain_statistics(self) -> list: ...
    async def force_activation_collection(self, age_limit: float = 0.0) -> int: ...
    async def force_tensor_collection(self, idle_ticks: int = 0) -> int: ...
    async def get_runtime_statistics(self) -> list: ...
    async def get_tensor_statistics(self) -> list: ...
    async def capture_profile(self, ticks: int = 8) -> list: ...
    async def lookup(self, grain_id: GrainId) -> Optional[str]: ...
    async def unregister(self, grain_id: GrainId) -> bool: ...


@grain_class
class ManagementGrain(Grain, IManagementGrain):
    """Fan-out over every active silo's SiloControl
    (reference: ManagementGrain.cs:38 — GetSiloAddresses + per-silo
    ISiloControl calls gathered)."""

    @property
    def _silo(self):
        return self._activation.runtime.silo

    def _active(self) -> List[SiloAddress]:
        return list(self._silo.active_silos())

    async def _fanout(self, method: str, *args) -> List[Any]:
        import asyncio
        silo = self._silo
        results = await asyncio.gather(
            *(silo.system_rpc(target, "silo_control", method, args)
              for target in self._active()),
            return_exceptions=True)
        return [r for r in results if not isinstance(r, Exception)]

    async def get_hosts(self, only_active: bool = True) -> dict:
        """(reference: ManagementGrain.GetHosts)"""
        oracle = self._silo.membership_oracle
        if oracle is None:
            return {str(self._silo.address): "ACTIVE"}
        view = dict(oracle.view)
        # the oracle's table view may omit the local silo (it trusts its
        # own status field, like GetApproximateSiloStatuses includeMyself)
        view.setdefault(self._silo.address, oracle.my_status)
        return {str(s): status.name
                for s, status in view.items()
                if not only_active or status.name == "ACTIVE"}

    async def get_total_activation_count(self) -> int:
        return sum(await self._fanout("get_activation_count"))

    async def get_simple_grain_statistics(self) -> list:
        out: List[SimpleGrainStatistic] = []
        for chunk in await self._fanout("get_simple_grain_statistics"):
            out.extend(chunk)
        return out

    async def force_activation_collection(self,
                                          age_limit: float = 0.0) -> int:
        return sum(await self._fanout("force_activation_collection",
                                      age_limit))

    async def force_tensor_collection(self, idle_ticks: int = 0) -> int:
        return sum(await self._fanout("force_tensor_collection", idle_ticks))

    async def get_runtime_statistics(self) -> list:
        return await self._fanout("get_runtime_statistics")

    async def get_tensor_statistics(self) -> list:
        """Per-silo tick-engine counters, empty dicts filtered."""
        return [s for s in await self._fanout("get_tensor_statistics") if s]

    async def capture_profile(self, ticks: int = 8) -> list:
        """Cluster-wide deep capture: every silo starts a jax.profiler
        trace over its next ``ticks`` ticks; returns the per-silo
        capture records (error entries filtered by _fanout)."""
        return await self._fanout("capture_profile", ticks)

    async def lookup(self, grain_id: GrainId) -> Optional[str]:
        return await self._silo.system_rpc(
            self._silo.grain_directory.owner_of(grain_id), "silo_control",
            "directory_lookup", (grain_id,))

    async def unregister(self, grain_id: GrainId) -> bool:
        return await self._silo.system_rpc(
            self._silo.grain_directory.owner_of(grain_id), "silo_control",
            "directory_unregister", (grain_id,))
