"""Turn semantics: single-threading, reentrancy, deadlock, lifecycle, timers.

Reference analogs: Tester/BasicActivationTests, GrainActivateDeactivateTests,
ReentrancyTests, DeadlockDetectionTests, TimerTests, StatelessWorkerTests,
ExceptionPropagationTests.
"""

import asyncio

import pytest

from orleans_tpu.runtime.dispatcher import DeadlockError
from orleans_tpu.runtime.silo import Silo
from orleans_tpu.providers.memory_storage import MemoryStorage

from tests.fixture_grains import (
    IFailingGrain,
    ILifecycleGrain,
    IPingA,
    IReentrantGrain,
    ISlowGrain,
    ITimerGrain,
    IWorkerGrain,
    LifecycleGrain,
)


async def make_silo(**kw) -> Silo:
    silo = Silo(storage_providers={"Default": MemoryStorage()}, **kw)
    await silo.start()
    return silo


def test_non_reentrant_turns_serialize(run):
    async def main():
        silo = await make_silo()
        try:
            g = silo.attach_client().get_grain(ISlowGrain, 1)
            await asyncio.gather(g.slow_echo("a", 0.02), g.slow_echo("b", 0.02),
                                 g.slow_echo("c", 0.02))
            log = await g.get_log()
            # no interleaving: every start is immediately followed by its end
            for i in range(0, len(log), 2):
                assert log[i].split(":")[1] == log[i + 1].split(":")[1]
                assert log[i].startswith("start") and log[i + 1].startswith("end")
        finally:
            await silo.stop()

    run(main())


def test_read_only_interleaves(run):
    async def main():
        silo = await make_silo()
        try:
            g = silo.attach_client().get_grain(ISlowGrain, 2)
            results = await asyncio.gather(*(g.peek() for _ in range(5)))
            assert max(results) > 1  # read-only turns overlapped
        finally:
            await silo.stop()

    run(main())


def test_reentrant_interleaves(run):
    async def main():
        silo = await make_silo()
        try:
            g = silo.attach_client().get_grain(IReentrantGrain, 1)
            await asyncio.gather(*(g.slow(0.02) for _ in range(4)))
            assert await g.overlap() > 1
        finally:
            await silo.stop()

    run(main())


def test_deadlock_detection(run):
    async def main():
        silo = await make_silo()
        try:
            a = silo.attach_client().get_grain(IPingA, 1)
            # A(1) → B(2) → A(1).touch() is a call-chain cycle
            with pytest.raises(DeadlockError):
                await a.start_cycle(2)
        finally:
            await silo.stop()

    run(main())


def test_deadlock_detection_disabled_times_out(run):
    async def main():
        silo = await make_silo()
        silo.dispatcher.perform_deadlock_detection = False
        silo.runtime_client.response_timeout = 0.2
        try:
            a = silo.attach_client().get_grain(IPingA, 1)
            with pytest.raises(asyncio.TimeoutError):
                await a.start_cycle(2)
        finally:
            silo.kill()

    run(main())


def test_lifecycle_and_deactivate_on_idle(run):
    async def main():
        silo = await make_silo()
        try:
            g = silo.attach_client().get_grain(ILifecycleGrain, 7)
            before_act = LifecycleGrain.activated
            assert await g.events() == ["activate"]
            assert LifecycleGrain.activated == before_act + 1
            before = LifecycleGrain.deactivated
            await g.die()
            await asyncio.sleep(0.05)
            assert LifecycleGrain.deactivated == before + 1
            assert len(silo.catalog.directory) == 0
            # next call re-activates transparently (virtual actor contract)
            assert await g.events() == ["activate"]
            assert LifecycleGrain.activated == before_act + 2
        finally:
            await silo.stop()

    run(main())


def test_age_based_collection(run):
    async def main():
        silo = await make_silo()
        try:
            g = silo.attach_client().get_grain(ILifecycleGrain, 8)
            await g.events()
            assert len(silo.catalog.directory) == 1
            await asyncio.sleep(0.05)
            collected = silo.catalog.collect_idle_activations(age_limit=0.01)
            assert collected == 1
            await asyncio.sleep(0.05)
            assert len(silo.catalog.directory) == 0
        finally:
            await silo.stop()

    run(main())


def test_timers(run):
    async def main():
        silo = await make_silo()
        try:
            g = silo.attach_client().get_grain(ITimerGrain, 1)
            await g.start(0.02)
            await asyncio.sleep(0.15)
            ticks = await g.ticks()
            assert ticks >= 3
        finally:
            await silo.stop()

    run(main())


def test_stateless_worker_scales_out(run):
    async def main():
        silo = await make_silo()
        try:
            g = silo.attach_client().get_grain(IWorkerGrain, 0)
            ids = await asyncio.gather(*(g.work(0.03) for _ in range(4)))
            assert len(set(ids)) > 1  # multiple local replicas served
            assert len(set(ids)) <= 4  # bounded by max_local
        finally:
            await silo.stop()

    run(main())


def test_exception_propagation(run):
    async def main():
        silo = await make_silo()
        try:
            g = silo.attach_client().get_grain(IFailingGrain, 1)
            with pytest.raises(ValueError, match="kaboom"):
                await g.boom()
            assert await g.ok() == "fine"  # activation survives user faults
        finally:
            await silo.stop()

    run(main())


def test_request_context_flows(run):
    async def main():
        from orleans_tpu import RequestContext

        silo = await make_silo()
        try:
            g = silo.attach_client().get_grain(IFailingGrain, 2)
            RequestContext.set("trace_id", "t-123")
            assert await g.ok() == "fine"
        finally:
            await silo.stop()

    run(main())
