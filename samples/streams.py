"""Chat rooms & leaderboards — million-user scenarios riding the device
streams plane (tensor/streams_plane.py).

Both scenarios share one shape: a small-ish set of STREAMS (chat rooms /
leaderboards) with a large, churning SUBSCRIBER population (users /
board members).  The reference would run these as pub-sub over grains —
one rendezvous lookup + one grain call per (event, consumer)
(PubSubRendezvousGrain + PersistentStreamPullingAgent); here the
subscriber adjacency lives on device as arena CSR and a whole tick's
publishes fan out in one gather + segment reduction.

Exactness oracle (the routing-sweep discipline): every loader can REPLAY
its publish history against the HOST adjacency (numpy ``np.add.at`` /
``np.maximum.at`` — the per-event pub-sub delivery semantics, one
virtual grain call per (event, subscriber)) and compare the device
arenas field for field.  All checked fields are integers, so equality is
EXACT — the device delivery multiset equals the host replay or the test
fails, at every churn point (subscribe / unsubscribe / evict / slot
reuse).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from orleans_tpu.core.grain import batched_method
from orleans_tpu.tensor import (
    Batch,
    DeviceSubscriptions,
    VectorGrain,
    field,
    seg_max,
    seg_sum,
    vector_grain,
)

#: checksum mixers (primes) — integer, so device vs host equality is exact
_MSG_MIX = 1009
_SRC_MIX = 97


@vector_grain
class ChatRoomGrain(VectorGrain):
    """Stream ingress: one row per room.  ``publish`` records the
    room-side effects; delivery to every member rides the registered
    DeviceSubscriptions (engine.register_subscriptions)."""

    published = field(jnp.int32, 0)
    last_msg = field(jnp.int32, -1)

    @batched_method
    @staticmethod
    def publish(state, batch: Batch, n_rows: int):
        rows = batch.rows
        ones = jnp.asarray(batch.mask, jnp.int32)
        msg = jnp.where(batch.mask,
                        jnp.asarray(batch.args["msg_id"], jnp.int32), -1)
        return {
            **state,
            "published": state["published"] + seg_sum(ones, rows, n_rows),
            "last_msg": jnp.maximum(state["last_msg"],
                                    seg_max(msg, rows, n_rows)),
        }


@vector_grain
class ChatUserGrain(VectorGrain):
    """Subscriber: one row per user.  ``receive`` is segment-aware — a
    pull-mode delivery (lanes grouped by user row, Batch.segments) runs
    entirely scatter-free; push-mode redeliveries use the same handler
    through the ordinary scatter reductions."""

    received = field(jnp.int32, 0)
    last_msg = field(jnp.int32, -1)
    checksum = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def receive(state, batch: Batch, n_rows: int):
        rows, args, seg = batch.rows, batch.args, batch.segments
        ones = jnp.where(batch.mask, 1, 0).astype(jnp.int32)
        msg = jnp.asarray(args["msg_id"], jnp.int32)
        src = jnp.asarray(args["src_key"], jnp.int32)
        mix = jnp.where(batch.mask,
                        msg % _MSG_MIX + src % _SRC_MIX, 0)
        return {
            **state,
            "received": state["received"]
            + seg_sum(ones, rows, n_rows, segments=seg),
            "last_msg": jnp.maximum(
                state["last_msg"],
                seg_max(jnp.where(batch.mask, msg, -1), rows, n_rows,
                        segments=seg, fill=-1)),
            "checksum": state["checksum"]
            + seg_sum(mix, rows, n_rows, segments=seg),
        }


@vector_grain
class LeaderboardGrain(VectorGrain):
    """Stream ingress: one row per board; score posts aggregate on the
    board and broadcast to every follower."""

    rounds = field(jnp.int32, 0)
    top_score = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def post(state, batch: Batch, n_rows: int):
        rows = batch.rows
        ones = jnp.asarray(batch.mask, jnp.int32)
        score = jnp.where(batch.mask,
                          jnp.asarray(batch.args["score"], jnp.int32), 0)
        return {
            **state,
            "rounds": state["rounds"] + seg_sum(ones, rows, n_rows),
            "top_score": jnp.maximum(state["top_score"],
                                     seg_max(score, rows, n_rows)),
        }


@vector_grain
class BoardMemberGrain(VectorGrain):
    """Subscriber: a user following one or more boards."""

    updates = field(jnp.int32, 0)
    best_seen = field(jnp.int32, 0)
    checksum = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def observe(state, batch: Batch, n_rows: int):
        rows, args, seg = batch.rows, batch.args, batch.segments
        ones = jnp.where(batch.mask, 1, 0).astype(jnp.int32)
        score = jnp.asarray(args["score"], jnp.int32)
        mix = jnp.where(batch.mask,
                        score % _MSG_MIX
                        + jnp.asarray(args["src_key"], jnp.int32)
                        % _SRC_MIX, 0)
        return {
            **state,
            "updates": state["updates"]
            + seg_sum(ones, rows, n_rows, segments=seg),
            "best_seen": jnp.maximum(
                state["best_seen"],
                seg_max(jnp.where(batch.mask, score, 0), rows, n_rows,
                        segments=seg, fill=0)),
            "checksum": state["checksum"]
            + seg_sum(mix, rows, n_rows, segments=seg),
        }


# ---------------------------------------------------------------------------
# graph construction
# ---------------------------------------------------------------------------

def build_membership(n_streams: int, n_subscribers: int,
                     mean_memberships: float = 3.0, zipf_a: float = 1.2,
                     seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """(stream_keys, sub_keys) edge arrays: room/board popularity ~ Zipf
    (a few huge rooms, a long tail — the power-law stress), every
    subscriber belongs to at least one stream."""
    rng = np.random.default_rng(seed)
    n_edges = int(n_subscribers * mean_memberships)
    ranks = rng.permutation(n_streams) + 1
    weights = ranks.astype(np.float64) ** (-zipf_a)
    weights /= weights.sum()
    streams = rng.choice(n_streams, size=n_edges, p=weights)
    subs = np.concatenate([
        np.arange(n_subscribers),                       # coverage
        rng.integers(0, n_subscribers, n_edges - n_subscribers),
    ]) if n_edges >= n_subscribers else rng.integers(
        0, n_subscribers, n_edges)
    return streams.astype(np.int64), subs.astype(np.int64)


class _HostMirror:
    """The oracle's expected subscriber state, advanced per publish by
    the HOST pub-sub semantics (one virtual delivery per (event,
    subscriber)); re-derives its expansion whenever the adjacency
    changes."""

    def __init__(self, subs: DeviceSubscriptions, n_users: int) -> None:
        self.subs = subs
        self.received = np.zeros(n_users, np.int64)
        self.last_msg = np.full(n_users, -1, np.int64)
        self.checksum = np.zeros(n_users, np.int64)
        self._streams: Optional[np.ndarray] = None
        self._dsts: Optional[np.ndarray] = None
        self._srcs: Optional[np.ndarray] = None
        self._version = -1

    def _expansion(self, stream_keys: np.ndarray):
        if self._version != self.subs.layout_version \
                or self._streams is None \
                or not np.array_equal(self._streams, stream_keys):
            dsts, srcs = self.subs.host_expand(stream_keys)
            self._streams = stream_keys.copy()
            self._dsts, self._srcs = dsts, srcs
            self._version = self.subs.layout_version
        return self._dsts, self._srcs

    def publish(self, stream_keys: np.ndarray, msg_or_score: np.ndarray,
                kind: str = "chat") -> None:
        dsts, srcs = self._expansion(stream_keys)
        v = msg_or_score[srcs].astype(np.int64)
        sk = stream_keys[srcs].astype(np.int64)
        np.add.at(self.received, dsts, 1)
        if kind == "chat":
            np.maximum.at(self.last_msg, dsts, v)
        else:
            np.maximum.at(self.last_msg, dsts, np.maximum(v, 0))
        np.add.at(self.checksum, dsts, v % _MSG_MIX + sk % _SRC_MIX)

    def evict_keys(self, keys: np.ndarray) -> None:
        """Mirror invalidation on adjacency-affecting eviction (the
        subscription survives eviction — delivery reactivates — so the
        expected state does NOT change; only the cached expansion may)."""
        self._version = -1


def check_chat_exact(engine, n_users: int, mirror: _HostMirror,
                     kind: str = "chat") -> Dict[str, bool]:
    """Device arenas vs the host replay — exact integer equality (the
    delivery-multiset oracle: counts + order-free checksums + max)."""
    type_name = "ChatUserGrain" if kind == "chat" else "BoardMemberGrain"
    f_recv = "received" if kind == "chat" else "updates"
    f_max = "last_msg" if kind == "chat" else "best_seen"
    arena = engine.arena_for(type_name)
    users = np.arange(n_users, dtype=np.int64)
    rows, ok = arena.lookup_rows(users)
    live = ok
    got_recv = np.asarray(arena.state[f_recv])[rows]
    got_max = np.asarray(arena.state[f_max])[rows]
    got_sum = np.asarray(arena.state["checksum"])[rows]
    exp_max = mirror.last_msg if kind == "chat" \
        else np.maximum(mirror.last_msg, 0)
    return {
        "received_exact": bool(
            np.array_equal(got_recv[live], mirror.received[live])),
        "max_exact": bool(np.array_equal(got_max[live],
                                         exp_max[live])),
        "checksum_exact": bool(
            np.array_equal(got_sum[live], mirror.checksum[live])),
        "live_subscribers": int(live.sum()),
    }


# ---------------------------------------------------------------------------
# load drivers
# ---------------------------------------------------------------------------

def wire_chat(engine, n_rooms: int, n_users: int,
              mean_memberships: float = 3.0, seed: int = 0,
              subs: Optional[DeviceSubscriptions] = None
              ) -> DeviceSubscriptions:
    """Build the room→member adjacency, register it as the engine's
    publish route, and pre-activate + bind the steady publish pattern."""
    if subs is None:
        subs = DeviceSubscriptions(engine, "ChatUserGrain", "receive")
        streams, members = build_membership(n_rooms, n_users,
                                            mean_memberships, seed=seed)
        subs.subscribe_many(streams, members)
    engine.register_subscriptions("ChatRoomGrain", "publish", subs)
    engine.arena_for("ChatUserGrain").reserve(n_users)
    engine.arena_for("ChatUserGrain").resolve_rows(
        np.arange(n_users, dtype=np.int64))
    engine.arena_for("ChatRoomGrain").reserve(n_rooms)
    subs.bind(np.arange(n_rooms, dtype=np.int64))
    return subs


async def run_chat_load(engine, n_rooms: int = 1_000,
                        n_users: int = 100_000,
                        mean_memberships: float = 3.0,
                        n_ticks: int = 16, seed: int = 0,
                        subs: Optional[DeviceSubscriptions] = None,
                        verify: bool = False,
                        mirror: Optional[_HostMirror] = None
                        ) -> Dict[str, float]:
    """Every room gets one published message per tick; members absorb
    the fan-in through the plane.  Message accounting matches the
    reference's pub-sub: one publish per room + one delivery per
    (event, member edge)."""
    import jax as _jax

    subs = wire_chat(engine, n_rooms, n_users, mean_memberships, seed,
                     subs=subs)
    rooms = np.arange(n_rooms, dtype=np.int64)
    injector = engine.make_injector("ChatRoomGrain", "publish", rooms)
    if verify and mirror is None:
        mirror = _HostMirror(subs, n_users)
    arena = engine.arena_for("ChatUserGrain")
    edges = subs.edge_count

    msg_base = np.int32(seed * 1_000_000)
    t0 = time.perf_counter()
    for t in range(n_ticks):
        msg_ids = (np.arange(n_rooms, dtype=np.int32)
                   + np.int32(t * n_rooms) + msg_base)
        injector.stage({"msg_id": msg_ids})
        injector.inject()
        await engine.drain_queues()
        if mirror is not None:
            mirror.publish(rooms, msg_ids.astype(np.int64))
    await engine.flush()
    _jax.block_until_ready(arena.state["received"])
    elapsed = time.perf_counter() - t0

    events = (n_rooms + edges) * n_ticks
    stats: Dict[str, float] = {
        "rooms": n_rooms, "users": n_users, "edges": edges,
        "ticks": n_ticks, "seconds": elapsed, "events": events,
        "events_per_sec": events / elapsed,
    }
    if mirror is not None:
        stats["oracle"] = check_chat_exact(engine, n_users, mirror)
        stats["mirror"] = mirror
    return stats


async def run_leaderboard_load(engine, n_boards: int = 512,
                               n_members: int = 100_000,
                               mean_follows: float = 2.0,
                               n_ticks: int = 16, seed: int = 0,
                               verify: bool = False) -> Dict[str, float]:
    """Score rounds: every board posts one aggregated score per tick and
    broadcasts it to every follower (rank-watchers)."""
    import jax as _jax

    rng = np.random.default_rng(seed)
    subs = DeviceSubscriptions(engine, "BoardMemberGrain", "observe")
    streams, members = build_membership(n_boards, n_members,
                                        mean_follows, seed=seed + 1)
    subs.subscribe_many(streams, members)
    engine.register_subscriptions("LeaderboardGrain", "post", subs)
    engine.arena_for("BoardMemberGrain").reserve(n_members)
    engine.arena_for("BoardMemberGrain").resolve_rows(
        np.arange(n_members, dtype=np.int64))
    engine.arena_for("LeaderboardGrain").reserve(n_boards)
    boards = np.arange(n_boards, dtype=np.int64)
    subs.bind(boards)
    injector = engine.make_injector("LeaderboardGrain", "post", boards)
    mirror = _HostMirror(subs, n_members) if verify else None
    arena = engine.arena_for("BoardMemberGrain")
    edges = subs.edge_count

    scores = [rng.integers(1, 1_000_000, n_boards).astype(np.int32)
              for _ in range(n_ticks)]
    t0 = time.perf_counter()
    for t in range(n_ticks):
        injector.stage({"score": scores[t]})
        injector.inject()
        await engine.drain_queues()
        if mirror is not None:
            mirror.publish(boards, scores[t].astype(np.int64),
                           kind="board")
    await engine.flush()
    _jax.block_until_ready(arena.state["updates"])
    elapsed = time.perf_counter() - t0

    events = (n_boards + edges) * n_ticks
    stats: Dict[str, float] = {
        "boards": n_boards, "members": n_members, "edges": edges,
        "ticks": n_ticks, "seconds": elapsed, "events": events,
        "events_per_sec": events / elapsed,
    }
    if mirror is not None:
        stats["oracle"] = check_chat_exact(engine, n_members, mirror,
                                           kind="board")
    return stats


async def run_chat_stream_load(silo, provider_name: str = "cstream",
                               n_rooms: int = 1_000,
                               n_users: int = 100_000,
                               mean_memberships: float = 3.0,
                               n_slabs: int = 10, seed: int = 0,
                               subs: Optional[DeviceSubscriptions] = None
                               ) -> Dict[str, float]:
    """The PERSISTENT-STREAMS pipeline end to end, on the device plane:
    producers enqueue slab items into the durable queue, the pulling
    agent drains them in batched dequeue/ack transactions, the tensor
    sink injects each pull cycle's slab (staged h2d under the previous
    slab's compute), and the engine's registered subscriptions fan the
    publishes out to every member — the queue-fed twin of
    run_chat_load.  The silo must host a provider named
    ``provider_name`` with ``bind_tensor_sink("chat-pub",
    "ChatRoomGrain", "publish")``; call ``wire_chat`` on its engine
    first (or pass ``subs``)."""
    import asyncio

    from orleans_tpu.streams.core import StreamId

    provider = silo.stream_providers[provider_name]
    engine = silo.tensor_engine
    subs = wire_chat(engine, n_rooms, n_users, mean_memberships, seed,
                     subs=subs)
    edges = subs.edge_count
    rooms = np.arange(n_rooms, dtype=np.int64)
    stream_id = StreamId(provider=provider_name, namespace="chat-pub",
                         key=0)
    slabs = [{"key": rooms.copy(),
              "msg_id": (np.arange(n_rooms, dtype=np.int32)
                         + np.int32(t * n_rooms))}
             for t in range(n_slabs)]
    agents = provider.manager.agents
    delivered0 = sum(a.delivered for a in agents.values())

    t0 = time.perf_counter()
    for slab in slabs:
        await provider.produce(stream_id, [slab])
    while sum(a.delivered for a in agents.values()) - delivered0 \
            < n_slabs:
        await asyncio.sleep(0.002)
    await engine.flush()
    import jax as _jax
    _jax.block_until_ready(
        engine.arena_for("ChatUserGrain").state["received"])
    elapsed = time.perf_counter() - t0

    # one queue event per (slab, room) + one delivery per member edge
    messages = (n_rooms + edges) * n_slabs
    return {
        "rooms": n_rooms, "users": n_users, "edges": edges,
        "slabs": n_slabs, "seconds": elapsed, "messages": messages,
        "messages_per_sec": messages / elapsed,
        "pipeline": "producer → durable queue (batched enqueue) → "
                    "pulling agent (ONE dequeue+ack transaction per "
                    "cycle) → staged slab → ChatRoomGrain.publish → "
                    "device subscription fan-out (pull-mode)",
    }


async def run_chat_load_fused(engine, n_rooms: int = 1_000,
                              n_users: int = 100_000,
                              mean_memberships: float = 3.0,
                              n_ticks: int = 32, window: int = 16,
                              seed: int = 0,
                              subs: Optional[DeviceSubscriptions] = None
                              ) -> Dict[str, float]:
    """Chat through the FUSED tick path: the publish kernel + the pull
    CSR expansion + the member fan-in compile into one program per
    window (the route's offsets ride as trace constants; an adjacency
    rebuild or live toggle re-traces, cause config_toggle)."""
    import jax as _jax

    from orleans_tpu.tensor.fused import plan_windows

    subs = wire_chat(engine, n_rooms, n_users, mean_memberships, seed,
                     subs=subs)
    rooms = np.arange(n_rooms, dtype=np.int64)
    prog = engine.fuse_ticks("ChatRoomGrain", "publish", rooms)
    arena = engine.arena_for("ChatUserGrain")
    edges = subs.edge_count
    window, n_windows, n_ticks = plan_windows(window, n_ticks)

    def stacked_for(base: int):
        return {"msg_id": (jnp.arange(window, dtype=jnp.int32)[:, None]
                           * np.int32(n_rooms)
                           + jnp.arange(n_rooms, dtype=jnp.int32)[None]
                           + np.int32(base * n_rooms))}

    prog.run(stacked_for(0))  # untimed warm window (compile)
    _jax.block_until_ready(arena.state["received"])
    windows = [stacked_for(w + 1) for w in range(n_windows)]
    _jax.block_until_ready(windows)

    t0 = time.perf_counter()
    for stacked in windows:
        prog.run(stacked)
    _jax.block_until_ready(arena.state["received"])
    elapsed = time.perf_counter() - t0
    misses = prog.verify()
    if misses:  # not assert: -O must not skip exactness verification
        raise RuntimeError(
            f"fused chat window missed {misses} deliveries")

    events = (n_rooms + edges) * n_ticks
    return {
        "rooms": n_rooms, "users": n_users, "edges": edges,
        "ticks": n_ticks, "seconds": elapsed, "events": events,
        "events_per_sec": events / elapsed, "engine": "fused",
    }
