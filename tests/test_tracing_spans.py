"""Distributed tracing plane (orleans_tpu/spans.py): span model, trace
propagation over RequestContext, batched engine-tick spans, the flight
recorder, and the three-ledger drop lint; plus the satellite fixes —
TraceLogger bulk-summary/prune and bounded telemetry capture."""

import asyncio
import logging
import time

import numpy as np
import pytest

from orleans_tpu import Grain, grain_interface
from orleans_tpu.client import GrainClient
from orleans_tpu.core.context import RequestContext
from orleans_tpu.core.grain import grain_class
from orleans_tpu.config import SiloConfig
from orleans_tpu.resilience import (
    DEAD_LETTER_REASONS,
    REASON_COUNTER_ATTR,
    REASON_EXPIRED,
    REASON_SHED,
)
from orleans_tpu.spans import (
    DEAD_LETTER_SPAN_STATUS,
    STATUS_ERROR,
    STATUS_OK,
    SpanRecorder,
    TRACE_KEY,
)
from orleans_tpu.stats import SiloMetrics
from orleans_tpu.testing.cluster import TestingCluster


# ---------------------------------------------------------------------------
# lint: every dead-letter reason code keeps THREE ledgers in sync — a
# SiloMetrics counter, a DeadLetterRing reason code, and a span status
# (extends check_dead_letter_accounting's two-ledger invariant)
# ---------------------------------------------------------------------------

@pytest.mark.tracing
def test_dead_letter_reasons_have_counter_and_span_status():
    metrics = SiloMetrics()
    for reason in DEAD_LETTER_REASONS:
        attr = REASON_COUNTER_ATTR.get(reason)
        assert attr is not None, f"{reason}: no SiloMetrics counter mapping"
        assert hasattr(metrics, attr), \
            f"{reason}: SiloMetrics has no attribute {attr!r}"
        assert isinstance(getattr(metrics, attr), int)
        assert reason in DEAD_LETTER_SPAN_STATUS, \
            f"{reason}: no span status mapping"
    # no stale mappings for reasons that no longer exist, and statuses
    # stay distinguishable per reason
    assert set(REASON_COUNTER_ATTR) == set(DEAD_LETTER_REASONS)
    assert set(DEAD_LETTER_SPAN_STATUS) == set(DEAD_LETTER_REASONS)
    statuses = list(DEAD_LETTER_SPAN_STATUS.values())
    assert len(statuses) == len(set(statuses))


# ---------------------------------------------------------------------------
# span recorder: head sampling, always-on failures, drop spans
# ---------------------------------------------------------------------------

@pytest.mark.tracing
def test_sampling_discards_ok_keeps_errors_and_drops():
    class _Msg:
        request_context = None

    rec = SpanRecorder("t", sample_rate=0.0, seed=1)
    trace = rec.begin_trace()
    assert trace is not None and not trace["sampled"]
    # unsampled traces open NO hop spans (the hot-path cost envelope)...
    span = rec.start("a", "client.send", trace)
    assert span is None
    rec.close_hop(span, _Msg(), "a", "client.send", STATUS_OK)
    assert rec.recorded == 0

    # ...but failures record ALWAYS, retroactively, against the carried
    # trace context
    msg = _Msg()
    msg.request_context = {TRACE_KEY: trace}
    rec.close_hop(None, msg, "b", "client.send", STATUS_ERROR, error="boom")
    assert rec.recorded == 1
    failed = rec.flight.spans[-1]
    assert failed.trace_id == trace["trace_id"]
    assert failed.status == STATUS_ERROR

    rec.drop(REASON_SHED, detail="d", trace_id=trace["trace_id"])
    assert rec.drop_spans == 1 and rec.recorded == 2
    statuses = [s.status for s in rec.flight.spans]
    assert DEAD_LETTER_SPAN_STATUS[REASON_SHED] in statuses

    # unsampled-OK events allocate nothing
    rec.event("e", "forward", trace)
    assert rec.recorded == 2

    disabled = SpanRecorder("off", enabled=False)
    assert disabled.begin_trace() is None
    assert disabled.start("x", "k", {"trace_id": "t", "sampled": True}) is None


@pytest.mark.tracing
def test_sampled_trace_records_and_force_sample():
    rec = SpanRecorder("t", sample_rate=1.0, seed=1)
    trace = rec.begin_trace()
    assert trace["sampled"]
    span = rec.start("a", "client.send", trace)
    rec.finish(span)
    assert rec.recorded == 1
    forced = SpanRecorder("t2", sample_rate=0.0).begin_trace(
        force_sample=True)
    assert forced["sampled"]


@pytest.mark.tracing
def test_flight_recorder_ring_bound_and_dump_correlation():
    rec = SpanRecorder("t", sample_rate=1.0, flight_capacity=4, seed=2)
    traces = [rec.begin_trace() for _ in range(6)]
    for t in traces:
        rec.finish(rec.start("hop", "client.send", t))
    assert len(rec.flight.spans) == 4 and rec.flight.dropped == 2
    kept_tid = traces[-1]["trace_id"]
    dead_letters = [{"reason": REASON_EXPIRED, "trace_id": kept_tid,
                     "detail": "x"},
                    {"reason": REASON_EXPIRED, "trace_id": "unrelated",
                     "detail": "y"}]
    dump = rec.flight.dump("test", dead_letters=dead_letters,
                           breaker_transitions=[{"target": "s", "to": "open"}])
    assert dump["reason"] == "test"
    assert kept_tid in dump["traces"]
    assert dump["traces"][kept_tid]["dead_letters"][0]["detail"] == "x"
    assert dump["dead_letters_untraced"][0]["detail"] == "y"
    assert dump["breaker_transitions"][0]["to"] == "open"


# ---------------------------------------------------------------------------
# satellite: TraceLogger bulk-throttle summary + prune
# ---------------------------------------------------------------------------

class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


@pytest.mark.tracing
def test_trace_logger_bulk_summary_on_window_roll():
    from orleans_tpu.tracing import TraceLogger
    logger = TraceLogger("test.bulk.roll")
    logger.bulk_window = 0.05
    cap = _Capture()
    logger._log.addHandler(cap)
    logger._log.propagate = False
    try:
        for _ in range(9):
            logger.warn("spam", code=42)
        # limit=5 pass + 1 "further messages suppressed" notice
        assert len(cap.messages) == 6
        time.sleep(0.06)
        logger.warn("spam again", code=42)  # window rolled
        summaries = [m for m in cap.messages if "suppressed 4 messages" in m]
        assert summaries, cap.messages  # 9 - 5 = 4 swallowed, now surfaced
        assert any("spam again" in m for m in cap.messages)
    finally:
        logger._log.removeHandler(cap)


@pytest.mark.tracing
def test_trace_logger_prunes_stale_bulk_entries():
    from orleans_tpu.tracing import TraceLogger
    logger = TraceLogger("test.bulk.prune")
    logger.bulk_window = 0.05
    cap = _Capture()
    logger._log.addHandler(cap)
    logger._log.propagate = False
    try:
        for code in range(100, 130):
            for _ in range(7):
                logger.warn("noise", code=code)
        assert len(logger._bulk) == 30
        time.sleep(0.06)
        logger.warn("other", code=999)  # triggers the prune sweep
        assert len(logger._bulk) == 1  # only the live (999) entry survives
        # every pruned over-limit code surfaced its suppression summary
        summaries = [m for m in cap.messages if "suppressed 2 messages" in m]
        assert len(summaries) == 30
    finally:
        logger._log.removeHandler(cap)


# ---------------------------------------------------------------------------
# satellite: bounded InMemoryTelemetryConsumer
# ---------------------------------------------------------------------------

@pytest.mark.tracing
def test_inmemory_consumer_capture_is_bounded():
    from orleans_tpu.telemetry import InMemoryTelemetryConsumer
    sink = InMemoryTelemetryConsumer(capture_limit=5)
    for i in range(8):
        sink.track_metric(f"m{i}", float(i))
    assert len(sink.metrics) == 5
    assert sink.dropped == 3
    assert sink.metrics[0][0] == "m3"  # newest retained
    sink.track_span({"span_id": "s"})
    assert list(sink.spans) == [{"span_id": "s"}]


# ---------------------------------------------------------------------------
# RequestContext + trace propagation: client → gateway → silo →
# cross-silo forward → resend; cleared between turns
# ---------------------------------------------------------------------------

@grain_interface
class ICtxEcho:
    async def who(self) -> dict: ...
    async def leak(self) -> None: ...
    async def read_leak(self): ...


@grain_class
class CtxEchoGrain(Grain, ICtxEcho):
    async def who(self) -> dict:
        t = RequestContext.get(TRACE_KEY)
        return {"k": RequestContext.get("k"),
                "trace_id": t.get("trace_id") if t else None,
                "sampled": bool(t and t.get("sampled"))}

    async def leak(self) -> None:
        RequestContext.set("leaked", "x")

    async def read_leak(self):
        return RequestContext.get("leaked")


async def _key_hosted_on(cluster, silo, start: int = 0) -> int:
    """Activate candidate grains until one lands on ``silo`` (default
    placement is hash-based, so the host follows the key)."""
    factory = cluster.silos[0].attach_client()
    for key in range(start, start + 64):
        ref = factory.get_grain(ICtxEcho, key)
        await ref.who()
        if cluster.find_silo_hosting(ref.grain_id) is silo:
            return key
    raise AssertionError("no key hashed to the target silo in 64 tries")


@pytest.mark.tracing
def test_request_context_survives_client_gateway_cross_silo_resend(run):
    from orleans_tpu.runtime.messaging import Category, Direction

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        client = None
        try:
            # pick a key hosted on silos[1], so the external client's
            # calls via silos[0]'s gateway must cross silos
            key = await _key_hosted_on(cluster, cluster.silos[1])

            client = await GrainClient(trace_sample_rate=1.0).connect(
                cluster.silos[0])
            ref = client.get_grain(ICtxEcho, key)
            RequestContext.set("k", "v")
            RequestContext.set(TRACE_KEY, {"trace_id": "fixed-tid",
                                           "span_id": "", "sampled": True})
            got = await ref.who()
            # app context AND trace ids survive client → gateway →
            # silo0 → cross-silo hop to silo1
            assert got["k"] == "v"
            assert got["trace_id"] == "fixed-tid"
            assert got["sampled"] is True

            # resend leg: reject the next request once at the hosting
            # silo; the client's transparent resend must re-carry the
            # same exported context
            original = cluster.silos[1].dispatcher._should_inject_error
            fired = {"n": 0}

            def inject_once(msg):
                if (msg.category == Category.APPLICATION
                        and msg.direction == Direction.REQUEST
                        and msg.method_name == "who" and fired["n"] == 0):
                    fired["n"] += 1
                    return True
                return False

            cluster.silos[1].dispatcher._should_inject_error = inject_once
            try:
                got = await ref.who()
            finally:
                cluster.silos[1].dispatcher._should_inject_error = original
            assert fired["n"] == 1
            assert client.requests_resent == 1
            assert got["k"] == "v" and got["trace_id"] == "fixed-tid"

            # context set INSIDE a turn must not leak into the next turn
            # on the same activation
            RequestContext.clear()
            await ref.leak()
            assert await ref.read_leak() is None

            # and with no ambient trace the client mints one per request
            # (ingress): the grain still sees SOME trace id, not ours
            got = await ref.who()
            assert got["trace_id"] not in (None, "fixed-tid")
        finally:
            if client is not None:
                await client.close()
            await cluster.stop()

    run(main())


@pytest.mark.tracing
def test_cross_silo_trace_spans_reach_both_silos(run):
    """A sampled request through the cluster leaves spans on both the
    sending and executing silo under ONE trace id.  The sampled call
    RIDES the batched planes end to end (it no longer falls back): the
    trace crosses the silo→silo fabric as a frame column and BOTH silos
    record their window-link hops.  A request carrying a rich ambient
    context keeps the per-message pipeline and still reaches the
    executing silo's turn and queue-wait hops under its trace."""

    async def main():
        def cfg(name):
            c = SiloConfig(name=name)
            c.tracing.sample_rate = 1.0
            return c

        cluster = await TestingCluster(n_silos=2,
                                       config_factory=cfg).start()
        try:
            key = await _key_hosted_on(cluster, cluster.silos[1],
                                       start=1000)
            f0 = cluster.silos[0].attach_client()
            got = await f0.get_grain(ICtxEcho, key).who()
            tid = got["trace_id"]
            assert tid
            kinds0 = {s.kind for s in cluster.silos[0].spans.flight.spans
                      if s.trace_id == tid}
            kinds1 = {s.kind for s in cluster.silos[1].spans.flight.spans
                      if s.trace_id == tid}
            assert "rpc.window.link" in kinds0
            assert "rpc.window.link" in kinds1

            # a rich ambient context pins the per-message pipeline: the
            # same trace id reaches the executing silo's turn and
            # queue-wait hops through the envelope
            RequestContext.set("k", "v")
            RequestContext.set(TRACE_KEY, {"trace_id": "pm-tid",
                                           "span_id": "", "sampled": True})
            try:
                got = await f0.get_grain(ICtxEcho, key).who()
            finally:
                RequestContext.clear()
            assert got["trace_id"] == "pm-tid" and got["k"] == "v"
            kinds1 = {s.kind for s in cluster.silos[1].spans.flight.spans
                      if s.trace_id == "pm-tid"}
            assert "activation.turn" in kinds1
            assert "dispatch.queue" in kinds1
        finally:
            await cluster.stop()

    run(main())


# ---------------------------------------------------------------------------
# batched engine-tick spans
# ---------------------------------------------------------------------------

def _define_span_counter():
    import jax.numpy as jnp

    from orleans_tpu.tensor import Batch, VectorGrain, field, seg_sum
    from orleans_tpu.tensor.vector_grain import (
        batched_method,
        vector_grain,
        vector_type,
    )

    if vector_type("SpanCounter") is not None:
        return

    @vector_grain
    class SpanCounter(VectorGrain):
        total = field(jnp.float32, 0.0)

        @batched_method
        @staticmethod
        def poke(state, batch: Batch, n_rows: int):
            return {
                "total": state["total"] + seg_sum(batch.args["v"],
                                                  batch.rows, n_rows),
            }, None, ()


@pytest.mark.tracing
def test_engine_tick_spans_are_batched_and_linked(run):
    from orleans_tpu.runtime.silo import Silo

    async def main():
        _define_span_counter()
        silo = Silo(name="tick-span")
        await silo.start()
        try:
            engine = silo.tensor_engine
            RequestContext.set(TRACE_KEY, {"trace_id": "tick-tid",
                                           "span_id": "", "sampled": True})
            n = 64
            engine.send_batch("SpanCounter", "poke",
                              np.arange(n, dtype=np.int64),
                              {"v": np.ones(n, np.float32)})
            await engine.flush()
            RequestContext.clear()
            spans = list(silo.spans.flight.spans)
            ticks = [s for s in spans if s.kind == "engine.tick"]
            links = [s for s in spans if s.kind == "engine.tick.link"]
            # BATCHED: one span per executing tick, never per message
            assert ticks and len(ticks) < n
            executed = [t for t in ticks if t.attrs["messages"] > 0]
            assert sum(t.attrs["messages"] for t in executed) >= n
            assert any("SpanCounter.poke" in t.attrs["per_method"]
                       for t in executed)
            # the tick is the shared child of the request that rode it
            assert links and links[0].trace_id == "tick-tid"
            tick_ids = {t.span_id for t in ticks}
            assert links[0].attrs["tick_span_id"] in tick_ids
        finally:
            await silo.stop(graceful=False)

    run(main())


# ---------------------------------------------------------------------------
# dead letters ↔ drop spans ↔ flight dump correlation
# ---------------------------------------------------------------------------

@pytest.mark.tracing
def test_dead_letter_emits_drop_span_and_dump_correlates(run):
    from orleans_tpu.runtime.messaging import Category, Direction, Message
    from orleans_tpu.runtime.silo import Silo

    async def main():
        silo = Silo(name="drop-span")
        await silo.start()
        try:
            msg = Message(
                category=Category.APPLICATION, direction=Direction.REQUEST,
                method_name="work",
                request_context={TRACE_KEY: {"trace_id": "drop-tid",
                                             "span_id": "abc",
                                             "sampled": False}},
                expiration=time.monotonic() - 1.0)  # already expired
            silo.dead_letters.record(msg, REASON_EXPIRED, "expired in test")
            assert silo.dead_letters.entries[-1]["trace_id"] == "drop-tid"
            # the drop span recorded even though the trace was UNSAMPLED
            drops = [s for s in silo.spans.flight.spans if s.kind == "drop"]
            assert drops and drops[-1].trace_id == "drop-tid"
            assert drops[-1].status == DEAD_LETTER_SPAN_STATUS[REASON_EXPIRED]
            dump = silo.flight_dump("test")
            assert "drop-tid" in dump["traces"]
            assert dump["traces"]["drop-tid"]["dead_letters"], dump
        finally:
            await silo.stop(graceful=False)

    run(main())
