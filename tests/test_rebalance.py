"""Closed-loop rebalance plane (runtime/rebalancer.py + the batched
live-migration primitive).

Covers the PR's contracts: the PURE planner against synthetic
HotSet/skew fixtures (move budget, hysteresis, no-move-below-threshold,
burning-shard selection, cooldown, idle disarm, SLO-burn trigger
halving), arena/engine migration exactness against a never-migrated
oracle — including grains journaled and checkpointed ACROSS the move,
recovered after a hard kill — the in-flight cached-row redelivery
discipline, the closed shard loop end to end (hot spot detected from
the plane's own telemetry → grains migrate off the burning shard →
telemetry converges), cross-silo migration (placement override +
state-slab adoption + routing), elastic join/drain handoff migration,
and the host-path regression: migrating a catalog activation bumps the
deactivation epoch so the batched RPC plane's pre-resolved invoke
tables never touch the dead activation.
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from orleans_tpu.config import (
    MetricsConfig,
    RebalanceConfig,
    SiloConfig,
    TensorEngineConfig,
)
from orleans_tpu.runtime.rebalancer import (
    ArenaSignals,
    RebalanceController,
    RebalancePlanner,
)
from orleans_tpu.tensor import Batch, TensorEngine, VectorGrain, field, seg_sum
from orleans_tpu.tensor.arena import shard_of_keys
from orleans_tpu.tensor.vector_grain import (
    batched_method,
    vector_grain,
    vector_type,
)
from orleans_tpu.testing import TestingCluster

pytestmark = pytest.mark.rebalance


def _define_ledger():
    if vector_type("RebalLedger") is not None:
        return

    @vector_grain
    class RebalLedger(VectorGrain):
        balance = field(jnp.int32, 0)

        @batched_method
        @staticmethod
        def deposit(state, batch: Batch, n_rows: int):
            return {**state, "balance": state["balance"]
                    + seg_sum(batch.args["amount"], batch.rows,
                              n_rows)}, None, ()


_define_ledger()


# ---------------------------------------------------------------------------
# planner decision logic (pure — synthetic HotSet/skew fixtures, no engine)
# ---------------------------------------------------------------------------

def _cfg(**kw) -> RebalanceConfig:
    base = dict(enabled=True, trigger_share=0.4,
                hysteresis_intervals=1, cooldown_intervals=0,
                move_budget=4, min_grain_share=0.0,
                min_interval_msgs=100)
    base.update(kw)
    return RebalanceConfig(**base)


def _sig(shard_msgs, hot=None, n_shards=4) -> ArenaSignals:
    return ArenaSignals(
        arena="RebalLedger", n_shards=n_shards,
        interval_shard_msgs=np.asarray(shard_msgs, dtype=np.int64),
        hot=hot or [])


def _hot(keys, shard, share=0.1):
    return [{"key": int(k), "msgs": 100, "share": share,
             "shard": shard} for k in keys]


def test_planner_no_move_below_threshold():
    p = RebalancePlanner(_cfg(trigger_share=0.6))
    sig = _sig([500, 200, 200, 100], hot=_hot([1, 2], 0))
    assert p.plan([sig]) == []
    assert p.skipped_below_trigger == 1
    # ... and the balanced case can never trigger (the 1.25/n floor)
    p2 = RebalancePlanner(_cfg(trigger_share=0.01))
    assert p2.plan([_sig([250, 250, 250, 251],
                         hot=_hot([1], 3))]) == []


def test_planner_hysteresis():
    p = RebalancePlanner(_cfg(hysteresis_intervals=2))
    sig = lambda: _sig([900, 50, 25, 25], hot=_hot([1, 2, 3], 0))  # noqa: E731
    assert p.plan([sig()]) == []          # first over-trigger interval
    assert p.skipped_hysteresis == 1
    moves = p.plan([sig()])               # second arms the move
    assert len(moves) == 1
    # an idle interval DISARMS: the count starts over
    assert p.plan([_sig([0, 0, 0, 0])]) == []
    assert p.plan([sig()]) == []
    assert p.skipped_hysteresis == 2


def test_planner_move_budget_and_burning_shard_selection():
    p = RebalancePlanner(_cfg(move_budget=3))
    hot = _hot([10, 11, 12, 13, 14], 2) + _hot([50, 51], 0)
    moves = p.plan([_sig([50, 25, 900, 25], hot=hot)])
    assert len(moves) == 1
    mv = moves[0]
    assert mv.src_shard == 2
    # budget caps the wave, movers come ONLY from the burning shard
    assert len(mv.keys) == 3
    assert set(mv.keys.tolist()) <= {10, 11, 12, 13, 14}
    # destinations never include the burning shard, coolest first
    assert 2 not in mv.dst_shards.tolist()
    assert mv.dst_shards[0] in (1, 3)  # the two coolest shards


def test_planner_min_grain_share_filters_cold_movers():
    p = RebalancePlanner(_cfg(min_grain_share=0.05))
    hot = [{"key": 1, "msgs": 10, "share": 0.01, "shard": 0},
           {"key": 2, "msgs": 900, "share": 0.12, "shard": 0}]
    moves = p.plan([_sig([900, 50, 25, 25], hot=hot)])
    assert len(moves) == 1
    assert moves[0].keys.tolist() == [2]
    assert p.pending_replications == []


def test_planner_replicate_share_routes_to_replication():
    """A grain whose OWN share clears replicate_share is beyond the
    single-shard ceiling: it leaves the mover list and becomes a
    Replicate decision (migrating it would just relocate the burn)."""
    p = RebalancePlanner(_cfg(min_grain_share=0.05))
    hot = [{"key": 1, "msgs": 60, "share": 0.06, "shard": 0},
           {"key": 2, "msgs": 900, "share": 0.6, "shard": 0}]
    moves = p.plan([_sig([900, 50, 25, 25], hot=hot)])
    assert len(moves) == 1
    assert moves[0].keys.tolist() == [1]          # only the mild mover
    assert len(p.pending_replications) == 1
    rp = p.pending_replications[0]
    assert rp.key == 2 and rp.src_shard == 0
    assert rp.k >= 2 and rp.fallback_dst != 0
    assert p.replications_planned == 1
    # replicate_share=0 disables the lever entirely (pure migration)
    p2 = RebalancePlanner(_cfg(min_grain_share=0.05,
                               replicate_share=0.0))
    moves2 = p2.plan([_sig([900, 50, 25, 25], hot=hot)])
    assert moves2[0].keys.tolist() == [1, 2]
    assert p2.pending_replications == []


def test_planner_hot_grain_blocked_routes_to_replication():
    """THE BUGFIX: a burning shard whose heat rides one grain below the
    mover floor used to spin forever — hysteresis armed, zero
    candidates, zero action every interval.  It now counts
    hot_grain_blocked and routes the hottest grain to replication."""
    p = RebalancePlanner(_cfg(min_grain_share=0.2))
    hot = [{"key": 9, "msgs": 850, "share": 0.14, "shard": 0}]
    moves = p.plan([_sig([900, 50, 25, 25], hot=hot)])
    assert moves == []
    assert p.hot_grain_blocked == 1
    assert p.skipped_no_candidates == 0
    assert len(p.pending_replications) == 1
    assert p.pending_replications[0].key == 9
    # with replication disabled the old silent-idle remains, but it is
    # at least counted as no-candidates (not an infinite armed spin)
    p2 = RebalancePlanner(_cfg(min_grain_share=0.2,
                               replicate_share=0.0))
    assert p2.plan([_sig([900, 50, 25, 25], hot=hot)]) == []
    assert p2.skipped_no_candidates == 1
    assert p2.hot_grain_blocked == 0


def test_planner_cooldown_then_rearm():
    p = RebalancePlanner(_cfg(cooldown_intervals=2))
    sig = lambda: _sig([900, 50, 25, 25], hot=_hot([1, 2], 0))  # noqa: E731
    assert len(p.plan([sig()])) == 1      # wave fires
    assert p.plan([sig()]) == []          # cooling
    assert p.plan([sig()]) == []          # cooling
    assert p.skipped_cooldown == 2
    assert len(p.plan([sig()])) == 1      # re-armed


def test_planner_slo_burn_halves_trigger():
    p = RebalancePlanner(_cfg(trigger_share=0.6, slo_burn_trigger=1.0))
    sig = _sig([450, 200, 200, 150], hot=_hot([1], 0))  # share 0.45
    assert p.plan([sig], slo_burn=0.5) == []   # under trigger, no burn
    moves = p.plan([sig], slo_burn=2.0)        # burning: trigger 0.3
    assert len(moves) == 1
    assert moves[0].trigger == pytest.approx(0.3125)  # floored at 1.25/4


# ---------------------------------------------------------------------------
# migration primitive: exactness, identity, in-flight redelivery
# ---------------------------------------------------------------------------

def _engine(n_shards=4, **kw) -> TensorEngine:
    cfg = kw.pop("config", None) or TensorEngineConfig(
        tick_interval=0.0, auto_fusion_ticks=0)
    e = TensorEngine(config=cfg, **kw)
    e.n_shards = n_shards  # logical shard blocks (no mesh needed)
    return e


def _balances(engine, keys) -> np.ndarray:
    arena = engine.arenas["RebalLedger"]
    rows, found = arena.lookup_rows(keys)
    assert found.all()
    return np.asarray(arena.state["balance"])[rows]


def test_migration_exactness_vs_never_migrated_oracle(run):
    """The acceptance oracle: the same injection sequence through a
    migrating engine and a never-migrated one ends bit-exact, and the
    migrated keys live in their pinned blocks."""

    async def main():
        rng = np.random.default_rng(7)
        engine, oracle = _engine(4), _engine(1)
        keys = np.arange(128, dtype=np.int64)
        for t in range(12):
            amounts = rng.integers(1, 100, 128).astype(np.int32)
            for e in (engine, oracle):
                e.send_batch("RebalLedger", "deposit", keys,
                             {"amount": amounts})
                e.run_tick()
            if t in (3, 7):
                movers = rng.choice(keys, 32, replace=False)
                engine.migrate_keys("RebalLedger", movers,
                                    rng.integers(0, 4, 32))
        await engine.flush()
        await oracle.flush()
        assert np.array_equal(_balances(engine, keys),
                              _balances(oracle, keys))
        arena = engine.arenas["RebalLedger"]
        rows, _ = arena.lookup_rows(keys)
        assert np.array_equal(rows // arena.shard_capacity,
                              arena.home_shards(keys))
        assert engine.grains_migrated > 0

    run(main())


def test_migration_across_checkpoint_and_journal_recovers_exact(run):
    """Grains journaled AND checkpointed across the move: full + delta
    checkpoints span the migrations, the engine hard-kills mid-cadence,
    and a fresh engine recovers — balances equal the oracle over the
    acknowledged prefix and the migration pins survive recovery (a
    post-recovery evict→reactivate still honors them)."""

    async def main():
        from orleans_tpu.tensor import MemorySnapshotStore

        backing = {}
        cfg = TensorEngineConfig(
            tick_interval=0.0, auto_fusion_ticks=0,
            ckpt_full_every_ticks=10, ckpt_delta_every_ticks=5,
            ckpt_pause_budget_s=0.002, journal_flush_every_ticks=3)
        engine = _engine(4, config=cfg,
                         snapshot_store=MemorySnapshotStore(backing))
        engine.register_journal("RebalLedger", "deposit")
        rng = np.random.default_rng(11)
        keys = np.arange(96, dtype=np.int64)
        amounts_by_tick = []
        for t in range(29):
            amounts = rng.integers(1, 100, 96).astype(np.int32)
            amounts_by_tick.append(amounts)
            engine.send_batch("RebalLedger", "deposit", keys,
                              {"amount": amounts})
            engine.run_tick()
            if t in (8, 13):
                movers = rng.choice(keys, 24, replace=False)
                engine.migrate_keys("RebalLedger", movers,
                                    rng.integers(0, 4, 24))
        await engine.flush()
        pins = dict(engine.arenas["RebalLedger"]._shard_override)
        assert pins, "scenario degenerate: no pins to recover"
        site = engine.checkpointer.journal.sites[("RebalLedger",
                                                  "deposit")]
        acked = site.committed_lanes // 96
        assert 0 < acked < 29, "kill must land mid-cadence"
        oracle = np.zeros(96, dtype=np.int64)
        for amounts in amounts_by_tick[:acked]:
            oracle += amounts
        # HARD KILL → recovery on a fresh engine over the same backing
        engine2 = _engine(4, config=cfg,
                          snapshot_store=MemorySnapshotStore(backing))
        stats = await engine2.checkpointer.recover()
        assert stats["recovered"]
        got = _balances(engine2, keys).astype(np.int64)
        assert np.array_equal(got, oracle)
        arena2 = engine2.arenas["RebalLedger"]
        assert arena2._shard_override == pins
        # pins survive USE after recovery: evict a pinned key, touch it
        k = np.asarray([next(iter(pins))], dtype=np.int64)
        arena2.evict_keys(k, write_back=False)
        rows = arena2.resolve_rows(k, tick=engine2.tick_number)
        assert rows[0] // arena2.shard_capacity == pins[int(k[0])]

    run(main())


def test_inflight_cached_rows_redeliver_after_migration(run):
    """The miss-machinery contract: an injector's cached device rows go
    stale at the epoch bump; the next inject re-validates, re-resolves
    and delivers to the migrated rows — nothing lost, nothing doubled."""

    async def main():
        engine = _engine(4)
        keys = np.arange(64, dtype=np.int64)
        inj = engine.make_injector("RebalLedger", "deposit", keys)
        amounts = np.ones(64, np.int32)
        for _ in range(3):
            inj.inject({"amount": amounts})
            engine.run_tick()
        engine.migrate_keys("RebalLedger", keys[:16],
                            (shard_of_keys(keys[:16], 4) + 1) % 4)
        for _ in range(2):
            inj.inject({"amount": amounts})
            engine.run_tick()
        await engine.flush()
        assert (_balances(engine, keys) == 5).all()

    run(main())


def test_streams_subscription_survives_migration(run):
    """A subscribed grain migrates: the subscription survives (unlike
    eviction) and post-move publishes deliver to the NEW row."""

    async def main():
        from orleans_tpu.tensor.streams_plane import DeviceSubscriptions

        engine = _engine(4)
        arena = engine.arena_for("RebalLedger")
        subs = np.arange(32, dtype=np.int64)
        arena.resolve_rows(subs)
        route = DeviceSubscriptions(engine, "RebalLedger", "deposit")
        engine.register_subscriptions("RebalLedger", "deposit", route)
        route.subscribe_many(np.full(32, 5, np.int64), subs)
        route._merge_host()
        route._pull_dirty = False  # pretend a built layout
        engine.migrate_keys("RebalLedger", subs[:8],
                            (shard_of_keys(subs[:8], 4) + 2) % 4)
        # subscription host truth intact (migration ≠ eviction: the
        # movers stay subscribed); the row-addressed pull layout is
        # dirtied for rebuild
        assert len(route._edges) == 32
        assert route._pull_dirty

    run(main())


# ---------------------------------------------------------------------------
# the closed loop (shard leg, engine-only — how the bench drives it)
# ---------------------------------------------------------------------------

def test_controller_closes_the_loop_on_a_hot_shard(run):
    """End to end on the plane's own telemetry: Zipf-style hot traffic
    pinned to one shard arms the trigger, the controller migrates the
    hot grains off it, and the interval telemetry converges back under
    the trigger (no further moves — convergence, not thrash)."""

    async def main():
        engine = _engine(4, metrics=MetricsConfig(
            attribution_enabled=True, attribution_top_k=16))
        keys = np.arange(256, dtype=np.int64)
        home = shard_of_keys(keys, 4)
        hot = keys[home == 0][:8]
        assert len(hot) == 8
        cfg = _cfg(trigger_share=0.4, hysteresis_intervals=2,
                   cooldown_intervals=0, move_budget=8,
                   min_interval_msgs=64)
        ctrl = RebalanceController(engine=engine, config=cfg)
        amounts = np.ones(len(hot), np.int32)
        moved_at = None
        for interval in range(6):
            for _ in range(4):  # hot wave: ~all traffic to shard 0
                engine.send_batch("RebalLedger", "deposit",
                                  np.tile(hot, 8),
                                  {"amount": np.tile(amounts, 8)})
                engine.run_tick()
            await engine.flush()
            moved = await ctrl.run_once()
            if moved and moved_at is None:
                moved_at = interval
        assert moved_at is not None, ctrl.planner.snapshot()
        # hysteresis: never on the very first interval
        assert moved_at >= 1
        arena = engine.arenas["RebalLedger"]
        rows, _ = arena.lookup_rows(hot)
        shards = rows // arena.shard_capacity
        assert (shards != 0).all(), "hot grains still on the burning shard"
        # converged: the last interval's signal is balanced → no wave
        snap = ctrl.snapshot()
        assert snap["grains_moved"] >= 8
        assert snap["skipped_below_trigger"] >= 1

    run(main())


# ---------------------------------------------------------------------------
# cross-silo migration + elastic join/drain
# ---------------------------------------------------------------------------

def _residents(silo, universe):
    a = silo.tensor_engine.arenas.get("RebalLedger")
    return set() if a is None else \
        set(a.keys().tolist()) & set(universe.tolist())


@pytest.mark.cluster
def test_cross_silo_migration_state_and_routing(run):
    """migrate_keys_out: state lands on the target (no store anywhere),
    the placement override routes subsequent traffic there, and
    single-activation holds throughout."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            s0, s1 = cluster.silos
            keys = np.arange(2000, 2064, dtype=np.int64)
            amounts = np.ones(64, np.int32)
            for _ in range(5):
                s0.tensor_engine.send_batch("RebalLedger", "deposit",
                                            keys, {"amount": amounts})
                await cluster.quiesce_engines()
            movers = np.array(sorted(_residents(s0, keys))[:8],
                              dtype=np.int64)
            n = await s0.vector_router.migrate_keys_out(
                "RebalLedger", movers, s1.address)
            assert n == len(movers)
            assert not (_residents(s0, keys) & set(movers.tolist()))
            assert set(movers.tolist()) <= _residents(s1, keys)
            a1 = s1.tensor_engine.arenas["RebalLedger"]
            rows, found = a1.lookup_rows(movers)
            assert found.all()
            assert (np.asarray(a1.state["balance"])[rows] == 5).all()
            # post-move traffic follows the override
            for _ in range(3):
                s0.tensor_engine.send_batch("RebalLedger", "deposit",
                                            keys, {"amount": amounts})
                await cluster.quiesce_engines()
            rows, _ = a1.lookup_rows(movers)
            assert (np.asarray(a1.state["balance"])[rows] == 8).all()
            assert not (_residents(s0, keys) & _residents(s1, keys))
            assert s0.vector_router.grains_migrated_out >= 8
            assert s1.vector_router.grains_adopted >= 8
        finally:
            await cluster.stop()

    run(main())


@pytest.mark.cluster
def test_join_and_drain_migrate_state_storeless(run):
    """Elastic scale-out/in: a JOIN pushes moved keys' state to the new
    owner (no store, no first-touch miss), a graceful DRAIN migrates
    the leaver's residents out — state exact at every step."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            keys = np.arange(3000, 3096, dtype=np.int64)
            amounts = np.ones(96, np.int32)

            async def drive(n):
                for _ in range(n):
                    cluster.silos[0].tensor_engine.send_batch(
                        "RebalLedger", "deposit", keys,
                        {"amount": amounts})
                    await cluster.quiesce_engines()

            await drive(4)
            s2 = await cluster.start_additional_silo()
            await cluster.wait_for_liveness_convergence()
            await asyncio.sleep(0.3)  # adopt frames land
            res = [_residents(s, keys) for s in cluster.silos]
            assert set.union(*res) == set(keys.tolist())
            assert sum(len(r) for r in res) == len(keys)  # no doubles
            assert len(_residents(s2, keys)) > 0
            for s in cluster.silos:
                r = _residents(s, keys)
                if not r:
                    continue
                a = s.tensor_engine.arenas["RebalLedger"]
                rows, _ = a.lookup_rows(np.asarray(sorted(r), np.int64))
                assert (np.asarray(a.state["balance"])[rows] == 4).all()
            await drive(2)
            # DRAIN one original silo; its residents migrate out
            s1 = cluster.silos[1]
            await cluster.stop_silo(s1)
            await asyncio.sleep(0.3)
            await drive(2)
            res = [_residents(s, keys) for s in cluster.silos]
            assert set.union(*res) == set(keys.tolist())
            assert sum(len(r) for r in res) == len(keys)
            for s in cluster.silos:
                r = _residents(s, keys)
                if not r:
                    continue
                a = s.tensor_engine.arenas["RebalLedger"]
                rows, _ = a.lookup_rows(np.asarray(sorted(r), np.int64))
                assert (np.asarray(a.state["balance"])[rows] == 8).all()
        finally:
            await cluster.stop()

    run(main())


# ---------------------------------------------------------------------------
# host path: migration bumps the invoke-table epoch (PR 14 regression)
# ---------------------------------------------------------------------------

@pytest.mark.cluster
def test_host_migration_drops_invoke_table_cache(run):
    """Migrate a host grain mid-RPC-load: the deactivation epoch bump
    drops the batched RPC plane's (activation, bound-method) cache, the
    next call re-resolves on the NEW home instead of invoking the dead
    activation, and in-flight calls all answer correctly."""

    async def main():
        from samples.helloworld import IHello

        cluster = await TestingCluster(n_silos=2).start()
        try:
            factory = cluster.attach_client(0)
            ref0 = factory.get_grain(IHello, 77001)
            expect = "You said: 'warm', I say: Hello!"
            assert await ref0.say_hello("warm") == expect
            host = cluster.find_silo_hosting(ref0.grain_id)
            target = next(s for s in cluster.silos if s is not host)
            # drive the RPC load through the HOSTING silo's front door —
            # the pre-resolved invoke table caches only locally-executed
            # windows (remote grains fall back per call by design)
            ref = host.attach_client().get_grain(IHello, 77001)
            await ref.say_hello("warm")
            await ref.say_hello("warm")  # cached fast turn
            entry = host.dispatcher.invoke_table.resolve(
                ref.grain_id.type_code, "say_hello")
            assert ref.grain_id in entry.acts
            old_act = entry.acts[ref.grain_id][0]
            epoch0 = host.catalog.deactivations_count

            # migration under load: a burst is in flight while the
            # activation moves; every call must still answer
            futs = [ref.say_hello(f"x{i}") for i in range(12)]
            ok = await host.catalog.migrate_activation(
                ref.grain_id, target.address)
            assert ok
            replies = await asyncio.gather(*futs)
            assert replies == [f"You said: 'x{i}', I say: Hello!"
                               for i in range(12)]
            # the epoch moved and the cache entry is gone — the next
            # window on the old host can never touch the dead activation
            assert host.catalog.deactivations_count > epoch0
            entry2 = host.dispatcher.invoke_table.resolve(
                ref.grain_id.type_code, "say_hello")
            assert entry2 is entry
            assert ref.grain_id not in entry.acts
            from orleans_tpu.runtime.activation import ActivationState
            assert old_act.state == ActivationState.INVALID
            # the new home serves the next call
            assert await ref.say_hello("after") \
                == "You said: 'after', I say: Hello!"
            assert cluster.find_silo_hosting(ref.grain_id) is target
            assert host.catalog.migrations_count == 1
        finally:
            await cluster.stop()

    run(main())


# ---------------------------------------------------------------------------
# publication: rebalance.* metrics, load-report capacity, dashboard row
# ---------------------------------------------------------------------------

def test_rebalance_metrics_and_dashboard_row(run):
    """Strict catalog publication of the rebalance.* rows + the
    dashboard's rebalance section over a live silo's snapshot."""

    async def main():
        from orleans_tpu.dashboard import render_text, view_from_snapshots
        from orleans_tpu.runtime.silo import Silo

        silo = Silo(config=SiloConfig(
            name="rb", rebalance=RebalanceConfig(enabled=True)))
        await silo.start()
        try:
            eng = silo.tensor_engine
            eng.n_shards = 4
            keys = np.arange(64, dtype=np.int64)
            eng.send_batch("RebalLedger", "deposit", keys,
                           {"amount": np.ones(64, np.int32)})
            await eng.flush()
            eng.migrate_keys("RebalLedger", keys[:4],
                             (shard_of_keys(keys[:4], 4) + 1) % 4)
            await silo.rebalancer.run_once()
            snap = silo.collect_metrics()
            assert snap["counters"]["rebalance.intervals"][""] >= 1
            assert snap["counters"]["rebalance.migrated_grains"][""] >= 4
            view = view_from_snapshots([snap])
            rb = view["cluster"]["rebalance"]
            assert rb["migrations"] >= 1
            assert rb["migrated_grains"] >= 4
            assert "rebalance:" in render_text(view)
        finally:
            await silo.stop(graceful=False)

    run(main())


@pytest.mark.cluster
def test_load_report_carries_capacity(run):
    """Satellite: the gossiped load report includes per-arena occupancy
    + memory headroom, and the controller's peer picker consumes it."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            s0, s1 = cluster.silos
            keys = np.arange(4000, 4032, dtype=np.int64)
            s0.tensor_engine.send_batch(
                "RebalLedger", "deposit", keys,
                {"amount": np.ones(32, np.int32)})
            await cluster.quiesce_engines()
            await s0.load_publisher.publish_statistics()
            await s1.load_publisher.publish_statistics()
            st = s0.load_publisher.periodic_stats[s1.address]
            assert st.arena_occupancy is not None
            occ = st.arena_occupancy.get("RebalLedger")
            assert occ is not None and occ["capacity"] > 0
            assert occ["live"] == len(_residents(s1, keys))
            # the controller's peer picker reads the same report
            peer = s0.rebalancer._pick_peer()
            assert peer == s1.address
        finally:
            await cluster.stop()

    run(main())


def test_rebalance_config_from_dict_roundtrip():
    cfg = SiloConfig.from_dict(
        {"rebalance": {"enabled": True, "move_budget": 3,
                       "trigger_share": 0.5}})
    assert cfg.rebalance.enabled
    assert cfg.rebalance.move_budget == 3
    assert cfg.rebalance.trigger_share == 0.5
    # defaults preserved for unspecified knobs
    assert cfg.rebalance.handoff_migration
