"""Persistence bridge: grain state ↔ storage providers.

Parity: reference IStorageProvider / GrainStateStorageBridge
(reference: src/Orleans/Storage/IStorageProvider.cs; src/Orleans/Core/
GrainStateStorageBridge.cs; etag discipline per provider, e.g.
AzureTableStorage.cs:68), loaded during activation stage 2
(reference: Catalog.SetupActivationState, Catalog.cs:731).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from orleans_tpu.ids import GrainId


class InconsistentStateError(Exception):
    """Etag mismatch on write (reference: InconsistentStateException)."""

    def __init__(self, stored_etag: Optional[str], current_etag: Optional[str]):
        super().__init__(
            f"etag conflict: stored={stored_etag!r} current={current_etag!r}")
        self.stored_etag = stored_etag
        self.current_etag = current_etag


@dataclass
class GrainState:
    """State record + etag (reference: GrainState.cs / IGrainState)."""

    data: Any = None
    etag: Optional[str] = None
    record_exists: bool = False


class StorageProvider:
    """Provider contract (reference: IStorageProvider.cs).

    Implementations must honor etags: a write with a stale etag raises
    InconsistentStateError; a successful write returns the new etag.
    """

    name: str = "?"

    async def init(self, name: str, config: Dict[str, Any]) -> None:
        self.name = name

    async def close(self) -> None:
        pass

    async def read_state(self, grain_type: str, grain_id: GrainId,
                         state: GrainState) -> None:
        raise NotImplementedError

    async def write_state(self, grain_type: str, grain_id: GrainId,
                          state: GrainState) -> None:
        raise NotImplementedError

    async def clear_state(self, grain_type: str, grain_id: GrainId,
                          state: GrainState) -> None:
        raise NotImplementedError


class GrainStateStorageBridge:
    """Per-activation storage facade injected into StatefulGrain
    (reference: GrainStateStorageBridge.cs).

    When a ``SpanRecorder`` is attached (catalog passes the silo's),
    every provider call emits a *dependency span* under the ambient
    trace — storage IO becomes an attributable hop of the request whose
    turn triggered it (orleans_tpu/spans.py)."""

    def __init__(self, grain_type: str, grain_id: GrainId,
                 provider: Optional[StorageProvider],
                 initial_state: Optional[Callable[[], Any]] = None,
                 recorder: Any = None) -> None:
        self.grain_type = grain_type
        self.grain_id = grain_id
        self.provider = provider
        self._initial_state = initial_state
        self._spans = recorder
        self.grain_state = GrainState()
        if initial_state is not None:
            self.grain_state.data = initial_state()

    async def _provider_call(self, op: str, call) -> None:
        """Run one provider coroutine under a dependency span (ambient
        trace; failures always record — see SpanRecorder.finish)."""
        if self._spans is None or not self._spans.enabled:
            await call()
            return
        from orleans_tpu import spans as _spans
        trace = _spans.current_trace()
        span = None
        if trace is not None and trace.get("sampled"):
            span = self._spans.start(
                f"storage.{op} {self.grain_type}", "dependency", trace,
                provider=getattr(self.provider, "name", "?"),
                grain=str(self.grain_id))
        try:
            await call()
        except Exception as exc:
            # failures record even for unsampled traces (retroactively)
            if span is not None:
                self._spans.finish(span, _spans.STATUS_ERROR,
                                   error=repr(exc))
            else:
                self._spans.event(
                    f"storage.{op} {self.grain_type}", "dependency", trace,
                    status=_spans.STATUS_ERROR, error=repr(exc),
                    provider=getattr(self.provider, "name", "?"))
            raise
        self._spans.finish(span)

    @property
    def state(self) -> Any:
        return self.grain_state.data

    @state.setter
    def state(self, value: Any) -> None:
        self.grain_state.data = value

    @property
    def etag(self) -> Optional[str]:
        return self.grain_state.etag

    async def read_state(self) -> None:
        if self.provider is None:
            return
        await self._provider_call(
            "read", lambda: self.provider.read_state(
                self.grain_type, self.grain_id, self.grain_state))
        if not self.grain_state.record_exists and self._initial_state is not None:
            self.grain_state.data = self._initial_state()

    async def write_state(self) -> None:
        if self.provider is None:
            raise RuntimeError(
                f"grain type {self.grain_type} has no storage provider "
                f"configured (reference: [StorageProvider] attribute missing)")
        await self._provider_call(
            "write", lambda: self.provider.write_state(
                self.grain_type, self.grain_id, self.grain_state))

    async def clear_state(self) -> None:
        if self.provider is None:
            raise RuntimeError(
                f"grain type {self.grain_type} has no storage provider configured")
        await self._provider_call(
            "clear", lambda: self.provider.clear_state(
                self.grain_type, self.grain_id, self.grain_state))
        if self._initial_state is not None:
            self.grain_state.data = self._initial_state()
