"""Benchmark-workload samples: Chirper, GPSTracker, TwitterSentiment.

These are the three BASELINE.json configs beyond HelloWorld/Presence.
Each test checks the vector-grain implementation against an exact
host-side (numpy/dict) oracle of the reference semantics:
Chirper's follower fan-out (ChirperAccount.cs:129-156), GPSTracker's
movement gate + speed (DeviceGrain.cs:37), TwitterSentiment's
per-hashtag scoring + first-activation counting (HashtagGrain.cs:70).
"""

import numpy as np
import pytest

from orleans_tpu.tensor import DeviceFanout, FanoutOverflowError, TensorEngine
from orleans_tpu.tensor.fanout import KEY_SENTINEL

from samples.chirper import (
    ChirperAccount,
    build_follow_graph,
    run_chirper_load,
)
from samples.gpstracker import (
    N_NOTIFIERS,
    DeviceGrain,
    PushNotifierGrain,
    run_gps_load,
)
from samples.twitter_sentiment import (
    TweetCounterGrain,
    HashtagGrain,
    flatten_tweets,
    hashtag_key,
    run_twitter_load,
)


# ---------------------------------------------------------------------------
# DeviceFanout (the ragged-expansion primitive)
# ---------------------------------------------------------------------------

def test_fanout_expansion_matches_adjacency():
    import jax.numpy as jnp

    fan = DeviceFanout(budget=64)
    adj = {1: [10, 11, 12], 2: [20], 5: [50, 51]}
    for s, ds in adj.items():
        for d in ds:
            fan.follow(s, d)

    src = jnp.asarray(np.array([2, 1, 7, 5], np.int32))  # 7 has no followers
    args = {"v": jnp.asarray(np.array([200, 100, 700, 500], np.int32))}
    dst, gargs, valid = fan.expand(src, args)
    dst, v, sk, valid = (np.asarray(dst), np.asarray(gargs["v"]),
                         np.asarray(gargs["src_key"]), np.asarray(valid))
    got = sorted(zip(dst[valid].tolist(), v[valid].tolist(),
                     sk[valid].tolist()))
    want = sorted([(20, 200, 2), (10, 100, 1), (11, 100, 1), (12, 100, 1),
                   (50, 500, 5), (51, 500, 5)])
    assert got == want
    assert (dst[~valid] == KEY_SENTINEL).all()
    assert fan.overflow_check() == 0  # nothing overflowed: no parked lanes


def test_fanout_mutation_and_empty_graph():
    import jax.numpy as jnp

    fan = DeviceFanout(budget=16)
    src = jnp.asarray(np.array([3], np.int32))
    dst, _, valid = fan.expand(src, {"v": jnp.zeros(1)})
    assert not np.asarray(valid).any()          # empty graph: no expansion

    fan.follow(3, 9)
    dst, _, valid = fan.expand(src, {"v": jnp.zeros(1)})
    assert np.asarray(dst)[np.asarray(valid)].tolist() == [9]

    fan.unfollow(3, 9)                          # mirror rebuilds lazily
    dst, _, valid = fan.expand(src, {"v": jnp.zeros(1)})
    assert not np.asarray(valid).any()


def test_fanout_overflow_parks_lanes_not_raises():
    """Per-round expansion overflow is a PARK event now, never a
    mid-tick error (the ShardExchange contract): the overflowing source
    lane delivers NOTHING this round (all-or-nothing — a partial prefix
    would double-deliver on redelivery) and comes back as a device-side
    dropped mask; only the storage budget (too many EDGES) still raises
    at rebuild."""
    import jax.numpy as jnp

    fan = DeviceFanout(budget=4)
    for d in range(3):
        fan.follow(1, 100 + d)
    # two publishes from key 1 in one round: 6 expansions > width 4 —
    # the FIRST lane's 3 slots fit, the second lane parks whole
    src = jnp.asarray(np.array([1, 1], np.int32))
    dst, _gargs, valid = fan.expand(src, {"v": jnp.zeros(2)})
    n_dropped, dropped = fan.take_drop()
    assert int(n_dropped) == 1
    assert np.asarray(dropped).tolist() == [False, True]
    # the completed lane delivered ALL its slots, the parked one none
    assert sorted(np.asarray(dst)[np.asarray(valid)].tolist()) \
        == [100, 101, 102]
    # re-expanding exactly the parked lanes completes the delivery
    dst2, _g2, valid2 = fan.expand(src, {"v": jnp.zeros(2)},
                                   jnp.asarray(np.array(dropped)))
    n2, _d2 = fan.take_drop()
    assert int(n2) == 0
    assert sorted(np.asarray(dst2)[np.asarray(valid2)].tolist()) \
        == [100, 101, 102]
    assert fan.overflow_check() == 0  # both drops were taken

    # the STORAGE budget stays a hard error
    over = DeviceFanout(budget=2)
    for d in range(3):
        over.follow(1, 200 + d)
    with pytest.raises(FanoutOverflowError):
        over.expand(jnp.asarray(np.array([1], np.int32)),
                    {"v": jnp.zeros(1)})


# ---------------------------------------------------------------------------
# Chirper
# ---------------------------------------------------------------------------

def test_chirper_exact_small_graph(run):
    """5 accounts, known graph: received counts / checksums must equal the
    sequential per-follower delivery of the reference."""

    async def main():
        engine = TensorEngine()
        fan = DeviceFanout(budget=64)
        adj = {0: [1, 2, 3], 1: [2], 3: [0, 4]}
        for s, ds in adj.items():
            for d in ds:
                fan.follow(s, d)

        stats = await run_chirper_load(engine, n_accounts=5, n_ticks=3,
                                       fanout=fan)
        arena = engine.arena_for("ChirperAccount")
        received = np.asarray(arena.state["received"])
        rows = arena.resolve_rows(np.arange(5, dtype=np.int64))

        # oracle: per-account fan-in = number of accounts following them
        followers_of = {k: 0 for k in range(5)}
        for s, ds in adj.items():
            for d in ds:
                followers_of[d] += 1
        for acct in range(5):
            assert received[rows[acct]] == 3 * followers_of[acct], acct
        published = np.asarray(arena.state["published"])
        assert all(published[rows[a]] == 3 for a in range(5))
        assert stats["messages"] == 3 * (5 + 6)

    run(main())


def test_chirper_power_law_load(run):
    """Power-law graph at small scale: total deliveries equal edge count
    per tick and the expansion is exact per account."""

    async def main():
        engine = TensorEngine()
        fan = build_follow_graph(200, mean_followers=8.0, seed=3)
        await run_chirper_load(engine, n_accounts=200, n_ticks=2, fanout=fan)
        arena = engine.arena_for("ChirperAccount")
        received = np.asarray(arena.state["received"])
        rows = arena.resolve_rows(np.arange(200, dtype=np.int64))
        followers_of = np.zeros(200, np.int64)
        for s in range(200):
            for d in fan.followers_of(s):
                followers_of[d] += 1
        np.testing.assert_array_equal(received[rows], 2 * followers_of)
        # power-law sanity: the most-followed account dominates the median
        deg = np.asarray([len(fan.followers_of(s)) for s in range(200)])
        assert deg.max() >= 10 * max(1, int(np.median(deg)))

    run(main())


# ---------------------------------------------------------------------------
# GPSTracker
# ---------------------------------------------------------------------------

def test_gps_movement_gate_and_speed(run):
    """Only moved devices notify; speed matches the equirectangular
    formula (reference: DeviceGrain.GetSpeed)."""

    async def main():
        import jax.numpy as jnp

        engine = TensorEngine()
        engine.arena_for("DeviceGrain").reserve(4)
        engine.arena_for("PushNotifierGrain").reserve(N_NOTIFIERS)
        devices = np.arange(4, dtype=np.int64)
        inj = engine.make_injector("DeviceGrain", "process_message", devices)

        lat0 = np.array([47.60, 47.61, 47.62, 47.63], np.float32)
        lon0 = np.full(4, -122.1, np.float32)
        base = {"lon": jnp.asarray(lon0),
                "device": jnp.asarray(devices.astype(np.int32))}
        inj.inject({**base, "lat": jnp.asarray(lat0),
                    "ts": jnp.full(4, 1.0, jnp.float32)})
        await engine.flush()

        # second fix: only devices 0 and 2 move (0.001 deg north over 10s)
        lat1 = lat0 + np.array([1e-3, 0, 1e-3, 0], np.float32)
        inj.inject({**base, "lat": jnp.asarray(lat1),
                    "ts": jnp.full(4, 11.0, jnp.float32)})
        await engine.flush()

        dev_arena = engine.arena_for("DeviceGrain")
        rows = dev_arena.resolve_rows(devices)
        moves = np.asarray(dev_arena.state["moves"])[rows]
        np.testing.assert_array_equal(moves, [2, 1, 2, 1])  # first fix counts

        # expected speed: dist = dlat(rad) * R over 10s
        expected = np.deg2rad(1e-3) * 6371000.0 / 10.0
        speed = np.asarray(dev_arena.state["speed"])[rows]
        # float32 keeps ~1e-6 deg resolution at lat 47 — 1e-3 rtol covers it
        np.testing.assert_allclose(speed[[0, 2]], expected, rtol=1e-3)
        np.testing.assert_allclose(speed[[1, 3]], 0.0)

        notif = engine.arena_for("PushNotifierGrain")
        total_forwarded = int(np.asarray(notif.state["forwarded"]).sum())
        assert total_forwarded == 4 + 2  # all first fixes + two moves

    run(main())


def test_gps_load_driver(run):
    async def main():
        engine = TensorEngine()
        stats = await run_gps_load(engine, n_devices=500, n_ticks=4,
                                   move_fraction=0.5, seed=1)
        notif = engine.arena_for("PushNotifierGrain")
        forwarded = int(np.asarray(notif.state["forwarded"]).sum())
        assert forwarded == stats["notified"]
        assert stats["messages"] == 500 * 4 + forwarded

    run(main())


# ---------------------------------------------------------------------------
# TwitterSentiment
# ---------------------------------------------------------------------------

def test_twitter_scoring_exact(run):
    """Sign-split totals and the first-activation counter match the
    reference semantics exactly."""

    async def main():
        engine = TensorEngine()
        engine.arena_for("HashtagGrain").reserve(16)
        engine.arena_for("TweetCounterGrain").reserve(1)

        tweets = [
            {"hashtags": ["jax", "tpu"], "score": 1},
            {"hashtags": ["jax"], "score": -1},
            {"hashtags": ["tpu"], "score": 0},
            {"hashtags": ["jax", "xla"], "score": 1},
        ]
        flat = flatten_tweets(tweets)
        engine.send_batch("HashtagGrain", "add_score", flat["keys"],
                          {"score": flat["scores"]})
        await engine.flush()

        arena = engine.arena_for("HashtagGrain")
        rows = arena.resolve_rows(np.asarray(
            [hashtag_key(t) for t in ("jax", "tpu", "xla")], np.int64))
        total = np.asarray(arena.state["total"])[rows]
        pos = np.asarray(arena.state["positive"])[rows]
        neg = np.asarray(arena.state["negative"])[rows]
        np.testing.assert_array_equal(total, [3, 2, 1])
        np.testing.assert_array_equal(pos, [2, 1, 1])
        np.testing.assert_array_equal(neg, [1, 0, 0])

        counter = engine.arena_for("TweetCounterGrain")
        crow = counter.resolve_rows(np.array([0], np.int64))
        assert int(np.asarray(counter.state["hashtags"])[crow][0]) == 3

        # second wave: old tags don't re-count, a new one does
        engine.send_batch("HashtagGrain", "add_score",
                          np.asarray([hashtag_key("jax"),
                                      hashtag_key("new")], np.int64),
                          {"score": np.asarray([1, -1], np.int32)})
        await engine.flush()
        assert int(np.asarray(counter.state["hashtags"])[crow][0]) == 4

    run(main())


def test_twitter_load_driver(run):
    async def main():
        engine = TensorEngine()
        stats = await run_twitter_load(engine, n_tweets_per_tick=1000,
                                       n_hashtags=50, tags_per_tweet=2,
                                       n_ticks=3)
        arena = engine.arena_for("HashtagGrain")
        total = int(np.asarray(arena.state["total"]).sum())
        assert total == 1000 * 2 * 3
        counter = engine.arena_for("TweetCounterGrain")
        crow = counter.resolve_rows(np.array([0], np.int64))
        counted = int(np.asarray(counter.state["hashtags"])[crow][0])
        assert 0 < counted <= 50
        assert stats["messages"] == (2000 + 1000) * 3

    run(main())


# ---------------------------------------------------------------------------
# Chirper host path (per-message actor parity surface)
# ---------------------------------------------------------------------------

def test_chirper_host_path(run):
    """Follow → publish → per-follower delivery over the asyncio host
    path (reference: ChirperAccount.cs full RPC loop)."""

    async def main():
        from orleans_tpu.runtime.silo import Silo
        from samples.chirper_host import IHostChirperAccount

        silo = Silo(name="chirper-host")
        await silo.start()
        try:
            factory = silo.attach_client()
            a, b, c = (factory.get_grain(IHostChirperAccount, i)
                       for i in (1001, 1002, 1003))
            await b.follow(1001)
            await c.follow(1001)
            await c.follow(1002)
            await a.publish(7)
            await b.publish(8)
            # publish awaits all deliveries (reference WhenAll parity)
            assert await b.received_count() == 1
            assert await c.received_count() == 2
            got = await c.recent_chirps()
            assert sorted(got) == [(7, 1001), (8, 1002)]
        finally:
            await silo.stop()

    run(main())


def test_fanout_no_duplicate_delivery_on_miss_redelivery(run):
    """Publishing from NOT-yet-activated keys via the optimistic device
    path must deliver each chirp to each follower exactly once: the
    miss-check redelivery (which re-runs the publish state update) must
    not re-expand the fan-out."""

    async def main():
        import jax.numpy as jnp

        engine = TensorEngine()
        fan = DeviceFanout(budget=64)
        fan.follow(1, 10)
        fan.follow(1, 11)
        fan.follow(2, 10)
        engine.register_fanout("ChirperAccount", "publish", fan,
                               "ChirperAccount", "new_chirp")
        # no reserve/injector: publisher keys are unseen -> optimistic
        # resolution parks a miss-check and redelivers
        engine.send_batch(
            "ChirperAccount", "publish",
            jnp.asarray(np.array([1, 2], np.int32)),
            {"chirp_id": jnp.asarray(np.array([100, 200], np.int32))})
        await engine.flush()

        arena = engine.arena_for("ChirperAccount")
        rows = arena.resolve_rows(np.array([1, 2, 10, 11], np.int64))
        received = np.asarray(arena.state["received"])[rows]
        published = np.asarray(arena.state["published"])[rows]
        np.testing.assert_array_equal(received, [0, 0, 2, 1])
        np.testing.assert_array_equal(published, [1, 1, 0, 0])

    run(main())


def test_gps_host_path(run):
    """Host-path GPS parity: per-fix RPC with movement-gated notifier
    forward (reference: DeviceGrain.ProcessMessage)."""

    async def main():
        import asyncio as _a

        from orleans_tpu.runtime.silo import Silo
        from samples.gpstracker_host import (
            HostPushNotifierGrain,
            IHostDevice,
            IHostPushNotifier,
        )

        HostPushNotifierGrain.forwarded = 0
        HostPushNotifierGrain.speed_sum = 0.0
        silo = Silo(name="gps-host")
        await silo.start()
        try:
            f = silo.attach_client()
            d = f.get_grain(IHostDevice, 3001)
            await d.process_message(47.60, -122.1, 1.0)   # first fix: moved
            await d.process_message(47.60, -122.1, 2.0)   # unchanged: gated
            await d.process_message(47.601, -122.1, 12.0)  # moved again
            await _a.sleep(0.05)  # one-way forwards drain
            n = f.get_grain(IHostPushNotifier, 0)
            forwarded, speed_sum = await n.totals()
            assert forwarded == 2, forwarded
            # second move: ~0.001 deg over 10s ≈ 11.1 m/s
            assert 10.0 < speed_sum < 13.0, speed_sum
        finally:
            await silo.stop()

    run(main())


def test_presence_pipelined_latency_mode_fused_exact(run):
    """The pipelined latency operating point rides window=1 fused
    programs with DONATED state and event-driven completion (the
    honest 10ms mode).  Exactness: every injected heartbeat lands
    exactly one game update, asserted through both the state columns
    and the device miss counters folded at end of run; honored flags
    are direct observations (no floor fields exist any more)."""

    async def main():
        from samples.presence import run_presence_pipelined

        engine = TensorEngine()
        stats = await run_presence_pipelined(
            engine, n_players=4096, n_games=64, budget=0.05,
            n_ticks=12, warm_ticks=4)
        assert stats["messages"] > 0
        assert stats["tick_p99_seconds"] > 0
        assert stats["mean_batch"] >= 2048
        assert stats["pipeline_depth"] >= 2
        # the floor is gone, not netted out: no sync-floor keys, and
        # honored IS honored_strict
        assert "sync_floor_s" not in stats
        assert stats["honored"] == stats["honored_strict"]
        assert stats["donation_fallbacks"] == 0  # donated path active
        upd = np.asarray(engine.arena_for("GameGrain").state["updates"])
        hb = np.asarray(
            engine.arena_for("PresenceGrain").state["heartbeats"])
        assert int(upd.sum()) == int(hb.sum())  # one update per heartbeat
        # verify() folded the emit deliveries into messages_processed
        assert engine.messages_processed == int(upd.sum()) + int(hb.sum())

    run(main())


def test_twitter_fused_matches_unfused(run):
    """The fused twitter tier (dispatcher pool + per-tick slab args +
    in-window hashtag resolve) must produce byte-identical hashtag and
    counter state to the unfused engine over the same Zipf payloads."""

    async def main():
        from samples.twitter_sentiment import (
            COUNTER_KEY,
            _zipf_payloads,
            run_twitter_load,
            run_twitter_load_fused,
        )

        n_tweets, n_tags, T = 2_000, 300, 8
        plain = TensorEngine()
        await run_twitter_load(plain, n_tweets_per_tick=n_tweets,
                               n_hashtags=n_tags, n_ticks=T,
                               warm_ticks=0, seed=3)
        fused = TensorEngine()
        stats = await run_twitter_load_fused(
            fused, n_tweets_per_tick=n_tweets, n_hashtags=n_tags,
            n_ticks=T, window=4, seed=3)
        assert stats["engine"] == "fused"

        tag_keys, _ = _zipf_payloads(n_tags, n_tweets * 2, T, 1.4, 3)
        a_ref = plain.arena_for("HashtagGrain")
        a_fus = fused.arena_for("HashtagGrain")
        rows_ref = a_ref.resolve_rows(tag_keys)
        rows_fus = a_fus.resolve_rows(tag_keys)
        for col in ("total", "positive", "negative", "counted",
                    "last_score"):
            np.testing.assert_array_equal(
                np.asarray(a_fus.state[col])[rows_fus],
                np.asarray(a_ref.state[col])[rows_ref],
                err_msg=f"HashtagGrain.{col} diverged under fusion")
        c_ref = plain.arena_for("TweetCounterGrain").read_row(COUNTER_KEY)
        c_fus = fused.arena_for("TweetCounterGrain").read_row(COUNTER_KEY)
        assert int(c_ref["hashtags"]) == int(c_fus["hashtags"])

    run(main())
