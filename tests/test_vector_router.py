"""Cross-silo vector data plane: slab shipping, single-activation, handoff.

The reference's silo boundary is per-message with batched serialization at
the socket (reference: OutgoingMessageSender.cs:128-176); here a vector
batch crossing silos stays a batch end to end (tensor/router.py).  These
tests are the composition VERDICT r2 flagged as uncovered: multi-silo
clusters carrying tensor traffic, with the single-activation guarantee of
the reference's directory registration race (Catalog.cs:533-563) enforced
for arenas.
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from orleans_tpu.core.grain import batched_method
from orleans_tpu.hashing import ring_hash_int_keys
from orleans_tpu.ids import GrainId
from orleans_tpu.tensor import (
    Batch,
    VectorGrain,
    field,
    seg_sum,
    vector_grain,
)
from orleans_tpu.tensor.persistence import MemoryVectorStore
from orleans_tpu.testing.cluster import TestingCluster


@vector_grain
class RouteCounter(VectorGrain):
    total = field(jnp.float32, 0.0)
    count = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def add(state, batch: Batch, n_rows: int):
        state = {
            **state,
            "total": state["total"] + seg_sum(batch.args["v"], batch.rows,
                                              n_rows),
            "count": state["count"] + seg_sum(
                jnp.ones_like(batch.rows, dtype=jnp.int32) *
                (batch.rows >= 0), batch.rows, n_rows),
        }
        return state, {"echo": batch.args["v"] * 2}, ()


async def settle(cluster):
    await cluster.quiesce_engines()


def arena_rows(cluster, type_name):
    """{key: (silo_name, row_state)} across the cluster; asserts no key is
    active on two silos (the single-activation invariant)."""
    seen = {}
    for silo in cluster.silos:
        arena = silo.tensor_engine.arenas.get(type_name)
        if arena is None:
            continue
        for k in arena.keys():
            assert int(k) not in seen, \
                f"key {k} active on {seen[int(k)][0]} AND {silo.name}"
            seen[int(k)] = (silo.name, arena.read_row(int(k)))
    return seen


def test_ring_hash_vectorized_matches_scalar():
    rng = np.random.default_rng(7)
    keys = np.concatenate([rng.integers(0, 2**63, 500, dtype=np.int64),
                           np.arange(32)])
    for tc in (1, 77, 2**30 + 123):
        vec = ring_hash_int_keys(tc, keys)
        scalar = np.array([GrainId.from_int(tc, int(k)).ring_hash()
                           for k in keys], dtype=np.uint32)
        np.testing.assert_array_equal(vec, scalar)


def test_send_batch_partitions_across_silos(run):
    async def main():
        cluster = await TestingCluster(n_silos=3).start()
        try:
            a = cluster.silos[0]
            n = 600
            keys = np.arange(n, dtype=np.int64)
            a.tensor_engine.send_batch(
                "RouteCounter", "add", keys,
                {"v": np.ones(n, np.float32)})
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")
            assert set(rows) == set(range(n))
            # exact delivery: every key counted exactly once
            assert all(int(r["count"]) == 1 for _, r in rows.values())
            # the batch really split: at least two silos host rows, and
            # slabs (not per-message sends) carried the remote partitions
            hosts = {s for s, _ in rows.values()}
            assert len(hosts) >= 2
            shipped = a.vector_router.messages_shipped
            slabs = a.vector_router.slabs_shipped
            assert shipped > 0 and slabs <= 4  # one slab per remote owner
            received = sum(s.vector_router.messages_received
                           for s in cluster.silos)
            assert received == shipped
        finally:
            await cluster.stop()

    run(main())


def test_single_activation_under_concurrent_cross_silo_calls(run):
    """Two silos, same key, concurrent calls through BOTH silos' entry
    points — exactly one arena row exists in the cluster afterwards
    (reference: DuplicateActivationException race, Catalog.cs:533-563)."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            key = 42
            futs = []
            for _ in range(5):
                for silo in cluster.silos:
                    futs.append(silo.tensor_engine.send_batch(
                        "RouteCounter", "add",
                        np.array([key], dtype=np.int64),
                        {"v": np.array([1.0], np.float32)},
                        want_results=True))
            results = await asyncio.gather(*futs)
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")
            assert list(rows) == [key]
            assert int(rows[key][1]["count"]) == 10
            assert all(float(np.asarray(r["echo"])[0]) == 2.0
                       for r in results)
            # the row lives on the ring owner, nowhere else
            owner = cluster.silos[0].ring.calculate_target_silo(
                GrainId.from_int(
                    cluster.silos[0].tensor_engine.arena_for(
                        "RouteCounter").info.type_code, key))
            assert rows[key][0] == next(
                s.name for s in cluster.silos if s.address == owner)
        finally:
            await cluster.stop()

    run(main())


def test_device_key_misses_ship_to_owner(run):
    """Device-key batches (the emit hot path) resolve optimistically;
    remote-owned keys surface as misses and ship as slabs at the
    quiescence point instead of activating locally."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            a = cluster.silos[0]
            n = 200
            keys_dev = jnp.arange(n, dtype=jnp.int32)
            a.tensor_engine.send_batch(
                "RouteCounter", "add", keys_dev,
                {"v": jnp.ones(n, jnp.float32)})
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")
            assert set(rows) == set(range(n))
            assert all(int(r["count"]) == 1 for _, r in rows.values())
            assert {s for s, _ in rows.values()} == \
                {s.name for s in cluster.silos}
        finally:
            await cluster.stop()

    run(main())


def test_cluster_injector_exact_counts(run):
    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            a = cluster.silos[0]
            n = 300
            keys = np.arange(n, dtype=np.int64)
            inj = a.tensor_engine.make_injector("RouteCounter", "add", keys)
            from orleans_tpu.tensor.router import ClusterInjector
            assert isinstance(inj, ClusterInjector)  # mixed ownership
            for _ in range(3):
                inj.inject({"v": np.ones(n, np.float32)})
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")
            assert set(rows) == set(range(n))
            assert all(int(r["count"]) == 3 for _, r in rows.values())
            total = sum(float(r["total"]) for _, r in rows.values())
            assert total == 3 * n
        finally:
            await cluster.stop()

    run(main())


def test_device_key_want_results_routes_instead_of_activating(run):
    """Device-key batches with want_results cannot ride the optimistic
    path (a resolved future can't be retro-fixed) — they must route by
    owner, NOT eagerly activate remote keys locally."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            a = cluster.silos[0]
            n = 60
            fut = a.tensor_engine.send_batch(
                "RouteCounter", "add", jnp.arange(n, dtype=jnp.int32),
                {"v": np.ones(n, np.float32)}, want_results=True)
            res = await fut
            np.testing.assert_allclose(np.asarray(res["echo"]),
                                       np.full(n, 2.0))
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")  # asserts no dupes
            assert set(rows) == set(range(n))
        finally:
            await cluster.stop()

    run(main())


def test_injector_repartitions_after_membership_change(run):
    """An injector built before a join must re-split by the new ring —
    injecting through the stale split would re-activate keys the handoff
    just evicted (duplicate activations)."""

    async def main():
        backing = MemoryVectorStore.shared_backing()

        def setup(silo):
            silo.tensor_engine.store = MemoryVectorStore(backing)

        cluster = TestingCluster(n_silos=1, silo_setup=setup)
        await cluster.start()
        try:
            a = cluster.silos[0]
            n = 120
            keys = np.arange(n, dtype=np.int64)
            inj = a.tensor_engine.make_injector("RouteCounter", "add", keys)
            inj.inject({"v": np.ones(n, np.float32)})
            await settle(cluster)

            await cluster.start_additional_silo()
            await cluster.wait_for_liveness_convergence()
            await asyncio.sleep(0.1)  # handoff eviction

            inj.inject({"v": np.ones(n, np.float32)})
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")  # asserts no dupes
            assert set(rows) == set(range(n))
            assert {s for s, _ in rows.values()} == \
                {s.name for s in cluster.silos}
            assert all(int(r["count"]) == 2 for _, r in rows.values())
        finally:
            await cluster.stop()

    run(main())


def test_stale_enqueued_batch_reroutes_at_resolve_time(run):
    """A host-key batch queued BEFORE a ring change must not re-activate
    keys the handoff evicted: ownership is re-checked at resolve time and
    strays ship to the owner (the enqueue-time check alone is racy)."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            a, b = cluster.silos
            n = 80
            keys = np.arange(n, dtype=np.int64)
            # simulate the race: a batch that bypassed enqueue routing
            # (as one proven local before a ring move would have)
            a.tensor_engine.enqueue_local_batch(
                "RouteCounter", "add", keys,
                {"v": np.ones(n, np.float32)})
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")  # asserts no dupes
            assert set(rows) == set(range(n))
            assert {s for s, _ in rows.values()} == {a.name, b.name}
            assert all(int(r["count"]) == 1 for _, r in rows.values())
        finally:
            await cluster.stop()

    run(main())


def test_fuse_ticks_rejects_remote_keys(run):
    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            a = cluster.silos[0]
            with pytest.raises(ValueError, match="ring-owned by other"):
                a.tensor_engine.fuse_ticks(
                    "RouteCounter", "add", np.arange(50, dtype=np.int64))
        finally:
            await cluster.stop()

    run(main())


def test_call_slab_hop_bound(run):
    """A want_results slab arriving at a silo that (by its ring view)
    doesn't own the keys re-routes with a bounded hop chain — diverged
    views surface as an error, never an infinite bounce."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            b = cluster.silos[1]
            # keys owned by silo A from B's view, arriving at B with the
            # hop budget already spent
            info = b.tensor_engine.arena_for("RouteCounter").info
            key = next(
                k for k in range(100)
                if b.ring.calculate_target_silo(
                    GrainId.from_int(info.type_code, k)) != b.address)
            with pytest.raises(RuntimeError, match="forward count"):
                await b.vector_router.call_slab(
                    "RouteCounter", "add", np.array([key], dtype=np.int64),
                    {"v": np.array([1.0], np.float32)},
                    hops=b.max_forward_count + 1)
        finally:
            await cluster.stop()

    run(main())


def test_dispatcher_forwards_vector_message_to_owner(run):
    """Per-message path parity: a vector-grain call entering through a
    NON-owner silo's dispatcher forwards to the owner instead of
    injecting locally (reference: Dispatcher.TryForwardRequest :474)."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            info = cluster.silos[0].tensor_engine.arena_for(
                "RouteCounter").info
            # pick a key owned by silo B, call it via silo A's client
            key = next(
                k for k in range(100)
                if cluster.silos[0].ring.calculate_target_silo(
                    GrainId.from_int(info.type_code, k))
                == cluster.silos[1].address)
            factory = cluster.attach_client(0)
            ref = factory.get_grain("RouteCounter", key)
            res = await ref.add({"v": 5.0})
            assert float(np.asarray(res["echo"])) == 10.0
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")
            assert rows[key][0] == cluster.silos[1].name
        finally:
            await cluster.stop()

    run(main())


def test_graceful_handoff_restores_state(run):
    """Graceful silo stop writes its arena rows through the shared store;
    the surviving owner re-activates them with state on first touch
    (reference: GrainDirectoryHandoffManager.cs:141 + Catalog.cs:731)."""

    async def main():
        backing = MemoryVectorStore.shared_backing()

        def setup(silo):
            silo.tensor_engine.store = MemoryVectorStore(backing)

        cluster = TestingCluster(n_silos=2, silo_setup=setup)
        await cluster.start()
        try:
            a, b = cluster.silos[0], cluster.silos[1]
            n = 200
            keys = np.arange(n, dtype=np.int64)
            a.tensor_engine.send_batch("RouteCounter", "add", keys,
                                       {"v": np.ones(n, np.float32)})
            await settle(cluster)
            before = arena_rows(cluster, "RouteCounter")
            b_keys = [k for k, (s, _) in before.items() if s == b.name]
            assert b_keys, "expected some keys on silo B"

            await cluster.stop_silo(b)
            await cluster.wait_for_liveness_convergence()

            # touch every key again through the survivor
            a.tensor_engine.send_batch("RouteCounter", "add", keys,
                                       {"v": np.ones(n, np.float32)})
            await settle(cluster)
            after = arena_rows(cluster, "RouteCounter")
            assert set(after) == set(range(n))
            # counters survived exactly: 1 (pre-handoff) + 1 (post)
            assert all(int(r["count"]) == 2 for _, r in after.values()), \
                sorted(set(int(r["count"]) for _, r in after.values()))
        finally:
            await cluster.stop()

    run(main())


def test_join_evicts_strays_to_new_owner(run):
    """A silo joining shifts ring ownership; rows the old owner no longer
    owns are written back and evicted, and the new owner restores them on
    first touch — counters conserved across the move."""

    async def main():
        backing = MemoryVectorStore.shared_backing()

        def setup(silo):
            silo.tensor_engine.store = MemoryVectorStore(backing)

        cluster = TestingCluster(n_silos=1, silo_setup=setup)
        await cluster.start()
        try:
            a = cluster.silos[0]
            n = 150
            keys = np.arange(n, dtype=np.int64)
            a.tensor_engine.send_batch("RouteCounter", "add", keys,
                                       {"v": np.ones(n, np.float32)})
            await settle(cluster)
            assert len(arena_rows(cluster, "RouteCounter")) == n

            await cluster.start_additional_silo()
            await cluster.wait_for_liveness_convergence()
            await asyncio.sleep(0.1)  # let the handoff eviction run

            a.tensor_engine.send_batch("RouteCounter", "add", keys,
                                       {"v": np.ones(n, np.float32)})
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")
            assert set(rows) == set(range(n))
            hosts = {s for s, _ in rows.values()}
            assert len(hosts) == 2, "new silo took no keys"
            assert all(int(r["count"]) == 2 for _, r in rows.values()), \
                sorted(set(int(r["count"]) for _, r in rows.values()))
        finally:
            await cluster.stop()

    run(main())


def test_hard_kill_restores_from_periodic_checkpoint(run):
    """KILL (no goodbye, no graceful write-back) a silo holding vector
    rows mid-load on a 2-silo TCP cluster.  With the periodic checkpoint
    cadence on (checkpoint_every_ticks), the survivor detects the death,
    takes over the ring ranges, and re-activates the dead silo's keys
    from the last checkpoint on first touch — counts exact up to the
    checkpoint boundary, which the cadence bounds (reference:
    GrainDirectoryHandoffManager.ProcessSiloRemoveEvent :141 — the
    DEATH path, not shutdown)."""

    async def main():
        backing = MemoryVectorStore.shared_backing()

        def setup(silo):
            silo.tensor_engine.store = MemoryVectorStore(backing)
            # tightest loss window: write back at every tick boundary
            silo.tensor_engine.config.checkpoint_every_ticks = 1

        cluster = TestingCluster(n_silos=2, silo_setup=setup,
                                 transport="tcp")
        await cluster.start()
        try:
            a, b = cluster.silos[0], cluster.silos[1]
            n = 200
            keys = np.arange(n, dtype=np.int64)
            for _ in range(3):  # mid-load: several ticks of updates
                a.tensor_engine.send_batch(
                    "RouteCounter", "add", keys,
                    {"v": np.ones(n, np.float32)})
                await settle(cluster)
            before = arena_rows(cluster, "RouteCounter")
            b_keys = [k for k, (s, _) in before.items() if s == b.name]
            assert b_keys, "expected some keys on silo B"
            assert all(int(r["count"]) == 3 for _, r in before.values())

            cluster.kill_silo(b)  # no goodbye, no handoff write-back
            await cluster.wait_for_liveness_convergence()

            # first touch after the death: survivor restores B's keys
            # from the periodic checkpoint
            a.tensor_engine.send_batch("RouteCounter", "add", keys,
                                       {"v": np.ones(n, np.float32)})
            await settle(cluster)
            after = arena_rows(cluster, "RouteCounter")
            assert set(after) == set(range(n))
            assert all(s == a.name for s, _ in after.values())
            # every tick before the kill was checkpointed (cadence=1), so
            # nothing was lost: 3 pre-kill + 1 post-kill
            assert all(int(r["count"]) == 4 for _, r in after.values()), \
                sorted(set(int(r["count"]) for _, r in after.values()))
            restored = sum(
                s.tensor_engine.arenas["RouteCounter"].restored_count
                for s in cluster.silos)
            assert restored >= len(b_keys)
        finally:
            await cluster.stop()

    run(main())


def test_hard_kill_loss_window_bounded_by_cadence(run):
    """Without a checkpoint between the last updates and the kill, the
    loss is AT MOST the updates since the previous checkpoint — the
    documented, bounded window (state restores from the last checkpoint,
    never from field defaults)."""

    async def main():
        backing = MemoryVectorStore.shared_backing()
        engines = []

        def setup(silo):
            silo.tensor_engine.store = MemoryVectorStore(backing)
            engines.append(silo.tensor_engine)

        cluster = TestingCluster(n_silos=2, silo_setup=setup,
                                 transport="tcp")
        await cluster.start()
        try:
            a, b = cluster.silos[0], cluster.silos[1]
            n = 100
            keys = np.arange(n, dtype=np.int64)
            a.tensor_engine.send_batch("RouteCounter", "add", keys,
                                       {"v": np.ones(n, np.float32)})
            await settle(cluster)
            # explicit checkpoint at count=1 …
            for e in engines:
                await e.checkpoint()
            # … then one more UNcheckpointed tick of updates
            a.tensor_engine.send_batch("RouteCounter", "add", keys,
                                       {"v": np.ones(n, np.float32)})
            await settle(cluster)

            cluster.kill_silo(b)
            await cluster.wait_for_liveness_convergence()
            a.tensor_engine.send_batch("RouteCounter", "add", keys,
                                       {"v": np.ones(n, np.float32)})
            await settle(cluster)

            after = arena_rows(cluster, "RouteCounter")
            assert set(after) == set(range(n))
            counts = {k: int(r["count"]) for k, (_, r) in after.items()}
            # keys that lived on A: all 3 ticks.  Keys that lived on B:
            # restored from the checkpoint (count=1) + the post-kill
            # touch = 2 — the window lost exactly the uncheckpointed
            # tick, never more (and never down to field defaults)
            assert set(counts.values()) <= {2, 3}, sorted(set(
                counts.values()))
            assert 2 in counts.values()  # B really lost only the window
        finally:
            await cluster.stop()

    run(main())


@vector_grain
class FenceGrain(VectorGrain):
    """Source/subscriber pair for the handoff-fence ordering test."""

    hits = field(jnp.int32, 0)
    notes = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def ping(state, batch: Batch, n_rows: int):
        ones = jnp.ones_like(batch.rows, dtype=jnp.int32) * batch.mask
        return {**state, "hits": state["hits"] + seg_sum(
            ones, batch.rows, n_rows)}

    @batched_method
    @staticmethod
    def note(state, batch: Batch, n_rows: int):
        ones = jnp.ones_like(batch.rows, dtype=jnp.int32) * batch.mask
        return {**state, "notes": state["notes"] + seg_sum(
            ones, batch.rows, n_rows)}


def test_fence_defers_batch_with_fanout_unexpanded(run):
    """A batch the handoff fence defers must defer WITH its fan-out
    unexpanded: under the r4 ordering the subscriber delivery applied a
    full tick before the source grain's own update, so a tick-boundary
    checkpoint between the two persisted subscriber effects without the
    source update.  Source update and subscriber delivery must land in
    the SAME tick once the fence releases."""

    async def main():
        from orleans_tpu.tensor.fanout import DeviceFanout

        cluster = await TestingCluster(n_silos=1).start()
        try:
            silo = cluster.silos[0]
            engine = silo.tensor_engine
            fan = DeviceFanout(budget=16)
            fan.follow(1, 2)  # subscriber key 2 follows source key 1
            engine.register_fanout("FenceGrain", "ping", fan,
                                   "FenceGrain", "note")

            # subscriber key 2 is ACTIVE before the fence arms; source
            # key 1 stays unseen (first-touch activation is what the
            # fence gates)
            engine.send_batch("FenceGrain", "note",
                              np.array([2], dtype=np.int64),
                              {"v": np.array([0], np.int32)})
            await engine.drain_queues()
            arena = engine.arena_for("FenceGrain")
            assert int(arena.read_row(2)["notes"]) == 1

            router = silo.vector_router
            orig = router.handoff_settled
            router.handoff_settled = lambda: False
            try:
                engine.send_batch("FenceGrain", "ping",
                                  np.array([1], dtype=np.int64),
                                  {"v": np.array([7], np.int32)})
                for _ in range(3):  # fenced ticks: batch defers each time
                    engine.run_tick()
                # NOTHING may have applied while the fence held — neither
                # the source update (key 1 unseen) nor, critically, the
                # subscriber delivery its fan-out would expand
                assert int(arena.read_row(2)["notes"]) == 1, \
                    "subscriber delivery applied while source was fenced"
                rows, found = arena.lookup_rows(
                    np.array([1], dtype=np.int64))
                assert not found.any(), "fenced source key activated"
            finally:
                router.handoff_settled = orig
            await engine.flush()
            assert int(arena.read_row(1)["hits"]) == 1
            assert int(arena.read_row(2)["notes"]) == 2
        finally:
            await cluster.stop()

    run(main())


def test_sender_aggregation_merges_fragments_per_destination(run):
    """Tentpole: slab fragments produced within one drain cycle merge
    into ONE frame per (destination, type, method) — delivery stays
    exact, and the merge ratio (the health indicator) exceeds 1."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            a = cluster.silos[0]
            n, parts = 400, 8
            keys = np.arange(n, dtype=np.int64)
            # 8 fragments submitted in one synchronous burst
            for i in range(parts):
                lo, hi = i * n // parts, (i + 1) * n // parts
                a.tensor_engine.send_batch(
                    "RouteCounter", "add", keys[lo:hi],
                    {"v": np.ones(hi - lo, np.float32)})
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")
            assert set(rows) == set(range(n))
            assert all(int(r["count"]) == 1 for _, r in rows.values())
            snap = a.vector_router.snapshot()
            # fragments merged: far fewer frames than fragments
            assert snap["slab_fragments"] > snap["slab_frames"]
            assert snap["slab_merge_ratio"] > 1.0
            # one merged frame per remote destination for the burst
            remote_silos = len(cluster.silos) - 1
            assert snap["slab_frames"] <= remote_silos
        finally:
            await cluster.stop()

    run(main())


def test_aggregation_toggle_off_ships_fragments_unmerged(run):
    """The A/B toggle (config.tensor.slab_aggregation=False) bypasses
    the merge: every fragment is its own frame, delivery still exact."""
    from orleans_tpu.config import SiloConfig

    def cfg(name):
        c = SiloConfig(name=name)
        c.liveness.probe_period = 0.1
        c.liveness.probe_timeout = 0.1
        c.liveness.num_missed_probes_limit = 2
        c.liveness.table_refresh_timeout = 0.2
        c.tensor.slab_aggregation = False
        return c

    async def main():
        cluster = await TestingCluster(n_silos=2,
                                       config_factory=cfg).start()
        try:
            a = cluster.silos[0]
            n, parts = 400, 4
            keys = np.arange(n, dtype=np.int64)
            for i in range(parts):
                lo, hi = i * n // parts, (i + 1) * n // parts
                a.tensor_engine.send_batch(
                    "RouteCounter", "add", keys[lo:hi],
                    {"v": np.ones(hi - lo, np.float32)})
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")
            assert set(rows) == set(range(n))
            assert all(int(r["count"]) == 1 for _, r in rows.values())
            snap = a.vector_router.snapshot()
            assert snap["slab_frames"] == snap["slab_fragments"]
            assert snap["slab_merge_ratio"] == 1.0
        finally:
            await cluster.stop()

    run(main())


def test_merged_fragments_preserve_scalar_leaf_broadcast(run):
    """Fragments whose args carry scalar leaves merge by broadcasting
    each scalar to its fragment's row count (different scalars per
    fragment must NOT bleed into each other's rows)."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            a, b = cluster.silos
            n = 200
            keys = np.arange(n, dtype=np.int64)
            # two fragments with DIFFERENT scalar payloads, one burst
            a.tensor_engine.send_batch(
                "RouteCounter", "add", keys[:n // 2],
                {"v": np.float32(1.0)})
            a.tensor_engine.send_batch(
                "RouteCounter", "add", keys[n // 2:],
                {"v": np.float32(3.0)})
            await settle(cluster)
            rows = arena_rows(cluster, "RouteCounter")
            assert set(rows) == set(range(n))
            for k, (_, r) in rows.items():
                want = 1.0 if k < n // 2 else 3.0
                assert float(r["total"]) == want, (k, float(r["total"]))
        finally:
            await cluster.stop()

    run(main())


def test_bounced_slab_reinjects_with_backoff_and_redelivers(run):
    """Satellite fix: a slab frame the transport bounces (transient link
    failure) must NOT lose its payload — it re-enters through
    _backoff_reinject and redelivers once the link heals."""
    from orleans_tpu.config import SiloConfig

    def patient(name):
        # the severed window must stay a TRANSPORT event: probes ride the
        # same link, and test-default liveness would declare the peer
        # dead (ring change) before the first bounce even fires
        cfg = SiloConfig(name=name)
        cfg.liveness.probe_timeout = 5.0
        cfg.liveness.probe_period = 5.0
        cfg.liveness.num_missed_probes_limit = 20
        return cfg

    async def main():
        cluster = await TestingCluster(n_silos=2, transport="tcp",
                                       config_factory=patient).start()
        try:
            a, b = cluster.silos
            transport = a._bound_transport.transport
            # sever the link: point the peer's endpoint at a dead port and
            # drop the established connection, so the next send reconnects
            # into a refused socket and the frame bounces
            transport.register_endpoint(b.address, "127.0.0.1", 1)
            stale = transport._senders.pop(b.address, None)
            if stale is not None:
                stale.cancel()
            transport._queues.pop(b.address, None)
            transport._queue_bytes.pop(b.address, None)
            n = 300
            keys = np.arange(n, dtype=np.int64)
            a.tensor_engine.send_batch(
                "RouteCounter", "add", keys,
                {"v": np.ones(n, np.float32)})
            # let the frame bounce + park at least once
            for _ in range(100):
                await asyncio.sleep(0.02)
                if a.vector_router.slab_bounces > 0:
                    break
            assert a.vector_router.slab_bounces > 0, \
                "transport never routed the bounce through the router"
            # heal the link: the parked slab's backoff retry must deliver
            transport.register_endpoint(b.address, b.address.host,
                                        b.address.port)
            deadline = asyncio.get_running_loop().time() + 10
            while True:
                await settle(cluster)
                rows = arena_rows(cluster, "RouteCounter")
                if set(rows) == set(range(n)) and \
                        all(int(r["count"]) == 1 for _, r in rows.values()):
                    break
                assert asyncio.get_running_loop().time() < deadline, \
                    f"only {len(rows)} rows redelivered"
                await asyncio.sleep(0.05)
            assert a.vector_router.messages_dropped == 0
        finally:
            await cluster.stop()

    run(main())


def test_data_plane_telemetry_publication(run):
    """Router slab counters + per-link transport frames/bytes mirror into
    the telemetry manager (snapshot() AND telemetry surfacing)."""
    from orleans_tpu import telemetry
    from orleans_tpu.telemetry import InMemoryTelemetryConsumer

    async def main():
        consumer = InMemoryTelemetryConsumer()
        telemetry.default_manager.add(consumer)
        cluster = await TestingCluster(n_silos=2, transport="tcp").start()
        try:
            a = cluster.silos[0]
            n = 200
            a.tensor_engine.send_batch(
                "RouteCounter", "add", np.arange(n, dtype=np.int64),
                {"v": np.ones(n, np.float32)})
            await settle(cluster)
            for s in cluster.silos:
                s.publish_data_plane_telemetry()
            names = {m[0] for m in consumer.metrics}
            assert "router.slab_merge_ratio" in names
            assert "router.slabs_shipped" in names
            assert "transport.link.bytes_sent" in names
            sent = [m for m in consumer.metrics
                    if m[0] == "transport.link.bytes_sent"]
            assert any(v > 0 for _, v, _, _ in sent)
        finally:
            telemetry.default_manager.remove(consumer)
            await cluster.stop()

    run(main())
