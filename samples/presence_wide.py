"""Presence with WIDE (64-bit hashed-identity) game keys.

The same heartbeat→fan-in pipeline as samples/presence.py, but game
identities live in the full [0, 2^63) key space (hashed string names —
the reference's UniqueKey shape, UniqueKey.cs:34) and emits address them
as (hi, lo) int32 word pairs through the arena's two-level wide mirror
(arena.device_index_wide).  Used by the wide-key tests and the multichip
dryrun.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from orleans_tpu.core.grain import batched_method
from orleans_tpu.hashing import jenkins_hash
from orleans_tpu.tensor import (
    Batch,
    Emit,
    VectorGrain,
    field,
    seg_sum,
    vector_grain,
)
from orleans_tpu.tensor.vector_grain import scatter_add_rows


def wide_game_keys(n: int) -> np.ndarray:
    """String-identity games hashed into the full 64-bit space."""
    return np.array(
        [((jenkins_hash(f"game-{i}".encode()) << 33)
          ^ jenkins_hash(f"g2-{i}".encode())) & 0x7FFFFFFFFFFFFFFF
         for i in range(n)],
        dtype=np.uint64).astype(np.int64)


@vector_grain
class WidePresence(VectorGrain):
    """Presence whose emit destination is an (hi, lo) word pair."""

    heartbeats = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def heartbeat(state, batch: Batch, n_rows: int):
        ones = jnp.ones_like(batch.rows, dtype=jnp.int32) * batch.mask
        state = {**state,
                 "heartbeats": scatter_add_rows(state["heartbeats"],
                                                batch.rows, ones)}
        emit = Emit(interface="WideGame", method="update",
                    keys=(batch.args["game_hi"], batch.args["game_lo"]),
                    args={"score": batch.args["score"], "count": ones},
                    mask=batch.mask)
        return state, None, (emit,)


@vector_grain
class WideGame(VectorGrain):
    total_score = field(jnp.float32, 0.0)
    updates = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def update(state, batch: Batch, n_rows: int):
        return {
            **state,
            "total_score": state["total_score"]
            + seg_sum(batch.args["score"], batch.rows, n_rows),
            "updates": state["updates"]
            + seg_sum(batch.args["count"], batch.rows, n_rows),
        }
