"""Incremental activation collection: free-list arena, chunked
write-back, bounded eviction pauses.

The reference deactivates idle grains continuously without ever stalling
the message pump (reference: ActivationCollector.cs:37, Catalog.cs:836).
The tensor-path analog here must give the same guarantees at arena
granularity:

- deactivation frees rows IN PLACE (per-shard free lists): survivors do
  not move, the arena generation is preserved, and cached resolved rows
  over surviving keys stay valid — no re-resolution/recompile storm;
- ``eviction_epoch`` invalidates caches that might reference a freed
  row, with a cheap liveness re-check on the injector fast path;
- collection drains in pause-budgeted slices between ticks (chunked
  columnar write-back), and victims are never freed before the store
  acks — an injected storage fault leaves them live for the retry;
- full compaction still runs past the fragmentation threshold (and on
  grow/reshard, where it always did).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from orleans_tpu.config import TensorEngineConfig
from orleans_tpu.tensor import MemoryVectorStore, TensorEngine
from orleans_tpu.tensor.arena import _hash_keys_u64

import tests.test_tensor_engine  # noqa: F401 — registers AccumGrain


def _add(engine, keys, v=1.0):
    engine.send_batch("AccumGrain", "add",
                      np.asarray(keys, dtype=np.int64),
                      {"v": np.full(len(keys), v, np.float32)})


# ---- free-list allocator -------------------------------------------------


def test_eviction_preserves_generation_and_bumps_epoch(run):
    async def go():
        store = MemoryVectorStore()
        engine = TensorEngine(store=store, initial_capacity=64)
        _add(engine, range(16), v=2.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        gen0, epoch0 = arena.generation, arena.eviction_epoch

        engine.tick_number += 100
        arena.resolve_rows(np.arange(8, dtype=np.int64),
                           tick=engine.tick_number)
        assert engine.collect_idle(max_idle_ticks=50) == 8
        # THE tentpole property: no rows moved, so no generation bump —
        # surviving caches, device mirrors and compiled programs for the
        # survivors stay valid
        assert arena.generation == gen0
        assert arena.eviction_epoch > epoch0
        # survivors still resolve to the same rows and hold their state
        assert float(arena.read_row(3)["total"]) == 2.0

    run(go())


def test_freed_slots_reused_in_place(run):
    """Churn (activate → evict → activate new keys) reuses freed slots:
    capacity stays flat and the reused slot starts from field inits, not
    the evicted grain's stale state."""

    async def go():
        engine = TensorEngine(initial_capacity=64)
        _add(engine, range(32), v=9.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        arena.compact_fragmentation = 0.0  # isolate free-list reuse
        cap0, gen0 = arena.capacity, arena.generation
        rows_before = set(
            arena.resolve_rows(np.arange(32, dtype=np.int64)).tolist())

        engine.tick_number += 100
        assert engine.collect_idle(50, write_back=False) == 32

        # new keys land in the freed slots — same rows, no growth
        _add(engine, range(100, 132), v=1.0)
        await engine.flush()
        rows_after = set(
            arena.resolve_rows(np.arange(100, 132, dtype=np.int64)).tolist())
        assert rows_after == rows_before
        assert arena.capacity == cap0
        assert arena.generation == gen0
        # the reused slot must NOT leak the evicted grain's 9.0
        assert float(arena.read_row(100)["total"]) == 1.0
        assert int(arena.read_row(100)["count"]) == 1

    run(go())


def test_free_list_survives_grow(run):
    """Freed slots remap across growth (row ids shift with the per-shard
    block layout) and remain reusable afterwards."""

    async def go():
        engine = TensorEngine(initial_capacity=32)
        _add(engine, range(24), v=5.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        engine.tick_number += 100
        arena.resolve_rows(np.arange(8, dtype=np.int64),
                           tick=engine.tick_number)
        assert engine.collect_idle(50, write_back=False) == 16
        free_before = sum(len(f) for f in arena._free)
        assert free_before == 16

        # activation burst past bump+free space forces growth
        _add(engine, range(1000, 1060), v=1.0)
        await engine.flush()
        assert arena.capacity > 32
        # survivors kept state through the repack
        assert float(arena.read_row(3)["total"]) == 5.0
        assert float(arena.read_row(1005)["total"]) == 1.0
        # every key resolves to exactly one row in its home shard
        keys = arena.keys()
        rows = arena.resolve_rows(keys)
        assert len(set(rows.tolist())) == len(keys)

    run(go())


def test_fragmentation_threshold_triggers_compact(run):
    async def go():
        engine = TensorEngine(initial_capacity=64)
        engine.config.compact_fragmentation_threshold = 0.5
        _add(engine, range(40), v=1.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        arena.compact_fragmentation = 0.5
        gen0 = arena.generation

        engine.tick_number += 100
        arena.resolve_rows(np.arange(4, dtype=np.int64),
                           tick=engine.tick_number)
        # evicting 36 of 40 pushes freed/high-water past 0.5 → repack
        assert engine.collect_idle(50, write_back=False) == 36
        assert arena.generation > gen0          # rows moved
        assert arena.fragmentation() == 0.0     # holes reclaimed
        assert sum(len(f) for f in arena._free) == 0
        assert float(arena.read_row(2)["total"]) == 1.0  # survivors intact

    run(go())


def test_compact_vectorized_layout_under_mesh(run):
    """Explicit compaction repacks every shard block contiguously (the
    vectorized argsort/cumsum path must match the per-shard semantics)."""
    import jax
    from jax.sharding import Mesh

    async def go():
        mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("grains",))
        engine = TensorEngine(mesh=mesh, initial_capacity=128)
        _add(engine, range(64), v=3.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        engine.tick_number += 100
        keep = np.arange(0, 64, 2, dtype=np.int64)
        arena.resolve_rows(keep, tick=engine.tick_number)
        assert engine.collect_idle(50, write_back=False) == 32

        arena._compact()
        # live rows contiguous from each block base, in their home shard
        rows = arena.resolve_rows(keep)
        shards = rows // arena.shard_capacity
        expected = (_hash_keys_u64(keep) % np.uint64(8)).astype(np.int64)
        np.testing.assert_array_equal(shards, expected)
        for s in range(8):
            in_s = np.sort(rows[shards == s]) - s * arena.shard_capacity
            np.testing.assert_array_equal(in_s, np.arange(len(in_s)))
        assert float(arena.read_row(4)["total"]) == 3.0

    run(go())


# ---- cache validity across eviction --------------------------------------


def test_injector_survives_foreign_eviction_without_reresolve(run):
    """An injector whose keys were NOT evicted keeps its cached device
    rows across another key's eviction — the cheap epoch re-check, not a
    full re-resolution (the 4M recompile-storm fix)."""

    async def go():
        store = MemoryVectorStore()
        engine = TensorEngine(store=store, initial_capacity=64)
        _add(engine, range(16), v=1.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")

        hot = np.arange(8, dtype=np.int64)
        inj = engine.make_injector("AccumGrain", "add", hot)
        cached_rows = inj.rows
        engine.tick_number += 100
        arena.resolve_rows(hot, tick=engine.tick_number)
        assert engine.collect_idle(50) == 8  # keys 8..15 evicted

        inj.inject({"v": np.ones(8, np.float32)})
        await engine.flush()
        # same device array object: no re-resolve, no re-upload
        assert inj.rows is cached_rows
        assert inj.generation == arena.generation
        assert inj.epoch == arena.eviction_epoch
        assert float(arena.read_row(0)["total"]) == 2.0

    run(go())


def test_injector_over_evicted_key_reactivates_through_store(run):
    async def go():
        store = MemoryVectorStore()
        engine = TensorEngine(store=store, initial_capacity=64)
        keys = np.arange(4, dtype=np.int64)
        inj = engine.make_injector("AccumGrain", "add", keys)
        inj.inject({"v": np.full(4, 3.0, np.float32)})
        await engine.flush()
        arena = engine.arena_for("AccumGrain")

        engine.tick_number += 100
        assert engine.collect_idle(50) == 4  # the injector's own keys
        assert len(store.list_keys("AccumGrain")) == 4

        inj.inject({"v": np.ones(4, np.float32)})
        await engine.flush()
        # full re-resolve: reactivation read the written-back state
        assert float(arena.read_row(2)["total"]) == 4.0
        assert arena.restored_count == 4

    run(go())


def test_injector_key_reactivated_in_different_slot(run):
    """Evict an injector's key, let ANOTHER key reuse its slot, then
    reactivate the original key elsewhere: the injector's epoch
    revalidation must detect the row move (liveness alone is not
    enough) and re-resolve — never write into the usurper's row."""

    async def go():
        store = MemoryVectorStore()
        engine = TensorEngine(store=store, initial_capacity=64)
        arena = engine.arena_for("AccumGrain")
        arena.compact_fragmentation = 0.0
        keys = np.arange(4, dtype=np.int64)
        inj = engine.make_injector("AccumGrain", "add", keys)
        inj.inject({"v": np.full(4, 2.0, np.float32)})
        await engine.flush()
        old_rows = arena.resolve_rows(keys).copy()

        # evict the injector's keys, then let keys 100..103 LIFO-reuse
        # their slots, then reactivate the originals (new slots)
        engine.tick_number += 100
        assert engine.collect_idle(50) == 4
        _add(engine, range(100, 104), v=7.0)
        await engine.flush()
        usurped = arena.resolve_rows(np.arange(100, 104, dtype=np.int64))
        assert set(usurped.tolist()) == set(old_rows.tolist())
        arena.resolve_rows(keys, tick=engine.tick_number)  # reactivate

        inj.inject({"v": np.ones(4, np.float32)})
        await engine.flush()
        # the usurpers' state is untouched, the originals got the adds
        for k in range(100, 104):
            assert float(arena.read_row(k)["total"]) == 7.0
        for k in range(4):
            assert float(arena.read_row(k)["total"]) == 3.0  # 2 + 1

    run(go())


def test_collect_idle_completes_across_threshold_compaction(run):
    """A mid-drain threshold compaction drops that sweep's remaining
    victim ids (generation moved) — the explicit collect_idle API must
    re-sweep and still evict EVERY eligible row before returning."""

    async def go():
        engine = TensorEngine(store=MemoryVectorStore(),
                              initial_capacity=64)
        engine.config.collection_chunk_rows = 32
        _add(engine, range(1000), v=1.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        assert arena.compact_fragmentation == 0.75  # threshold active
        engine.tick_number += 100
        assert engine.collect_idle(50) == 1000
        assert arena.live_count == 0
        assert engine.collector.victims_dropped_stale > 0  # compaction hit

    run(go())


def test_evict_while_pending_batch_targets_victim(run):
    """A batch already queued (device keys, resolved optimistically)
    whose destination is evicted before the miss-check settles must
    round-trip through the store — state written back at eviction is
    visible to the redelivered message."""

    async def go():
        store = MemoryVectorStore()
        engine = TensorEngine(store=store, initial_capacity=64)
        _add(engine, range(8), v=5.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")

        # queue (do not flush) a device-key batch to key 7, then evict 7
        engine.send_batch("AccumGrain", "add",
                          jnp.asarray(np.array([7], np.int32)),
                          {"v": np.ones(1, np.float32)})
        engine.tick_number += 100
        arena.resolve_rows(np.arange(7, dtype=np.int64),
                           tick=engine.tick_number)
        assert engine.collect_idle(50) == 1

        await engine.flush()  # miss-path redelivery reactivates key 7
        assert float(arena.read_row(7)["total"]) == 6.0  # 5 persisted + 1
        assert arena.restored_count == 1

    run(go())


def test_write_back_false_discards_state(run):
    async def go():
        store = MemoryVectorStore()
        engine = TensorEngine(store=store, initial_capacity=64)
        _add(engine, range(4), v=7.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        engine.tick_number += 100
        assert engine.collect_idle(50, write_back=False) == 4
        assert len(store.list_keys("AccumGrain")) == 0
        # reactivation restarts from field inits
        _add(engine, [2], v=1.0)
        await engine.flush()
        assert float(arena.read_row(2)["total"]) == 1.0

    run(go())


# ---- chunked write-back & faults ------------------------------------------


class _FlakyStore(MemoryVectorStore):
    """Fails the first N columnar writes — the chaos storage seam's
    ``fail`` action as seen by the tensor bridge."""

    def __init__(self):
        super().__init__()
        self.fail_next = 0
        self.columnar_writes = 0

    def write_many_columnar(self, type_name, keys, columns):
        self.columnar_writes += 1
        if self.fail_next > 0:
            self.fail_next -= 1
            raise IOError("chaos: injected storage write failure")
        super().write_many_columnar(type_name, keys, columns)


def test_storage_fault_mid_chunk_keeps_victims_live(run):
    """Victims are freed only after write-back acks: a storage fault
    leaves them live (and their state intact) for the retry — the
    tick-interleaved collector parks the chunk and retries next slice."""

    async def go():
        store = _FlakyStore()
        engine = TensorEngine(store=store, initial_capacity=64)
        _add(engine, range(12), v=4.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        live0 = arena.live_count
        engine.tick_number += 100

        store.fail_next = 2
        engine.collector.start_sweep(engine.tick_number - 50)
        evicted = engine.collector.run_slice(0.0, chunk_rows=4)
        # the first chunk failed: nothing freed by it, slice aborted
        assert engine.collector.write_back_failures == 1
        assert arena.live_count == live0 - evicted
        assert engine.collector.active()
        # state still readable (nothing was freed before the ack)
        assert float(arena.read_row(0)["total"]) == 4.0

        # fault clears → retry drains the remainder, nothing lost
        store.fail_next = 0
        while engine.collector.active():
            engine.collector.run_slice(0.0, chunk_rows=4)
        assert arena.live_count == 0
        assert len(store.list_keys("AccumGrain")) == 12
        # every record carries the written-back state
        _add(engine, [11], v=1.0)
        await engine.flush()
        assert float(arena.read_row(11)["total"]) == 5.0

    run(go())


def test_synchronous_collect_propagates_storage_fault(run):
    async def go():
        store = _FlakyStore()
        engine = TensorEngine(store=store, initial_capacity=64)
        _add(engine, range(6), v=1.0)
        await engine.flush()
        engine.tick_number += 100
        store.fail_next = 10**9  # permanent fault
        with pytest.raises(IOError):
            engine.collect_idle(50)
        # nothing freed, nothing lost
        assert engine.arena_for("AccumGrain").live_count == 6

    run(go())


def test_chaos_seam_fault_through_provider_bridge(run):
    """The chaos interposer's storage seam (StorageProvider.write_state)
    sits under StorageProviderVectorStore: an injected write failure
    during chunked write-back must leave the victims live."""
    from orleans_tpu.chaos.interposer import Interposer
    from orleans_tpu.chaos.plan import FaultPlan
    from orleans_tpu.providers.memory_storage import MemoryStorage
    from orleans_tpu.tensor import StorageProviderVectorStore

    async def go():
        plan = FaultPlan(seed=7)
        plan.rule("wb-fault", "storage", "fail", count=1)
        interposer = Interposer(plan)
        provider = MemoryStorage()
        interposer.attach_storage(provider, "mem")
        engine = TensorEngine(store=StorageProviderVectorStore(provider),
                              initial_capacity=64)
        _add(engine, range(5), v=2.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        arena.compact_fragmentation = 0.0  # keep the sweep's row ids live
        engine.tick_number += 100

        engine.collector.start_sweep(engine.tick_number - 50)
        engine.collector.run_slice(0.0, chunk_rows=2)
        assert engine.collector.write_back_failures == 1
        assert arena.live_count > 0  # faulted chunk stayed live
        # retry succeeds once the rule's budget is spent
        while engine.collector.active():
            engine.collector.run_slice(0.0, chunk_rows=2)
        assert arena.live_count == 0
        assert interposer.counters["storage_failed"] == 1

    run(go())


# ---- incremental pipeline / bounded pauses --------------------------------


def test_tick_interleaved_collection_bounded_slices(run):
    """The automatic (tick-loop) path drains a sweep across MULTIPLE
    ticks — per-slice chunking really interleaves with traffic — and
    hot rows stay live throughout."""

    async def go():
        cfg = TensorEngineConfig(collection_idle_ticks=10,
                                 collection_every_ticks=8,
                                 collection_pause_budget_s=1e-9,
                                 collection_chunk_rows=16)
        engine = TensorEngine(config=cfg, store=MemoryVectorStore(),
                              initial_capacity=256)
        _add(engine, range(128), v=1.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")

        hot = np.arange(8, dtype=np.int64)
        inj = engine.make_injector("AccumGrain", "add", hot)
        engine.tick_number += 100
        evicted_by_tick = []
        for _ in range(24):
            inj.inject({"v": np.ones(8, np.float32)})
            engine.run_tick()
            evicted_by_tick.append(arena.evicted_count)
        await engine.flush()

        assert arena.evicted_count == 120  # the idle majority went
        assert arena.live_count == 8
        # the sweep spanned several ticks (budget ~0 → one chunk/slice)
        progress_ticks = sum(1 for a, b in zip(evicted_by_tick,
                                               evicted_by_tick[1:])
                             if b > a)
        assert progress_ticks >= 3
        assert engine.collector.slices_run >= 3
        assert float(arena.read_row(0)["total"]) >= 24.0

    run(go())


def test_collect_slice_spans_and_flight_dump(run):
    """Each slice emits ONE batched engine.collect span; the flight
    recorder dump carries the recent collection slices."""
    from orleans_tpu.spans import SpanRecorder

    async def go():
        engine = TensorEngine(store=MemoryVectorStore(),
                              initial_capacity=64)

        class _SiloStub:
            spans = SpanRecorder("collect-test", enabled=True,
                                 sample_rate=0.0)

        engine.silo = _SiloStub()
        _add(engine, range(10), v=1.0)
        await engine.flush()
        engine.arena_for("AccumGrain").compact_fragmentation = 0.0
        engine.tick_number += 100
        engine.collector.start_sweep(engine.tick_number - 50)
        while engine.collector.active():
            engine.collector.run_slice(0.0, chunk_rows=4)

        rec = _SiloStub.spans
        collect_spans = [s for s in rec.flight.spans
                         if s.kind == "engine.collect"]
        assert len(collect_spans) == engine.collector.slices_run
        assert collect_spans[-1].attrs["sweep_done"] is True
        assert sum(s.attrs["evicted"] for s in collect_spans) == 10

        dump = rec.flight.dump(
            reason="test",
            collection_slices=engine.collector.last_slices)
        assert len(dump["collection_slices"]) == engine.collector.slices_run
        assert dump["collection_slices"][-1]["sweep_done"] is True

    run(go())


def test_collection_telemetry_gauges(run):
    from orleans_tpu import telemetry

    async def go():
        consumer = telemetry.InMemoryTelemetryConsumer()
        telemetry.default_manager.add(consumer)
        try:
            engine = TensorEngine(store=MemoryVectorStore(),
                                  initial_capacity=64)
            _add(engine, range(10), v=1.0)
            await engine.flush()
            engine.tick_number += 100
            assert engine.collect_idle(50) == 10
            names = {m[0] for m in consumer.metrics}
            assert "collect.pause_s" in names
            assert "arena.fragmentation" in names
        finally:
            telemetry.default_manager.remove(consumer)

    run(go())


def test_columnar_write_back_per_grain_records(run):
    """write_many_columnar preserves per-grain record granularity: each
    key's record round-trips individually through read_many."""

    async def go():
        store = MemoryVectorStore()
        engine = TensorEngine(store=store, initial_capacity=64)
        keys = np.arange(6, dtype=np.int64)
        engine.send_batch("AccumGrain", "add", keys,
                          {"v": np.arange(6, dtype=np.float32)})
        await engine.flush()
        engine.tick_number += 100
        assert engine.collect_idle(50) == 6
        rows = store.read_many("AccumGrain", [0, 3, 5])
        assert float(rows[3]["total"]) == 3.0
        assert float(rows[5]["total"]) == 5.0
        assert int(rows[0]["count"]) == 1

    run(go())


def test_autofused_pattern_survives_foreign_eviction(run):
    """Auto-fusion over a hot key set keeps running across an eviction
    of OTHER keys: the epoch change re-traces the window program (the
    baked directory mirror is stale) but the pattern re-engages and the
    result stays exact."""

    async def go():
        cfg = TensorEngineConfig(auto_fusion_ticks=4, auto_fusion_window=4,
                                 tick_interval=0.0)
        engine = TensorEngine(config=cfg, store=MemoryVectorStore(),
                              initial_capacity=64)
        _add(engine, range(16), v=1.0)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        gen0 = arena.generation

        hot = np.arange(8, dtype=np.int64)
        inj = engine.make_injector("AccumGrain", "add", hot)
        for _ in range(24):
            inj.inject({"v": np.ones(8, np.float32)})
            engine.run_tick()
        await engine.flush()
        assert engine.autofuser.windows_run >= 1

        # evict the idle half mid-steady-state
        engine.tick_number += 100
        arena.resolve_rows(hot, tick=engine.tick_number)
        assert engine.collect_idle(50) == 8
        assert arena.generation == gen0  # no repack happened

        for _ in range(24):
            inj.inject({"v": np.ones(8, np.float32)})
            engine.run_tick()
        await engine.flush()
        # exactness: every tick's adds landed exactly once
        assert float(arena.read_row(0)["total"]) == 1.0 + 48.0

    run(go())
