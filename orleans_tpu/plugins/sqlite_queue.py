"""Durable queue adapter for persistent streams, backed by sqlite.

Parity: the reference's production persistent-stream backend is a
durable external queue service — AzureQueueAdapter writes each event
batch to an Azure Storage queue and receivers pull/delete by receipt
(reference: src/OrleansAzureUtils/Providers/Streams/AzureQueue/
AzureQueueAdapter.cs:34, AzureQueueAdapterReceiver).  This adapter plays
that role with sqlite on a shared path: events survive process restarts,
multiple processes can produce/consume the same queues, and the pulling
agents' at-least-once + ack/trim discipline is identical to the
in-memory adapter's (streams/persistent.py) — so the whole persistent-
stream suite runs unchanged on a durable store.

Concurrency discipline: sequence allocation is a read-modify-write, so
every mutation runs under ``BEGIN IMMEDIATE`` (sqlite's write lock —
the cross-process serialization the reference gets from the queue
service), and all sqlite work runs in a worker thread via
``asyncio.to_thread`` so disk commits never stall the silo's event loop.

Delivery cursor: one durable row per queue records the ack offset (the
analog of queue-message deletion after processing); events at or below
it are trimmed on ack.
"""

from __future__ import annotations

import asyncio
import sqlite3
import threading
from typing import List

from orleans_tpu.codec import default_manager as codec
from orleans_tpu.streams.persistent import (
    QueueAdapter,
    QueueAdapterReceiver,
    QueueMessage,
)


class SqliteQueueAdapter(QueueAdapter):
    """(reference: AzureQueueAdapter.cs:34 — durable queue per queue id)"""

    _SCHEMA = """
    CREATE TABLE IF NOT EXISTS stream_events (
        queue_id  INTEGER NOT NULL,
        seq       INTEGER NOT NULL,
        payload   BLOB    NOT NULL,
        PRIMARY KEY (queue_id, seq)
    );
    CREATE TABLE IF NOT EXISTS stream_cursors (
        queue_id  INTEGER PRIMARY KEY,
        cursor    INTEGER NOT NULL,
        next_seq  INTEGER NOT NULL
    );
    """

    #: events kept after ack for rewind-token replay
    retain: int = 256

    def __init__(self, path: str = ":memory:", n_queues: int = 8) -> None:
        self.path = path
        self.n_queues = n_queues
        # manual transactions (BEGIN IMMEDIATE) + worker-thread execution
        self._conn = sqlite3.connect(path, isolation_level=None,
                                     check_same_thread=False)
        self._conn.execute("PRAGMA busy_timeout=5000")
        self._lock = threading.Lock()  # serialize our own threads
        #: sqlite round-trips (write transactions + pull selects) — the
        #: batching contract's observable: one produce() of k items is
        #: ONE transaction, one pull cycle's dequeue+ack is ONE
        #: transaction (tests assert the before/after counts)
        self.transactions = 0
        with self._lock:
            self._conn.executescript(self._SCHEMA)

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    # -- synchronous cores (run via asyncio.to_thread) ----------------------

    def _enqueue_many_sync(self, queue_id: int,
                           msgs: List[QueueMessage]) -> int:
        """Insert a whole produce() batch under ONE write transaction —
        k items no longer pay k sequence-allocation round-trips (the
        per-event half of the old stream-plane host cost)."""
        with self._lock:
            # IMMEDIATE takes the write lock BEFORE the read, so two
            # producer processes cannot both read the same next_seq
            self._conn.execute("BEGIN IMMEDIATE")
            self.transactions += 1
            try:
                self._conn.execute(
                    "INSERT OR IGNORE INTO stream_cursors (queue_id, "
                    "cursor, next_seq) VALUES (?, 0, 0)", (queue_id,))
                (next_seq,) = self._conn.execute(
                    "SELECT next_seq FROM stream_cursors WHERE queue_id=?",
                    (queue_id,)).fetchone()
                first = next_seq
                rows = []
                for msg in msgs:
                    msg.seq = next_seq
                    rows.append((queue_id, next_seq, codec.serialize(msg)))
                    next_seq += 1
                self._conn.executemany(
                    "INSERT INTO stream_events (queue_id, seq, payload) "
                    "VALUES (?,?,?)", rows)
                self._conn.execute(
                    "UPDATE stream_cursors SET next_seq=? WHERE queue_id=?",
                    (next_seq, queue_id))
                self._conn.execute("COMMIT")
                return first
            except BaseException:
                self._conn.execute("ROLLBACK")
                raise

    def _pull_sync(self, queue_id: int, max_count: int) -> List[QueueMessage]:
        with self._lock:
            self.transactions += 1
            row = self._conn.execute(
                "SELECT cursor FROM stream_cursors WHERE queue_id=?",
                (queue_id,)).fetchone()
            cursor = row[0] if row is not None else 0
            rows = self._conn.execute(
                "SELECT payload FROM stream_events WHERE queue_id=? AND "
                "seq>=? ORDER BY seq LIMIT ?",
                (queue_id, cursor, max_count)).fetchall()
        return [codec.deserialize(b) for (b,) in rows]

    def _ack_sync(self, queue_id: int, up_to_seq: int) -> None:
        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            self.transactions += 1
            try:
                self._conn.execute(
                    "UPDATE stream_cursors SET cursor=MAX(cursor, ?) "
                    "WHERE queue_id=?", (up_to_seq + 1, queue_id))
                self._conn.execute(
                    "DELETE FROM stream_events WHERE queue_id=? AND seq<"
                    "(SELECT cursor FROM stream_cursors WHERE queue_id=?)"
                    " - ?", (queue_id, queue_id, self.retain))
                self._conn.execute("COMMIT")
            except BaseException:
                self._conn.execute("ROLLBACK")
                raise

    def _pull_ack_sync(self, queue_id: int, max_count: int,
                       ack_up_to: int) -> List[QueueMessage]:
        """One pull cycle's dequeue AND the previous cycle's ack in ONE
        write transaction (the pulling agent's batching contract —
        today's equivalent was one ack round-trip per delivered run,
        i.e. per EVENT on un-sinked streams)."""
        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            self.transactions += 1
            try:
                if ack_up_to >= 0:
                    self._conn.execute(
                        "UPDATE stream_cursors SET cursor=MAX(cursor, ?) "
                        "WHERE queue_id=?", (ack_up_to + 1, queue_id))
                    self._conn.execute(
                        "DELETE FROM stream_events WHERE queue_id=? AND "
                        "seq<(SELECT cursor FROM stream_cursors WHERE "
                        "queue_id=?) - ?",
                        (queue_id, queue_id, self.retain))
                row = self._conn.execute(
                    "SELECT cursor FROM stream_cursors WHERE queue_id=?",
                    (queue_id,)).fetchone()
                cursor = row[0] if row is not None else 0
                rows = self._conn.execute(
                    "SELECT payload FROM stream_events WHERE queue_id=? "
                    "AND seq>=? ORDER BY seq LIMIT ?",
                    (queue_id, cursor, max_count)).fetchall()
                self._conn.execute("COMMIT")
            except BaseException:
                self._conn.execute("ROLLBACK")
                raise
        return [codec.deserialize(b) for (b,) in rows]

    # -- adapter contract ----------------------------------------------------

    async def queue_message(self, queue_id: int, msg: QueueMessage) -> None:
        await asyncio.to_thread(self._enqueue_many_sync, queue_id, [msg])

    async def queue_messages(self, queue_id: int,
                             msgs: List[QueueMessage]) -> None:
        """Batch enqueue: one transaction for the whole produce() call."""
        if msgs:
            await asyncio.to_thread(self._enqueue_many_sync, queue_id, msgs)

    def create_receiver(self, queue_id: int) -> "SqliteQueueReceiver":
        return SqliteQueueReceiver(self, queue_id)


class SqliteQueueReceiver(QueueAdapterReceiver):
    """(reference: AzureQueueAdapterReceiver — pull, then delete-on-ack)"""

    def __init__(self, adapter: SqliteQueueAdapter, queue_id: int) -> None:
        self.adapter = adapter
        self.queue_id = queue_id

    async def get_queue_messages(self, max_count: int) -> List[QueueMessage]:
        return await asyncio.to_thread(self.adapter._pull_sync,
                                       self.queue_id, max_count)

    async def ack(self, up_to_seq: int) -> None:
        """Durable delivery offset + trim past the retention window (the
        delete-after-processing of the reference's queue receipts)."""
        await asyncio.to_thread(self.adapter._ack_sync, self.queue_id,
                                up_to_seq)

    async def pull_and_ack(self, max_count: int,
                           ack_up_to: int) -> List[QueueMessage]:
        """Combined dequeue + previous-cycle ack: ONE sqlite write
        transaction per pull cycle (the pulling agent's batching path —
        ``ack_up_to < 0`` = nothing to ack yet)."""
        return await asyncio.to_thread(self.adapter._pull_ack_sync,
                                       self.queue_id, max_count,
                                       ack_up_to)

    async def read_from(self, seq: int,
                        max_count: int) -> List[QueueMessage]:
        def _read():
            with self.adapter._lock:
                rows = self.adapter._conn.execute(
                    "SELECT payload FROM stream_events WHERE queue_id=? "
                    "AND seq>=? AND seq<(SELECT cursor FROM stream_cursors"
                    " WHERE queue_id=?) ORDER BY seq LIMIT ?",
                    (self.queue_id, seq, self.queue_id,
                     max_count)).fetchall()
            return [codec.deserialize(b) for (b,) in rows]
        return await asyncio.to_thread(_read)
