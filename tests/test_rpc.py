"""Batched host RPC plane (orleans_tpu/runtime/rpc.py + the codec
fast path + the batched gateway ingress).

Covers the contracts the PR claims: per-sender FIFO across coalesced
windows, fastpath/fallback codec roundtrip equivalence against the
general token-stream codec, invoke-table invalidation on the
deactivation epoch, per-call TTL rebase inside one batched frame (the
near-deadline call still dead-letters on time), batched-vs-unbatched
reply bit-exactness, and the real multi-process smoke (client process →
TCP gateway → silo process).
"""

import asyncio
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import orleans_tpu.codec as codec_mod
from orleans_tpu.client import GrainClient
from orleans_tpu.codec import default_manager as codec
from orleans_tpu.core.grain import get_interface
from orleans_tpu.ids import GrainId
from orleans_tpu.runtime.rpc import _Call, RpcCoalescer
from orleans_tpu.runtime.runtime_client import (
    RejectionError,
    RequestTimeoutError,
)
from orleans_tpu.runtime.silo import Silo
from orleans_tpu.testing import TestingCluster

from samples.helloworld import IHello

from orleans_tpu import Grain, grain_interface
from orleans_tpu.core.grain import grain_class

pytestmark = pytest.mark.rpc

HELLO = "You said: '{0}', I say: Hello!"


@grain_interface
class IRpcRecorder:
    async def note(self, tag: str) -> str: ...
    async def note_b(self, tag: str) -> str: ...


@grain_class
class RpcRecorderGrain(Grain, IRpcRecorder):
    """Appends every invocation to a class-level log so tests can assert
    cross-window execution order."""

    log: list = []

    async def note(self, tag: str) -> str:
        RpcRecorderGrain.log.append(("note", int(self.grain_id.n1), tag))
        return tag

    async def note_b(self, tag: str) -> str:
        RpcRecorderGrain.log.append(("note_b", int(self.grain_id.n1), tag))
        return tag


@grain_interface
class IRpcEcho:
    async def echo(self, v) -> object: ...
    async def nested(self, key: int, tag: str) -> str: ...


@grain_class
class RpcEchoGrain(Grain, IRpcEcho):
    async def echo(self, v):
        return v

    async def nested(self, key: int, tag: str) -> str:
        # a nested grain call made from inside a fast turn: the ambient
        # runtime/context set by invoke_window must make this work
        other = self.get_grain(IHello, key)
        return await other.say_hello(tag)


async def _start_silo(name="rpc-test", **cfg_overrides):
    from orleans_tpu.config import SiloConfig
    config = SiloConfig(name=name)
    for k, v in cfg_overrides.items():
        setattr(config, k, v)
    silo = Silo(config=config)
    await silo.start()
    return silo


# ===========================================================================
# coalescer + invoke windows (in-process)
# ===========================================================================

def test_fastpath_exact_vs_per_message(run):
    """Batched and unbatched replies are bit-exact, and the batched
    plane actually engages (hits counted, windows > 0)."""

    async def main():
        silo = await _start_silo()
        try:
            factory = silo.attach_client()
            refs = [factory.get_grain(IHello, 21000 + i) for i in range(64)]
            batched = await asyncio.gather(
                *(r.say_hello(f"m{i % 7}") for i, r in enumerate(refs)))
            # second round is pure fastpath (warm activations)
            batched2 = await asyncio.gather(
                *(r.say_hello(f"m{i % 7}") for i, r in enumerate(refs)))
            assert silo.rpc.fastpath_hits > 0
            assert silo.rpc.windows_run > 0
            silo.update_config({"rpc": {"fastpath_enabled": False}})
            unbatched = await asyncio.gather(
                *(r.say_hello(f"m{i % 7}") for i, r in enumerate(refs)))
            assert batched == unbatched == batched2
            assert unbatched[3] == HELLO.format("m3")
        finally:
            await silo.stop(graceful=False)

    run(main())


def test_per_sender_fifo_across_windows(run):
    """A sender's calls execute in submission order even when they
    alternate between (type, method) windows — the window builder never
    lets a later call land in an earlier window."""

    async def main():
        silo = await _start_silo()
        try:
            factory = silo.attach_client()
            # warm both methods' activations + invoke tables
            r = factory.get_grain(IRpcRecorder, 22000)
            await r.note("warm")
            await r.note_b("warm")
            RpcRecorderGrain.log.clear()

            iface = get_interface(IRpcRecorder)
            note = iface.methods_by_name["note"]
            note_b = iface.methods_by_name["note_b"]
            coal: RpcCoalescer = silo.rpc
            loop = asyncio.get_running_loop()
            # two synthetic senders, interleaved methods: A:note, B:note,
            # A:note_b, B:note, A:note, B:note_b ... per-sender order
            # must survive the (type, method) grouping
            sender_a, sender_b = object(), object()
            gid = r.grain_id
            futs = []
            plan = [(sender_a, note, "a0"), (sender_b, note, "b0"),
                    (sender_a, note_b, "a1"), (sender_b, note, "b1"),
                    (sender_a, note, "a2"), (sender_b, note_b, "b2"),
                    (sender_a, note_b, "a3"), (sender_b, note, "b3")]
            for sender, minfo, tag in plan:
                fut = loop.create_future()
                futs.append(fut)
                coal.submit(_Call(gid, minfo, iface.interface_id, (tag,),
                                  fut, time.monotonic() + 30.0, sender))
            await asyncio.gather(*futs)
            seen = [(m, tag) for m, _k, tag in RpcRecorderGrain.log]
            order_a = [tag for _m, tag in seen if tag.startswith("a")]
            order_b = [tag for _m, tag in seen if tag.startswith("b")]
            assert order_a == ["a0", "a1", "a2", "a3"], seen
            assert order_b == ["b0", "b1", "b2", "b3"], seen
        finally:
            await silo.stop(graceful=False)

    run(main())


def test_invoke_table_invalidation_on_deactivation_epoch(run):
    """A deactivation bumps the catalog epoch and drops the cached
    per-key bindings; the next window re-resolves and must not touch
    the dead activation object."""

    async def main():
        silo = await _start_silo()
        try:
            factory = silo.attach_client()
            ref = factory.get_grain(IHello, 23000)
            await ref.say_hello("warm")
            await ref.say_hello("hot")  # cached fast turn
            entry = silo.dispatcher.invoke_table.resolve(
                ref.grain_id.type_code, "say_hello")
            assert ref.grain_id in entry.acts
            old_act = entry.acts[ref.grain_id][0]

            # deactivate → epoch bump
            silo.catalog.schedule_deactivation(old_act)
            await old_act.deactivation_task
            entry2 = silo.dispatcher.invoke_table.resolve(
                ref.grain_id.type_code, "say_hello")
            assert entry2 is entry
            assert ref.grain_id not in entry.acts  # cache dropped

            # the grain reactivates through the fallback and serves again
            assert await ref.say_hello("again") == HELLO.format("again")
            await ref.say_hello("cached")
            assert entry.acts[ref.grain_id][0] is not old_act
        finally:
            await silo.stop(graceful=False)

    run(main())


def test_window_expiry_dead_letters_per_call(run):
    """Per-call TTLs inside ONE coalesced window: the expired call
    dead-letters (reason expired) and answers an EXPIRED rejection
    while its window-mates succeed."""

    async def main():
        silo = await _start_silo()
        try:
            factory = silo.attach_client()
            ref = factory.get_grain(IHello, 23500)
            await ref.say_hello("warm")
            iface = get_interface(IHello)
            minfo = iface.methods_by_name["say_hello"]
            loop = asyncio.get_running_loop()
            ok_fut, dead_fut = loop.create_future(), loop.create_future()
            now = time.monotonic()
            silo.rpc.submit(_Call(ref.grain_id, minfo, iface.interface_id,
                                  ("live",), ok_fut, now + 30.0, None))
            silo.rpc.submit(_Call(ref.grain_id, minfo, iface.interface_id,
                                  ("dead",), dead_fut, now - 0.001, None))
            assert await ok_fut == HELLO.format("live")
            with pytest.raises(RejectionError) as exc:
                await dead_fut
            assert "EXPIRED" in str(exc.value)
            assert silo.rpc.expired == 1
            reasons = [e["reason"] for e in silo.dead_letters.entries]
            assert "expired" in reasons
        finally:
            await silo.stop(graceful=False)

    run(main())


def test_fastpath_error_and_one_way(run):
    """User faults flow to the caller exactly like invoke(); one-way
    calls ride the window without a future."""
    from tests.fixture_grains import IFailingGrain

    async def main():
        silo = await _start_silo()
        try:
            factory = silo.attach_client()
            bad = factory.get_grain(IFailingGrain, 23700)
            assert await bad.ok() == "fine"
            with pytest.raises(ValueError, match="kaboom"):
                await bad.boom()  # warm → this is a window turn
            with pytest.raises(ValueError, match="kaboom"):
                await bad.boom()
            assert silo.metrics.turns_faulted >= 1
        finally:
            await silo.stop(graceful=False)

    run(main())


def test_fastpath_nested_call_context(run):
    """A fast turn that makes a nested grain call: invoke_window's
    ambient runtime/activation context must route it correctly."""

    async def main():
        silo = await _start_silo()
        try:
            factory = silo.attach_client()
            echo = factory.get_grain(IRpcEcho, 23800)
            await echo.echo(1)  # warm
            got = await echo.nested(23801, "deep")
            assert got == HELLO.format("deep")
            got = await echo.nested(23801, "deep2")  # both warm now
            assert got == HELLO.format("deep2")
        finally:
            await silo.stop(graceful=False)

    run(main())


def test_busy_activation_falls_back_to_mailbox(run):
    """A call to an activation with a turn in flight hands back to the
    per-message mailbox — ordering stays with the admission gate."""

    async def main():
        silo = await _start_silo()
        try:
            factory = silo.attach_client()
            ref = factory.get_grain(IRpcRecorder, 23900)
            await ref.note("warm")       # cold: fallback, activates
            await ref.note("warm2")      # warm: fast turn, caches
            act = silo.dispatcher.invoke_table.resolve(
                ref.grain_id.type_code, "note").acts[ref.grain_id][0]
            # occupy the gate like a running turn
            token = object()
            act.running[id(token)] = token
            before = silo.rpc.fastpath_fallbacks
            fut = ref.note("queued")
            await asyncio.sleep(0.05)
            assert not fut.done()  # parked behind the fake turn
            assert silo.rpc.fastpath_fallbacks > before
            act.running.pop(id(token))
            act._pump()
            assert await fut == "queued"
        finally:
            await silo.stop(graceful=False)

    run(main())


def test_rpc_metrics_published_strict(run):
    """The rpc.* names publish through the strict catalog-checked
    registry and the coalescer's snapshot shape holds."""

    async def main():
        silo = await _start_silo()
        try:
            factory = silo.attach_client()
            refs = [factory.get_grain(IHello, 24000 + i) for i in range(16)]
            await asyncio.gather(*(r.say_hello("a") for r in refs))
            await asyncio.gather(*(r.say_hello("b") for r in refs))
            snap = silo.collect_metrics()
            counters = snap["counters"]
            assert counters["rpc.fastpath_hits"][""] > 0
            assert counters["rpc.windows"][""] > 0
            gauges = snap["gauges"]
            assert "rpc.ingress_batch_size" in gauges
            assert "rpc.coalesce_wait_s" in gauges
        finally:
            await silo.stop(graceful=False)

    run(main())


# ===========================================================================
# codec fast path
# ===========================================================================

VALUE_ZOO = [
    None, True, False, 0, 1, -1, 2 ** 40, -(2 ** 40), 0.0, 3.25, -1e300,
    "", "hello", "ünïcode-✓", b"", b"\x00\xff raw",
    np.arange(12, dtype=np.int32).reshape(3, 4),
    np.array([], dtype=np.float64),
    np.array(7, dtype=np.uint8),
    np.linspace(0, 1, 5, dtype=np.float32),
    # general-codec fallback values (mutable containers, identity types)
    [1, "two", 3.0], {"k": [1, 2]}, (1, (2, 3)),
    GrainId.from_int(4242, 7),
]


def _eq(a, b):
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (isinstance(a, np.ndarray) and isinstance(b, np.ndarray)
                and a.dtype == b.dtype and a.shape == b.shape
                and np.array_equal(a, b))
    return a == b and type(a) is type(b)


def test_rpc_codec_roundtrip_equivalence_property():
    """Property: every value zoo member round-trips through the rpc
    fast-path frame IDENTICALLY to the general token-stream codec —
    per-call args, common args, and result frames."""
    rng = np.random.default_rng(7)
    for trial in range(24):
        k = int(rng.integers(1, 6))
        idx = rng.integers(0, len(VALUE_ZOO), size=(4, k))
        args_list = [tuple(VALUE_ZOO[j] for j in row) for row in idx]
        keys = np.arange(4, dtype=np.uint64) + trial
        ttls = rng.uniform(0.01, 30.0, size=4)
        segments = codec_mod.encode_rpc_calls(
            codec, rpc_id=3, batch_id=trial + 1, keys=keys, ttls=ttls,
            args_list=args_list)
        payload = b"".join(bytes(memoryview(s).cast("B"))
                           for s in segments)
        frame = codec_mod.decode_rpc_frame(codec, payload)
        assert frame.kind == codec_mod.RPC_KIND_CALLS
        assert frame.n == 4 and frame.rpc_id == 3
        assert np.array_equal(frame.keys, keys)
        assert np.allclose(frame.ttls, ttls)
        for got, want in zip(frame.args_list, args_list):
            general = codec.deserialize(codec.serialize(list(want)))
            assert len(got) == len(want) == len(general)
            for g, w, gen in zip(got, want, general):
                assert _eq(g, w), (g, w)
                # equivalence vs the general codec's roundtrip
                if not isinstance(w, np.ndarray):
                    assert _eq(g, gen) or isinstance(w, tuple), (g, gen)


def test_rpc_codec_common_args_and_results():
    keys = np.array([5, 6, 7], dtype=np.uint64)
    arr = np.arange(6, dtype=np.float32)
    segments = codec_mod.encode_rpc_calls(
        codec, rpc_id=1, batch_id=9, keys=keys, ttls=None,
        args_list=None, common_args=("shared", 42, arr))
    frame = codec_mod.decode_rpc_frame(
        codec, b"".join(bytes(memoryview(s).cast("B")) for s in segments))
    assert frame.common_args[0] == "shared"
    assert frame.common_args[1] == 42
    assert np.array_equal(frame.common_args[2], arr)
    assert not frame.common_args[2].flags.writeable  # zero-copy view

    statuses = np.array([0, 1, 0], dtype=np.uint8)
    values = ["ok", ValueError("boom"), "ok2"]
    segments = codec_mod.encode_rpc_results(codec, 9, statuses, values)
    frame = codec_mod.decode_rpc_frame(
        codec, b"".join(bytes(memoryview(s).cast("B")) for s in segments))
    assert frame.kind == codec_mod.RPC_KIND_RESULTS
    assert np.array_equal(frame.statuses, statuses)
    assert frame.values[0] == "ok"
    assert isinstance(frame.values[1], ValueError)
    # common-value results frame
    segments = codec_mod.encode_rpc_results(
        codec, 10, np.zeros(4, np.uint8), None,
        common_value="same", common=True)
    frame = codec_mod.decode_rpc_frame(
        codec, b"".join(bytes(memoryview(s).cast("B")) for s in segments))
    assert frame.values is None and frame.common_value == "same"


def test_rpc_codec_rejects_malformation():
    keys = np.array([1], dtype=np.uint64)
    segments = codec_mod.encode_rpc_calls(
        codec, 1, 1, keys, None, [("x",)])
    payload = b"".join(bytes(memoryview(s).cast("B")) for s in segments)
    with pytest.raises(codec_mod.SerializationError):
        codec_mod.decode_rpc_frame(codec, payload[:-3])  # truncated
    with pytest.raises(codec_mod.SerializationError):
        codec_mod.decode_rpc_frame(codec, payload + b"xx")  # trailing
    with pytest.raises(codec_mod.SerializationError):
        codec_mod.decode_rpc_frame(codec, b"\x07garbage")


# ===========================================================================
# TCP gateway: batched frames end to end
# ===========================================================================

def test_tcp_batched_rpc_roundtrip_and_fallback_equivalence(run):
    """Batched calls over a real socket: exact replies, negotiated
    dictionary reuse, and bit-equality with a per-message client."""

    async def main():
        cluster = await TestingCluster(n_silos=1, transport="tcp").start()
        try:
            silo = cluster.silos[0]
            assert silo.gateway_port > 0
            from orleans_tpu.core.reference import bind_runtime
            fast = await GrainClient(trace_sample_rate=0.0).connect(
                (silo.address.host, silo.gateway_port))
            slow = await GrainClient(trace_sample_rate=0.0,
                                     rpc_fastpath=False).connect(
                (silo.address.host, silo.gateway_port))
            try:
                refs_f = [fast.get_grain(IHello, 25000 + i)
                          for i in range(24)]
                refs_s = [slow.get_grain(IHello, 25000 + i)
                          for i in range(24)]
                # references resolve the AMBIENT runtime — re-bind per
                # client (connect() bound `slow` last)
                bind_runtime(fast)
                a = await asyncio.gather(
                    *(r.say_hello(f"x{i}") for i, r in enumerate(refs_f)))
                bind_runtime(slow)
                b = await asyncio.gather(
                    *(r.say_hello(f"x{i}") for i, r in enumerate(refs_s)))
                assert a == b
                # steady state again → windows engaged
                bind_runtime(fast)
                a2 = await asyncio.gather(
                    *(r.say_hello(f"x{i}") for i, r in enumerate(refs_f)))
                assert a2 == a
                assert silo.rpc.fastpath_hits > 0
                # error propagation through the results frame
                from tests.fixture_grains import IFailingGrain
                bad = fast.get_grain(IFailingGrain, 25100)
                assert await bad.ok() == "fine"
                with pytest.raises(ValueError, match="kaboom"):
                    await bad.boom()
            finally:
                await fast.close()
                await slow.close()
        finally:
            await cluster.stop()

    run(main())


def test_tcp_frame_ttl_rebase_per_call(run):
    """REGRESSION (the frame-level rebase bug class): two calls in ONE
    batched frame with different TTLs — the near-deadline one still
    dead-letters on time at the silo while its frame-mate succeeds."""

    async def main():
        cluster = await TestingCluster(n_silos=1, transport="tcp").start()
        try:
            silo = cluster.silos[0]
            client = await GrainClient(trace_sample_rate=0.0).connect(
                (silo.address.host, silo.gateway_port))
            try:
                iface = get_interface(IHello)
                minfo = iface.methods_by_name["say_hello"]
                live = client.get_grain(IHello, 25200)
                await live.say_hello("warm")
                # ONE flush → one frame carrying both TTLs
                f_live = client.send_request(live.grain_id, iface, minfo,
                                             ("ok",), timeout=30.0)
                f_dead = client.send_request(live.grain_id, iface, minfo,
                                             ("late",), timeout=0.0)
                assert await f_live == HELLO.format("ok")
                with pytest.raises((RejectionError,
                                    RequestTimeoutError)):
                    await f_dead
                # the SILO dead-lettered the expired call (per-call
                # rebase — a frame-level rebase would have given it the
                # 30s deadline and executed it)
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if silo.rpc.expired >= 1:
                        break
                    await asyncio.sleep(0.01)
                assert silo.rpc.expired >= 1
                reasons = [e["reason"]
                           for e in silo.dead_letters.entries]
                assert "expired" in reasons
            finally:
                await client.close()
        finally:
            await cluster.stop()

    run(main())


def test_gateway_serves_batched_frames_with_fastpath_disabled(run):
    """A silo with the coalescer live-disabled still answers batched
    client frames (per-call fallback through the per-message pipeline)."""

    async def main():
        cluster = await TestingCluster(n_silos=1, transport="tcp").start()
        try:
            silo = cluster.silos[0]
            silo.update_config({"rpc": {"fastpath_enabled": False}})
            client = await GrainClient(trace_sample_rate=0.0).connect(
                (silo.address.host, silo.gateway_port))
            try:
                refs = [client.get_grain(IHello, 25300 + i)
                        for i in range(8)]
                out = await asyncio.gather(
                    *(r.say_hello("off") for r in refs))
                assert out == [HELLO.format("off")] * 8
                assert silo.rpc.fastpath_hits == 0
                assert silo.rpc.fastpath_fallbacks >= 8
            finally:
                await client.close()
        finally:
            await cluster.stop()

    run(main())


def test_tcp_ndarray_args_zero_copy(run):
    """ndarray args ride the frame as raw segments and arrive exact
    (read-only zero-copy views on the silo side)."""

    async def main():
        cluster = await TestingCluster(n_silos=1, transport="tcp").start()
        try:
            silo = cluster.silos[0]
            client = await GrainClient(trace_sample_rate=0.0).connect(
                (silo.address.host, silo.gateway_port))
            try:
                echo = client.get_grain(IRpcEcho, 25400)
                await echo.echo(0)  # warm
                arr = np.arange(1024, dtype=np.float32).reshape(32, 32)
                got = await echo.echo(arr)
                assert isinstance(got, np.ndarray)
                assert got.dtype == arr.dtype and np.array_equal(got, arr)
            finally:
                await client.close()
        finally:
            await cluster.stop()

    run(main())


def test_tcp_mixed_args_and_results_never_collapse(run):
    """REGRESSION (review findings): (a) a flush mixing scalar and
    ndarray args for one (type, method) must not crash the common-args
    compare (ndarray == scalar raises elementwise out of the flush
    callback, stranding every future); (b) a window of mixed-type or
    bool/int replies must come back TYPE-exact — 1, True and 1.0 never
    collapse into one shared value."""

    async def main():
        cluster = await TestingCluster(n_silos=1, transport="tcp").start()
        try:
            silo = cluster.silos[0]
            client = await GrainClient(trace_sample_rate=0.0).connect(
                (silo.address.host, silo.gateway_port))
            try:
                e0 = client.get_grain(IRpcEcho, 26000)
                e1 = client.get_grain(IRpcEcho, 26001)
                e2 = client.get_grain(IRpcEcho, 26002)
                await asyncio.gather(e0.echo(0), e1.echo(0), e2.echo(0))
                # (a) scalar + ndarray args in ONE loop iteration
                arr = np.arange(4, dtype=np.int32)
                a, b = await asyncio.gather(e0.echo(1), e1.echo(arr))
                assert a == 1 and type(a) is int
                assert isinstance(b, np.ndarray) \
                    and np.array_equal(b, arr)
                # (b) bool/int/float replies stay type-exact in one
                # window (value-equality collapse would conflate them)
                r = await asyncio.gather(e0.echo(1), e1.echo(True),
                                         e2.echo(1.0))
                assert r == [1, True, 1.0]
                assert [type(v) for v in r] == [int, bool, float]
            finally:
                await client.close()
        finally:
            await cluster.stop()

    run(main())


def test_coalescer_snapshot_is_pure(run):
    """snapshot() is a pure read shareable by bench/tests/debug dumps;
    only collect_interval() (owned by silo.collect_metrics) advances
    the interval baseline."""

    async def main():
        silo = await _start_silo()
        try:
            factory = silo.attach_client()
            refs = [factory.get_grain(IHello, 26100 + i)
                    for i in range(16)]
            await asyncio.gather(*(r.say_hello("a") for r in refs))
            await asyncio.gather(*(r.say_hello("b") for r in refs))
            s1 = silo.rpc.snapshot()
            silo.collect_metrics()  # interval read happens in here
            s2 = silo.rpc.snapshot()
            assert s1["ingress_batch_size"] == s2["ingress_batch_size"]
            assert s1["ingress_batch_size"] > 0
            # a second interval read with no new windows reads 0
            assert silo.rpc.collect_interval()["ingress_batch_size"] \
                == 0.0
        finally:
            await silo.stop(graceful=False)

    run(main())


# ===========================================================================
# multi-process proof: client process → TCP gateway → silo process
# ===========================================================================

def _spawn(args, **kw):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "orleans_tpu.runtime.rpc", *args],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, env=env, cwd=repo, **kw)


def test_multiprocess_smoke():
    """Real processes, real sockets: one silo SERVER process, one client
    DRIVER process, exact reply values asserted in the driver.  Needs
    only subprocess spawn + loopback TCP (no jax.distributed) — skips
    cleanly where either is unavailable rather than erroring."""
    if not os.path.exists(sys.executable):
        pytest.skip("no python executable for subprocess workers")
    import selectors
    server = _spawn(["serve", "--name", "mp-silo"])
    try:
        # bounded banner wait: a hung server must fail THIS test, not
        # idle out the whole tier's timeout
        sel = selectors.DefaultSelector()
        sel.register(server.stdout, selectors.EVENT_READ)
        ready = sel.select(timeout=120)
        sel.close()
        if not ready:
            server.kill()
            raise AssertionError("silo server produced no banner in 120s")
        line = server.stdout.readline()
        if not line:
            err = server.stderr.read().decode(errors="replace")[-2000:]
            if server.poll() is not None:
                pytest.skip(f"silo server process could not start "
                            f"(sandboxed environment?): {err}")
            raise AssertionError(f"no server banner: {err}")
        banner = json.loads(line)
        assert banner.get("ok") and banner["gateway_port"] > 0
        driver = _spawn(["drive",
                         "--gateways",
                         f"127.0.0.1:{banner['gateway_port']}",
                         "--grains", "64", "--rounds", "3"])
        try:
            out, err = driver.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            driver.kill()
            raise
        assert driver.returncode == 0, err.decode(errors="replace")[-2000:]
        result = json.loads(out.splitlines()[-1])
        assert result["ok"] and result["exact"]
        assert result["calls"] == 64 * 3
        assert result["rpc_per_sec"] > 0
    finally:
        if server.poll() is None:
            server.stdin.close()  # EOF → clean server shutdown
            try:
                server.wait(timeout=15)
            except subprocess.TimeoutExpired:
                server.kill()
