"""Admin surface: SiloControl, ManagementGrain fan-out, watchdog, CLI.

Reference analogs: ManagementGrain.cs:38 / SiloControl.cs:33 /
Watchdog.cs:32 / OrleansManager Program.cs.
"""

import asyncio

import numpy as np

from orleans_tpu.core.grain import grain_id_for
from orleans_tpu.runtime.management import IManagementGrain
from orleans_tpu.testing import TestingCluster

from tests.fixture_grains import ICounterGrain


def test_management_grain_fanout(run):
    """hosts/stats/grainstats/activations aggregate over every silo."""

    async def main():
        cluster = await TestingCluster(n_silos=3).start()
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(0)
            refs = [factory.get_grain(ICounterGrain, 5000 + i)
                    for i in range(15)]
            await asyncio.gather(*(r.add(1) for r in refs))

            mgmt = factory.get_grain(IManagementGrain, 0)
            hosts = await mgmt.get_hosts()
            assert len(hosts) == 3
            assert all(v == "ACTIVE" for v in hosts.values())

            total = await mgmt.get_total_activation_count()
            # 15 counters + the management grain itself
            assert total >= 16, total

            stats = await mgmt.get_simple_grain_statistics()
            counter_total = sum(s.activation_count for s in stats
                                if s.grain_type == "CounterGrain")
            assert counter_total == 15, stats

            runtime_stats = await mgmt.get_runtime_statistics()
            assert len(runtime_stats) == 3
            assert sum(s.activation_count for s in runtime_stats) >= 16
        finally:
            await cluster.stop()

    run(main())


def test_management_lookup_and_unregister(run):
    """Directory repair path (reference: OrleansManager unregister)."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(0)
            ref = factory.get_grain(ICounterGrain, 5555)
            await ref.add(1)
            mgmt = factory.get_grain(IManagementGrain, 0)

            gid = grain_id_for(ICounterGrain, 5555)
            found = await mgmt.lookup(gid)
            assert found is not None and "silo" in found, found

            assert await mgmt.unregister(gid) is True
            # the directory entry is actually gone
            assert await mgmt.lookup(gid) is None
            # and a fresh call re-activates cleanly
            assert await ref.add(1) >= 1
        finally:
            await cluster.stop()

    run(main())


def test_silo_control_forced_collection(run):
    """force_activation_collection(0) deactivates idle activations
    cluster-wide (reference: ForceActivationCollection)."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(0)
            refs = [factory.get_grain(ICounterGrain, 5600 + i)
                    for i in range(10)]
            await asyncio.gather(*(r.add(1) for r in refs))
            before = cluster.total_activations()
            assert before >= 10

            mgmt = factory.get_grain(IManagementGrain, 0)
            collected = await mgmt.force_activation_collection(0.0)
            assert collected >= 10
            # deactivations are scheduled; let them run
            await asyncio.sleep(0.1)
            assert cluster.total_activations() < before
        finally:
            await cluster.stop()

    run(main())


def test_silo_control_tensor_stats_and_collection(run):
    """The admin surface covers the tensor plane too."""

    async def main():
        from orleans_tpu.runtime.silo import Silo
        from samples.presence import PresenceGrain  # registers vector type

        silo = Silo(name="mgmt-tensor")
        await silo.start()
        try:
            engine = silo.tensor_engine
            engine.send_batch("PresenceGrain", "heartbeat",
                              np.arange(20, dtype=np.int64),
                              {"game": np.zeros(20, np.int32),
                               "score": np.ones(20, np.float32),
                               "tick": np.full(20, 1, np.int32)})
            await engine.flush()

            control = silo.system_targets["silo_control"]
            stats = await control.get_simple_grain_statistics()
            tensor_rows = {s.grain_type: s.activation_count
                           for s in stats if s.plane == "tensor"}
            assert tensor_rows.get("PresenceGrain") == 20, stats

            # idle_ticks=0 collects rows idle since before the current
            # tick (rows touched AT the current tick survive the sweep)
            collected = await control.force_tensor_collection(0)
            assert collected >= 20, collected
        finally:
            await silo.stop()

    run(main())


def test_watchdog_detects_dead_participant(run):
    async def main():
        from orleans_tpu.config import SiloConfig
        from orleans_tpu.runtime.silo import Silo

        cfg = SiloConfig(name="watchdog-test")
        cfg.watchdog_period = 0.05
        silo = Silo(config=cfg)
        await silo.start()
        try:
            wd = silo.watchdog
            assert wd is not None and wd._running

            class Sick:
                def check_health(self):
                    return False

            class Throwing:
                def check_health(self):
                    raise RuntimeError("boom")

            wd.register(Sick())
            wd.register(Throwing())
            failures = wd.check_participants()
            assert failures == 2
            # healthy built-ins don't fail: re-check only them
            wd.participants = [p for p in wd.participants
                               if not isinstance(p, (Sick, Throwing))]
            assert wd.check_participants() == 0
        finally:
            await silo.stop()

    run(main())


def test_watchdog_detects_loop_stall(run):
    async def main():
        from orleans_tpu.config import SiloConfig
        from orleans_tpu.runtime.silo import Silo
        import time

        cfg = SiloConfig(name="stall-test")
        cfg.watchdog_period = 0.05
        silo = Silo(config=cfg)
        await silo.start()
        try:
            wd = silo.watchdog
            wd.stall_threshold = 0.1
            await asyncio.sleep(0.1)   # let the loop settle into a sleep
            time.sleep(0.4)            # synchronously hog the event loop
            await asyncio.sleep(0.15)  # watchdog wakes late, records stall
            assert wd.loop_stalls >= 1
        finally:
            await silo.stop()

    run(main())


def test_manager_cli_commands(run, tmp_path, capsys):
    """The CLI joins via the shared membership table, runs commands
    through the management grain, and leaves (reference: OrleansManager)."""

    async def main():
        from orleans_tpu.host import build_silo
        from orleans_tpu.manager import run_command

        db = str(tmp_path / "cli-cluster.db")
        cfg = {"host": "127.0.0.1", "membership_db": db,
               "storage": {"Default": {"kind": "memory"}},
               "silo": {"liveness": {
                   "probe_period": 0.1, "probe_timeout": 0.1,
                   "num_missed_probes_limit": 2,
                   "table_refresh_timeout": 0.2,
                   "iam_alive_table_publish": 0.5}}}
        silo = build_silo({**cfg, "name": "cli-host"})
        await silo.start()
        try:
            factory = silo.attach_client()
            await asyncio.gather(*(factory.get_grain(ICounterGrain,
                                                     5700 + i).add(1)
                                   for i in range(5)))
            hosts = await run_command(cfg, "hosts", [])
            assert any("ACTIVE" == v for v in hosts.values())
            total = await run_command(cfg, "activations", [])
            assert total >= 5
            stats = await run_command(cfg, "grainstats", [])
            assert any("CounterGrain" in line for line in stats)
        finally:
            await silo.stop()

    run(main())


def test_non_hosting_member_gets_no_placements(run, tmp_path):
    """A host_grains=False member (the CLI's mode) joins membership but
    never receives grain placements and takes no ring ranges."""

    async def main():
        from orleans_tpu.host import build_silo

        db = str(tmp_path / "observer-cluster.db")
        cfg = {"host": "127.0.0.1", "membership_db": db,
               "storage": {"Default": {"kind": "memory"}},
               "silo": {"liveness": {
                   "probe_period": 0.1, "probe_timeout": 0.1,
                   "num_missed_probes_limit": 2,
                   "table_refresh_timeout": 0.2,
                   "iam_alive_table_publish": 0.5}}}
        host = build_silo({**cfg, "name": "real-host"})
        observer_cfg = {**cfg, "name": "observer",
                        "silo": {**cfg["silo"], "host_grains": False,
                                 "gateway_enabled": False,
                                 "reminders": {"enabled": False},
                                 "tensor": {"enabled": False}}}
        observer = build_silo(observer_cfg)
        await host.start()
        await observer.start()
        try:
            deadline = asyncio.get_running_loop().time() + 10
            while not (len(host.active_silos()) == 2
                       and len(observer.active_silos()) == 2):
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)

            # placement-eligible set excludes the observer everywhere
            assert host.hosting_silos() == [host.address]
            assert observer.hosting_silos() == [host.address]
            # the observer never joined the real host's ring
            assert observer.address not in host.ring.members

            # activations driven from the observer all land on the host
            factory = observer.attach_client()
            refs = [factory.get_grain(ICounterGrain, 5800 + i)
                    for i in range(8)]
            await asyncio.gather(*(r.add(1) for r in refs))
            assert len(observer.catalog.directory) == 0
            assert len(host.catalog.directory) >= 8
        finally:
            await observer.stop()
            await host.stop()

    run(main())


def test_tensor_statistics_fanout(run):
    """Tick-engine counters (throughput, true latency percentiles, arena
    sizes) flow through the management surface."""

    def patient_liveness(name):
        # the presence load's jit compiles stall the event loop for
        # longer than the default test liveness budget (probe 0.1s × 2
        # missed) — under file-level cache timing both silos could vote
        # each other DEAD mid-test and the fan-out read an empty
        # membership view.  This test is about the management surface,
        # not liveness: give probes compile-sized patience.
        cfg = TestingCluster._default_config(name)
        cfg.liveness.probe_period = 1.0
        cfg.liveness.probe_timeout = 2.0
        cfg.liveness.num_missed_probes_limit = 5
        return cfg

    async def main():
        cluster = await TestingCluster(
            n_silos=2, config_factory=patient_liveness).start()
        try:
            await cluster.wait_for_liveness_convergence()
            # put some tensor traffic on silo 0's engine
            from samples.presence import run_presence_load
            await run_presence_load(cluster.silos[0].tensor_engine,
                                    n_players=300, n_games=3, n_ticks=3)

            factory = cluster.attach_client(0)
            mgmt = factory.get_grain(IManagementGrain, 0)
            stats = await mgmt.get_tensor_statistics()
            assert len(stats) >= 1
            # the vector router splits the load by ring owner, so the
            # cluster-wide totals (what the admin surface is for) carry
            # the traffic, spread over the member silos
            assert sum(s["messages"] for s in stats) >= 2 * 300 * 3
            busy = max(stats, key=lambda s: s["messages"])
            lat = busy["tick_latency"]
            assert lat["n"] > 0 and lat["p99"] >= lat["p50"] > 0
            assert sum(s["arenas"].get("PresenceGrain", 0)
                       for s in stats) == 300
        finally:
            await cluster.stop()

    run(main())
