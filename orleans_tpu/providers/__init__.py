"""Storage provider implementations (reference: src/OrleansProviders/ +
per-backend utils projects)."""

from orleans_tpu.providers.file_storage import FileStorage
from orleans_tpu.providers.memory_storage import (
    ErrorInjectionStorage,
    MemoryStorage,
    MemoryStorageWithLatency,
)
from orleans_tpu.providers.sharded_storage import ShardedStorageProvider
from orleans_tpu.providers.sqlite_storage import SqliteStorage

__all__ = [
    "ErrorInjectionStorage",
    "FileStorage",
    "MemoryStorage",
    "MemoryStorageWithLatency",
    "ShardedStorageProvider",
    "SqliteStorage",
]
