"""Tensor data-plane tests: arenas, batched dispatch, emits, proxy interop.

Reference analog: there is no reference analog — this is the rebuild's
batched replacement for Dispatcher/Scheduler hot-path behavior, tested for
the same *semantic* guarantees (per-grain fan-in equals sequential mailbox
drain for commutative updates; auto-activation on first message).
"""

import asyncio

import jax.numpy as jnp
import numpy as np

from orleans_tpu.core.grain import batched_method
from orleans_tpu.tensor import (
    Batch,
    TensorEngine,
    VectorGrain,
    field,
    seg_sum,
    vector_grain,
)
from orleans_tpu.tensor.arena import GrainArena
from orleans_tpu.tensor.vector_grain import scatter_add_rows, vector_type

from samples.presence import GameGrain, PresenceGrain, run_presence_load


@vector_grain
class AccumGrain(VectorGrain):
    total = field(jnp.float32, 0.0)
    count = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def add(state, batch: Batch, n_rows: int):
        state = {
            **state,
            "total": state["total"] + seg_sum(batch.args["v"], batch.rows,
                                              n_rows),
            "count": state["count"] + seg_sum(
                jnp.ones_like(batch.rows, dtype=jnp.int32) * batch.mask,
                batch.rows, n_rows),
        }
        results = {"echo": batch.args["v"] * 2}
        return state, results, ()


def test_arena_resolve_and_autoactivate():
    engine = TensorEngine()
    arena = engine.arena_for("AccumGrain")
    keys = np.array([5, 7, 5, 9], dtype=np.int64)
    rows = arena.resolve_rows(keys)
    assert rows[0] == rows[2] and rows[0] != rows[1]
    assert arena.live_count == 3
    # stable across calls
    rows2 = arena.resolve_rows(keys)
    np.testing.assert_array_equal(rows, rows2)


def test_arena_growth_preserves_state(run):
    async def main():
        engine = TensorEngine(initial_capacity=8)
        engine.send_batch("AccumGrain", "add", np.array([1]),
                          {"v": np.array([10.0], np.float32)})
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        # force several growths
        arena.resolve_rows(np.arange(100, 200, dtype=np.int64))
        row = arena.read_row(1)
        assert row is not None and float(row["total"]) == 10.0

    run(main())


def test_batched_fan_in_matches_sequential(run):
    async def main():
        engine = TensorEngine()
        keys = np.array([1, 2, 1, 1, 2], dtype=np.int64)
        vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0], dtype=np.float32)
        fut = engine.send_batch("AccumGrain", "add", keys, {"v": vals},
                                want_results=True)
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        assert float(arena.read_row(1)["total"]) == 8.0   # 1+3+4
        assert float(arena.read_row(2)["total"]) == 7.0   # 2+5
        assert int(arena.read_row(1)["count"]) == 3
        res = fut.result()
        np.testing.assert_allclose(res["echo"], vals * 2)

    run(main())


def test_bucket_padding_does_not_corrupt(run):
    async def main():
        engine = TensorEngine()
        # 3 messages → padded to bucket 256; pads must not touch row 0
        keys = np.array([3, 4, 5], dtype=np.int64)
        engine.send_batch("AccumGrain", "add", keys,
                          {"v": np.ones(3, np.float32)})
        await engine.flush()
        arena = engine.arena_for("AccumGrain")
        for k in (3, 4, 5):
            assert float(arena.read_row(k)["total"]) == 1.0
            assert int(arena.read_row(k)["count"]) == 1

    run(main())


def test_presence_emit_chain(run):
    async def main():
        engine = TensorEngine()
        n_players, n_games = 1000, 10
        stats = await run_presence_load(engine, n_players=n_players,
                                        n_games=n_games, n_ticks=3)
        assert stats["messages"] == 2 * n_players * 3
        game_arena = engine.arena_for("GameGrain")
        assert game_arena.live_count == n_games
        total_updates = sum(
            int(game_arena.read_row(g)["updates"]) for g in range(n_games))
        assert total_updates == n_players * 3
        presence = engine.arena_for("PresenceGrain")
        assert presence.live_count == n_players
        assert int(presence.read_row(0)["heartbeats"]) == 3

    run(main())


def test_proxy_call_routes_to_engine(run):
    """Vector grains remain callable through normal grain references."""

    async def main():
        from orleans_tpu.runtime.silo import Silo

        silo = Silo(name="tensor-proxy")
        await silo.start()
        try:
            factory = silo.attach_client()
            ref = factory.get_grain("AccumGrain", 77)
            res = await ref.add({"v": np.float32(21.0)})
            assert float(res["echo"]) == 42.0
            arena = silo.tensor_engine.arena_for("AccumGrain")
            assert float(arena.read_row(77)["total"]) == 21.0
        finally:
            await silo.stop()

    run(main())


def test_multi_round_tick_caps_and_spills(run):
    """Emit chains longer than max_rounds_per_tick spill to the next tick
    (the analog of MaxForwardCount bounding intra-tick chains)."""

    async def main():
        engine = TensorEngine()
        engine.config.max_rounds_per_tick = 2
        n = 100
        stats = await run_presence_load(engine, n_players=n, n_games=2,
                                        n_ticks=1)
        # heartbeat round + game round both fit in one tick here
        assert engine.rounds_run >= 2
        assert stats["messages"] == 2 * n

    run(main())


def test_latency_stats_are_true_percentiles(run):
    """snapshot()['tick_latency'] reports real percentiles over per-tick
    durations, not a mean (VERDICT r1: the published p99 was a mean)."""

    async def main():
        engine = TensorEngine()
        stats = await run_presence_load(engine, n_players=500, n_games=5,
                                        n_ticks=8, measure_latency=True)
        assert "tick_p99_seconds" in stats
        assert stats["tick_p99_seconds"] >= stats["tick_p50_seconds"] > 0
        lat = engine.latency_stats()
        assert lat["n"] >= 8
        assert lat["max"] >= lat["p99"] >= lat["p50"] > 0
        assert lat["p99"] <= lat["max"]

    run(main())


def test_adaptive_tick_interval_controller():
    """With a latency budget set, overruns shrink the accumulation interval
    multiplicatively and headroom grows it back, clamped to the bounds
    (SURVEY §7 hard-part 5: adaptive tick sizing)."""
    engine = TensorEngine()
    cfg = engine.config
    cfg.target_tick_latency = 0.010
    cfg.tick_interval_min = 0.0002
    cfg.tick_interval_max = 0.05
    engine._adaptive_interval = 0.004

    # tick far over budget: interval halves
    engine._adapt(tick_duration=0.050)
    assert engine._adaptive_interval == 0.002
    # repeated overruns clamp at the floor
    for _ in range(20):
        engine._adapt(tick_duration=0.050)
    assert engine._adaptive_interval == cfg.tick_interval_min
    assert engine.tick_interval() == cfg.tick_interval_min

    # fast ticks: interval recovers but never exceeds half the headroom
    for _ in range(200):
        engine._adapt(tick_duration=0.001)
    assert engine._adaptive_interval <= (cfg.target_tick_latency - 0.001) / 2
    assert engine._adaptive_interval > cfg.tick_interval_min

    # no budget -> fixed interval
    cfg.target_tick_latency = 0.0
    assert engine.tick_interval() == cfg.tick_interval


def test_turn_observer_tolerates_cancellation(run):
    """Non-graceful stop cancels in-flight turns; the done-callback must
    not re-raise CancelledError (VERDICT r1: bench teardown spewed
    unhandled CancelledError tracebacks)."""

    async def main():
        from orleans_tpu.runtime.activation import _observe_turn

        async def hang():
            await asyncio.sleep(30)

        task = asyncio.get_running_loop().create_task(hang())
        await asyncio.sleep(0)
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        _observe_turn(task)  # must not raise

        async def boom():
            raise RuntimeError("x")

        task2 = asyncio.get_running_loop().create_task(boom())
        try:
            await task2
        except RuntimeError:
            pass
        _observe_turn(task2)  # marks retrieved, must not raise

    run(main())


def test_wide_keys_resolve_on_device(run):
    """Keys beyond int32 route on DEVICE through the two-level
    hash/bucket mirror (arena.device_index_wide; the r3-era refusal is
    gone — only the NARROW mirror still refuses wide keys, because the
    wide one serves them).  Host-path dispatch and results keep working
    unchanged."""

    async def main():
        import pytest
        import jax.numpy as _jnp
        from orleans_tpu.tensor.arena import split_wide_keys
        from orleans_tpu.tensor.engine import resolve_rows_on_device

        engine = TensorEngine()
        arena = engine.arena_for("AccumGrain")
        wide = np.array([2**40 + 1, 2**40 + 2], dtype=np.int64)

        # host path: resolution, dispatch and results all work
        fut = engine.send_batch("AccumGrain", "add", wide,
                                {"v": np.float32([1.0, 2.0])},
                                want_results=True)
        await engine.flush()
        res = await fut
        np.testing.assert_allclose(res["echo"], [2.0, 4.0])
        rows = arena.resolve_rows(wide)
        assert arena.live_count >= 2 and rows[0] != rows[1]

        # device path: the wide mirror resolves the same keys to the
        # same rows, entirely on device
        hi, lo = split_wide_keys(wide)
        drows, miss = resolve_rows_on_device(
            arena, (_jnp.asarray(hi), _jnp.asarray(lo)),
            _jnp.ones(2, dtype=bool))
        assert int(miss) == 0
        np.testing.assert_array_equal(np.asarray(drows), rows)

        # the narrow int32 mirror still refuses loudly (it cannot hold
        # these keys); the wide mirror is the supported path
        with pytest.raises(OverflowError, match="int32"):
            arena.device_index()

    run(main())


def test_per_stage_tick_profiling_names_the_slow_stage(run):
    """The tick pipeline is profiled per stage (resolve/apply/route/...),
    the StageAnalysis analog (reference: src/Orleans/Statistics/
    StageAnalysis.cs:81): a slow tick must be attributable to a stage."""

    async def main():
        import time as _time

        engine = TensorEngine()
        keys = np.arange(64, dtype=np.int64)
        engine.send_batch("AccumGrain", "add", keys,
                          {"v": np.float32(np.ones(64))})
        await engine.flush()
        snap = engine.snapshot()
        stages = snap["stages"]
        assert {"resolve", "apply", "route"} <= set(stages)
        assert all(v >= 0 for v in stages.values())
        # stage sum cannot exceed total tick wall time
        assert sum(snap["last_tick_stages"].values()) <= \
            max(engine.tick_durations) + 1e-6

        # make resolution artificially slow; the breakdown must name it
        arena = engine.arena_for("AccumGrain")
        orig = arena.resolve_rows

        def slow_resolve(*a, **kw):
            _time.sleep(0.05)
            return orig(*a, **kw)

        arena.resolve_rows = slow_resolve
        engine.stage_seconds.clear()
        engine.send_batch("AccumGrain", "add", keys,
                          {"v": np.float32(np.ones(64))})
        await engine.flush()
        stages = engine.snapshot()["stages"]
        assert max(stages, key=stages.get) == "resolve"
        assert stages["resolve"] >= 0.05

    run(main())


def test_scatter_helpers_drop_padding_rows():
    """scatter_rows / scatter_add_rows must DROP padding rows (-1), not
    let JAX's negative-index normalization wrap them onto the LAST row —
    once an arena fills, that wrap silently corrupts whichever grain
    lives there (the padded host-batch path hits this every tick)."""
    import jax.numpy as jnp

    from orleans_tpu.tensor.vector_grain import (
        scatter_add_rows,
        scatter_rows,
    )

    col = jnp.zeros(4, jnp.int32)
    rows = jnp.asarray([-1, 1, -1, 3])
    vals = jnp.asarray([9, 5, 9, 7], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(scatter_rows(col, rows, vals)), [0, 5, 0, 7])
    np.testing.assert_array_equal(
        np.asarray(scatter_add_rows(col, rows, vals)), [0, 5, 0, 7])
