"""Tick fusion: compile a steady-state message loop into ONE XLA program.

Why this exists (measured, not guessed): at 1M grains a presence tick's
kernels take ~9ms of pure device time, but the per-tick host
orchestration — one jit dispatch per round plus Python queue plumbing —
costs an order of magnitude more.  The dispatcher's job in steady state
is *structurally constant*: the same (type, method) group arrives every
tick, its emits go to the same destination types, and the directory
doesn't change.  That constancy is exactly what XLA wants: trace the
whole tick — source kernel → device-mirror resolve → destination
kernels → registered fan-outs, recursively to the round cap — once, wrap
it in ``lax.scan`` over a stacked window of T ticks, and dispatch ONE
program where the unfused engine dispatched 3-5 per tick.

This is the north star's "batched graph-propagation kernel" taken to its
conclusion (SURVEY §7: the scheduler IS the tick loop; here the tick
loop IS a compiled program).  The reference has no analog — its
dispatcher walks queues per message (Dispatcher.cs:38); fusion is the
payoff for making dispatch data-flow.

Steady-state contract (checked, not assumed):
* the injected key set is fixed for the window (the injector's set);
* every emit destination key resolves in the frozen directory mirror —
  misses are COUNTED on device and surfaced after the window; a nonzero
  count means the window touched cold grains and the caller must fall
  back to the unfused path (which activates them);
* collection/elasticity/persistence do not run inside a window (they
  are between-tick work, same as the unfused engine).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from orleans_tpu.tensor.exchange import exchangeable_args
from orleans_tpu.tensor.profiler import (
    CAUSE_BUCKET_GROWTH,
    CAUSE_CONFIG_TOGGLE,
    CAUSE_EPOCH_MISMATCH,
    CAUSE_GENERATION_REPACK,
    CAUSE_MESH_RESHARD,
    CAUSE_NEW_WINDOW,
)
from orleans_tpu.tensor.vector_grain import (
    KEY_SENTINEL,
    Batch,
    Emit,
    ones_mask,
    vector_type,
)


def plan_windows(window: int, n_ticks: int):
    """Uniform-window schedule used by the fused load drivers: one window
    shape for the whole run (one compile), ticks rounded UP to whole
    windows.  Returns (window, n_windows, total_ticks)."""
    window = max(1, min(window, n_ticks))
    n_windows = -(-n_ticks // window)
    return window, n_windows, n_windows * window


def _normalize(out):
    if isinstance(out, dict):
        return out, None, ()
    out = tuple(out)
    state = out[0]
    results = out[1] if len(out) > 1 else None
    emits = out[2] if len(out) > 2 else ()
    return state, results, emits


class _Source:
    """One injection pattern of a fused window (a multi-pattern window
    applies several per tick, in a canonical order).

    Two modes.  STATIC (the steady-state autofuse mode): one key set,
    identical every tick — rows resolve once and ride the trace as
    constants.  STACKED (``_Source.stacked``, the journal fold-replay
    mode): a per-tick [T, m] key matrix with a [T, m] presence mask —
    rows resolve host-side into a [T, m] matrix that rides the scan xs
    as ``__rows__``/``__mask__`` leaves; absent lanes carry row -1 and
    mask False, which every handler/exchange path already treats as an
    exact no-op (the same contract as emit-resolution misses)."""

    def __init__(self, engine, type_name: str, method: str,
                 keys: np.ndarray) -> None:
        if vector_type(type_name) is None:
            raise KeyError(f"{type_name!r} is not a @vector_grain type")
        self.type_name = type_name
        self.method = method
        self.arena = engine.arena_for(type_name)
        self.stacked_rows = False
        self.keys = np.asarray(keys, dtype=np.int64)
        self.refresh_rows()

    @classmethod
    def stacked(cls, engine, type_name: str, method: str,
                keys2d: np.ndarray, mask2d: np.ndarray) -> "_Source":
        if vector_type(type_name) is None:
            raise KeyError(f"{type_name!r} is not a @vector_grain type")
        self = cls.__new__(cls)
        self.type_name = type_name
        self.method = method
        self.arena = engine.arena_for(type_name)
        self.stacked_rows = True
        self.keys2d = np.asarray(keys2d, dtype=np.int64)
        self.mask2d = np.asarray(mask2d, dtype=bool)
        self.lanes = int(self.keys2d.shape[1])
        # the flat unique key set (activation + re-resolution domain)
        self.keys = (np.unique(self.keys2d[self.mask2d])
                     if self.mask2d.any()
                     else np.empty(0, dtype=np.int64))
        self.refresh_rows()
        return self

    def refresh_rows(self) -> None:
        """(Re-)resolve keys → rows against the arena's CURRENT layout
        (activates missing keys — may grow the arena, so rollback
        snapshots must come after; the prepare() contract)."""
        if not self.stacked_rows:
            self.rows = jnp.asarray(self.arena.spread_rows_host(
                self.arena.resolve_rows(self.keys)))
            return
        if len(self.keys):
            self.arena.resolve_rows(self.keys)
        flat = self.keys2d.reshape(-1).copy()
        flat[~self.mask2d.reshape(-1)] = -1
        rows, found = self.arena.lookup_rows(flat)
        rows = np.where(found, rows.astype(np.int64), np.int64(-1))
        self.rows2d = rows.reshape(self.keys2d.shape)


class FusedTickProgram:
    """One compiled multi-tick program for one or more stable injection
    patterns.

    Built by ``TensorEngine.fuse_ticks`` (single pattern — ``run`` takes
    one stacked/static pytree pair) or ``FusedTickProgram.multi``
    (several concurrent steady patterns — ``run`` takes LISTS aligned
    with the sources, applied per tick in source order).  Calling
    ``run`` executes T ticks in one dispatch and updates the arenas'
    states; ``misses`` accumulates the device-side count of emit
    destinations that were not in the frozen directory mirror (must be
    0 for the window to be exact — check with ``verify()``)."""

    def __init__(self, engine, type_name: str, method: str,
                 keys: np.ndarray) -> None:
        self.engine = engine
        self.sources = [_Source(engine, type_name, method, keys)]
        self._finish_init()

    @classmethod
    def multi(cls, engine,
              sources: "List[Tuple[str, str, np.ndarray]]"
              ) -> "FusedTickProgram":
        self = cls.__new__(cls)
        self.engine = engine
        self.sources = [_Source(engine, t, m, k) for t, m, k in sources]
        self._finish_init()
        return self

    @classmethod
    def replay(cls, engine,
               sites: "List[Tuple[str, str, np.ndarray, np.ndarray]]"
               ) -> "FusedTickProgram":
        """Stacked-rows window for journal fold-replay: each site is
        (type_name, method, keys2d [T, m], mask2d [T, m]) — a run of T
        consecutive journaled ticks with per-tick key sets, applied in
        site order each tick.  Absent (site, tick) pairs ride with
        mask False / row -1 and are exact no-ops."""
        self = cls.__new__(cls)
        self.engine = engine
        self.sources = [_Source.stacked(engine, t, m, k2, mk)
                        for t, m, k2, mk in sites]
        self._finish_init()
        return self

    def _finish_init(self) -> None:
        self.n_msgs = sum(
            s.lanes if s.stacked_rows else len(s.keys)
            for s in self.sources)
        self._generations: Dict[str, int] = {}
        # eviction epochs of touched arenas at trace time: the window
        # bakes each arena's directory mirror in as trace constants, so
        # rows FREED since the trace (free-list deactivation — no
        # generation bump) would leave emits resolving to dead slots;
        # prepare() re-traces on mismatch, same as a repack
        self._epochs: Dict[str, int] = {}
        self._touched: List[str] = []
        self._compiled: Callable | None = None
        self._totals = None  # device [miss, delivered] since last verify
        # cross-shard exchange occupancy feedback: the per-site
        # per-destination bucket-demand maxima the window accumulated on
        # device ({site: int32[n_shards]}; read with _totals at verify
        # and folded into the exchange's estimators — fused steady
        # traffic keeps the caps honest in both directions)
        self._xneed = None
        self._exchange_sites: List[str] = []
        self._exchange_shapes: List[Tuple] = []
        self._site_keys: Dict[str, Tuple[str, str]] = {}
        self._exchange_plan_sig: "Tuple | None" = None
        # host-side shard alignment plans per source (or None): baked
        # take/rows/mask constants that pack the source batch
        # home-shard-local so its exchange runs the cap-0 fast path
        self._align: List[Any] = [None] * len(self.sources)
        # latency-ledger integration (tensor/ledger.py): when the owning
        # engine's ledger is enabled at BUILD time, the window program
        # threads the [slots, buckets] histogram through its scan and
        # every applied batch accumulates inside the compiled program —
        # zero per-window host work.  Inside a window each tick's
        # messages complete in their own (virtual) tick, so the recorded
        # delta is 0: the fused steady state IS the zero-queue-delay
        # operating point, and wall latency comes from seconds-per-tick
        # (bench.py's device-ledger points measure exactly that).
        self._ledger_on = False
        self._hist_shape: "Tuple[int, int] | None" = None
        # workload attribution (tensor/attribution.py): baked at build
        # time like the ledger — the window threads the per-arena
        # traffic counts + sketch + slot counters through its scan; a
        # live toggle/sketch-layout change re-traces (config_toggle)
        self._attr_on = False
        self._attr_sig: "Tuple | None" = None
        # cross-shard exchange (tensor/exchange.py): baked at build time
        # like the ledger — the window threads the all_to_all through
        # its scan; a live toggle re-traces (cause config_toggle).
        # In-window bucket overflows fold into the miss counter, so a
        # skewed window fails verify() and replays unfused (exactness
        # over throughput, the standing fused contract).
        self._exchange_on = False
        # stream-subscription routes (tensor/streams_plane.py): the
        # live toggle and every route's adjacency layout version are
        # baked at build time; prepare() re-traces on either moving
        self._streams_on = False
        self._stream_sig: "Tuple | None" = None
        # donation (config.donate_state, default on): the window takes
        # the state columns as donated inputs, so XLA double-buffers in
        # place and back-to-back windows pipeline without a host round
        # trip.  Callers that may need to ROLL BACK (the auto-fuser)
        # must take their snapshot as a device COPY BEFORE the first
        # donated run — copy-before-donate (autofuse._run_window); a
        # rolled-back chain then restores the copy and never touches a
        # donated-away buffer.  donate=False is the undonated serial
        # baseline the exactness A/B replays against.  An explicit
        # caller assignment PINS the mode (prepare() then never syncs
        # it back to the live config — manual drivers that snapshot
        # pre-run buffers by reference rely on staying undonated).
        self._donate = self.engine.config.donate_state
        self._donate_pinned = False
        self._built_donate: "bool | None" = None  # mode _build baked
        # compile-churn attribution: engine.reshard bumps this counter,
        # so a post-reshard re-trace names the reshard as its cause
        # instead of the generation bump it also produced
        self._reshard_count = self.engine.reshard_count

    @property
    def donate(self) -> bool:
        return self._donate

    @donate.setter
    def donate(self, value: bool) -> None:
        self._donate = bool(value)
        self._donate_pinned = True

    # -- legacy single-source aliases (manual drivers, tests) ---------------

    @property
    def type_name(self) -> str:
        return self.sources[0].type_name

    @property
    def method(self) -> str:
        return self.sources[0].method

    @property
    def keys(self) -> np.ndarray:
        return self.sources[0].keys

    @property
    def src_arena(self):
        return self.sources[0].arena

    @property
    def src_rows(self):
        return self.sources[0].rows

    @src_rows.setter
    def src_rows(self, value) -> None:
        self.sources[0].rows = value

    def _is_multi(self) -> bool:
        return len(self.sources) > 1

    def _as_lists(self, stacked_args: Any, static_args: Any
                  ) -> Tuple[List[Any], List[Any]]:
        if self._is_multi():
            return list(stacked_args), list(static_args or
                                            [{}] * len(self.sources))
        return [stacked_args], [static_args or {}]

    # -- trace-time recursion over the emit graph ---------------------------

    def _apply_group(self, states: Dict[str, Any], type_name: str,
                     method: str, rows, args, mask, depth: int, hist,
                     attr, xneed, segments=None, host_keys=None,
                     aligned: bool = False):
        """Apply one (type, method) batch and recurse into its emits,
        registered fan-outs, and registered stream-subscription routes
        — the trace-time unrolling of the engine's multi-round tick.
        ``hist`` is the latency-ledger accumulator threaded through the
        window (unchanged when the ledger is off); ``attr`` is the
        workload-attribution SCAN carry (counts + slots — the sketch is
        folded ONCE per window from the counts delta, see ``window``),
        empty when that plane is off.  ``xneed`` is the exchange's
        per-site bucket-demand accumulator ({site: int32[n_shards]},
        max-merged — the occupancy estimator's fused-path feedback).
        ``segments`` marks a pull-mode delivery batch (row-aligned
        offsets — tensor/streams_plane.py); ``host_keys`` is the source
        pattern's host key set (depth-1 sources only), which the stream
        route uses to recognize its bound publish set; ``aligned`` marks
        a source batch the build packed home-shard-local (its exchange
        plans cap 0 — the classification-only fast path)."""
        info = vector_type(type_name)
        handler = info.handlers[method]
        if type_name not in states:
            # discovery pass: arenas are pulled in lazily as the emit
            # graph is walked; the compiled window carries all of them
            states[type_name] = self.engine.arena_for(type_name).state
            self._note_arena(type_name, self.engine.arena_for(type_name))
        n_rows = next(iter(states[type_name].values())).shape[0]
        miss_total = jnp.int32(0)
        xch = self.engine.exchange
        if self._exchange_on and xch is not None and not aligned \
                and xch.engaged():
            # aligned sources SKIP the exchange entirely: the build
            # packed every lane into its home chunk from concrete rows,
            # and any layout move (grow/compact/eviction/reshard) re-
            # traces through prepare()'s generation/epoch discipline
            # before the constants can go stale — an in-scan
            # classification would re-prove a static fact every tick.
            # A DISENGAGED exchange (identity mode — host-virtual mesh)
            # traces nothing at all: the window IS the exchange-off
            # program, and a live engagement flip re-traces through the
            # plan signature.
            arena = self.engine.arena_for(type_name)
            if arena.sharding is not None:
                # cross-shard exchange INSIDE the window: sources and
                # recursed emit deliveries alike arrive shard-local at
                # their kernel; bucket-overflow lanes count as misses
                # (the window is then non-exact and replays unfused —
                # no in-window redelivery path exists by design)
                site = (type_name, method)
                rows, args, mask, dropped, need = xch.apply_traced(
                    site, int(arena.shard_capacity), rows, args, mask)
                miss_total = miss_total + dropped
                skey = f"{type_name}.{method}"
                if skey in xneed:
                    xneed = {**xneed,
                             skey: jnp.maximum(xneed[skey], need)}
                else:  # discovery pass only — window pre-populates
                    xneed = {**xneed, skey: need}
        # named_scope labels the window HLO for jax.profiler deep
        # captures (tensor/profiler.py) — trace-time only
        with jax.named_scope(f"orleans.fused.{type_name}.{method}"):
            state2, _results, emits = _normalize(
                handler(states[type_name],
                        Batch(rows=rows, args=args, mask=mask,
                              segments=segments), n_rows))
        states = {**states, type_name: state2}
        if self._ledger_on:
            # in-window latency ledger: every applied lane lands in
            # bucket 0 (each tick's messages complete in their own
            # virtual tick — delta 0 by construction), so the general
            # one-hot kernel COLLAPSES to one masked count + a scalar
            # add.  Bit-identical to ledger.accumulate at delta 0, and
            # it removes a per-group scatter from every scanned tick
            # (measured as the dominant in-window plane cost on
            # scatter-hostile backends).
            slot = self.engine.ledger.slot_for(type_name, method)
            hist = hist.at[jnp.int32(slot), 0].add(
                jnp.sum(jnp.asarray(mask, jnp.int32)))
        if self._attr_on:
            # in-window workload attribution, counts + slots only: the
            # sketch fold moved OUT of the scan — window() re-derives
            # it once per window from the counts delta (integer adds
            # commute, so the result is bit-identical to per-lane
            # folds at a fraction of the scatter cost).  Pull-mode
            # delivery batches (segments) fold their counts with the
            # same scatter-free cumulative-sum reduction the handler
            # uses.
            from orleans_tpu.tensor import attribution as _attr
            att = self.engine.attribution
            counts = attr["counts"].get(type_name)
            if counts is None:
                # arena discovered mid-trace (discovery pass only — the
                # real window trace receives every touched arena's
                # accumulator as an input)
                counts = att.counts_for(type_name)
            c2, sl2 = _attr.fold_counts(
                counts, attr["slots"],
                jnp.int32(att.slots.slot_for(type_name, method)),
                rows, jnp.asarray(mask, bool), segments=segments)
            attr = {"counts": {**attr["counts"], type_name: c2},
                    "slots": sl2}
        delivered = jnp.int32(0)
        at_cap = depth >= self.engine.config.max_rounds_per_tick

        out_batches: List[Tuple[str, str, Any, Any, Any]] = []
        emits = emits if isinstance(emits, (tuple, list)) else (emits,)
        for e in emits:
            if e is None:
                continue
            if isinstance(e.keys, tuple):
                # wide destination keys ((hi, lo) int32 words) resolve
                # through the wide mirror inside the window too
                ekeys = tuple(
                    k if (hasattr(k, "dtype") and k.dtype == jnp.int32)
                    else jnp.asarray(k, jnp.int32) for k in e.keys)
                m = ekeys[0].shape[0]
            else:
                ekeys = e.keys if (hasattr(e.keys, "dtype")
                                   and e.keys.dtype == jnp.int32) \
                    else jnp.asarray(e.keys, jnp.int32)
                m = ekeys.shape[0]
            emask = e.mask if e.mask is not None else ones_mask(m)
            out_batches.append((e.interface, e.method, ekeys, e.args, emask))

        fan = self.engine._fanouts.get((type_name, method))
        if fan is not None and not at_cap:
            fanout, dst_type, dst_method = fan
            src_keys = self._src_keys_for(type_name, rows)
            dkeys, dargs, dvalid = fanout.expand(src_keys, args, mask)
            n_dropped, _dmask = fanout.take_drop()
            # source lanes whose expansion overflowed the CSR width
            # parked (delivering nothing this round): count them as
            # misses so verify() fails loudly — the rollback's unfused
            # replay then re-delivers them through the engine's
            # park-and-redeliver path (never silent loss)
            miss_total = miss_total + n_dropped
            out_batches.append((dst_type, dst_method, dkeys, dargs, dvalid))
        elif fan is not None and at_cap:
            # a fan-out the cap prevents from running would silently lose
            # deliveries — surface it via the miss counter
            miss_total = miss_total + jnp.sum(
                jnp.asarray(mask, jnp.int32))

        # stream-subscription routes (tensor/streams_plane.py): the
        # stream-ingress method's messages also fan out to the streams'
        # subscribers.  Baked at build time like the ledger (a live
        # config.stream_plane toggle re-traces, cause config_toggle).
        route = self.engine._stream_routes.get((type_name, method)) \
            if self._streams_on else None
        if route is not None and not at_cap:
            dst_arena = self.engine.arena_for(route.type_name)
            self._note_arena(route.type_name, dst_arena)
            pull = route.pull_layout(dst_arena) \
                if host_keys is not None \
                and route._matches_bound(host_keys) else None
            if pull is not None and pull["n_edges"] > 0:
                # pull fast path, inside the scan: one payload gather
                # per edge + the row-aligned segment reduction in the
                # destination handler — the CSR/offsets ride as trace
                # constants, stamped by prepare()'s re-trace predicate
                lane = pull["src_lane"]
                gargs = jax.tree_util.tree_map(
                    lambda a: a if jnp.ndim(a) == 0
                    else jnp.asarray(a)[lane], args)
                if isinstance(gargs, dict) and "src_key" not in gargs:
                    gargs = {**gargs, "src_key": pull["src_key"]}
                emask = jnp.asarray(mask, bool)[lane]
                delivered = delivered + jnp.sum(emask.astype(jnp.int32))
                states, sub_miss, sub_del, hist, attr, xneed = \
                    self._apply_group(
                        states, route.type_name, route.method,
                        pull["rows"], gargs, emask, depth + 1, hist,
                        attr, xneed, segments=pull["offsets"])
                miss_total = miss_total + sub_miss
                delivered = delivered + sub_del
            else:
                # push path in-window: expand to subscriber keys and
                # resolve like any emit; overflowing source lanes fold
                # into the miss counter (rollback + unfused replay
                # redelivers them — the DeviceFanout contract)
                src_keys = self._src_keys_for(type_name, rows)
                dkeys, dargs, dvalid = route.expand(
                    src_keys, args, jnp.asarray(mask, bool))
                n_dropped, _dmask = route.take_drop()
                miss_total = miss_total + n_dropped
                out_batches.append((route.type_name, route.method,
                                    dkeys, dargs, dvalid))
        elif route is not None and at_cap:
            miss_total = miss_total + jnp.sum(
                jnp.asarray(mask, jnp.int32))
        elif not self._streams_on \
                and (type_name, method) in self.engine._stream_routes:
            # the plane is live-DISABLED but a route exists: its
            # deliveries belong to the host-expansion path, which a
            # compiled window cannot run — count every source lane as a
            # miss so verify() fails and the rollback's unfused replay
            # delivers through _run_stream_routes_pre (fusion is
            # effectively off for routed sources while the toggle is
            # off; silently verifying would LOSE every delivery)
            miss_total = miss_total + jnp.sum(
                jnp.asarray(mask, jnp.int32))

        if at_cap:
            # the unfused engine SPILLS round-cap emits to the next tick;
            # a fused window cannot, so count them as misses — verify()
            # then tells the caller this chain is too deep to fuse
            for _, _, _ekeys, _eargs, emask in out_batches:
                miss_total = miss_total + jnp.sum(
                    jnp.asarray(emask, jnp.int32))
            return states, miss_total, delivered, hist, attr, xneed

        for dst_type, dst_method, ekeys, eargs, emask in out_batches:
            dst_arena = self.engine.arena_for(dst_type)
            self._note_arena(dst_type, dst_arena)
            from orleans_tpu.tensor.engine import resolve_rows_on_device
            drows, miss = resolve_rows_on_device(dst_arena, ekeys, emask)
            delivered = delivered + jnp.sum(jnp.asarray(emask, jnp.int32))
            states, sub_miss, sub_del, hist, attr, xneed = \
                self._apply_group(
                    states, dst_type, dst_method, drows, eargs,
                    drows >= 0, depth + 1, hist, attr, xneed)
            miss_total = miss_total + miss + sub_miss
            delivered = delivered + sub_del
        return states, miss_total, delivered, hist, attr, xneed

    def _src_keys_for(self, type_name: str, rows):
        arena = self.engine.arena_for(type_name)
        # key-of-row lookup on device for fan-out expansion
        key_col = jnp.asarray(arena._key_of_row.astype(np.int64)
                              .clip(0, 2**31 - 2).astype(np.int32))
        return key_col[jnp.clip(rows, 0, key_col.shape[0] - 1)]

    def _note_arena(self, name: str, arena) -> None:
        if name not in self._generations:
            self._generations[name] = arena.generation
            self._epochs[name] = arena.eviction_epoch
            self._touched.append(name)

    # -- compile + run -------------------------------------------------------

    def _build(self, example_args_t: Any) -> Callable:
        from orleans_tpu.tensor.ledger import MAX_SLOTS

        examples = example_args_t if self._is_multi() \
            else [example_args_t]
        # latency ledger: bake the decision at build time (a live toggle
        # takes effect on the next re-trace); the hist shape is part of
        # the compiled signature, so prepare() re-traces when it changes
        self._ledger_on = self.engine.ledger.enabled
        self._hist_shape = (MAX_SLOTS, self.engine.ledger.n_buckets)
        # workload attribution: same bake-at-build discipline as the
        # ledger (prepare() re-traces on toggle/sketch-layout change)
        self._attr_on = self.engine.attribution.enabled
        self._attr_sig = self.engine.attribution.build_signature()
        # cross-shard exchange: same bake-at-build discipline
        self._exchange_on = self.engine._exchange_live()
        # packed cross-lanes (tensor/exchange.py): a source whose key
        # set is static for the window's lifetime is PACKED home-shard-
        # local here, on the host, once — its in-scan exchange then
        # plans cap 0 (classification only: no sort, no all_to_all,
        # output width == input width).  Sources feeding a stream route
        # keep their lane order (pull layouts precompute per-edge
        # source lanes against the bound key order).
        self._align = [None] * len(self.sources)
        if self._exchange_on \
                and self.engine.exchange.engaged() \
                and self.engine.config.exchange_align_sources:
            for i, s in enumerate(self.sources):
                arena = self.engine.arena_for(s.type_name)
                if s.stacked_rows \
                        or arena.sharding is None \
                        or (s.type_name, s.method) \
                        in self.engine._stream_routes \
                        or not exchangeable_args(examples[i],
                                                 len(s.keys)):
                    # stacked sources change lanes per tick — there is
                    # no one host packing to bake
                    continue
                plan = self.engine.exchange.align_plan(
                    np.asarray(s.rows), int(arena.shard_capacity))
                if plan is None:
                    continue
                # the aligned layout is a transport width: this
                # source's EMIT batches inherit it, and their exchange
                # must keep the per-shard split exact
                self.engine.exchange.note_transport_width(
                    len(plan["rows"]))
                self._align[i] = {
                    "take": jnp.asarray(
                        np.clip(plan["take"], 0, None).astype(np.int32)),
                    "rows": jnp.asarray(plan["rows"]),
                    "mask": jnp.asarray(plan["take"] >= 0),
                }
        src_rows = [None if s.stacked_rows
                    else (al["rows"] if al is not None else s.rows)
                    for al, s in zip(self._align, self.sources)]
        masks = [None if s.stacked_rows
                 else (al["mask"] if al is not None
                       else ones_mask(len(s.keys)))
                 for al, s in zip(self._align, self.sources)]
        # the discovery/trace examples must match the lane layout the
        # window's gather produces
        examples = [self._align_tree(i, ex, axis=0)
                    for i, ex in enumerate(examples)]
        # stream-subscription routes (tensor/streams_plane.py): bake the
        # live toggle and warm every route's pull layout EAGERLY — a
        # rebuild under the trace would produce trace-local mirrors, so
        # pull_layout refuses to rebuild there and the trace would bake
        # the push path for a pattern the engine runs pulled
        self._streams_on = self.engine._streams_live()
        if self._streams_on:
            for _key, route in self.engine._stream_routes.items():
                route.pull_layout(self.engine.arena_for(route.type_name))
                if route._push_dirty or route._push is None:
                    # warm the push CSR too: an in-trace rebuild would
                    # bump layout_version AFTER the signature below is
                    # captured, and the next prepare() would spuriously
                    # re-trace the whole window a second time
                    route._rebuild_push()
        self._stream_sig = self.engine._stream_routes_signature()

        def apply_all(states, per_source_args, hist, attr, xneed):
            miss_tot = jnp.int32(0)
            del_tot = jnp.int32(0)
            for i, src in enumerate(self.sources):
                args_i = per_source_args[i]
                if src.stacked_rows:
                    # stacked mode: this tick's rows/mask ride the scan
                    # xs as reserved leaves (per-tick key sets); pop
                    # them so the handler sees only its own args
                    args_i = dict(args_i)
                    rows_i = args_i.pop("__rows__")
                    mask_i = args_i.pop("__mask__")
                    hk = None
                else:
                    rows_i, mask_i, hk = src_rows[i], masks[i], src.keys
                states, miss, dd, hist, attr, xneed = self._apply_group(
                    states, src.type_name, src.method, rows_i,
                    args_i, mask_i, depth=1, hist=hist,
                    attr=attr, xneed=xneed, host_keys=hk,
                    aligned=self._align[i] is not None)
                miss_tot = miss_tot + miss
                del_tot = del_tot + dd
            return states, miss_tot, del_tot, hist, attr, xneed

        def reset_discovery() -> None:
            self._generations = {s.type_name: s.arena.generation
                                 for s in self.sources}
            self._epochs = {s.type_name: s.arena.eviction_epoch
                            for s in self.sources}
            self._touched = []
            for s in self.sources:
                if s.type_name not in self._touched:
                    self._touched.append(s.type_name)

        reset_discovery()

        # discovery: abstractly trace ONE tick so the emit graph's
        # destination arenas are known before the scan carry is fixed.
        # Arenas first touched DURING the abstract trace get tracer-backed
        # state columns; recreate those eagerly and re-discover until the
        # emit graph introduces no new arenas (bounded by the round cap).
        # A FRESH closure per iteration: discovery works by side effect
        # (_note_arena), and jax caches traces by function identity — a
        # reused closure would hit the cache and silently skip the trace.
        xch = self.engine.exchange
        while True:
            known = set(self.engine.arenas)
            reset_discovery()
            if xch is not None:
                # the discovery trace walks every exchange site —
                # capture them (and their in/out widths) for the xneed
                # accumulator layout + the utilization counters
                xch.trace_log = []

            def discover(args_per_source):
                states: Dict[str, Any] = {
                    s.type_name: s.arena.state for s in self.sources}
                hist0 = jnp.zeros(self._hist_shape, jnp.int32)
                attr0 = self._scan_attr(self.attr_state_in(
                    [s.type_name for s in self.sources]))
                _states, miss, _d, _h, _a, _x = apply_all(
                    states, args_per_source, hist0, attr0, {})
                return miss

            jax.eval_shape(discover, examples)
            born_in_trace = set(self.engine.arenas) - known
            if not born_in_trace:
                break
            for name in born_in_trace:
                self.engine.arenas.pop(name)
                self.engine.arena_for(name)  # eager, concrete columns
        touched = list(self._touched)
        shapes = list(xch.trace_log) \
            if (self._exchange_on and xch is not None) else []
        self._exchange_shapes = shapes
        self._site_keys: Dict[str, Tuple[str, str]] = {}
        for site, _mi, _mo in shapes:
            self._site_keys.setdefault(f"{site[0]}.{site[1]}", site)
        self._exchange_sites = list(self._site_keys)
        self._exchange_plan_sig = xch.plan_signature(
            list(self._site_keys.values())) \
            if (self._exchange_on and xch is not None) else None

        def window(states, statics, stackeds, totals_in, hist_in,
                   attr_in, xneed_in):
            scan_attr_in = self._scan_attr(attr_in)
            # packed sources: ONE gather per leaf per window (outside
            # the scan) re-lays the natural-order inputs home-shard-
            # local; the per-tick exchange inside the scan then runs
            # the cap-0 fast path
            statics = [self._align_tree(i, statics[i], axis=0)
                       for i in range(len(self.sources))]
            stackeds = [self._align_tree(i, stackeds[i], axis=1)
                        for i in range(len(self.sources))]

            def one_tick(carry, args_ts):
                states, hist, attr, xneed = carry
                # static leaves (identical every tick) ride OUTSIDE the
                # scan xs: slicing a [T, m] stack per iteration costs
                # real bandwidth; a closed-over [m] array costs nothing
                merged = [{**statics[i], **args_ts[i]}
                          for i in range(len(self.sources))]
                states, miss, delivered, hist, attr, xneed = apply_all(
                    states, merged, hist, attr, xneed)
                return (states, hist, attr, xneed), (miss, delivered)
            (states, hist, attr, xneed), (misses, delivered) = \
                jax.lax.scan(
                    one_tick, (states, hist_in, scan_attr_in, xneed_in),
                    tuple(stackeds))
            if attr_in:
                # sketch fold, ONCE per window: the scan carried only
                # counts + slots; the CMS re-derives from each arena's
                # counts delta (same hashed row buckets, integer adds
                # commute — bit-identical to per-lane folds, at one
                # capacity-sized scatter per window instead of one
                # lane-sized scatter per group per tick)
                from orleans_tpu.tensor import attribution as _attr
                seeds = self.engine.attribution._seed_arr()
                cms_out = {
                    t: _attr.fold_cms_dense(
                        attr_in["cms"][t],
                        attr["counts"].get(t, attr_in["counts"][t])
                        - attr_in["counts"][t], seeds)
                    for t in attr_in["cms"]}
                attr = {"counts": attr["counts"], "cms": cms_out,
                        "slots": attr["slots"]}
            # totals accumulate ON DEVICE across runs: verify() then
            # reads one 2-element buffer no matter how many windows ran
            # (each completion observation costs ~100ms on tunneled
            # runtimes, so per-window reads would dominate).  The ledger
            # hist, the attribution pytree and the exchange demand
            # maxima likewise stay on device until an explicit snapshot.
            return states, totals_in + jnp.stack(
                [jnp.sum(misses), jnp.sum(delivered)]), hist, attr, xneed

        self._touched = touched
        self._built_donate = self.donate
        return jax.jit(window,
                       donate_argnums=(0,) if self.donate else ())

    def attr_state_in(self, touched: "List[str] | None" = None):
        """The attribution accumulator pytree a window run (or the
        auto-fuser's AOT lower) passes as ``attr_in`` — empty when the
        plane was off at build time, so the signature stays stable."""
        if not self._attr_on:
            return {}
        return self.engine.attribution.device_state_in(
            touched if touched is not None else self._touched)

    def _align_tree(self, i: int, tree: Any, axis: int) -> Any:
        """Gather one source's args into its packed home-shard-local
        lane order (no-op for unaligned sources).  ``axis=0`` for
        natural [m, ...] leaves (statics / single-tick examples),
        ``axis=1`` for stacked [T, m, ...] leaves — a stacked leaf of
        rank 1 is a per-tick scalar and passes through untouched."""
        al = self._align[i]
        if al is None:
            return tree
        take = al["take"]

        def gather(a):
            if jnp.ndim(a) == 0:
                return a
            if axis == 0:
                return jnp.asarray(a)[take]
            if jnp.ndim(a) == 1:
                return a
            return jnp.asarray(a)[:, take]

        return jax.tree_util.tree_map(gather, tree)

    def xneed_state_in(self):
        """The exchange demand accumulator a window run (or the
        auto-fuser's AOT lower) passes as ``xneed_in`` — empty when the
        exchange was off at build time, so the signature stays
        stable."""
        if not self._exchange_on or not self._exchange_sites:
            return {}
        if self._xneed is not None:
            return self._xneed
        # [2n]: per-dest demand maxed over sources ‖ summed over
        # sources (the per-dest formulation's receive-rung signal) —
        # matches apply_traced's need vector; max-merge is correct for
        # both halves (each is a per-tick peak)
        n = self.engine.n_shards
        return {k: jnp.zeros(2 * n, jnp.int32)
                for k in self._exchange_sites}

    def _fold_xneed(self) -> None:
        """Read the accumulated per-site bucket demand (one small
        transfer per site, at an existing sync point) into the
        exchange's occupancy estimators — the fused path's half of the
        cap-sizing feedback loop."""
        xn, self._xneed = self._xneed, None
        xch = self.engine.exchange
        if not xn or xch is None:
            return
        for skey, vec in xn.items():
            site = self._site_keys.get(skey)
            if site is not None:
                xch.observe_need(site, np.asarray(vec))

    @staticmethod
    def _scan_attr(attr_in):
        """The slice of the attribution pytree that rides the scan
        carry: counts + slots.  The sketch stays OUTSIDE the scan and
        folds once per window from the counts delta (see window)."""
        if not attr_in:
            return {}
        return {"counts": attr_in["counts"], "slots": attr_in["slots"]}

    def prepare(self, stacked_args: Any, static_args: Any = None) -> None:
        """Re-resolve the source rows and re-trace if any touched arena
        grew/repacked since the trace (the unfused engine's generation
        discipline).  Idempotent; ``run`` calls it first.  Callers that
        snapshot arena state for rollback (the auto-fuser) MUST call
        this BEFORE taking the snapshot: source re-resolution
        auto-activates evicted keys, which can GROW an arena — a
        post-snapshot grow would make the snapshot unrestorable."""
        engine = self.engine
        stackeds, statics = self._as_lists(stacked_args, static_args)
        from orleans_tpu.tensor.ledger import MAX_SLOTS
        # cause-coded re-trace decision (tensor/profiler.py churn
        # taxonomy): the FIRST matching condition names the cause —
        # reshard outranks the generation bump it also produced
        # donation target: an explicit caller pin wins (manual drivers
        # that snapshot pre-run buffers by reference stay undonated);
        # otherwise the live config decides and a toggle re-traces
        donate_target = self._donate if self._donate_pinned \
            else engine.config.donate_state
        cause = None
        if self._compiled is None:
            cause = CAUSE_NEW_WINDOW
        elif self._reshard_count != engine.reshard_count:
            cause = CAUSE_MESH_RESHARD
        elif any(engine.arena_for(n).generation != g
                 for n, g in self._generations.items()):
            cause = CAUSE_GENERATION_REPACK
        elif any(engine.arena_for(n).eviction_epoch != e
                 for n, e in self._epochs.items()):
            cause = CAUSE_EPOCH_MISMATCH
        elif self._hist_shape != (MAX_SLOTS, engine.ledger.n_buckets) \
                or self._ledger_on != engine.ledger.enabled \
                or self._attr_sig != engine.attribution.build_signature() \
                or self._exchange_on != engine._exchange_live() \
                or self._streams_on != engine._streams_live() \
                or self._stream_sig != engine._stream_routes_signature():
            # stream-plane toggles AND adjacency rebuilds both land
            # here: the window bakes the CSR/offsets as trace
            # constants, so a layout_version bump must re-trace
            cause = CAUSE_CONFIG_TOGGLE
        elif self._built_donate != donate_target:
            # the compiled window baked the other donation mode (live
            # donate_state toggle, or a re-pinned cached program) —
            # re-trace; the step-program twin clears _step_cache for
            # the same reason
            cause = CAUSE_CONFIG_TOGGLE
        elif self._exchange_on and engine.exchange is not None \
                and self._exchange_plan_sig != engine.exchange \
                .plan_signature(list(self._site_keys.values())):
            # an exchange cap re-quantized (the occupancy estimator
            # moved a grant, or the sizing knobs were live-reloaded):
            # the window baked the old bucket widths as trace constants.
            # Re-trace HERE, cause-coded — grants only move at drain/
            # verify boundaries (estimators fold there), so a steady
            # stream can never recompile per tick
            cause = CAUSE_BUCKET_GROWTH
        if cause is not None:
            # fold pending demand observations under the OLD site
            # layout before the rebuild replaces it
            self._fold_xneed()
            self._donate = donate_target
            for s in self.sources:
                s.refresh_rows()
            examples = [
                {**statics[i], **jax.tree_util.tree_map(lambda a: a[0],
                                                        stackeds[i])}
                for i in range(len(self.sources))]
            t_build = time.perf_counter()
            self._compiled = self._build(
                examples if self._is_multi() else examples[0])
            self._reshard_count = engine.reshard_count
            t_built = time.perf_counter() - t_build
            engine.compile_tracker.record(
                cause,
                key="fused:" + "+".join(f"{s.type_name}.{s.method}"
                                        for s in self.sources),
                seconds=t_built,
                tick=engine.tick_number)
            rec = engine._span_recorder()
            if rec is not None:
                # re-trace episodes on the exchange track: the
                # timeline shows WHEN a window re-baked and why
                rec.plane_span("exchange", f"re-trace {cause}",
                               duration=t_built, cause=cause,
                               tick=engine.tick_number,
                               sources=len(self.sources))

    def run(self, stacked_args: Any, static_args: Any = None) -> None:
        """Execute T fused ticks.

        ``stacked_args``: pytree of genuinely per-tick leaves with a
        leading [T, ...] axis (e.g. the tick counter).  ``static_args``:
        leaves identical every tick, passed at their natural [m, ...]
        shape — they are closed over by the scan instead of stacked, so a
        steady payload costs no per-tick slicing bandwidth.  Multi-source
        programs (``FusedTickProgram.multi``) take LISTS of both, aligned
        with ``sources``."""
        engine = self.engine
        stackeds, statics = self._as_lists(stacked_args, static_args)
        leaves = jax.tree_util.tree_leaves(stackeds)
        if not leaves:
            raise ValueError(
                "stacked_args needs at least one [T, ...] leaf (e.g. a "
                "tick counter) — it sets the window length")
        n_ticks = leaves[0].shape[0]
        self.prepare(stacked_args, static_args)
        states = {n: engine.arena_for(n).state for n in self._touched}
        totals_in = self._totals if self._totals is not None \
            else jnp.zeros(2, dtype=jnp.int32)
        new_states, self._totals, hist_out, attr_out, xneed_out = \
            self._compiled(
                states, statics, stackeds, totals_in,
                engine.ledger.device_hist_in(), self.attr_state_in(),
                self.xneed_state_in())
        if self._ledger_on:
            engine.ledger.device_hist_out(hist_out)
        if self._attr_on:
            engine.attribution.device_state_out(attr_out)
        if self._exchange_on:
            self._xneed = xneed_out
            if engine.exchange is not None:
                engine.exchange.fold_fused_shapes(
                    self._exchange_shapes, n_ticks)
        for n in self._touched:
            # double-buffer flip: donated windows consumed the inputs;
            # the outputs are the live columns now (layout validated)
            engine.arena_for(n).adopt_state(new_states[n])
        # the window's on-device totals accumulator doubles as the
        # pipeline's completion FENCE: it is a program output nothing
        # ever donates (it feeds the NEXT window as a plain input), so
        # event-driven completion can block on it while later windows
        # donate the state buffers away
        engine._tick_fence = self._totals
        if not self._donate:
            engine.donation_fallbacks += 1
        engine.tick_number += n_ticks
        engine.ticks_run += n_ticks
        engine.messages_processed += n_ticks * self.n_msgs
        # collection safety: the window advanced the tick clock without
        # routing through the engine's touch path — every row of a fused
        # arena is a live participant, so stamp them all or the idle
        # sweep would evict hot state mid-steady-state
        for n in self._touched:
            arena = engine.arena_for(n)
            arena.last_use_tick[arena._key_of_row >= 0] = engine.tick_number

    def verify(self) -> int:
        """Sync point: total exactness violations across run() calls since
        the last verify — emit misses (cold destinations), fan-out budget
        overflows, and round-cap spills all count.  Nonzero = the window
        was NOT exact; re-run those ticks unfused.  Also folds the
        windows' emit/fan-out delivery counts into the engine's
        messages_processed (run() counts only source injections eagerly —
        delivery counts live on device until this sync).  ONE 2-element
        device read regardless of how many windows ran since the last
        verify (the on-device totals accumulator).  Also folds the
        accumulated exchange bucket demand into the occupancy
        estimators — an in-window bucket overflow both fails the window
        AND grows the cap, so the re-traced window is exact again."""
        self._fold_xneed()
        if self._totals is None:
            return 0
        totals = np.asarray(self._totals)
        self._totals = None
        self.engine.messages_processed += int(totals[1])
        return int(totals[0])
