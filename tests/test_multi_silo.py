"""Multi-silo cluster tests: cross-silo RPC, placement, membership,
failure detection, recovery.

Reference analogs: Tester/MembershipTests/LivenessTests.cs,
SilosStopTests.cs, and the directory/single-activation suites.
"""

import asyncio

import pytest

from orleans_tpu.core.grain import grain_id_for
from orleans_tpu.testing import TestingCluster

from tests.fixture_grains import ICounterGrain, IFailingGrain, ISlowGrain


def test_cross_silo_rpc(run):
    async def main():
        cluster = await TestingCluster(n_silos=3).start()
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(0)
            # spread 30 grains — hash placement should use several silos
            refs = [factory.get_grain(IFailingGrain, i) for i in range(30)]
            results = await asyncio.gather(*(r.ok() for r in refs))
            assert all(r == "fine" for r in results)
            hosting = [len(s.catalog.directory) for s in cluster.silos]
            assert sum(hosting) == 30
            assert sum(1 for h in hosting if h > 0) >= 2, hosting
        finally:
            await cluster.stop()

    run(main())


def test_single_activation_across_silos(run):
    async def main():
        cluster = await TestingCluster(n_silos=3).start()
        try:
            await cluster.wait_for_liveness_convergence()
            # clients attached to different silos call the same grain
            f0 = cluster.attach_client(0)
            ref0 = f0.get_grain(ICounterGrain, 42)
            r0 = await asyncio.gather(*(ref0.add(1) for _ in range(5)))
            f1 = cluster.attach_client(1)
            ref1 = f1.get_grain(ICounterGrain, 42)
            r1 = await ref1.add(1)
            # one activation total, counter is linear
            gid = grain_id_for(ICounterGrain, 42)
            hosts = [s for s in cluster.silos
                     if s.catalog.directory.by_grain.get(gid)]
            assert len(hosts) == 1
            assert r1 == 6
        finally:
            await cluster.stop()

    run(main())


def test_kill_silo_detected_and_grain_reactivates(run):
    async def main():
        cluster = await TestingCluster(n_silos=3).start()
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(0)
            refs = [factory.get_grain(ICounterGrain, i) for i in range(20)]
            await asyncio.gather(*(r.add(1) for r in refs))

            # find a victim hosting at least one grain, not the client silo
            victim = next(s for s in cluster.silos[1:]
                          if len(s.catalog.directory) > 0)
            lost = len(victim.catalog.directory)
            cluster.kill_silo(victim)

            # survivors must declare it dead via probes + votes
            deadline = asyncio.get_running_loop().time() + 10
            while any(victim.address in s.active_silos()
                      for s in cluster.silos):
                assert asyncio.get_running_loop().time() < deadline, \
                    "victim never declared dead"
                await asyncio.sleep(0.1)

            # every grain remains callable (dead ones re-activate elsewhere)
            results = await asyncio.gather(*(r.add(1) for r in refs))
            assert len(results) == 20
            assert lost > 0
            for s in cluster.silos:
                assert victim.address not in s.active_silos()
        finally:
            await cluster.stop()

    run(main())


def test_graceful_shutdown_moves_grains(run):
    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(0)
            refs = [factory.get_grain(ICounterGrain, i) for i in range(10)]
            await asyncio.gather(*(r.add(5) for r in refs))
            # persist so state survives the move
            await asyncio.gather(*(r.save() for r in refs))

            leaver = cluster.silos[1]
            await cluster.stop_silo(leaver)
            await cluster.wait_for_liveness_convergence()

            values = await asyncio.gather(*(r.get() for r in refs))
            assert all(v == 5 for v in values), values
            # everything now lives on the surviving silo
            assert len(cluster.silos[0].catalog.directory) == 10
        finally:
            await cluster.stop()

    run(main())


def test_restarted_silo_is_new_incarnation(run):
    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            await cluster.wait_for_liveness_convergence()
            old = cluster.silos[1]
            old_addr = old.address
            new = await cluster.restart_silo(old)
            assert new.address.matches(old_addr)          # same endpoint
            assert new.address.generation > old_addr.generation
            await cluster.wait_for_liveness_convergence()
            for s in cluster.silos:
                assert old_addr not in s.active_silos()
                assert new.address in s.active_silos() \
                    or s.address == new.address
        finally:
            await cluster.stop()

    run(main())


def test_silo_kills_itself_when_declared_dead(run):
    """A falsely-suspected silo must stop serving when it sees its own
    DEAD row — split-brain prevention (reference: MembershipOracle
    self-death on own DEAD entry)."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            await cluster.wait_for_liveness_convergence()
            victim = cluster.silos[1]
            # peers vote it dead behind its back (as after a long stall)
            await cluster.silos[0].membership_oracle.try_suspect_or_kill(
                victim.address)
            deadline = asyncio.get_running_loop().time() + 5
            from orleans_tpu.runtime.silo import SiloStatus
            while victim.status != SiloStatus.DEAD:
                assert asyncio.get_running_loop().time() < deadline, \
                    "victim kept running after being declared dead"
                await asyncio.sleep(0.05)
        finally:
            await cluster.stop()

    run(main())


def test_message_loss_injection_resend(run):
    """(reference: Dispatcher MessageLossInjectionRate) — in-proc fabric
    variant of the shared loss-injection scenario."""

    async def main():
        from tests.fixture_grains import assert_loss_injection_recovers

        cluster = await TestingCluster(n_silos=2).start()
        try:
            await cluster.wait_for_liveness_convergence()
            await assert_loss_injection_recovers(cluster, key_base=0,
                                                 n_grains=20, seed=7)
        finally:
            await cluster.stop()

    run(main())


def test_adaptive_cache_maintainer_refreshes_and_invalidates(run):
    """The adaptive directory-cache maintainer (reference:
    AdaptiveDirectoryCacheMaintainer.cs:34): hot cache lines validate
    against the directory owner in one batched RPC per owner — a
    still-registered entry refreshes (promote), a stale one (activation
    gone) drops before a message pays the wrong-silo forward hop."""

    async def main():
        cluster = await TestingCluster(n_silos=3).start()
        try:
            await cluster.wait_for_liveness_convergence()
            # activate grains through silo 2's client, then call them
            # through silo 0 so silo 0 fills directory-cache lines for
            # remotely-hosted, remotely-owned grains
            f2 = cluster.attach_client(2)
            f0 = cluster.attach_client(0)
            for i in range(40):
                await f2.get_grain(ICounterGrain, 900 + i).add(1)
            for i in range(40):
                await f0.get_grain(ICounterGrain, 900 + i).add(1)
            a = cluster.silos[0]
            cached = [g for g in list(a.grain_directory.cache._entries)]
            assert cached, "no cache lines formed on the calling silo"

            # touch the cached entries (hits feed the maintainer), then
            # run one maintenance round: all still valid → refreshed
            for g in cached:
                a.grain_directory.cache.get(g)
            m = a.cache_maintainer
            await m.run_round()
            assert m.refreshed >= len(cached), m.snapshot()
            assert m.invalidated == 0

            # make one entry stale: deactivate its activation (owner
            # partition unregisters) without telling silo 0
            victim = cached[0]
            host = next(s for s in cluster.silos
                        if s.catalog.directory.by_grain.get(victim))
            act = host.catalog.directory.by_grain[victim][0]
            host.catalog.schedule_deactivation(act)
            await asyncio.sleep(0.3)  # deactivation + unregister settle

            assert a.grain_directory.cache.get(victim) is not None
            await m.run_round()
            assert a.grain_directory.cache.get(victim) is None, \
                "stale cache line survived a maintenance round"
            assert m.invalidated >= 1
        finally:
            await cluster.stop()

    run(main())

def test_fast_suspect_converges_under_probe_interval(run):
    """Fast-suspect fan-out (membership satellite): a single non-quorum
    suspect vote gossips notify_suspected; recipients probe the victim
    OUT-OF-BAND and vote through the table themselves, reaching quorum
    within ~probe_timeout instead of waiting out another probe round.
    Regression pins the latency bound: probe loops and table refresh
    are parked far beyond the assertion window, so ONLY the fast path
    can produce the death declaration."""

    async def main():
        from orleans_tpu.config import SiloConfig

        def cfg(name):
            c = SiloConfig(name=name)
            # park the periodic paths OUTSIDE the assertion window —
            # convergence below can only come from the suspicion gossip
            c.liveness.probe_period = 30.0
            c.liveness.probe_timeout = 0.2
            c.liveness.num_missed_probes_limit = 2
            c.liveness.table_refresh_timeout = 0.5
            c.liveness.iam_alive_table_publish = 30.0
            return c

        cluster = await TestingCluster(n_silos=4,
                                       config_factory=cfg).start()
        try:
            await cluster.wait_for_liveness_convergence()
            victim = cluster.silos[3]
            cluster.kill_silo(victim)
            loop = asyncio.get_running_loop()
            t0 = loop.time()
            # one survivor's probe loop notices first and casts ONE
            # suspect vote — quorum needs 2, and every OTHER probe loop
            # is parked for 30s: without the fast-suspect fan-out the
            # victim would stay active for a full probe round
            await cluster.silos[0].membership_oracle.try_suspect_or_kill(
                victim.address)
            deadline = t0 + 10.0
            while any(victim.address in s.active_silos()
                      for s in cluster.silos):
                assert loop.time() < deadline, \
                    "fast-suspect never converged"
                await asyncio.sleep(0.02)
            elapsed = loop.time() - t0
            bound = cfg("x").liveness.probe_period
            assert elapsed < bound, \
                f"detection took {elapsed:.2f}s — not faster than a " \
                f"probe round ({bound}s): fast-suspect path inert"
            assert elapsed < 3.0, f"detection took {elapsed:.2f}s"
        finally:
            await cluster.stop()

    run(main())


def test_warm_standby_promotes_on_primary_death(run):
    """Cluster-level failover: a standby silo tails the primary's
    snapshot store (log shipping over the durable plane), membership
    declares the killed primary DEAD, and the standby promotes —
    exact state at the acknowledged prefix, promotion recorded with
    the measured RTO, standby-lag metrics wired through."""

    async def main():
        import numpy as np

        import samples.banking as banking
        from orleans_tpu.dashboard import view_from_snapshots
        from orleans_tpu.tensor import MemorySnapshotStore

        backing = MemorySnapshotStore.shared_backing()

        def cfg(name):
            c = TestingCluster._default_config(name)
            c.standby_poll_period = 0.01
            return c

        def setup(silo):
            banking.register_banking_journal(silo.tensor_engine)
            if silo.name == "silo1":
                silo.tensor_engine.checkpointer.attach_store(
                    MemorySnapshotStore(backing))
                silo.tensor_engine.config.ckpt_full_every_ticks = 0
                silo.tensor_engine.config.journal_flush_every_ticks = 3
            else:
                silo.arm_standby(MemorySnapshotStore(backing),
                                 primary="silo1")

        # TWO silos: the standby survivor inherits the whole ring on
        # the primary's death, so the adopted range is not immediately
        # re-partitioned from under the promotion
        cluster = await TestingCluster(n_silos=2, config_factory=cfg,
                                       silo_setup=setup).start()
        try:
            await cluster.wait_for_liveness_convergence()
            primary, standby = cluster.silos[0], cluster.silos[1]
            eng = primary.tensor_engine
            # drive ONLY keys the primary's ring range owns (deposits,
            # no emits): the standby tails ONE primary's store, and
            # its failover contract covers that primary's range
            owned = np.array([k for k in range(240)
                              if eng.router.owns_key("AccountGrain",
                                                     k)],
                             dtype=np.int64)
            assert len(owned) >= 40, "degenerate ring split"
            rng = np.random.default_rng(11)
            drive = []
            for _ in range(14):
                keys = rng.choice(owned, 24, replace=False)
                amounts = rng.integers(1, 100, 24).astype(np.int32)
                drive.append((keys, amounts))
            for i, (keys, amounts) in enumerate(drive):
                eng.send_batch("AccountGrain", "deposit", keys,
                               {"amount": amounts})
                eng.run_tick()
                if i == 5:
                    # mid-drive anchor: promotion must fold-replay the
                    # sealed tail beyond this cut, not just adopt it
                    eng.checkpointer.checkpoint_full()
            # the poll loop tails the committed cut
            deadline = asyncio.get_running_loop().time() + 5
            while standby.standby.adopted_rows == 0:
                assert asyncio.get_running_loop().time() < deadline, \
                    "standby never adopted the primary's checkpoint"
                await asyncio.sleep(0.02)
            # lag gauge discipline: standby >= 0, non-standby -1, and
            # the cluster row lets real lag dominate the sentinel
            snaps = [primary.collect_metrics(),
                     standby.collect_metrics()]
            # gauges[name][labelkey] = {source: value}
            lag = [next(iter(next(iter(
                s["gauges"]["ckpt.standby_lag_ticks"].values()))
                .values())) for s in snaps]
            assert lag[0] == -1.0
            assert lag[1] >= 0.0
            du = view_from_snapshots(snaps)["cluster"]["durability"]
            assert du["standby_lag_ticks"] >= 0.0
            # acked horizon + hard kill in ONE synchronous step: the
            # primary's background tick loop seals segments on its
            # cadence, so any await between the read and the kill
            # could move the horizon under us
            site = eng.checkpointer.journal.sites[("AccountGrain",
                                                   "deposit")]
            acked = site.committed_lanes // 24
            cluster.kill_silo(primary)
            assert 0 < acked <= len(drive)
            oracle = {}
            for keys, amounts in drive[:acked]:
                for k, a in zip(keys.tolist(), amounts.tolist()):
                    oracle[k] = oracle.get(k, 0) + a
            # membership declares the primary DEAD and on_silo_dead
            # promotes the armed standby
            deadline = asyncio.get_running_loop().time() + 10
            while standby.last_promotion is None:
                assert asyncio.get_running_loop().time() < deadline, \
                    "standby never promoted"
                await asyncio.sleep(0.02)
            prom = standby.last_promotion
            assert prom["promoted"]
            assert prom["fence_epoch"] >= 1
            assert "silo1" in prom["for"]
            # zero acknowledged-write loss: every acked deposit is in
            # the promoted standby, bit-exact
            touched = np.array(sorted(oracle), dtype=np.int64)
            got = banking.read_accounts(standby.tensor_engine, touched)
            want = np.array([oracle[int(k)] for k in touched],
                            dtype=np.int64)
            assert np.array_equal(got["balance"].astype(np.int64),
                                  want)
        finally:
            await cluster.stop()

    run(main())
