"""Structured logging with error codes and bulk throttling.

Parity: reference TraceLogger (reference: src/Orleans/Logging/
TraceLogger.cs:44 — bulk-message throttling :90-102, per-code ErrorCode,
pluggable ILogConsumer sinks, app/runtime logger split).

Implemented over the stdlib ``logging`` module: each silo gets a named
logger; bulk throttling collapses repeated (code, level) pairs inside a
time window, matching the reference's BulkMessageLimit behavior.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Tuple

BULK_LIMIT = 5           # (reference: BulkMessageLimit default)
BULK_WINDOW = 60.0       # seconds (reference: BulkMessageInterval)


class TraceLogger:

    def __init__(self, name: str, level: int = logging.INFO) -> None:
        self._log = logging.getLogger(f"orleans_tpu.{name}")
        self._log.setLevel(level)
        self._bulk: Dict[Tuple[int, int], Tuple[float, int]] = {}

    def child(self, suffix: str) -> "TraceLogger":
        return TraceLogger(f"{self._log.name.removeprefix('orleans_tpu.')}."
                           f"{suffix}")

    def _throttled(self, level: int, code: int) -> bool:
        """(reference: TraceLogger bulk throttling :90-102)"""
        if code == 0:
            return False
        now = time.monotonic()
        start, count = self._bulk.get((level, code), (now, 0))
        if now - start > BULK_WINDOW:
            start, count = now, 0
        count += 1
        self._bulk[(level, code)] = (start, count)
        if count == BULK_LIMIT + 1:
            self._log.log(level, "[code %d] further messages suppressed for "
                          "%ds (bulk limit)", code, int(BULK_WINDOW))
        return count > BULK_LIMIT

    def _emit(self, level: int, msg: str, code: int, exc_info=None) -> None:
        if self._throttled(level, code):
            return
        if code:
            msg = f"[code {code}] {msg}"
        self._log.log(level, msg, exc_info=exc_info)

    def debug(self, msg: str, code: int = 0) -> None:
        self._emit(logging.DEBUG, msg, code)

    def info(self, msg: str, code: int = 0) -> None:
        self._emit(logging.INFO, msg, code)

    def warn(self, msg: str, code: int = 0, exc_info=None) -> None:
        self._emit(logging.WARNING, msg, code, exc_info)

    def error(self, msg: str, code: int = 0, exc_info=None) -> None:
        self._emit(logging.ERROR, msg, code, exc_info)
