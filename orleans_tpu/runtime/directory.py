"""Grain directory: the distributed grain→activation map.

Parity: reference LocalGrainDirectory (reference: src/OrleansRuntime/
GrainDirectory/LocalGrainDirectory.cs:34 — CalculateTargetSilo :439,
RegisterSingleActivationAsync :510), per-silo partition
(GrainDirectoryPartition.cs:186), remote access through the
RemoteGrainDirectory system target (RemoteGrainDirectory.cs:32), LRU/adaptive
caches (LRUBasedGrainDirectoryCache.cs:30, AdaptiveGrainDirectoryCache.cs:30)
with invalidations piggybacked on messages (InsideGrainClient.cs:298-308),
and partition handoff on silo death (GrainDirectoryHandoffManager.cs:40).

TPU-first collapse: for ring-placed grains (HashBasedPlacement — the
default here), *the directory IS the sharding map*: owner(grain) =
ring-owner(hash(grain)), and the activation lives on its owner, so lookup
is a pure local computation with no remote hop and no cache misses.  The
full DHT path below exists for the general case (random/load-based
placement, migrations, stateless workers) — exactly the "exception table"
the north star calls for.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from orleans_tpu.ids import ActivationAddress, GrainId, SiloAddress
from orleans_tpu.runtime.ring import VirtualBucketsRing


class GrainDirectoryCache:
    """LRU cache of remote directory entries
    (reference: LRUBasedGrainDirectoryCache.cs:30), with per-round hit
    tracking feeding the adaptive maintainer
    (reference: AdaptiveGrainDirectoryCache.cs:30 access counts)."""

    def __init__(self, max_size: int = 100_000):
        self.max_size = max_size
        self._entries: "OrderedDict[GrainId, ActivationAddress]" = OrderedDict()
        # hit tracking is OFF until a maintainer attaches (track_hits):
        # with the maintenance loop disabled nothing would ever drain
        # _hits, and an unbounded per-distinct-grain dict is a slow leak
        self.track_hits = False
        self._hits: Dict[GrainId, int] = {}

    def get(self, grain_id: GrainId) -> Optional[ActivationAddress]:
        addr = self._entries.get(grain_id)
        if addr is not None:
            self._entries.move_to_end(grain_id)
            if self.track_hits:
                self._hits[grain_id] = self._hits.get(grain_id, 0) + 1
        return addr

    def peek(self, grain_id: GrainId) -> Optional[ActivationAddress]:
        """Read without recording a hit or touching LRU order — the
        maintainer's own checks must not make entries self-sustainingly
        hot."""
        return self._entries.get(grain_id)

    def drain_hits(self) -> Dict[GrainId, int]:
        """Hit counts since the last drain (one maintenance round)."""
        hits, self._hits = self._hits, {}
        return hits

    def put(self, grain_id: GrainId, addr: ActivationAddress) -> None:
        self._entries[grain_id] = addr
        self._entries.move_to_end(grain_id)
        while len(self._entries) > self.max_size:
            self._entries.popitem(last=False)

    def invalidate(self, grain_id: GrainId) -> None:
        self._entries.pop(grain_id, None)
        self._hits.pop(grain_id, None)

    def invalidate_silo(self, silo: SiloAddress) -> None:
        dead = [g for g, a in self._entries.items() if a.silo == silo]
        for g in dead:
            del self._entries[g]
            self._hits.pop(g, None)


class GrainDirectoryPartition:
    """This silo's owned slice of the grain→activation map
    (reference: GrainDirectoryPartition.cs:186)."""

    def __init__(self) -> None:
        self.entries: Dict[GrainId, ActivationAddress] = {}

    def register_single(self, addr: ActivationAddress
                        ) -> ActivationAddress:
        """First writer wins; returns the winning registration
        (reference: GrainDirectoryPartition.AddSingleActivation)."""
        existing = self.entries.get(addr.grain)
        if existing is not None:
            return existing
        self.entries[addr.grain] = addr
        return addr

    def lookup(self, grain_id: GrainId) -> Optional[ActivationAddress]:
        return self.entries.get(grain_id)

    def remove(self, addr: ActivationAddress) -> None:
        existing = self.entries.get(addr.grain)
        if existing is not None and existing.activation == addr.activation:
            del self.entries[addr.grain]

    def remove_silo_entries(self, silo: SiloAddress) -> List[GrainId]:
        """Drop every activation hosted on a (dead) silo
        (reference: GrainDirectoryPartition.RemoveSiloEntries)."""
        dead = [g for g, a in self.entries.items() if a.silo == silo]
        for g in dead:
            del self.entries[g]
        return dead

    def items(self) -> List[Tuple[GrainId, ActivationAddress]]:
        return list(self.entries.items())

    def merge(self, entries: Dict[GrainId, ActivationAddress]) -> None:
        """Handoff merge from a dying/dead silo's partition
        (reference: GrainDirectoryHandoffManager.ProcessSiloRemoveEvent :141)."""
        for g, a in entries.items():
            self.entries.setdefault(g, a)

    def split_out(self, predicate) -> Dict[GrainId, ActivationAddress]:
        """Extract entries matching ``predicate(grain_id)`` (handoff split)."""
        out = {g: a for g, a in self.entries.items() if predicate(g)}
        for g in out:
            del self.entries[g]
        return out


class LocalGrainDirectory:
    """The per-silo directory service (reference: LocalGrainDirectory.cs:34).

    Remote partition access goes through the DIRECTORY_SERVICE system
    target on the owner silo via ``silo.system_rpc`` (reference:
    RemoteGrainDirectory.cs:32).
    """

    def __init__(self, silo) -> None:
        self.silo = silo
        self.ring: VirtualBucketsRing = silo.ring
        self.partition = GrainDirectoryPartition()
        self.cache = GrainDirectoryCache()
        self.lookups_local = 0
        self.lookups_remote = 0
        self._heal_task = None
        self._heal_requested = False

    # -- ownership ----------------------------------------------------------

    def owner_of(self, grain_id: GrainId) -> SiloAddress:
        """(reference: LocalGrainDirectory.CalculateTargetSilo :439)"""
        owner = self.ring.calculate_target_silo(grain_id)
        return owner if owner is not None else self.silo.address

    # -- registration -------------------------------------------------------

    async def register_single_activation(self, addr: ActivationAddress
                                         ) -> ActivationAddress:
        """Register, resolving the single-activation race: the returned
        address is the winner (may differ from ``addr``)
        (reference: RegisterSingleActivationAsync :510)."""
        owner = self.owner_of(addr.grain)
        if owner == self.silo.address:
            self.lookups_local += 1
            return self.partition.register_single(addr)
        self.lookups_remote += 1
        winner = await self.silo.system_rpc(
            owner, "directory", "remote_register_single", (addr,))
        if winner.silo != self.silo.address:
            self.cache.put(addr.grain, winner)
        return winner

    async def unregister(self, addr: ActivationAddress) -> None:
        owner = self.owner_of(addr.grain)
        self.cache.invalidate(addr.grain)
        if owner == self.silo.address:
            self.partition.remove(addr)
            return
        try:
            await self.silo.system_rpc(owner, "directory",
                                       "remote_unregister", (addr,))
        except Exception:
            pass  # owner unreachable → its partition dies with it

    # -- lookup (reference: Catalog FastLookup :1213 / FullLookup :1224) ----

    def try_local_lookup(self, grain_id: GrainId) -> Optional[ActivationAddress]:
        """Local partition, then cache — no remote traffic.  Cache lines
        pointing at silos not currently believed alive are dropped, not
        returned (a membership change may race the death-cleanup sweep)."""
        if self.ring.owns_hash(grain_id.ring_hash()):
            return self.partition.lookup(grain_id)
        addr = self.cache.get(grain_id)
        if addr is not None and not self.silo.is_silo_alive(addr.silo):
            self.cache.invalidate(grain_id)
            return None
        return addr

    async def full_lookup(self, grain_id: GrainId) -> Optional[ActivationAddress]:
        from orleans_tpu.runtime.runtime_client import (
            RejectionError,
            RequestTimeoutError,
        )
        from orleans_tpu.utils import FixedBackoff, execute_with_retries

        # owner is re-evaluated per attempt: a lookup racing a membership
        # change may first target a silo just declared dead; once the ring
        # heals the next attempt lands on the live owner (reference:
        # LocalGrainDirectory retry on ring change during lookup)
        async def attempt_lookup(attempt: int):
            owner = self.owner_of(grain_id)
            if owner == self.silo.address:
                self.lookups_local += 1
                return self.partition.lookup(grain_id)
            self.lookups_remote += 1
            addr = await self.silo.system_rpc(owner, "directory",
                                              "remote_lookup", (grain_id,))
            if addr is not None:
                self.cache.put(grain_id, addr)
            return addr

        return await execute_with_retries(
            attempt_lookup, max_retries=4,
            retry_filter=lambda exc, i: isinstance(
                exc, (RejectionError, RequestTimeoutError)),
            backoff=FixedBackoff(0.05))

    # -- invalidation -------------------------------------------------------

    def invalidate_cache_entry(self, addr: ActivationAddress) -> None:
        """(reference: InsideGrainClient.cs:298-308 piggybacked invalidations)"""
        self.cache.invalidate(addr.grain)

    # -- silo lifecycle reactions ------------------------------------------

    def on_silo_dead(self, silo: SiloAddress) -> None:
        """Drop dead-silo entries + cache lines
        (reference: LocalGrainDirectory.SiloStatusChangeNotification :390)."""
        self.partition.remove_silo_entries(silo)
        self.cache.invalidate_silo(silo)

    async def heal_after_ring_change(self) -> None:
        """Re-assert every local activation with its (possibly new)
        directory owner after membership changed.

        This plays the role of the reference's partition handoff
        (reference: GrainDirectoryHandoffManager.cs:40 — split to a
        joining silo, merge from a dead one): (1) prune partition entries
        for hash ranges this silo no longer owns (they are rebuilt at the
        new owner by the hosting silos' heals — the split half), then
        (2) re-register what this silo *hosts* with the current owners
        (the merge half).  If re-registration loses the single-activation
        race, the winner is verified to actually exist before the local
        activation is deactivated as a duplicate
        (reference: Catalog.cs:533-563 DuplicateActivationException)."""
        from orleans_tpu.runtime.activation import ActivationState

        # (1) prune ranges we no longer own — prevents stale entries from
        # resurrecting if ownership later reverts to us
        self.partition.split_out(
            lambda g: not self.ring.owns_hash(g.ring_hash()))

        # (2) re-assert hosted activations
        for act in self.silo.catalog.directory.all():
            if act.class_info.stateless_worker or act.grain_id.is_client:
                continue
            if act.state not in (ActivationState.VALID,
                                 ActivationState.ACTIVATING):
                continue
            try:
                winner = await self.register_single_activation(act.address)
                if winner.activation == act.activation_id:
                    continue
                # lost the race — verify the winner is real before killing
                # our activation (the entry may be stale)
                alive = False
                if self.silo.is_silo_alive(winner.silo):
                    try:
                        alive = await self.silo.system_rpc(
                            winner.silo, "catalog", "has_activation",
                            (winner,), timeout=2.0)
                    except Exception:
                        alive = False
                if alive:
                    self.silo.catalog.schedule_deactivation(act)
                else:
                    # stale winner: purge it and re-assert ourselves
                    await self.unregister(winner)
                    await self.register_single_activation(act.address)
            except Exception:
                continue

    def schedule_heal(self) -> None:
        """Coalesce ring-change storms into at most one in-flight heal
        (plus one queued re-run)."""
        import asyncio
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        self._heal_requested = True
        if self._heal_task is None or self._heal_task.done():
            self._heal_task = loop.create_task(self._heal_runner())

    async def _heal_runner(self) -> None:
        while self._heal_requested:
            self._heal_requested = False
            await self.heal_after_ring_change()


class AdaptiveDirectoryCacheMaintainer:
    """Background refresh/promote loop over the directory cache's HOT
    entries (reference: AdaptiveDirectoryCacheMaintainer.cs:34 — the
    reference periodically revalidates cached entries by access count;
    stale ones drop before a message pays a wrong-silo forward hop).

    Each round: take the entries hit since the last round, batch them by
    DIRECTORY OWNER, validate each batch in one system-RPC
    (remote_lookup_batch), re-put still-valid entries (refreshing their
    LRU position — promotion) and invalidate moved/gone ones.  The
    device-mirror fast path makes this mostly moot for vector traffic;
    host-path RPC to remote grains is what benefits."""

    def __init__(self, directory: LocalGrainDirectory,
                 period: float = 5.0, max_batch: int = 512) -> None:
        self.directory = directory
        directory.cache.track_hits = True  # drained by run_round
        self.period = period
        self.max_batch = max_batch
        self.rounds = 0
        self.refreshed = 0
        self.invalidated = 0
        self._task = None

    def start(self) -> None:
        from orleans_tpu.utils.async_utils import spawn_in_fresh_context
        self._task = spawn_in_fresh_context(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        import asyncio
        while True:
            await asyncio.sleep(self.period)
            try:
                await self.run_round()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — advisory maintenance only
                pass

    async def run_round(self) -> None:
        d = self.directory
        hits = d.cache.drain_hits()
        if not hits:
            return
        self.rounds += 1
        hot = sorted(hits, key=hits.get, reverse=True)[:self.max_batch]
        by_owner: Dict[SiloAddress, List[GrainId]] = {}
        for g in hot:
            if d.cache.peek(g) is None:  # peek: a get() would record a
                continue                 # hit and self-sustain the entry
            by_owner.setdefault(d.owner_of(g), []).append(g)
        for owner, ids in by_owner.items():
            if owner == d.silo.address:
                addrs = [d.partition.lookup(g) for g in ids]
            else:
                try:
                    addrs = await d.silo.system_rpc(
                        owner, "directory", "remote_lookup_batch", (ids,),
                        timeout=5.0)
                except Exception:  # noqa: BLE001 — owner unreachable:
                    continue       # membership handles it, not this loop
            for g, addr in zip(ids, addrs):
                if addr is None or not d.silo.is_silo_alive(addr.silo):
                    d.cache.invalidate(g)
                    self.invalidated += 1
                else:
                    d.cache.put(g, addr)  # refresh + promote
                    self.refreshed += 1

    def snapshot(self) -> Dict[str, int]:
        return {"rounds": self.rounds, "refreshed": self.refreshed,
                "invalidated": self.invalidated}


class RemoteGrainDirectory:
    """System-target facade exposing partition ops to other silos
    (reference: RemoteGrainDirectory.cs:32).  Registered on every silo under
    the well-known name 'directory'."""

    def __init__(self, directory: LocalGrainDirectory) -> None:
        self.directory = directory

    async def remote_register_single(self, addr: ActivationAddress
                                     ) -> ActivationAddress:
        return self.directory.partition.register_single(addr)

    async def remote_unregister(self, addr: ActivationAddress) -> None:
        self.directory.partition.remove(addr)

    async def remote_lookup(self, grain_id: GrainId
                            ) -> Optional[ActivationAddress]:
        return self.directory.partition.lookup(grain_id)

    async def remote_lookup_batch(self, grain_ids: List[GrainId]
                                  ) -> List[Optional[ActivationAddress]]:
        """One round-trip validates a whole hot set (the adaptive cache
        maintainer's refresh batch)."""
        return [self.directory.partition.lookup(g) for g in grain_ids]

    async def accept_handoff(self, entries: Dict[GrainId, ActivationAddress]
                             ) -> None:
        """(reference: GrainDirectoryHandoffManager merge :141)"""
        self.directory.partition.merge(entries)
