"""Structured logging with error codes and bulk throttling.

Parity: reference TraceLogger (reference: src/Orleans/Logging/
TraceLogger.cs:44 — bulk-message throttling :90-102, per-code ErrorCode,
pluggable ILogConsumer sinks, app/runtime logger split).

Implemented over the stdlib ``logging`` module: each silo gets a named
logger; bulk throttling collapses repeated (code, level) pairs inside a
time window, matching the reference's BulkMessageLimit behavior.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Tuple

BULK_LIMIT = 5           # (reference: BulkMessageLimit default)
BULK_WINDOW = 60.0       # seconds (reference: BulkMessageInterval)


class TraceLogger:

    def __init__(self, name: str, level: int = logging.INFO) -> None:
        self._log = logging.getLogger(f"orleans_tpu.{name}")
        self._log.setLevel(level)
        self._bulk: Dict[Tuple[int, int], Tuple[float, int]] = {}
        # instance knobs (module constants are the defaults) so tests and
        # chatty components can tighten the window without global effect
        self.bulk_limit = BULK_LIMIT
        self.bulk_window = BULK_WINDOW
        self._last_prune = time.monotonic()

    def child(self, suffix: str) -> "TraceLogger":
        return TraceLogger(f"{self._log.name.removeprefix('orleans_tpu.')}."
                           f"{suffix}")

    def _summarize(self, level: int, code: int, count: int) -> None:
        """Closing summary for an expired window: the suppressed-message
        count must not vanish with the window roll."""
        if count > self.bulk_limit:
            self._log.log(level, "[code %d] suppressed %d messages in the "
                          "last %ds bulk window", code,
                          count - self.bulk_limit, int(self.bulk_window))

    def _prune(self, now: float) -> None:
        """Drop (level, code) entries whose window expired — emitting
        their suppression summaries — so ``_bulk`` cannot grow without
        bound across a long-lived silo's error-code population.  Runs at
        most once per window."""
        if now - self._last_prune < self.bulk_window:
            return
        self._last_prune = now
        for key, (start, count) in list(self._bulk.items()):
            if now - start > self.bulk_window:
                self._summarize(key[0], key[1], count)
                del self._bulk[key]

    def _throttled(self, level: int, code: int) -> bool:
        """(reference: TraceLogger bulk throttling :90-102)"""
        if code == 0:
            return False
        now = time.monotonic()
        self._prune(now)
        start, count = self._bulk.get((level, code), (now, 0))
        if now - start > self.bulk_window:
            # window rolled for a still-active code: surface what the old
            # window swallowed before resetting the counter
            self._summarize(level, code, count)
            start, count = now, 0
        count += 1
        self._bulk[(level, code)] = (start, count)
        if count == self.bulk_limit + 1:
            self._log.log(level, "[code %d] further messages suppressed for "
                          "%ds (bulk limit)", code, int(self.bulk_window))
        return count > self.bulk_limit

    def _emit(self, level: int, msg: str, code: int, exc_info=None) -> None:
        if self._throttled(level, code):
            return
        if code:
            msg = f"[code {code}] {msg}"
        self._log.log(level, msg, exc_info=exc_info)

    def debug(self, msg: str, code: int = 0) -> None:
        self._emit(logging.DEBUG, msg, code)

    def info(self, msg: str, code: int = 0) -> None:
        self._emit(logging.INFO, msg, code)

    def warn(self, msg: str, code: int = 0, exc_info=None) -> None:
        self._emit(logging.WARNING, msg, code, exc_info)

    def error(self, msg: str, code: int = 0, exc_info=None) -> None:
        self._emit(logging.ERROR, msg, code, exc_info)
