"""GrainReference: the location-transparent typed proxy.

Parity: reference GrainReference + codegen'd subclasses
(reference: src/Orleans/Runtime/GrainReference.cs:38 — InvokeMethodAsync
:321 → InvokeMethod_Impl :350 → RuntimeClient.SendRequest; codegen:
GrainReferenceGenerator.cs:47).  Instead of generated subclasses, one
generic proxy resolves methods against the interface's method table at
attribute access; the binding to "the runtime I'm executing inside"
(reference: RuntimeClient.Current) is a contextvar set by whichever silo or
client is running the current task.
"""

from __future__ import annotations

import asyncio
import contextvars
from typing import Any, Optional

from orleans_tpu.core.grain import InterfaceInfo, get_interface
from orleans_tpu.ids import GrainId

_current_runtime: contextvars.ContextVar[Any] = \
    contextvars.ContextVar("orleans_current_runtime", default=None)


def bind_runtime(runtime) -> contextvars.Token:
    """Bind the ambient runtime client (reference: RuntimeClient.Current)."""
    return _current_runtime.set(runtime)


def current_runtime():
    rc = _current_runtime.get()
    if rc is None:
        raise RuntimeError(
            "no runtime bound: grain calls must run inside a silo turn or "
            "an attached client context (reference: GrainClient.Initialize)")
    return rc


class GrainReference:
    """Serializable, location-transparent handle to a grain."""

    __slots__ = ("grain_id", "interface_id", "_methods")

    def __init__(self, grain_id: GrainId, interface_id: int) -> None:
        object.__setattr__(self, "grain_id", grain_id)
        object.__setattr__(self, "interface_id", interface_id)
        # per-instance method-proxy cache: resolving the interface and
        # building the bound closure once per (reference, method) keeps
        # the steady-state call to one dict hit — the reference's
        # codegen'd subclasses got this for free, and at batched-RPC
        # rates the per-call closure build was measurable
        object.__setattr__(self, "_methods", {})

    @property
    def interface(self) -> InterfaceInfo:
        return get_interface(self.interface_id)

    def __getattr__(self, name: str):
        cached = self._methods.get(name)
        if cached is not None:
            return cached
        iface = get_interface(self.interface_id)
        minfo = iface.methods_by_name.get(name)
        if minfo is None:
            raise AttributeError(
                f"{iface.name} has no grain method {name!r}")

        def call(*args):
            rc = current_runtime()
            future = rc.send_request(self.grain_id, iface, minfo, args)
            if future is None:  # one-way: return an already-done awaitable
                f: asyncio.Future = asyncio.get_running_loop().create_future()
                f.set_result(None)
                return f
            return future

        call.__name__ = name
        self._methods[name] = call
        return call

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, GrainReference)
                and self.grain_id == other.grain_id
                and self.interface_id == other.interface_id)

    def __hash__(self) -> int:
        return hash((self.grain_id, self.interface_id))

    def __repr__(self) -> str:
        return f"GrainReference({self.interface.name}, {self.grain_id})"


def _register_codec() -> None:
    """Wire GrainReference into the codec (the reference serializes
    references as GrainId + interface id; GrainReference.cs serializer
    region)."""
    from orleans_tpu import codec as codec_mod

    def ser(mgr, obj: GrainReference, w, ctx) -> None:
        mgr._write(obj.grain_id, w, ctx)
        w.varint(obj.interface_id)

    def deser(mgr, r, ctx) -> GrainReference:
        grain_id = mgr._read(r, ctx)
        interface_id = r.varint()
        return GrainReference(grain_id, interface_id)

    codec_mod.default_manager.register(
        GrainReference, name="orleans.GrainReference",
        serializer=ser, deserializer=deser,
        deep_copier=lambda ref: ref)  # references are immutable


_register_codec()
