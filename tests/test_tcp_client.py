"""Out-of-cluster clients over real TCP gateway sockets.

VERDICT-era gap: clients could only attach in-process.  Here GrainClient
dials a gateway silo's dedicated client port (the ProxyGatewayEndpoint
analog), handshakes, and runs RPC + observers over the socket — the
reference's GatewayConnection/ProxiedMessageCenter path (reference:
Gateway.cs:37, GatewayAcceptor.cs:32, ProxiedMessageCenter.cs:82,
GatewayManager.cs:41).
"""

import asyncio

import pytest

from orleans_tpu.client import GrainClient
from orleans_tpu.testing import TestingCluster

from tests.fixture_grains import ICounterGrain, IFailingGrain


def _gateway_endpoint(silo):
    return (silo.address.host, silo.gateway_port)


def test_tcp_client_rpc_roundtrip(run):
    """Requests, responses, errors and one-ways over the client socket."""

    async def main():
        cluster = await TestingCluster(n_silos=2, transport="tcp").start()
        try:
            await cluster.wait_for_liveness_convergence()
            assert cluster.silos[0].gateway_port > 0
            client = await GrainClient().connect(
                _gateway_endpoint(cluster.silos[0]))
            try:
                ref = client.get_grain(ICounterGrain, 8800)
                assert await ref.add(5) == 5
                assert await ref.add(2) == 7

                # errors propagate over the socket
                bad = client.get_grain(IFailingGrain, 8801)
                with pytest.raises(ValueError, match="kaboom"):
                    await bad.boom()

                # grains placed on the NON-gateway-connected silo still
                # answer (gateway routes into the cluster)
                refs = [client.get_grain(ICounterGrain, 8810 + i)
                        for i in range(10)]
                results = await asyncio.gather(*(r.add(1) for r in refs))
                assert results == [1] * 10
                placed = [len(s.catalog.directory) for s in cluster.silos]
                assert all(p > 0 for p in placed), placed
            finally:
                await client.close()
        finally:
            await cluster.stop()

    run(main())


def test_tcp_client_gateway_pool_failover(run):
    """Two gateway sockets; killing one leaves the pool serving through
    the survivor (reference: GatewayManager.GetLiveGateways skips dead
    gateways)."""

    async def main():
        cluster = await TestingCluster(n_silos=3, transport="tcp").start()
        try:
            await cluster.wait_for_liveness_convergence()
            client = await GrainClient().connect(
                _gateway_endpoint(cluster.silos[0]),
                _gateway_endpoint(cluster.silos[1]))
            try:
                refs = [client.get_grain(ICounterGrain, 8900 + i)
                        for i in range(6)]
                await asyncio.gather(*(r.add(1) for r in refs))

                victim = cluster.silos[0]
                cluster.kill_silo(victim)
                await cluster.wait_for_liveness_convergence(timeout=15.0)
                # event-driven death detection: the dead gateway's pump
                # exits on connection loss and sets its `closed` event —
                # no alive-polling loop racing the socket teardown (the
                # sleep/race recipe the PR 3 batch-edge fix replaced)
                await asyncio.wait_for(
                    asyncio.wait([asyncio.ensure_future(g.closed.wait())
                                  for g in client._gateways],
                                 return_when=asyncio.FIRST_COMPLETED),
                    timeout=10.0)
                assert not all(g.alive for g in client._gateways)

                # event-driven convergence instead of a one-shot gather
                # racing the survivors' directory heal: grains placed on
                # (or directory-owned by) the dead silo re-place/re-route
                # asynchronously after the kill, so each reference is
                # retried until its call lands — the assertion (all 6
                # callable through the surviving gateway) is unchanged,
                # only the wait is no longer a race
                deadline = asyncio.get_running_loop().time() + 30
                pending = dict(enumerate(refs))
                while pending:
                    results = await asyncio.gather(
                        *(r.add(1) for r in pending.values()),
                        return_exceptions=True)
                    for i, res in zip(list(pending), results):
                        if isinstance(res, int):
                            del pending[i]
                    if pending:
                        assert asyncio.get_running_loop().time() \
                            < deadline, f"still failing: {results}"
                        await asyncio.sleep(0.1)
            finally:
                await client.close()
        finally:
            await cluster.stop()

    run(main())


def test_tcp_client_observers(run):
    """Observer objects on the client receive grain-initiated calls over
    the socket (reference: CreateObjectReference + Gateway reply path)."""

    async def main():
        from orleans_tpu import Grain, grain_interface, one_way
        from orleans_tpu.core.grain import grain_class

        @grain_interface
        class ITcpNotifier:
            @one_way
            async def notify(self, value: int): ...

        @grain_interface
        class ITcpPublisher:
            async def subscribe(self, observer) -> None: ...
            async def publish(self, value: int) -> None: ...

        @grain_class
        class TcpPublisherGrain(Grain, ITcpPublisher):
            def __init__(self):
                self.observers = []

            async def subscribe(self, observer):
                self.observers.append(observer)

            async def publish(self, value):
                for obs in self.observers:
                    await obs.notify(value)

        cluster = await TestingCluster(n_silos=2, transport="tcp").start()
        try:
            await cluster.wait_for_liveness_convergence()
            client = await GrainClient().connect(
                _gateway_endpoint(cluster.silos[0]))
            try:
                got = []

                class Obs:
                    async def notify(self, value):
                        got.append(value)

                obs_ref = await client.create_object_reference(
                    ITcpNotifier, Obs())
                pub = client.get_grain(ITcpPublisher, 42)
                await pub.subscribe(obs_ref)
                await pub.publish(11)
                await pub.publish(22)
                deadline = asyncio.get_running_loop().time() + 5
                while len(got) < 2:
                    assert asyncio.get_running_loop().time() < deadline
                    await asyncio.sleep(0.02)
                assert got == [11, 22]
            finally:
                await client.close()
        finally:
            await cluster.stop()

    run(main())


def test_gateway_endpoints_advertised_in_membership(run):
    """The membership table advertises the CLIENT port (not the
    silo-to-silo port), so list providers hand clients dialable
    endpoints (reference: ProxyPort in the membership row)."""

    async def main():
        from orleans_tpu.plugins.gateway_list import (
            MembershipGatewayListProvider,
        )

        cluster = await TestingCluster(n_silos=2, transport="tcp").start()
        try:
            await cluster.wait_for_liveness_convergence()
            provider = MembershipGatewayListProvider(cluster.table)
            eps = await provider.get_gateway_endpoints()
            expected = {(s.address.host, s.gateway_port)
                        for s in cluster.silos}
            assert set(eps) == expected
            # and a client can connect via a discovered endpoint
            client = await GrainClient().connect(eps[0])
            try:
                assert await client.get_grain(ICounterGrain, 8950).add(1) == 1
            finally:
                await client.close()
        finally:
            await cluster.stop()

    run(main())


def test_tcp_client_batch_edge(run):
    """The batched client edge: a TCP client ships 10k-key presence
    batches as ONE gateway frame each; the gateway routes them through
    the vector plane — ZERO vector traffic on the per-message path
    (north star: 'batched adjacency+payload tensors' from the client;
    reference edge: Gateway.cs:37 proxies one message per call)."""

    async def main():
        import numpy as np
        import samples.presence  # registers PresenceGrain/GameGrain
        from tests.test_cross_silo_presence import relaxed_liveness

        cluster = await TestingCluster(
            n_silos=2, transport="tcp",
            config_factory=relaxed_liveness).start()
        try:
            await cluster.wait_for_liveness_convergence()
            client = await GrainClient().connect(
                _gateway_endpoint(cluster.silos[0]))
            try:
                turns_before = [s.metrics.snapshot().get("turns_executed", 0)
                                for s in cluster.silos]
                n = 10_000
                keys = np.arange(n, dtype=np.int64)
                games = (keys % 50).astype(np.int32)
                for t in range(3):
                    client.send_batch(
                        "PresenceGrain", "heartbeat", keys,
                        {"game": games,
                         "score": np.ones(n, np.float32),
                         "tick": np.full(n, t + 1, np.int32)})

                def totals():
                    """(heartbeats, updates) landed cluster-wide."""
                    hb = upd = 0
                    for silo in cluster.silos:
                        arenas = silo.tensor_engine.arenas
                        pa = arenas.get("PresenceGrain")
                        if pa is not None and len(pa.keys()):
                            rows, _ = pa.lookup_rows(pa.keys())
                            hb += int(np.asarray(
                                pa.state["heartbeats"])[rows].sum())
                        ga = arenas.get("GameGrain")
                        if ga is not None and len(ga.keys()):
                            rows, _ = ga.lookup_rows(ga.keys())
                            upd += int(np.asarray(
                                ga.state["updates"])[rows].sum())
                    return hb, upd

                # event-driven wait: the client's frames are STILL ON THE
                # SOCKET when send_batch returns, so an immediate quiesce
                # can observe a stable (empty) data plane before any slab
                # arrives and pass control to the assertions early — the
                # flake this test used to carry.  Wait for the expected
                # deliveries first, then quiesce to settle stragglers.
                deadline = asyncio.get_running_loop().time() + 60
                while totals() != (3 * n, 3 * n):
                    assert asyncio.get_running_loop().time() < deadline, \
                        f"only {totals()} of {(3 * n, 3 * n)} landed"
                    for silo in cluster.silos:
                        await silo.tensor_engine.flush()
                    await asyncio.sleep(0.02)
                await cluster.quiesce_engines()

                # exactness: every heartbeat landed exactly once (the wait
                # above proves >=; quiesce + re-check proves ==)
                assert totals() == (3 * n, 3 * n)

                # the per-message path carried NO vector traffic: no
                # grain turns were executed anywhere for these batches
                turns_after = [s.metrics.snapshot().get("turns_executed", 0)
                               for s in cluster.silos]
                assert turns_after == turns_before

                # want_results: one slab out, one result slab back, in
                # caller key order
                fut = client.send_batch(
                    "PresenceGrain", "heartbeat", keys[:64],
                    {"game": games[:64],
                     "score": np.ones(64, np.float32),
                     "tick": np.full(64, 9, np.int32)},
                    want_results=True)
                await asyncio.wait_for(fut, timeout=30)
            finally:
                await client.close()
        finally:
            await cluster.stop()

    run(main())


def test_tcp_client_wide_key_batch_edge_throughput(run):
    """Wide (64-bit hashed-identity) slabs over the TCP batch edge,
    MEASURED against the narrow-key edge on the same cluster (VERDICT r4
    next-#8: numbers, not just exactness).  Wide sources resolve by
    int64 host lookup and their emits ride the two-level wide device
    mirror, so parity with narrow is not expected — the stated bound is
    wide >= narrow/4, guarding unbounded regression."""

    async def main():
        import time

        import numpy as np
        import samples.presence  # registers PresenceGrain/GameGrain
        from samples.presence_wide import (  # registers wide types
            WideGame,  # noqa: F401
            WidePresence,  # noqa: F401
            wide_game_keys,
        )
        from tests.test_cross_silo_presence import relaxed_liveness

        cluster = await TestingCluster(
            n_silos=1, transport="tcp",
            config_factory=relaxed_liveness).start()
        try:
            await cluster.wait_for_liveness_convergence()
            silo = cluster.silos[0]
            client = await GrainClient().connect(_gateway_endpoint(silo))
            try:
                n, rounds = 50_000, 10
                # narrow edge: int player keys, int game keys
                nkeys = np.arange(n, dtype=np.int64)
                games = (nkeys % 100).astype(np.int32)

                async def narrow_rounds():
                    for t in range(rounds):
                        client.send_batch(
                            "PresenceGrain", "heartbeat", nkeys,
                            {"game": games,
                             "score": np.ones(n, np.float32),
                             "tick": np.full(n, t + 1, np.int32)})
                    await cluster.quiesce_engines()

                # wide edge: 64-bit hashed player identities, wide game
                # destinations as (hi, lo) word pairs
                wkeys = (np.arange(n, dtype=np.int64) * 2654435761
                         + 7) | (np.int64(1) << 40)
                wg = wide_game_keys(100)
                dst = wg[np.arange(n) % 100]
                ghi = (dst >> 32).astype(np.int32)
                glo = (dst & 0xFFFFFFFF).astype(np.int32)

                async def wide_rounds():
                    for t in range(rounds):
                        client.send_batch(
                            "WidePresence", "heartbeat", wkeys,
                            {"game_hi": ghi, "game_lo": glo,
                             "score": np.ones(n, np.float32)})
                    await cluster.quiesce_engines()

                await narrow_rounds()  # warm (activation + compiles)
                await wide_rounds()

                async def rate_of(fn):
                    # best of 2: each timed window carries 1M messages
                    # (well above the tunneled rig's ~100ms completion-
                    # observation floor) and a single rig hiccup cannot
                    # fail the comparison
                    best = 0.0
                    for _ in range(2):
                        t0 = time.perf_counter()
                        await fn()
                        best = max(best, 2 * n * rounds
                                   / (time.perf_counter() - t0))
                    return best

                narrow_rate = await rate_of(narrow_rounds)
                wide_rate = await rate_of(wide_rounds)

                # exactness across warm + 2 timed passes: every
                # heartbeat landed
                wa = silo.tensor_engine.arena_for("WidePresence")
                rows, found = wa.lookup_rows(wkeys)
                assert found.all()
                hb = np.asarray(wa.state["heartbeats"])[rows]
                np.testing.assert_array_equal(hb, 3 * rounds)
                ga = silo.tensor_engine.arena_for("WideGame")
                grows, gfound = ga.lookup_rows(wg)
                assert gfound.all()
                upd = np.asarray(ga.state["updates"])[grows]
                assert int(upd.sum()) == 3 * rounds * n

                # regression guard, not a perf claim: the on-device
                # >=1/2-of-narrow criterion lives in test_wide_keys.py;
                # this full-pipeline ratio rides machine load during a
                # suite run, so the bound is slack
                assert wide_rate >= narrow_rate / 6.0, \
                    f"wide edge {wide_rate:,.0f} msg/s vs narrow " \
                    f"{narrow_rate:,.0f} msg/s (bound: >= narrow/6)"
            finally:
                await client.close()
        finally:
            await cluster.stop()

    run(main())
