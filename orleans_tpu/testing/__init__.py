from orleans_tpu.testing.cluster import TestingCluster

__all__ = ["TestingCluster"]
