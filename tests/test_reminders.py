"""Reminder service tests (reference analog: Tester/ReminderTest/*,
TesterInternal reminder suites)."""

from __future__ import annotations

import asyncio

import pytest

from orleans_tpu import Grain, grain_interface
from orleans_tpu.core.grain import grain_class
from orleans_tpu.ids import GrainId
from orleans_tpu.runtime.reminders import (
    GrainBasedReminderTable,
    InMemoryReminderTable,
    IRemindable,
    MockReminderTable,
    ReminderEntry,
)
from orleans_tpu.runtime.silo import Silo
from orleans_tpu.testing.cluster import TestingCluster


@grain_interface
class IReminderTarget(IRemindable):
    async def get_ticks(self) -> list: ...
    async def arm(self, name: str, due: float, period: float): ...
    async def disarm(self, name: str): ...


@grain_class
class ReminderTargetGrain(Grain, IReminderTarget):
    def __init__(self) -> None:
        self.ticks = []

    async def receive_reminder(self, reminder_name, status):
        self.ticks.append((reminder_name, status.current_tick_time))

    async def get_ticks(self):
        return list(self.ticks)

    async def arm(self, name, due, period):
        await self.register_reminder(name, due, period)

    async def disarm(self, name):
        await self.unregister_reminder(name)


# ---------------------------------------------------------------------------
# table contract (reference: MembershipTablePluginTests-style contract suite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("make", [
    InMemoryReminderTable,
    lambda: MockReminderTable(delay=0.005),
])
def test_reminder_table_contract(run, make):
    async def go():
        table = make()
        gid = GrainId.from_int(1234, 42)
        assert await table.read_row(gid, "r1") is None
        etag = await table.upsert_row(
            ReminderEntry(grain_id=gid, name="r1", start_at=1.0, period=2.0))
        row = await table.read_row(gid, "r1")
        assert row.etag == etag and row.period == 2.0
        # upsert bumps etag
        etag2 = await table.upsert_row(
            ReminderEntry(grain_id=gid, name="r1", start_at=1.0, period=3.0))
        assert etag2 != etag
        # remove with stale etag fails, with fresh etag succeeds
        assert not await table.remove_row(gid, "r1", etag)
        assert await table.remove_row(gid, "r1", etag2)
        assert await table.read_rows(gid) == []

    run(go())


def test_grain_based_reminder_table(run):
    async def go():
        silo = Silo(name="rt")
        await silo.start()
        try:
            table = GrainBasedReminderTable(silo)
            gid = GrainId.from_int(99, 7)
            etag = await table.upsert_row(ReminderEntry(
                grain_id=gid, name="x", start_at=0.0, period=1.0))
            row = await table.read_row(gid, "x")
            assert row is not None and row.etag == etag
            assert await table.remove_row(gid, "x", etag)
        finally:
            await silo.stop(graceful=False)

    run(go())


# ---------------------------------------------------------------------------
# service behavior
# ---------------------------------------------------------------------------

def test_reminder_fires_periodically_and_unregisters(run):
    async def go():
        silo = Silo(name="rem1")
        await silo.start()
        try:
            factory = silo.attach_client()
            ref = factory.get_grain(IReminderTarget, 1)
            await ref.arm("beat", 0.05, 0.05)
            await asyncio.sleep(0.30)
            ticks = await ref.get_ticks()
            assert len(ticks) >= 3, ticks
            assert all(n == "beat" for n, _ in ticks)
            # periodic schedule is phase-locked to start_at + k*period
            times = [t for _, t in ticks]
            deltas = [round(b - a, 3) for a, b in zip(times, times[1:])]
            assert all(abs(d - 0.05) < 1e-6 for d in deltas), deltas

            await ref.disarm("beat")
            n = len(await ref.get_ticks())
            await asyncio.sleep(0.2)
            assert len(await ref.get_ticks()) == n  # no more ticks
        finally:
            await silo.stop(graceful=False)

    run(go())


def test_one_shot_reminder_removes_itself(run):
    async def go():
        silo = Silo(name="rem2")
        await silo.start()
        try:
            factory = silo.attach_client()
            ref = factory.get_grain(IReminderTarget, 2)
            await ref.arm("once", 0.05, 0.0)
            await asyncio.sleep(0.2)
            ticks = await ref.get_ticks()
            assert len(ticks) == 1
            # row is gone from the table
            gid = ref.grain_id
            reg = await silo.reminder_service.get_reminder(gid, "once")
            assert reg is None
        finally:
            await silo.stop(graceful=False)

    run(go())


def test_reminder_survives_deactivation(run):
    """The defining property vs timers: reminders outlive the activation
    (reference: reminders fire on deactivated grains, re-activating them)."""

    async def go():
        silo = Silo(name="rem3")
        await silo.start()
        try:
            factory = silo.attach_client()
            ref = factory.get_grain(IReminderTarget, 3)
            await ref.arm("beat", 0.05, 0.08)
            # force-deactivate the activation
            acts = silo.catalog.directory.by_grain.get(ref.grain_id)
            await silo.catalog._deactivate(acts[0])
            assert silo.catalog.directory.by_grain.get(ref.grain_id) in \
                (None, [])
            await asyncio.sleep(0.25)
            # a tick re-activated the grain (fresh instance ⇒ fresh tick
            # list, but at least one tick recorded)
            ticks = await ref.get_ticks()
            assert len(ticks) >= 1
        finally:
            await silo.stop(graceful=False)

    run(go())


def test_cluster_without_explicit_table_shares_rows_via_grain(run):
    """Silos joined by a fabric but given no reminder table must default to
    the grain-backed shared table — a private per-silo table would strand
    reminders whose ring owner differs from the registering silo."""

    async def go():
        from orleans_tpu.runtime.membership import InMemoryMembershipTable
        from orleans_tpu.runtime.reminders import GrainBasedReminderTable
        from orleans_tpu.runtime.transport import InProcTransport

        fabric = InProcTransport()
        table = InMemoryMembershipTable()
        silos = []
        for i in range(3):
            cfg = TestingCluster._default_config(f"g{i}")
            cfg.reminders.refresh_period = 0.2
            s = Silo(config=cfg, fabric=fabric, membership_table=table)
            assert isinstance(s.reminder_service.table,
                              GrainBasedReminderTable)
            await s.start()
            silos.append(s)
        try:
            factory = silos[0].attach_client()
            # several keys → at least one whose ring owner isn't silo 0
            refs = [factory.get_grain(IReminderTarget, 1000 + i)
                    for i in range(4)]
            for r in refs:
                await r.arm("beat", 0.05, 0.05)
            owners = {next(s.name for s in silos
                           if s.ring.owns_hash(r.grain_id.ring_hash()))
                      for r in refs}
            assert len(owners) > 1, "keys all landed on one silo; weak test"
            await asyncio.sleep(0.3)
            for r in refs:
                assert len(await r.get_ticks()) >= 3, \
                    f"reminder stranded for {r.grain_id}"
        finally:
            for s in reversed(silos):
                await s.stop(graceful=False)

    run(go())


def test_reminder_ownership_moves_on_silo_death(run):
    """Ring-range failover: kill the owner silo; the survivor's refresh
    adopts the reminder from the durable table (reference:
    LocalReminderService ring-range reacquisition, LivenessTests)."""

    async def go():
        def cfg(name):
            c = TestingCluster._default_config(name)
            c.reminders.refresh_period = 0.1
            return c

        cluster = TestingCluster(n_silos=3, config_factory=cfg)
        await cluster.start()
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(0)
            ref = factory.get_grain(IReminderTarget, 77)
            await ref.arm("beat", 0.05, 0.1)
            gid = ref.grain_id

            owner = next(s for s in cluster.silos
                         if s.ring.owns_hash(gid.ring_hash()))
            holders = [s for s in cluster.silos
                       if (gid, "beat") in s.reminder_service.local]
            assert holders == [owner]

            if owner is cluster.silos[0]:
                factory = cluster.attach_client(1)
                ref = factory.get_grain(IReminderTarget, 77)
            cluster.kill_silo(owner)
            await cluster.wait_for_liveness_convergence()

            # wait for a surviving silo to adopt it and deliver ticks
            async def adopted():
                while not any((gid, "beat") in s.reminder_service.local
                              for s in cluster.silos):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(adopted(), timeout=5.0)
            before = len(await ref.get_ticks())
            await asyncio.sleep(0.35)
            after = len(await ref.get_ticks())
            assert after > before
        finally:
            await cluster.stop()

    run(go())
