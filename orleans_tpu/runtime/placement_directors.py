"""Placement directors: execute per-class placement strategies.

Parity: reference PlacementDirectorsManager + per-strategy directors
(reference: src/OrleansRuntime/Placement/PlacementDirectorsManager.cs:32;
RandomPlacementDirector.cs; PreferLocalPlacementDirector.cs;
ActivationCountPlacementDirector.cs:35 — power-of-k choice :117 fed by
DeploymentLoadPublisher.cs:39; StatelessWorkerDirector.cs).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, Optional

from orleans_tpu.core.grain import registry as type_registry
from orleans_tpu.ids import ActivationAddress, GrainId, SiloAddress
from orleans_tpu.placement import (
    ActivationCountBasedPlacement,
    HashBasedPlacement,
    PlacementStrategy,
    PreferLocalPlacement,
    RandomPlacement,
    StatelessWorkerPlacement,
)
from orleans_tpu.runtime.messaging import Message


@dataclass
class PlacementResult:
    address: Optional[ActivationAddress] = None  # existing activation found
    silo: Optional[SiloAddress] = None           # new placement target


class PlacementDirectorsManager:

    def __init__(self, silo) -> None:
        self.silo = silo
        self._rng = random.Random(silo.address.ring_hash())
        # silo → activation count, fed by the load publisher
        # (reference: DeploymentLoadPublisher broadcasting silo stats)
        self.load_view: Dict[SiloAddress, int] = {}

    async def select_or_add_activation(self, grain_id: GrainId,
                                       msg: Message) -> PlacementResult:
        """(reference: PlacementDirectorsManager.SelectOrAddActivation,
        called from Dispatcher.AddressMessage :564)"""
        class_info = type_registry.by_type_code.get(grain_id.type_code)
        strategy: PlacementStrategy = class_info.placement if class_info \
            else HashBasedPlacement()

        if isinstance(strategy, StatelessWorkerPlacement):
            # stateless workers are always local, never in the directory
            # (reference: StatelessWorkerDirector.cs)
            return PlacementResult(silo=self.silo.address)

        # select: does an activation already exist anywhere?
        addr = await self.silo.grain_directory.full_lookup(grain_id)
        if addr is not None and self.silo.is_silo_alive(addr.silo):
            return PlacementResult(address=addr)

        # add: choose a silo for a new activation
        return PlacementResult(silo=self._choose_silo(strategy, grain_id))

    def _choose_silo(self, strategy: PlacementStrategy,
                     grain_id: GrainId) -> SiloAddress:
        members = self.silo.hosting_silos()
        if not members:
            return self.silo.address
        # "local" is only a valid answer when this silo hosts grains —
        # on a non-hosting observer (admin CLI) fall back to a stable
        # member choice instead
        local = self.silo.address if self.silo.address in members \
            else members[grain_id.ring_hash() % len(members)]
        if isinstance(strategy, HashBasedPlacement):
            owner = self.silo.grain_directory.owner_of(grain_id)
            return owner if owner in members else local
        if isinstance(strategy, RandomPlacement):
            return self._rng.choice(members)
        if isinstance(strategy, PreferLocalPlacement):
            return local
        if isinstance(strategy, ActivationCountBasedPlacement):
            # power-of-k-choices (reference:
            # ActivationCountPlacementDirector.SelectSiloPowerOfK :117)
            k = min(strategy.choose_out_of, len(members))
            candidates = self._rng.sample(members, k)
            return min(candidates, key=lambda s: self._load_of(s))
        return local

    def _load_of(self, silo: SiloAddress) -> int:
        if silo == self.silo.address:
            return len(self.silo.catalog.directory)
        return self.load_view.get(silo, 0)

    def update_load_view(self, silo: SiloAddress, activations: int) -> None:
        self.load_view[silo] = activations
