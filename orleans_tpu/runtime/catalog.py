"""Catalog: activation lifecycle + local activation directory + collector.

Parity: reference Catalog (reference: src/OrleansRuntime/Catalog/
Catalog.cs:43 — GetOrCreateActivation :411, InitActivation :487 with its
three stages directory-register → load-state → OnActivateAsync, failure
unwind :512-611, DeactivateActivations :836, destroy :945-1053),
ActivationDirectory (ActivationDirectory.cs:33) and the age-based
ActivationCollector (ActivationCollector.cs:37).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional

from orleans_tpu.core.grain import GrainClassInfo, registry as type_registry
from orleans_tpu.ids import ActivationAddress, ActivationId, GrainId
from orleans_tpu.runtime.activation import ActivationData, ActivationState


class DuplicateActivationError(Exception):
    """Lost the single-activation registration race; the winner's address
    is attached (reference: Catalog.cs:533-563 DuplicateActivationException
    handling — queued messages forward to the winner)."""

    def __init__(self, winner: ActivationAddress):
        super().__init__(f"duplicate activation; winner at {winner}")
        self.winner = winner


class ActivationDirectory:
    """Local ActivationId→ActivationData map + per-grain index
    (reference: ActivationDirectory.cs:33)."""

    def __init__(self) -> None:
        self.by_activation: Dict[ActivationId, ActivationData] = {}
        self.by_grain: Dict[GrainId, List[ActivationData]] = {}

    def record(self, act: ActivationData) -> None:
        self.by_activation[act.activation_id] = act
        self.by_grain.setdefault(act.grain_id, []).append(act)

    def remove(self, act: ActivationData) -> None:
        self.by_activation.pop(act.activation_id, None)
        lst = self.by_grain.get(act.grain_id)
        if lst is not None:
            try:
                lst.remove(act)
            except ValueError:
                pass
            if not lst:
                del self.by_grain[act.grain_id]

    def find_target(self, grain_id: GrainId,
                    activation_id: Optional[ActivationId]) -> Optional[ActivationData]:
        if activation_id is not None:
            act = self.by_activation.get(activation_id)
            if act is not None:
                return act
        lst = self.by_grain.get(grain_id)
        return lst[0] if lst else None

    def activations_of(self, grain_id: GrainId) -> List[ActivationData]:
        return list(self.by_grain.get(grain_id, ()))

    def __len__(self) -> int:
        return len(self.by_activation)

    def all(self) -> List[ActivationData]:
        return list(self.by_activation.values())


class Catalog:
    """Creates, initializes, collects, and destroys activations."""

    # Default age-out (reference: GlobalConfiguration
    # DefaultCollectionAgeLimit = 2h; shortened defaults live in config).
    DEFAULT_AGE_LIMIT = 2 * 3600.0

    def __init__(self, silo) -> None:
        self.silo = silo
        self.directory = ActivationDirectory()
        self.age_limit = self.DEFAULT_AGE_LIMIT
        self._pending_inits: Dict[ActivationId, asyncio.Future] = {}
        self._collector_task: Optional[asyncio.Task] = None
        self.deactivations_count = 0
        self.activations_count = 0
        self.migrations_count = 0
        # grains mid-migration (migrate_activation): local re-creation
        # holds until the move settles, else a message arriving between
        # the directory unregister and the target's registration would
        # re-activate the grain HERE and the target would lose the race
        self._migrations_pending: Dict[GrainId, asyncio.Future] = {}

    @property
    def runtime(self):
        return self.silo.runtime_client

    # -- creation (reference: Catalog.GetOrCreateActivation :411) -----------

    def get_activation(self, grain_id: GrainId,
                       activation_id: Optional[ActivationId] = None
                       ) -> Optional[ActivationData]:
        act = self.directory.find_target(grain_id, activation_id)
        if act is not None and act.state in (ActivationState.VALID,
                                             ActivationState.ACTIVATING):
            return act
        return None

    async def get_or_create_activation(self, grain_id: GrainId
                                       ) -> ActivationData:
        act = self.get_activation(grain_id)
        if act is not None:
            if act.state == ActivationState.ACTIVATING:
                await self.wait_for_init(act)
            return act
        # if a previous activation is mid-deactivation, let it finish so the
        # directory registration is released before we re-register
        # (reference: Catalog serializes destroy → re-create on one grain)
        for old in self.directory.activations_of(grain_id):
            if (old.state == ActivationState.DEACTIVATING
                    and old.deactivation_task is not None):
                await asyncio.shield(old.deactivation_task)
        pending = self._migrations_pending.get(grain_id)
        if pending is not None:
            # mid-migration: the new home registers between our
            # unregister and this create — wait for the move to settle,
            # then defer to wherever the directory says it landed
            await asyncio.shield(pending)
            addr = await self.silo.grain_directory.full_lookup(grain_id)
            if addr is not None and addr.silo != self.silo.address:
                raise DuplicateActivationError(addr)
        return await self.create_activation(grain_id)

    async def create_activation(self, grain_id: GrainId) -> ActivationData:
        class_info = type_registry.by_type_code.get(grain_id.type_code)
        if class_info is None:
            raise KeyError(f"no grain class registered for {grain_id}")
        act = ActivationData(grain_id, ActivationId.new(),
                             self.silo.address, class_info, self.runtime)
        act.max_enqueued = self.silo.config.messaging.max_enqueued_requests
        self.directory.record(act)
        init_done: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending_inits[act.activation_id] = init_done
        try:
            await self._init_activation(act)
            if not init_done.done():
                init_done.set_result(None)
            self.activations_count += 1
            return act
        except BaseException as exc:
            # failure unwind (reference: Catalog.cs:512-611): mark invalid,
            # unregister, let queued messages reroute.
            act.state = ActivationState.INVALID
            self.directory.remove(act)
            if not init_done.done():
                init_done.set_exception(exc)
                init_done.exception()  # mark retrieved
            raise
        finally:
            self._pending_inits.pop(act.activation_id, None)

    async def get_or_create_stateless_worker(self, grain_id: GrainId,
                                             class_info: GrainClassInfo
                                             ) -> ActivationData:
        """Pick an idle local replica or spin up a new one, up to the
        class's max_local (reference: StatelessWorkerDirector.cs local
        replica selection; [StatelessWorker] semantics)."""
        import os
        acts = [a for a in self.directory.activations_of(grain_id)
                if a.state in (ActivationState.VALID, ActivationState.ACTIVATING)]
        for a in acts:
            if not a.running and not a.waiting:
                return a
        max_local = class_info.placement.max_local
        if max_local <= 0:
            max_local = os.cpu_count() or 1
        if len(acts) < max_local:
            return await self.create_activation(grain_id)
        return min(acts, key=lambda a: len(a.waiting))

    async def wait_for_init(self, act: ActivationData) -> None:
        fut = self._pending_inits.get(act.activation_id)
        if fut is not None:
            await asyncio.shield(fut)

    async def _init_activation(self, act: ActivationData) -> None:
        """Three-stage init (reference: Catalog.InitActivation :487)."""
        act.state = ActivationState.ACTIVATING
        # stage 0: construct the grain instance
        # (reference: Catalog.CreateGrainInstance :622)
        instance = act.class_info.cls()
        instance._activation = act
        act.grain_instance = instance

        # stage 1: register in the grain directory (single-activation race:
        # the loser raises DuplicateActivationError and the dispatcher
        # forwards to the winner).
        if not act.class_info.stateless_worker and not act.grain_id.is_client:
            winner = await self.silo.grain_directory.register_single_activation(
                act.address)
            if winner.activation != act.activation_id:
                raise DuplicateActivationError(winner)

        # stage 2: load persistent state
        # (reference: Catalog.SetupActivationState :731)
        if act.class_info.storage_provider is not None or hasattr(
                instance, "_storage"):
            from orleans_tpu.runtime.storage import GrainStateStorageBridge
            provider = self.silo.storage_provider(act.class_info.storage_provider)
            bridge = GrainStateStorageBridge(
                grain_type=act.class_info.cls.__name__,
                grain_id=act.grain_id,
                provider=provider,
                initial_state=act.class_info.initial_state,
                recorder=self.silo.spans,  # storage IO as dependency spans
            )
            instance._storage = bridge
            if provider is not None:
                await bridge.read_state()

        # stage 3: user OnActivate (reference: Catalog.InvokeActivate)
        from orleans_tpu.core import context as grain_ctx
        from orleans_tpu.core.reference import _current_runtime, bind_runtime
        rt_token = bind_runtime(self.runtime)
        act_token = grain_ctx.set_current_activation(act)
        try:
            await act.run_closure_turn(instance.on_activate)
        finally:
            grain_ctx.reset_current_activation(act_token)
            _current_runtime.reset(rt_token)
        act.state = ActivationState.VALID
        act._pump()

    # -- deactivation (reference: Catalog.DeactivateActivations :836) -------

    def schedule_deactivation(self, act: ActivationData) -> None:
        if act.state != ActivationState.VALID:
            return
        act.state = ActivationState.DEACTIVATING
        act.deactivation_task = asyncio.get_running_loop().create_task(
            self._deactivate(act))

    async def _deactivate(self, act: ActivationData) -> None:
        self.deactivations_count += 1
        act.stop_timers()
        # wait for in-flight turns to finish
        while act.running:
            await asyncio.sleep(0.001)
        from orleans_tpu.core import context as grain_ctx
        from orleans_tpu.core.reference import _current_runtime, bind_runtime
        rt_token = bind_runtime(self.runtime)
        act_token = grain_ctx.set_current_activation(act)
        try:
            if act.grain_instance is not None:
                await act.grain_instance.on_deactivate()
        except Exception:
            if act.logger:
                act.logger.warn("on_deactivate failed", exc_info=True)
        finally:
            grain_ctx.reset_current_activation(act_token)
            _current_runtime.reset(rt_token)
        try:
            if not act.class_info.stateless_worker and not act.grain_id.is_client:
                await self.silo.grain_directory.unregister(act.address)
        except Exception:
            pass
        act.state = ActivationState.INVALID
        self.directory.remove(act)
        # live migration (migrate_activation): the new home activates
        # HERE — after the old registration is gone (its register_single
        # can win) and BEFORE the stragglers reroute (they then resolve
        # straight to the target instead of racing placement).  State
        # is persisted first so the target's activation read sees this
        # activation's final state — the handoff-fence ordering at
        # host-grain granularity.
        target = getattr(act, "migration_target", None)
        if target is not None:
            bridge = getattr(act.grain_instance, "_storage", None)
            try:
                if bridge is not None and bridge.provider is not None:
                    await bridge.write_state()
            except Exception:
                # surfaced, not swallowed: a silently-failed final
                # persist would hand the new home STALE storage state
                # with zero diagnostic.  The migration still proceeds —
                # the last successful persist is what any deactivation
                # path would have left behind anyway.
                self.silo.logger.warn(
                    f"migration of {act.grain_id}: final state persist "
                    f"failed — the new home reads the last successful "
                    f"write", code=2933)
            try:
                await self.silo.system_rpc(target, "catalog",
                                           "activate_grain",
                                           (act.grain_id,))
            except Exception:
                # stragglers fall back to ordinary placement
                self.silo.logger.warn(
                    f"migration of {act.grain_id}: proactive "
                    f"activation on {target} failed — next call "
                    f"re-places the grain", code=2934)
        for cb in act.on_destroyed:
            cb()
        # reroute any stragglers that queued during deactivation
        # (reference: Catalog destroy path rerouting :945-1053)
        while act.waiting:
            msg, _ = act.waiting.popleft()
            msg.target_activation = None
            self.silo.dispatcher.resend_message(msg)

    # -- live migration (deactivate-with-state-handoff → reactivate) --------

    async def migrate_activation(self, grain_id: GrainId,
                                 target_silo) -> bool:
        """Live migration of a host-path activation: deactivate here
        (through ``_deactivate``, which BUMPS ``deactivations_count`` —
        the host path's eviction epoch: the batched RPC plane's
        pre-resolved invoke tables key their (activation, bound-method)
        cache on it, so no coalesced window ever invokes the dead
        activation), persist the final state once every in-flight turn
        has drained, then proactively reactivate on ``target_silo`` so
        the next call re-resolves to the grain's new home instead of
        paying a fresh placement decision.  Returns True when the new
        home is registered."""
        if target_silo == self.silo.address:
            return False
        act = self.get_activation(grain_id)
        if act is None:
            return False
        # the hint _deactivate honors: persist state, then activate on
        # the target BETWEEN directory unregister and the straggler
        # reroute — queued/in-flight calls resolve straight to the new
        # home instead of racing a fresh placement decision
        act.migration_target = target_silo
        settled: asyncio.Future = \
            asyncio.get_running_loop().create_future()
        self._migrations_pending[grain_id] = settled
        try:
            self.schedule_deactivation(act)
            if act.deactivation_task is None:
                # not VALID (racing create/deactivate): nothing was
                # scheduled — clear the hint, or an unrelated
                # deactivation hours later would ship the grain to a
                # target no rebalance decision asked for
                act.migration_target = None
                return False
            await asyncio.shield(act.deactivation_task)
        finally:
            self._migrations_pending.pop(grain_id, None)
            settled.set_result(None)
        self.migrations_count += 1
        addr = await self.silo.grain_directory.full_lookup(grain_id)
        return addr is not None and addr.silo == target_silo

    async def deactivate_all(self) -> None:
        """Graceful shutdown: deactivate everything
        (reference: Catalog.DeactivateAllActivations via Silo.Terminate)."""
        tasks = []
        for act in self.directory.all():
            if act.state == ActivationState.VALID:
                self.schedule_deactivation(act)
            if act.deactivation_task is not None:
                tasks.append(act.deactivation_task)
        await asyncio.gather(*tasks, return_exceptions=True)

    # -- collector (reference: ActivationCollector.cs:37) -------------------

    def start_collector(self, quantum: float = 60.0) -> None:
        self._collector_task = asyncio.get_running_loop().create_task(
            self._collector_loop(quantum))

    def stop_collector(self) -> None:
        if self._collector_task is not None:
            self._collector_task.cancel()
            self._collector_task = None

    async def _collector_loop(self, quantum: float) -> None:
        try:
            while True:
                await asyncio.sleep(quantum)
                self.collect_idle_activations()
        except asyncio.CancelledError:
            pass

    def collect_idle_activations(self, age_limit: Optional[float] = None) -> int:
        """Age-out scan (reference: Catalog.OnTimer :225 →
        ActivationCollector time buckets)."""
        limit = age_limit if age_limit is not None else self.age_limit
        now = time.monotonic()
        n = 0
        for act in self.directory.all():
            cls_limit = getattr(act.class_info.cls, "__collection_age_limit__",
                                limit)
            if act.is_collectible(cls_limit, now):
                self.schedule_deactivation(act)
                n += 1
        return n
