"""Identity layer tests (reference analog: TesterInternal/Identifiertests.cs)."""

import uuid

from orleans_tpu.hashing import combine_hashes, jenkins_hash, stable_hash_u64
from orleans_tpu.ids import (
    ActivationAddress,
    ActivationId,
    GrainCategory,
    GrainId,
    SiloAddress,
)


def test_jenkins_hash_stable_and_spread():
    h1 = jenkins_hash(b"hello world")
    assert h1 == jenkins_hash(b"hello world")
    assert jenkins_hash(b"hello worlc") != h1
    # spread: 1000 sequential keys should hit many distinct high bytes
    buckets = {jenkins_hash(str(i).encode()) >> 24 for i in range(1000)}
    assert len(buckets) > 200


def test_stable_hash_u64():
    assert stable_hash_u64(42) == stable_hash_u64(42)
    assert stable_hash_u64(42) != stable_hash_u64(43)
    assert 0 <= stable_hash_u64(2**64 - 1) < 2**64
    assert combine_hashes(1, 2) != combine_hashes(2, 1)


def test_grain_id_interning_and_equality():
    a = GrainId.from_int(7, 123)
    b = GrainId.from_int(7, 123)
    assert a is b  # interned (reference: Interner.cs)
    assert a == b
    c = GrainId.from_int(7, 124)
    assert a != c
    assert a != GrainId.from_int(8, 123)


def test_grain_id_key_kinds():
    gi = GrainId.from_int(1, 99)
    assert gi.primary_key_int == 99
    u = uuid.uuid4()
    gg = GrainId.from_guid(1, u)
    assert gg.primary_key_guid == u
    gs = GrainId.from_string(1, "player/42")
    assert gs.primary_key_str == "player/42"
    assert gs.category == GrainCategory.KEY_EXT_GRAIN
    assert gs == GrainId.from_string(1, "player/42")
    assert gs != GrainId.from_string(1, "player/43")


def test_grain_id_packed_distinct():
    seen = {GrainId.from_int(5, k).packed() for k in range(10_000)}
    assert len(seen) == 10_000


def test_ring_hash_uniformity():
    # 8 equal-ish buckets over 10k grains
    counts = [0] * 8
    for k in range(10_000):
        h = GrainId.from_int(3, k).ring_hash()
        counts[h >> 29] += 1
    assert min(counts) > 800  # no empty/starved bucket


def test_silo_address():
    s1 = SiloAddress.new_local("hostA", 11111)
    s2 = SiloAddress.new_local("hostA", 11111)
    assert s1 != s2                      # generations differ
    assert s1.matches(s2)                # same endpoint
    assert s1.ring_hash() != s2.ring_hash()


def test_activation_address_roundtrip_str():
    silo = SiloAddress.new_local()
    grain = GrainId.from_int(2, 5)
    act = ActivationId.new()
    addr = ActivationAddress(silo, grain, act)
    assert str(grain) in str(addr)
