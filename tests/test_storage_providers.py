"""Storage provider contract suite run against every backend, plus
sharded-composite routing and event-sourced grains (reference analog:
Tester persistence provider tests + EventSourcingTests)."""

import pytest

from orleans_tpu.core.grain import Grain, grain_class, grain_interface
from orleans_tpu.event_sourcing import JournaledGrain, journaled_grain_class
from orleans_tpu.ids import GrainId
from orleans_tpu.providers.file_storage import FileStorage
from orleans_tpu.providers.memory_storage import MemoryStorage
from orleans_tpu.providers.sharded_storage import ShardedStorageProvider
from orleans_tpu.providers.sqlite_storage import SqliteStorage
from orleans_tpu.runtime.silo import Silo
from orleans_tpu.runtime.storage import GrainState, InconsistentStateError


def _providers(tmp_path):
    return {
        "memory": MemoryStorage(),
        "file": FileStorage(str(tmp_path / "files")),
        "sqlite": SqliteStorage(),
        "sharded": ShardedStorageProvider(
            [MemoryStorage(), MemoryStorage(), SqliteStorage()]),
    }


@pytest.mark.parametrize("kind", ["memory", "file", "sqlite", "sharded"])
def test_provider_contract(run, tmp_path, kind):
    """Shared contract: read-missing, write-new, reread, etag conflict,
    clear (the same suite shape as the reference's per-backend
    MembershipTablePluginTests pattern applied to storage)."""

    async def main():
        provider = _providers(tmp_path)[kind]
        gid = GrainId.from_int(0x1234, 42)
        s = GrainState()

        await provider.read_state("T", gid, s)
        assert not s.record_exists and s.etag is None

        s.data = {"n": 1, "items": [1, 2, 3]}
        await provider.write_state("T", gid, s)
        assert s.record_exists and s.etag is not None
        etag1 = s.etag

        s2 = GrainState()
        await provider.read_state("T", gid, s2)
        assert s2.record_exists and s2.data == {"n": 1, "items": [1, 2, 3]}
        assert s2.etag == etag1

        # stale-etag write must fail (etag discipline)
        stale = GrainState(data={"n": 99}, etag=None)
        with pytest.raises(InconsistentStateError):
            await provider.write_state("T", gid, stale)

        # fresh-etag write advances
        s2.data = {"n": 2}
        await provider.write_state("T", gid, s2)
        assert s2.etag != etag1

        # first writer after clear starts over
        await provider.clear_state("T", gid, s2)
        assert not s2.record_exists
        s3 = GrainState()
        await provider.read_state("T", gid, s3)
        assert not s3.record_exists

        # per-(type, id) isolation
        other = GrainId.from_int(0x1234, 43)
        so = GrainState(data="other")
        await provider.write_state("T", other, so)
        st = GrainState(data="typed")
        await provider.write_state("U", gid, st)
        back = GrainState()
        await provider.read_state("U", gid, back)
        assert back.data == "typed"
        await provider.close()

    run(main())


def test_file_storage_survives_reopen(run, tmp_path):
    async def main():
        gid = GrainId.from_int(0x77, 7)
        p1 = FileStorage(str(tmp_path / "dur"))
        s = GrainState(data={"balance": 100})
        await p1.write_state("Account", gid, s)
        # new provider instance over the same directory = process restart
        p2 = FileStorage(str(tmp_path / "dur"))
        s2 = GrainState()
        await p2.read_state("Account", gid, s2)
        assert s2.record_exists and s2.data == {"balance": 100}

    run(main())


def test_sqlite_storage_survives_reopen(run, tmp_path):
    async def main():
        db = str(tmp_path / "state.db")
        gid = GrainId.from_int(0x78, 8)
        p1 = SqliteStorage(db)
        s = GrainState(data=[1, 2, 3])
        await p1.write_state("G", gid, s)
        await p1.close()
        p2 = SqliteStorage(db)
        s2 = GrainState()
        await p2.read_state("G", gid, s2)
        assert s2.record_exists and s2.data == [1, 2, 3]
        await p2.close()

    run(main())


def test_sharded_routes_consistently(run, tmp_path):
    """The same grain always lands on the same child shard."""

    async def main():
        children = [MemoryStorage(), MemoryStorage()]
        sharded = ShardedStorageProvider(children)
        hits = []
        for i in range(40):
            gid = GrainId.from_int(0x55, i)
            s = GrainState(data=i)
            await sharded.write_state("G", gid, s)
        for child in children:
            hits.append(len(child._store))
        assert sum(hits) == 40
        assert all(h > 0 for h in hits)  # both shards used
        # reads resolve through the same routing
        for i in range(40):
            gid = GrainId.from_int(0x55, i)
            s = GrainState()
            await sharded.read_state("G", gid, s)
            assert s.data == i

    run(main())


def test_sharded_requires_two_children():
    with pytest.raises(ValueError):
        ShardedStorageProvider([MemoryStorage()])


# ---------------------------------------------------------------------------
# event sourcing (reference: JournaledGrain.cs:34)
# ---------------------------------------------------------------------------

class Deposited:
    def __init__(self, amount):
        self.amount = amount


class Withdrawn:
    def __init__(self, amount):
        self.amount = amount


@grain_interface
class IJournaledAccount:
    async def deposit(self, amount: float): ...
    async def withdraw(self, amount: float): ...
    async def balance(self) -> float: ...
    async def history_len(self) -> int: ...


@journaled_grain_class
class JournaledAccount(JournaledGrain, IJournaledAccount):
    def __init__(self):
        self.view_balance = 0.0

    def apply_Deposited(self, e):
        self.view_balance += e.amount

    def apply_Withdrawn(self, e):
        self.view_balance -= e.amount

    async def deposit(self, amount):
        await self.raise_event(Deposited(amount))

    async def withdraw(self, amount):
        await self.raise_event(Withdrawn(amount), commit=False)
        await self.commit()

    async def balance(self):
        return self.view_balance

    async def history_len(self):
        return len(self.events)


def test_journaled_grain_folds_and_survives_deactivation(run):
    async def main():
        silo = Silo(name="es", storage_providers={"Default": MemoryStorage()})
        await silo.start()
        try:
            f = silo.attach_client()
            acct = f.get_grain(IJournaledAccount, 900)
            await acct.deposit(100.0)
            await acct.deposit(50.0)
            await acct.withdraw(30.0)
            assert await acct.balance() == 120.0
            assert await acct.history_len() == 3

            # deactivate, then reactivate: view rebuilt by replay
            import asyncio
            for act in silo.catalog.directory.all():
                silo.catalog.schedule_deactivation(act)
            await asyncio.sleep(0.05)
            assert len(silo.catalog.directory) == 0
            assert await acct.balance() == 120.0
            assert await acct.history_len() == 3
        finally:
            await silo.stop(graceful=False)

    run(main())
