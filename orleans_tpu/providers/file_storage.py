"""JSON-file storage provider: one file per grain under a root directory.

Parity: the reference's sample file-based provider (reference:
Samples/StorageProviders/OrleansFileStorage.cs — grain state as a JSON
document per grain in a configured directory) with the etag discipline of
the table providers (reference: AzureTableStorage.cs:68): the stored etag
must match the caller's or the write fails with InconsistentStateError.

State payloads go through the framework codec, so anything a grain can
hold (pytrees, numpy arrays, ids) round-trips; the on-disk format is the
codec's binary with a small JSON sidecar header for the etag.
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
import uuid
from pathlib import Path
from typing import Optional

from orleans_tpu.codec import default_manager as codec
from orleans_tpu.hashing import jenkins_hash
from orleans_tpu.ids import GrainId
from orleans_tpu.runtime.storage import (
    GrainState,
    InconsistentStateError,
    StorageProvider,
)


class FileStorage(StorageProvider):

    def __init__(self, root: str) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, grain_type: str, grain_id: GrainId) -> Path:
        # full identity in the name (hash alone could collide and silently
        # cross-write two grains' state); hash only shortens long keys
        ident = f"{grain_type}/{grain_id}"
        safe = base64.urlsafe_b64encode(ident.encode()).decode().rstrip("=")
        if len(safe) > 120:
            safe = f"{safe[:100]}-{jenkins_hash(ident.encode()):08x}"
        return self.root / f"{safe}.json"

    async def read_state(self, grain_type: str, grain_id: GrainId,
                         state: GrainState) -> None:
        path = self._path(grain_type, grain_id)
        doc = await asyncio.to_thread(self._read_doc, path)
        if doc is None or doc.get("key") != str(grain_id):
            state.record_exists = False
            state.etag = None
            return
        state.data = codec.deserialize(base64.b64decode(doc["data"]))
        state.etag = doc["etag"]
        state.record_exists = True

    async def write_state(self, grain_type: str, grain_id: GrainId,
                          state: GrainState) -> None:
        path = self._path(grain_type, grain_id)
        doc = await asyncio.to_thread(self._read_doc, path)
        stored_etag = doc["etag"] if doc is not None \
            and doc.get("key") == str(grain_id) else None
        if stored_etag != state.etag:
            raise InconsistentStateError(stored_etag, state.etag)
        new_etag = uuid.uuid4().hex[:12]
        payload = {
            "key": str(grain_id),
            "grain_type": grain_type,
            "etag": new_etag,
            "data": base64.b64encode(codec.serialize(state.data)).decode(),
        }
        await asyncio.to_thread(self._write_doc, path, payload)
        state.etag = new_etag
        state.record_exists = True

    async def clear_state(self, grain_type: str, grain_id: GrainId,
                          state: GrainState) -> None:
        path = self._path(grain_type, grain_id)
        doc = await asyncio.to_thread(self._read_doc, path)
        stored_etag = doc["etag"] if doc is not None \
            and doc.get("key") == str(grain_id) else None
        if stored_etag != state.etag:
            raise InconsistentStateError(stored_etag, state.etag)
        await asyncio.to_thread(self._unlink, path)
        state.etag = None
        state.record_exists = False
        state.data = None

    # -- blocking file ops (run in a worker thread) -------------------------

    @staticmethod
    def _read_doc(path: Path) -> Optional[dict]:
        try:
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    @staticmethod
    def _write_doc(path: Path, doc: dict) -> None:
        tmp = path.with_suffix(f".tmp{os.getpid()}")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)  # atomic on POSIX

    @staticmethod
    def _unlink(path: Path) -> None:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
