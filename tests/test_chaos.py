"""Chaos plane tests: deterministic fault injection + invariant checkers.

The plan/interposer unit layer is timing-free (reproducibility is
asserted against a fixed event stream); the ``@pytest.mark.chaos`` smoke
suite runs real 2-3 silo scenarios — partition-heal, kill-during-handoff,
storage-flake — against the four cluster-wide invariant checkers.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from orleans_tpu.chaos import (
    ChaosCluster,
    ChaosInjectedError,
    FaultPlan,
    FaultTrace,
    Interposer,
    InvariantViolation,
    check_arena_conservation,
    check_at_least_once,
    check_single_activation,
    check_timer_conservation,
    wait_for_at_least_once,
)

from tests.fixture_grains import ICounterGrain


# ---------------------------------------------------------------------------
# determinism: same (seed, plan) + same event stream ⇒ identical trace
# ---------------------------------------------------------------------------

def _drive_storage_stream(seed: int, n_events: int = 200):
    """Pump a fixed sequence of storage writes through a fresh interposer
    and return the trace signature + fired pattern."""

    async def main():
        from orleans_tpu.providers.memory_storage import MemoryStorage
        from orleans_tpu.runtime.storage import GrainState

        plan = FaultPlan(seed=seed)
        plan.rule("flake", "storage", "fail", probability=0.3, after=5)
        plan.rule("molasses", "storage", "slow", probability=0.1,
                  delay=0.0)
        interposer = Interposer(plan, FaultTrace())
        provider = MemoryStorage()
        interposer.attach_storage(provider, "Default")
        outcomes = []
        for i in range(n_events):
            try:
                await provider.write_state("T", f"g{i}",
                                           GrainState(data=i))
                outcomes.append("ok")
            except ChaosInjectedError:
                outcomes.append("fail")
        return outcomes, interposer.counters["storage_failed"], \
            interposer.counters["storage_slowed"]

    return asyncio.run(main())


def test_seeded_plan_reproducible_and_seed_sensitive():
    """Same seed ⇒ identical fault sequence over the same event stream;
    different seed ⇒ a different one (the RNG is real, not constant)."""
    a1 = _drive_storage_stream(seed=42)
    a2 = _drive_storage_stream(seed=42)
    b = _drive_storage_stream(seed=43)
    assert a1 == a2
    assert a1[0] != b[0]
    # the probability/after gates actually gated
    assert a1[0][:5] == ["ok"] * 5      # after=5 skips the head
    assert a1[1] > 0 and a1[2] > 0      # both rules fired somewhere


def test_rule_count_and_match_gates():
    """count= caps firings; match= filters events; pinned rules carry
    their firings into the deterministic trace signature."""

    async def main():
        from orleans_tpu.providers.memory_storage import MemoryStorage
        from orleans_tpu.runtime.storage import GrainState

        plan = FaultPlan(seed=1)
        plan.rule("two-fails", "storage", "fail", count=2,
                  match=lambda ctx: ctx[0] == "Default")
        trace = FaultTrace()
        interposer = Interposer(plan, trace)
        default = MemoryStorage()
        other = MemoryStorage()
        interposer.attach_storage(default, "Default")
        interposer.attach_storage(other, "PubSubStore")
        fails = 0
        for i in range(6):
            # non-matching provider: never faulted
            await other.write_state("T", f"o{i}", GrainState(data=i))
            try:
                await default.write_state("T", f"g{i}", GrainState(data=i))
            except ChaosInjectedError:
                fails += 1
        assert fails == 2
        assert trace.signature() == (("rule", "two-fails", "fail", 0),
                                     ("rule", "two-fails", "fail", 1))
        # detach restores the original seam
        interposer.detach()
        await default.write_state("T", "after", GrainState(data=0))

    asyncio.run(main())


def test_membership_cas_conflict_injection():
    """The membership seam raises the table's own CasConflictError so the
    oracle's CAS retry discipline is what absorbs the fault."""

    async def main():
        from orleans_tpu.ids import SiloAddress
        from orleans_tpu.runtime.membership import (
            CasConflictError,
            InMemoryMembershipTable,
            MembershipEntry,
            SiloStatus,
        )

        table = InMemoryMembershipTable()
        addr = SiloAddress.new_local(host="cas-test", port=0)
        await table.insert_row(MembershipEntry(silo=addr,
                                               status=SiloStatus.JOINING), 0)
        plan = FaultPlan(seed=9)
        plan.rule("cas", "membership", "cas_conflict", count=1)
        interposer = Interposer(plan, FaultTrace())
        interposer.attach_membership_table(table)

        snapshot, version = await table.read_all()
        entry, etag = snapshot[addr]
        entry.status = SiloStatus.ACTIVE
        with pytest.raises(CasConflictError, match="chaos"):
            await table.update_row(entry, etag, version)
        # retry (the oracle's loop) goes through — count exhausted
        await table.update_row(entry, etag, version)
        snapshot, _ = await table.read_all()
        assert snapshot[addr][0].status == SiloStatus.ACTIVE

    asyncio.run(main())


def test_engine_corruption_is_seeded_and_copy_on_write():
    """corrupt_nan poisons a deterministic row subset of the slab args
    WITHOUT mutating the caller's arrays."""

    class FakeEngine:
        def __init__(self):
            self.sent = []

        def send_batch(self, interface, method, keys, args,
                       want_results=False):
            self.sent.append(args)

    def run(seed):
        plan = FaultPlan(seed=seed)
        plan.rule("nan", "engine", "corrupt_nan", count=1,
                  corrupt_fraction=0.25)
        engine = FakeEngine()
        interposer = Interposer(plan, FaultTrace())
        interposer.attach_engine(engine)
        keys = np.arange(32, dtype=np.int64)
        v = np.ones(32, np.float32)
        c = np.arange(32, dtype=np.int32)
        engine.send_batch("T", "m", keys, {"v": v, "c": c})
        assert not np.isnan(v).any()          # caller's array untouched
        sent = engine.sent[0]
        return np.nonzero(np.isnan(sent["v"]))[0].tolist(), sent["c"]

    rows1, c1 = run(5)
    rows2, _ = run(5)
    rows3, _ = run(6)
    assert rows1 and rows1 == rows2           # seeded: same rows
    assert rows1 != rows3                     # seed-sensitive
    np.testing.assert_array_equal(c1, np.arange(32))  # ints untouched


def test_at_least_once_checker():
    check_at_least_once([1, 2, 3], [3, 2, 1, 2])  # dup legal
    with pytest.raises(InvariantViolation, match="never delivered"):
        check_at_least_once([1, 2, 3], [1, 2])
    r = check_at_least_once([1, 2, 3], [1, 2], allowed_missing=1)
    assert r["missing"] == 1


# ---------------------------------------------------------------------------
# the @chaos smoke suite: real clusters under scripted faults
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_partition_heal_converges_and_serves(run):
    """Partition-heal smoke: isolate one silo of three long enough for a
    decisive outcome, heal, and require convergence + single activation +
    every grain still callable."""

    async def main():
        plan = FaultPlan(seed=77)
        plan.partition(0.05, [["silo1"], ["silo2", "silo3"]])
        plan.heal(1.2)
        cluster = await ChaosCluster(plan=plan, n_silos=3).start()
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(1)  # majority-side client
            refs = [factory.get_grain(ICounterGrain, 500 + i)
                    for i in range(15)]
            await asyncio.gather(*(r.add(1) for r in refs))

            await cluster.run_plan()

            report = await cluster.check_invariants(timeout=10.0)
            assert report["membership_convergence"]["ok"]
            # survivors serve every grain (dead-silo grains re-activate)
            factory = cluster.live_silos()[0].attach_client()
            values = await asyncio.gather(*(r.add(1) for r in refs))
            assert len(values) == 15
            check_single_activation(cluster)
            # the scripted faults really fired, in plan order
            sig = cluster.trace.signature()
            assert [s[2] for s in sig] == ["partition", "heal"]
        finally:
            await cluster.stop()

    run(main())


@pytest.mark.chaos
def test_chaos_kill_during_handoff_conserves_arena(run):
    """Kill-during-handoff smoke: hard-kill a silo right after a new one
    joins (ring reshuffle + handoff fence in flight) while vector slabs
    flow; population conservation + single activation must hold."""

    async def main():
        from orleans_tpu.chaos.report import define_chaos_counter
        define_chaos_counter()

        cluster = await ChaosCluster(plan=FaultPlan(seed=3),
                                     n_silos=2).start()
        try:
            await cluster.wait_for_liveness_convergence()
            keys = np.arange(96, dtype=np.int64)
            engine0 = cluster.silos[0].tensor_engine
            engine0.send_batch("ChaosCounter", "poke", keys,
                               {"v": np.ones(96, np.float32)})
            await cluster.quiesce_engines()

            # join → ring change → handoff fence arms; kill the newcomer
            # mid-window while more slabs flow
            newcomer = await cluster.start_additional_silo()
            engine0.send_batch("ChaosCounter", "poke", keys,
                               {"v": np.ones(96, np.float32)})
            cluster.kill_silo(newcomer)

            await cluster.wait_for_liveness_convergence()
            # re-touch so keys stranded on the corpse re-activate on the
            # survivors, then assert conservation
            engine0.send_batch("ChaosCounter", "poke", keys,
                               {"v": np.zeros(96, np.float32)})
            await check_arena_conservation(cluster, "ChaosCounter", keys)
            check_single_activation(cluster)
        finally:
            await cluster.stop()

    run(main())


@pytest.mark.chaos
def test_chaos_join_handoff_conserves_armed_timers(run):
    """Join-handoff smoke for the timers plane: arm a far-future timer on
    every resident key, then grow the cluster so the ring reshuffles and
    handoff migrates a slice of the arena — every timer must ride its
    state slab to exactly one wheel (none lost, none doubled)."""

    async def main():
        from orleans_tpu.chaos.report import define_chaos_counter
        define_chaos_counter()

        cluster = await ChaosCluster(plan=FaultPlan(seed=5),
                                     n_silos=2).start()
        try:
            await cluster.wait_for_liveness_convergence()
            keys = np.arange(64, dtype=np.int64)
            engine0 = cluster.silos[0].tensor_engine
            engine0.send_batch("ChaosCounter", "poke", keys,
                               {"v": np.ones(64, np.float32)})
            await cluster.quiesce_engines()

            # arm each key's timer on the silo where it is RESIDENT —
            # migration must then carry it wherever the key goes
            for silo in cluster.silos:
                eng = silo.tensor_engine
                arena = eng.arenas.get("ChaosCounter")
                resident = np.array(sorted(arena.keys()), np.int64) \
                    if arena is not None else np.array([], np.int64)
                if resident.size:
                    eng.timers.arm_batch(
                        "ChaosCounter", resident,
                        np.full(resident.size,
                                eng.tick_number + 10_000, np.int64),
                        0, "watch")

            await cluster.start_additional_silo()
            await cluster.wait_for_liveness_convergence()
            # traffic across the reshuffled ring drives the handoff
            engine0.send_batch("ChaosCounter", "poke", keys,
                               {"v": np.zeros(64, np.float32)})
            await cluster.quiesce_engines()

            await check_arena_conservation(cluster, "ChaosCounter", keys)
            check_timer_conservation(
                cluster, "ChaosCounter",
                [(int(k), "watch") for k in keys])
        finally:
            await cluster.stop()

    run(main())


@pytest.mark.chaos
def test_chaos_storage_flake_surfaces_and_recovers(run):
    """Storage-flake smoke: a finite window of injected write failures
    surfaces to callers (never silent corruption) and writes succeed
    once the window passes; stream delivery stays at-least-once under a
    concurrent transport delay rule."""

    async def main():
        from orleans_tpu.chaos.report import (
            DELIVERED,
            IChaosStreamEater,  # noqa: F401 — registers the consumer
        )
        from orleans_tpu.streams import InMemoryQueueAdapter
        from orleans_tpu.streams.persistent import PersistentStreamProvider

        backing = InMemoryQueueAdapter.shared_backing()

        def setup(silo):
            silo.add_stream_provider("pq", PersistentStreamProvider(
                InMemoryQueueAdapter(n_queues=2, backing=backing),
                pull_period=0.01, consumer_cache_ttl=0.1))

        plan = FaultPlan(seed=11)
        plan.rule("flake", "storage", "fail", count=3,
                  match=lambda ctx: ctx[0] == "Default")
        plan.rule("lag", "transport", "delay", probability=0.2,
                  delay=0.02, count=40)
        cluster = await ChaosCluster(plan=plan, n_silos=2,
                                     silo_setup=setup).start()
        stream_key = 424242
        DELIVERED.pop(stream_key, None)
        try:
            await cluster.wait_for_liveness_convergence()
            factory = cluster.attach_client(0)
            refs = [factory.get_grain(ICounterGrain, 800 + i)
                    for i in range(6)]
            await asyncio.gather(*(r.add(5) for r in refs))

            # the flake window: failures SURFACE as errors, then clear
            surfaced = 0
            for r in refs:
                for _ in range(5):
                    try:
                        await r.save()
                        break
                    except Exception:
                        surfaced += 1
            assert surfaced == 3  # exactly the injected window, no more

            produced = list(range(30))
            stream = cluster.silos[0].stream_provider("pq").get_stream(
                "chaos-events", stream_key)
            await stream.on_next_batch(produced)
            await wait_for_at_least_once(
                produced, lambda: list(DELIVERED.get(stream_key, [])),
                timeout=10.0)

            # saved state survived the flakes uncorrupted
            values = await asyncio.gather(*(r.get() for r in refs))
            assert all(v == 5 for v in values)
            await cluster.check_invariants(timeout=5.0)
        finally:
            await cluster.stop()

    run(main())


@pytest.mark.chaos
def test_chaos_smoke_plan_reproducible_end_to_end(run):
    """The acceptance scenario: the canonical seeded smoke plan
    (partition → heal → hard-kill) on a 3-silo ChaosCluster passes all
    nine invariant checkers TWICE with identical fault traces."""

    async def main():
        from orleans_tpu.chaos.report import run_smoke

        first = await run_smoke(seed=20260804)
        second = await run_smoke(seed=20260804)
        for report in (first, second):
            assert report["ok"], report["invariants"]
            assert set(report["invariants"]) == {
                "membership_convergence", "single_activation",
                "arena_conservation", "stream_at_least_once",
                "dead_letter_accounting", "durability_accounting",
                "migration_storm", "standby_failover",
                "fabric_midflush_failfast"}
        assert first["trace_signature"] == second["trace_signature"]
        assert len(first["trace_signature"]) >= 5

    run(main())


def test_delayed_message_respects_partition_imposed_meanwhile():
    """A delay-rule message fires from a timer; a partition imposed
    between the decision and the timer must still sever it."""

    async def main():
        class Fabric:
            def __init__(self):
                self.delivered = []

            def send(self, sender, msg):
                self.delivered.append((sender, msg.target_silo))

        class Msg:
            method_name = "m"

            def __init__(self, target):
                self.target_silo = target

        plan = FaultPlan(seed=1)
        plan.rule("lag", "transport", "delay", delay=0.03, count=1)
        interposer = Interposer(plan, FaultTrace())
        fabric = Fabric()
        interposer.attach_inproc_fabric(fabric)

        fabric.send("A", Msg("B"))                  # parked on a timer
        interposer.set_partition([{"A"}, {"B"}])    # cut lands meanwhile
        await asyncio.sleep(0.08)
        assert fabric.delivered == []               # timer hit the cut
        assert interposer.counters["partition_dropped"] == 1

        interposer.heal_partition()
        fabric.send("A", Msg("B"))                  # rule exhausted: flows
        assert fabric.delivered == [("A", "B")]

    asyncio.run(main())


@pytest.mark.chaos
def test_chaos_transport_seams_fire_on_merged_slab_frames(run):
    """The fault-injection plane must not silently bypass the aggregated
    slab fast path: a transport drop rule matched on inject_slab frames
    fires on MERGED frames (post-aggregation), the dropped payload is
    visible as a delivery shortfall, and duplicate/delay actions reach
    the same seam."""
    from orleans_tpu.runtime.messaging import is_slab_message
    from orleans_tpu.testing.cluster import TestingCluster
    from tests.test_vector_router import RouteCounter  # noqa: F401

    async def main():
        plan = FaultPlan(seed=9)
        plan.rule("slab_drop", "transport", "drop", count=1,
                  match=is_slab_message)
        interposer = Interposer(plan, FaultTrace())
        cluster = await TestingCluster(n_silos=2).start()
        try:
            a = cluster.silos[0]
            interposer.attach_cluster(cluster)
            n, parts = 400, 4
            keys = np.arange(n, dtype=np.int64)
            for i in range(parts):  # one burst → ONE merged frame out
                lo, hi = i * n // parts, (i + 1) * n // parts
                a.tensor_engine.send_batch(
                    "RouteCounter", "add", keys[lo:hi],
                    {"v": np.ones(hi - lo, np.float32)})
            await cluster.quiesce_engines()
            snap = a.vector_router.snapshot()
            assert snap["slab_merge_ratio"] > 1.0  # aggregation was live
            # the rule saw and dropped exactly one MERGED frame
            assert interposer.counters["transport_dropped"] == 1
            # the dropped frame's whole merged payload went missing —
            # proof the seam cut the aggregated path, not a fragment
            received = sum(s.vector_router.messages_received
                           for s in cluster.silos)
            shipped = a.vector_router.messages_shipped
            assert shipped - received > n // parts
        finally:
            interposer.detach()
            await cluster.stop()

    run(main())


@pytest.mark.chaos
def test_chaos_duplicate_slab_frames_double_deliver(run):
    """Duplicate action on the slab seam: the merged frame delivers
    twice (at-least-once semantics surface as doubled counts) — the
    interposer's transport actions compose with the new wire path."""
    from orleans_tpu.runtime.messaging import is_slab_message
    from orleans_tpu.testing.cluster import TestingCluster
    from tests.test_vector_router import (  # noqa: F401
        RouteCounter,
        arena_rows,
    )

    async def main():
        plan = FaultPlan(seed=5)
        plan.rule("slab_dup", "transport", "duplicate", count=1,
                  match=is_slab_message)
        interposer = Interposer(plan, FaultTrace())
        cluster = await TestingCluster(n_silos=2).start()
        try:
            a = cluster.silos[0]
            interposer.attach_cluster(cluster)
            n = 200
            keys = np.arange(n, dtype=np.int64)
            a.tensor_engine.send_batch(
                "RouteCounter", "add", keys,
                {"v": np.ones(n, np.float32)})
            await cluster.quiesce_engines()
            assert interposer.counters["transport_duplicated"] == 1
            rows = arena_rows(cluster, "RouteCounter")
            # remote rows saw the frame twice, local rows once
            counts = {int(r["count"]) for _, r in rows.values()}
            assert 2 in counts, f"duplicate never delivered: {counts}"
        finally:
            interposer.detach()
            await cluster.stop()

    run(main())
