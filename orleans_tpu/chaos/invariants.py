"""Cluster-wide invariant checkers: the guarantees a chaos run asserts.

Each checker returns a small report dict on success and raises
``InvariantViolation`` (an AssertionError, so pytest renders it natively)
with full evidence on failure.  The four documented guarantees:

1. **Membership converges** after partitions heal / kills are detected —
   every still-ACTIVE silo's view equals exactly the ACTIVE set
   (reference: table-based MembershipOracle convergence).
2. **No grain is doubly activated** — a host grain id has at most one
   activation cluster-wide, and a vector-grain key is live in at most one
   silo's arena (reference: the directory registration race,
   Catalog.cs:533-563).
3. **Arena population is conserved** across handoff — after the data
   plane quiesces, the union of live arena keys over the cluster is
   exactly the expected key set, with no key resident twice.
4. **Stream delivery stays within the at-least-once window** — every
   produced event is delivered at least once; duplicates are legal and
   reported, silent loss is a violation.
"""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional


class InvariantViolation(AssertionError):
    """A documented cluster guarantee observed broken."""


def _active_silos(cluster) -> List:
    from orleans_tpu.runtime.silo import SiloStatus
    return [s for s in cluster.silos if s.status == SiloStatus.ACTIVE]


async def check_membership_convergence(cluster,
                                       timeout: float = 10.0
                                       ) -> Dict[str, Any]:
    """Every ACTIVE silo's membership view must equal exactly the ACTIVE
    set — killed/self-killed silos DECLARED dead by every survivor.
    Unlike TestingCluster.wait_for_liveness_convergence this tolerates
    silos that died *as a consequence of the faults* (a partitioned
    minority voted dead kills itself on seeing its own DEAD row); they
    simply stop counting as expected members."""
    t0 = time.monotonic()
    deadline = t0 + timeout
    while True:
        active = _active_silos(cluster)
        expected = frozenset(s.address for s in active)
        views = {s.name: frozenset(s.active_silos()) for s in active}
        if active and all(v == expected for v in views.values()):
            return {"ok": True, "active": len(active),
                    "waited_s": round(time.monotonic() - t0, 3)}
        if time.monotonic() > deadline:
            raise InvariantViolation(
                f"membership did not converge within {timeout}s: "
                f"expected {sorted(map(str, expected))}, views "
                f"{ {n: sorted(map(str, v)) for n, v in views.items()} }")
        await asyncio.sleep(0.05)


def check_single_activation(cluster) -> Dict[str, Any]:
    """No host grain activated on two ACTIVE silos; no vector-grain key
    live in two arenas of the same type."""
    hosts: Dict[Any, List[str]] = defaultdict(list)
    n_host = 0
    for silo in _active_silos(cluster):
        for gid, acts in silo.catalog.directory.by_grain.items():
            # one entry PER activation: two activations of one grain on
            # the SAME silo are just as much a violation as cross-silo
            hosts[gid].extend([silo.name] * len(acts))
            n_host += len(acts)
    doubled = {str(g): names for g, names in hosts.items()
               if len(names) > 1}
    arena_keys: Dict[tuple, List[str]] = defaultdict(list)
    n_rows = 0
    for silo in _active_silos(cluster):
        if silo.tensor_engine is None:
            continue
        for type_name, arena in silo.tensor_engine.arenas.items():
            for k in arena.keys():
                arena_keys[(type_name, int(k))].append(silo.name)
                n_rows += 1
    doubled_rows = {f"{t}:{k}": names
                    for (t, k), names in arena_keys.items()
                    if len(names) > 1}
    if doubled or doubled_rows:
        raise InvariantViolation(
            f"double activation: host grains {doubled}, "
            f"arena keys {doubled_rows}")
    return {"ok": True, "host_activations": n_host, "arena_rows": n_rows}


async def check_arena_conservation(cluster, type_name: str,
                                   expected_keys: Iterable[int],
                                   quiesce: bool = True) -> Dict[str, Any]:
    """After the data plane quiesces, the union of live arena keys for
    ``type_name`` across ACTIVE silos equals the expected set exactly —
    no key lost in handoff, none resident twice."""
    if quiesce:
        await cluster.quiesce_engines()
    expected = {int(k) for k in expected_keys}
    seen: Dict[int, List[str]] = defaultdict(list)
    for silo in _active_silos(cluster):
        if silo.tensor_engine is None:
            continue
        arena = silo.tensor_engine.arenas.get(type_name)
        if arena is None:
            continue
        for k in arena.keys():
            seen[int(k)].append(silo.name)
    missing = sorted(expected - set(seen))
    extra = sorted(set(seen) - expected)
    doubled = {k: names for k, names in seen.items() if len(names) > 1}
    if missing or extra or doubled:
        raise InvariantViolation(
            f"arena population not conserved for {type_name!r}: "
            f"missing={missing[:20]} ({len(missing)} total), "
            f"extra={extra[:20]} ({len(extra)} total), doubled={doubled}")
    return {"ok": True, "type": type_name, "population": len(seen)}


def check_timer_conservation(cluster, type_name: str,
                             expected: Iterable) -> Dict[str, Any]:
    """Armed-timer conservation: every expected ``(key, name)`` timer is
    armed on EXACTLY one active silo's wheel — migration and ring
    handoff may move a timer between wheels (it rides the state slab:
    ``timers_plane.export_keys``/``adopt_keys``) but never lose one and
    never leave it armed twice (a doubled timer would fire twice)."""
    want = {(int(k), str(n)) for k, n in expected}
    seen: Dict[Any, List[str]] = defaultdict(list)
    for silo in _active_silos(cluster):
        eng = silo.tensor_engine
        if eng is None:
            continue
        for key in {k for k, _ in want}:
            for name, _due, _period in eng.timers.armed_for(type_name,
                                                            key):
                if (key, name) in want:
                    seen[(key, name)].append(silo.name)
    missing = sorted(want - set(seen))
    doubled = {kn: names for kn, names in seen.items() if len(names) > 1}
    if missing or doubled:
        raise InvariantViolation(
            f"armed timers not conserved for {type_name!r}: "
            f"missing={missing[:20]} ({len(missing)} total), "
            f"doubled={doubled}")
    return {"ok": True, "type": type_name, "armed": len(seen)}


def check_dead_letter_accounting(cluster) -> Dict[str, Any]:
    """Nothing vanishes without a dead-letter record.

    Every terminal drop site increments BOTH a metrics counter and a
    reason-coded dead-letter record; this checker asserts the two ledgers
    agree on every ACTIVE silo (a future drop path that bypasses the
    accounting shows up as a mismatch), and that the ring's own totals
    are internally consistent."""
    from orleans_tpu.resilience import REASON_COUNTER_ATTR
    mismatches: Dict[str, Dict[str, Any]] = {}
    totals = {"dead_letters": 0, "silos": 0}
    for silo in _active_silos(cluster):
        ring = silo.dead_letters
        m = silo.metrics
        # the reason → counter mapping is shared with the tracing-plane
        # lint (tests assert every reason ALSO has a span status): one
        # source of truth for all three ledgers
        pairs = {reason: getattr(m, attr)
                 for reason, attr in REASON_COUNTER_ATTR.items()}
        bad = {reason: {"metric": count, "ring": ring.count(reason)}
               for reason, count in pairs.items()
               if count != ring.count(reason)}
        # retained is bounded by both ledgers (== in steady state; < only
        # right after a live-reload capacity increase)
        if ring.total != sum(ring.by_reason.values()) \
                or len(ring.entries) > min(ring.total, ring.capacity):
            bad["_ring"] = {"total": ring.total,
                            "by_reason_sum": sum(ring.by_reason.values()),
                            "retained": len(ring.entries)}
        unknown = set(ring.by_reason) - set(pairs)
        if unknown:
            bad["_unknown_reasons"] = sorted(unknown)
        if bad:
            mismatches[silo.name] = bad
        totals["dead_letters"] += ring.total
        totals["silos"] += 1
    if mismatches:
        raise InvariantViolation(
            f"dead-letter accounting mismatch (drops without records, or "
            f"records without counters): {mismatches}")
    return {"ok": True, **totals}


def check_at_least_once(produced: Iterable, delivered: Iterable,
                        allowed_missing: int = 0) -> Dict[str, Any]:
    """Set/multiset form of the at-least-once contract: every produced
    token appears among the delivered ones (≥ once); duplicates are legal
    and counted.  ``allowed_missing`` admits the DOCUMENTED loss window
    (poison-capped events a scenario knowingly produced)."""
    produced = list(produced)
    delivered = list(delivered)
    counts: Dict[Any, int] = defaultdict(int)
    for d in delivered:
        counts[d] += 1
    missing = [p for p in produced if counts.get(p, 0) == 0]
    duplicates = sum(c - 1 for c in counts.values() if c > 1)
    if len(missing) > allowed_missing:
        raise InvariantViolation(
            f"at-least-once violated: {len(missing)} of {len(produced)} "
            f"produced events never delivered (allowed "
            f"{allowed_missing}): {missing[:20]}")
    return {"ok": True, "produced": len(produced),
            "delivered": len(delivered), "duplicates": duplicates,
            "missing": len(missing)}


async def wait_for_at_least_once(produced: Iterable,
                                 delivered_fn,
                                 timeout: float = 15.0,
                                 allowed_missing: int = 0
                                 ) -> Dict[str, Any]:
    """Poll ``delivered_fn()`` until the at-least-once contract holds (the
    retry/backoff window legitimately takes time after faults) or the
    window closes — the window IS the documented bound being checked."""
    produced = list(produced)
    deadline = time.monotonic() + timeout
    while True:
        try:
            return check_at_least_once(produced, delivered_fn(),
                                       allowed_missing=allowed_missing)
        except InvariantViolation:
            if time.monotonic() > deadline:
                raise
            await asyncio.sleep(0.05)


def check_mesh_single_activation(engine) -> Dict[str, Any]:
    """Mesh-path twin of ``check_single_activation``: within one
    engine's sharded arenas, every live key occupies exactly ONE row,
    and every row sits in the shard block the directory hash assigns —
    ``shard_of_keys``, the SAME function the cross-shard exchange
    buckets by (tensor/exchange.py).  Checked after mid-traffic mesh
    reshards and eviction-epoch churn: a key doubly resident, or
    resident in a foreign block, means the device cluster broke the
    single-activation guarantee the silo ring enforces at its own
    granularity."""
    import numpy as np

    report: Dict[str, Any] = {"ok": True, "arenas": {}}
    for name, arena in engine.arenas.items():
        keys = arena.keys()
        uniq, counts = np.unique(keys, return_counts=True)
        doubled = uniq[counts > 1]
        if len(doubled):
            raise InvariantViolation(
                f"mesh single-activation violated for {name!r}: keys "
                f"{doubled[:20].tolist()} live in multiple rows")
        rows, found = arena.lookup_rows(uniq)
        if not found.all():
            raise InvariantViolation(
                f"arena {name!r} index inconsistent: "
                f"{int((~found).sum())} live keys fail lookup")
        shards = rows // arena.shard_capacity
        # the expected shard is the stable hash OVERRIDDEN by any live
        # migration pin (arena.home_shards) — a rebalanced grain's home
        # IS its migrated block, and an unpinned stray is still a
        # directory/arena disagreement
        expected = arena.home_shards(uniq)
        strays = uniq[shards != expected]
        if len(strays):
            raise InvariantViolation(
                f"mesh placement violated for {name!r}: keys "
                f"{strays[:20].tolist()} resident outside their home "
                f"shard block (directory/arena disagreement)")
        report["arenas"][name] = {"live": int(arena.live_count),
                                  "n_shards": int(arena.n_shards),
                                  "migration_pins":
                                      len(arena._shard_override)}
    return report


def check_durability_accounting(engine,
                                expected: Optional[Dict[tuple, Dict[str,
                                                                    Any]]]
                                = None,
                                recover_stats: Optional[Dict[str, Any]]
                                = None,
                                rto_bound_s: Optional[float] = None
                                ) -> Dict[str, Any]:
    """The durable state plane's no-acknowledged-loss ledger
    (tensor/checkpoint.py):

    1. **Manifest integrity** — every blob the committed manifest
       references is readable (the blobs-first/manifest-last commit
       order makes a dangling reference impossible; one appearing means
       the contract broke), and journal segment sequences per site are
       strictly increasing with consistent lane totals.
    2. **Counter algebra** — per site, appended == committed + pending
       (nothing vanishes between the ring and the sealed segments).
    3. **Zero acknowledged-write loss** (when ``expected`` is given):
       for each ``(type_name, key)`` the restored arena state equals
       the oracle's value for every checked field — the oracle is the
       scenario's host replay over exactly the ACKNOWLEDGED horizon
       (``plane.durable_horizon()``), so any committed update missing
       from the restored state is a violation.
    4. **Recovery-time objective** (when ``recover_stats`` +
       ``rto_bound_s`` are given): the recovery's wall seconds are
       within the bound.
    """
    import numpy as np

    plane = engine.checkpointer
    if not plane.enabled:
        raise InvariantViolation(
            "durability accounting checked on an engine without a "
            "snapshot store (the scenario must attach one)")
    manifest = plane.store.read_manifest()
    if manifest is None:
        raise InvariantViolation("no committed manifest (the scenario "
                                 "must have committed a recovery point)")
    blobs_checked = 0
    rec = manifest.get("recovery") or {}
    for entry in ([rec.get("full")] if rec.get("full") else []) \
            + list(rec.get("deltas") or []):
        for name, ref in entry["arenas"].items():
            for blob in [ref["meta"]] + list(ref["parts"]):
                if plane.store.get_blob(blob) is None:
                    raise InvariantViolation(
                        f"manifest references missing snapshot blob "
                        f"{blob!r} (blobs-first commit order broken)")
                blobs_checked += 1
    for site_key, j in (manifest.get("journal") or {}).items():
        seqs = [s["seq"] for s in j["segments"]]
        if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
            raise InvariantViolation(
                f"journal site {site_key}: segment seqs not strictly "
                f"increasing: {seqs}")
        for s in j["segments"]:
            got = plane.store.get_blob(s["blob"])
            if got is None:
                raise InvariantViolation(
                    f"manifest references missing journal blob "
                    f"{s['blob']!r}")
            _arrays, meta = got
            if meta.get("lanes") != s["lanes"]:
                raise InvariantViolation(
                    f"journal segment {s['blob']!r}: manifest says "
                    f"{s['lanes']} lanes, blob says {meta.get('lanes')}")
            blobs_checked += 1
    for site in plane.journal.sites.values():
        if site.appended_lanes != site.committed_lanes \
                + site.segment_lanes:
            raise InvariantViolation(
                f"journal site {site.key}: appended "
                f"{site.appended_lanes} != committed "
                f"{site.committed_lanes} + pending {site.segment_lanes}")
    mismatches: Dict[str, Any] = {}
    checked_keys = 0
    if expected:
        for (type_name, key), fields in expected.items():
            arena = engine.arenas.get(type_name)
            row = arena.read_row(int(key)) if arena is not None else None
            if row is None:
                mismatches[f"{type_name}:{key}"] = "not restored"
                continue
            for fname, want in fields.items():
                got_v = np.asarray(row[fname])
                if not np.array_equal(got_v, np.asarray(want)):
                    mismatches[f"{type_name}:{key}.{fname}"] = {
                        "restored": got_v.tolist(),
                        "acknowledged": np.asarray(want).tolist()}
            checked_keys += 1
        if mismatches:
            raise InvariantViolation(
                f"acknowledged-write loss: restored state diverges from "
                f"the committed-horizon oracle: {mismatches}")
    rto_s = None
    if recover_stats is not None:
        rto_s = float(recover_stats.get("seconds", 0.0))
        if rto_bound_s is not None and rto_s > rto_bound_s:
            raise InvariantViolation(
                f"recovery-time objective missed: recovery took "
                f"{rto_s:.3f}s > bound {rto_bound_s}s")
    return {"ok": True, "blobs_checked": blobs_checked,
            "keys_checked": checked_keys,
            "recovery_s": rto_s,
            "horizon": plane.durable_horizon()}


def check_exchange_accounting(engine) -> Dict[str, Any]:
    """The exchange's no-silent-loss ledger: after quiescence, every
    bucket-overflow lane must have been re-delivered (parked checks all
    drained) and the delivered/cross counters internally consistent —
    the device-plane analog of ``check_dead_letter_accounting``."""
    xch = engine.exchange
    if xch is None:
        return {"ok": True, "exchange": None}
    if engine._exchange_checks:
        raise InvariantViolation(
            f"{len(engine._exchange_checks)} exchange overflow checks "
            "still parked after quiescence (drain/flush contract broken)")
    snap = xch.snapshot()
    if snap["cross_shard_msgs"] > snap["delivered_msgs"] + \
            snap["dropped_msgs"]:
        raise InvariantViolation(
            f"exchange counters inconsistent: {snap}")
    return {"ok": True, **snap}
