"""Auto-fusion (tensor/autofuse.py): the engine's transparent steady-state
compiler must never cost exactness or ordering.

Scenarios: engagement after K steady ticks; window exactness vs the
unfused engine; cold-destination rollback-and-replay; pattern-break
disengagement replaying buffered ticks BEFORE the breaking tick
(per-tick application order); static-leaf identity change disengaging
instead of freezing values; rollback hysteresis banning thrashing
patterns; the clustered ban for non-ring-owned key sets; and the engine
loop's idle flush draining a partial window without an explicit flush().
"""

import asyncio

import jax.numpy as jnp
import numpy as np

from orleans_tpu.config import TensorEngineConfig
from orleans_tpu.core.grain import batched_method
from orleans_tpu.tensor import (
    Batch,
    Emit,
    TensorEngine,
    VectorGrain,
    field,
    scatter_rows,
    seg_sum,
    vector_grain,
)
from orleans_tpu.tensor.vector_grain import scatter_add_rows

from samples.presence import run_presence_load


def _cfg(**kw) -> TensorEngineConfig:
    base = dict(auto_fusion_ticks=3, auto_fusion_window=4,
                tick_interval=0.0)
    base.update(kw)
    return TensorEngineConfig(**base)


@vector_grain
class LwwGrain(VectorGrain):
    """Last-writer-wins register + delivery counter: 'value' exposes
    application ORDER, 'count' exposes delivery EXACTNESS."""

    value = field(jnp.int32, 0)
    count = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def put(state, batch: Batch, n_rows: int):
        ones = jnp.ones_like(batch.rows, dtype=jnp.int32) * batch.mask
        v = jnp.broadcast_to(jnp.asarray(batch.args["v"], jnp.int32),
                             batch.rows.shape)
        return {
            **state,
            "value": scatter_rows(state["value"], batch.rows, v),
            "count": scatter_add_rows(state["count"], batch.rows, ones),
        }


@vector_grain
class HopGrain(VectorGrain):
    """Emits to a per-tick destination — lets a test steer emits at cold
    keys to force fused-window rollbacks."""

    sent = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def send(state, batch: Batch, n_rows: int):
        ones = jnp.ones_like(batch.rows, dtype=jnp.int32) * batch.mask
        state = {**state,
                 "sent": scatter_add_rows(state["sent"], batch.rows, ones)}
        emit = Emit(interface="LwwGrain", method="put",
                    keys=batch.args["dst"],
                    args={"v": batch.args["v"]}, mask=batch.mask)
        return state, None, (emit,)


def _lww_state(engine, keys):
    arena = engine.arena_for("LwwGrain")
    rows = arena.resolve_rows(np.asarray(keys, dtype=np.int64))
    return (np.asarray(arena.state["value"])[rows],
            np.asarray(arena.state["count"])[rows])


def test_engages_and_stays_exact(run):
    """After auto_fusion_ticks identical ticks the engine fuses windows;
    the loader only calls inject(); totals match the unfused engine."""

    async def main():
        n, T = 64, 24
        keys = np.arange(n, dtype=np.int64)

        engine = TensorEngine(config=_cfg())
        inj = engine.make_injector("LwwGrain", "put", keys)
        for t in range(T):
            inj.inject({"v": np.full(n, t + 1, np.int32)})
            await engine.drain_queues()
        await engine.flush()

        af = engine.autofuser
        assert af.windows_run > 0, "auto-fusion never engaged"
        assert af.ticks_fused > 0
        assert af.windows_rolled_back == 0
        value, count = _lww_state(engine, keys)
        np.testing.assert_array_equal(count, T)      # exact delivery
        np.testing.assert_array_equal(value, T)      # last writer wins
        assert engine.messages_processed == n * T

    run(main())


def test_presence_autofuses_with_inject_only_loader(run):
    """The presence loader (inject() per tick, nothing else) engages
    auto-fusion and matches the unfused engine's totals — the r2
    transparency criterion's exactness half."""

    async def main():
        n_players, n_games, T = 2000, 20, 16

        plain = TensorEngine(
            config=TensorEngineConfig(auto_fusion_ticks=0))
        await run_presence_load(plain, n_players=n_players,
                                n_games=n_games, n_ticks=T)

        auto = TensorEngine(config=_cfg(auto_fusion_ticks=4))
        stats = await run_presence_load(auto, n_players=n_players,
                                        n_games=n_games, n_ticks=T)
        assert stats["autofuse"]["windows_run"] > 0
        assert stats["autofuse"]["ticks_fused"] > 0

        for type_name, keys in (("PresenceGrain", np.arange(n_players)),
                                ("GameGrain", np.arange(n_games))):
            a_ref = plain.arena_for(type_name)
            a_auto = auto.arena_for(type_name)
            rows_ref = a_ref.resolve_rows(keys.astype(np.int64))
            rows_auto = a_auto.resolve_rows(keys.astype(np.int64))
            for col in a_ref.state:
                np.testing.assert_allclose(
                    np.asarray(a_auto.state[col])[rows_auto],
                    np.asarray(a_ref.state[col])[rows_ref], rtol=1e-5,
                    err_msg=f"{type_name}.{col} diverged under autofuse")

    run(main())


def test_rollback_replays_exactly_on_cold_destination(run):
    """A fused window whose emits touch an unactivated key rolls back and
    replays unfused — counts stay exact, the cold key activates."""

    async def main():
        n, T = 32, 24
        src = np.arange(n, dtype=np.int64)
        engine = TensorEngine(
            config=_cfg(auto_fusion_max_rollbacks=100))
        engine.arena_for("HopGrain").reserve(n)
        engine.arena_for("LwwGrain").reserve(n + 64)
        inj = engine.make_injector("HopGrain", "send", src)

        cold_tick = 18  # far past engagement, inside a fused window
        for t in range(T):
            dst = np.full(n, 5000 if t == cold_tick else 0, np.int32)
            inj.inject({"dst": dst, "v": np.full(n, t + 1, np.int32)})
            await engine.drain_queues()
        await engine.flush()

        af = engine.autofuser
        assert af.windows_run > 0
        assert af.windows_rolled_back >= 1, \
            "cold destination did not trigger a rollback"
        sent = np.asarray(engine.arena_for("HopGrain").state["sent"])
        rows = engine.arena_for("HopGrain").resolve_rows(src)
        np.testing.assert_array_equal(sent[rows], T)  # every tick applied
        # deliveries: T-1 ticks to key 0, one tick to the cold key 5000
        value0, count0 = _lww_state(engine, [0])
        valuec, countc = _lww_state(engine, [5000])
        assert int(count0[0]) == n * (T - 1)
        assert int(countc[0]) == n

    run(main())


def test_pattern_break_replays_buffer_before_breaking_tick(run):
    """Buffered window ticks must apply BEFORE the tick that broke the
    pattern — the breaking write wins the last-writer-wins register."""

    async def main():
        n = 16
        keys = np.arange(n, dtype=np.int64)
        engine = TensorEngine(config=_cfg(auto_fusion_window=8))
        inj = engine.make_injector("LwwGrain", "put", keys)

        # engage, then leave 2 ticks buffered in a partial window
        t_total = 0
        for t in range(8):
            inj.inject({"v": np.full(n, t + 1, np.int32)})
            await engine.drain_queues()
            t_total += 1
        assert engine.autofuser.has_buffer(), \
            "test setup: expected a partially-filled window"

        # breaking tick: different key-set identity → signature break
        other_keys = np.arange(n, dtype=np.int64)
        engine.send_batch("LwwGrain", "put", other_keys,
                          {"v": np.full(n, 99, np.int32)})
        await engine.drain_queues()
        t_total += 1
        await engine.flush()

        value, count = _lww_state(engine, keys)
        np.testing.assert_array_equal(count, t_total)  # nothing lost
        # ordering: buffered ticks (values ≤ 8) replayed BEFORE 99
        np.testing.assert_array_equal(value, 99)

    run(main())


def test_static_leaf_identity_change_disengages(run):
    """A leaf that was static at engage time changing identity mid-window
    disengages (and replays) instead of silently freezing its value."""

    async def main():
        n, T = 32, 12
        src = np.arange(n, dtype=np.int64)
        engine = TensorEngine(config=_cfg(auto_fusion_window=8))
        engine.arena_for("LwwGrain").reserve(n + 8)
        engine.arena_for("LwwGrain").resolve_rows(
            np.arange(2, dtype=np.int64))
        inj = engine.make_injector("HopGrain", "send", src)

        dst_static = np.zeros(n, np.int32)  # same identity → static leaf
        for t in range(T):
            inj.inject({"dst": dst_static,
                        "v": np.full(n, t + 1, np.int32)})
            await engine.drain_queues()
        assert engine.autofuser._program is not None, \
            "test setup: expected an engaged window"
        assert "dst" in engine.autofuser._patterns[0].static_args

        # mid-window: dst changes identity AND value — the new value must
        # apply (a frozen static would keep delivering to key 0)
        for t in range(T, T + 4):
            inj.inject({"dst": np.ones(n, np.int32),
                        "v": np.full(n, t + 1, np.int32)})
            await engine.drain_queues()
        await engine.flush()

        _, count0 = _lww_state(engine, [0])
        _, count1 = _lww_state(engine, [1])
        assert int(count0[0]) == n * T
        assert int(count1[0]) == n * 4, \
            "post-change dst values were dropped (frozen static leaf)"

    run(main())


def test_rollback_hysteresis_bans_thrashing_pattern(run):
    """A pattern that rolls back auto_fusion_max_rollbacks times is banned
    — no further windows run for it (until ring/generation change)."""

    async def main():
        n, T, W = 32, 64, 4
        src = np.arange(n, dtype=np.int64)
        engine = TensorEngine(
            config=_cfg(auto_fusion_max_rollbacks=2, auto_fusion_window=W))
        engine.arena_for("HopGrain").reserve(n)
        engine.arena_for("LwwGrain").reserve(4096)
        inj = engine.make_injector("HopGrain", "send", src)

        # every window touches a fresh cold key → rollback every window
        for t in range(T):
            dst = np.full(n, 100 + t // W, np.int32)
            inj.inject({"dst": dst, "v": np.full(n, 1, np.int32)})
            await engine.drain_queues()
        await engine.flush()

        af = engine.autofuser
        assert af.windows_rolled_back == 2, \
            f"expected exactly 2 rollbacks then a ban, " \
            f"got {af.windows_rolled_back}"
        assert af._disabled, "thrashing signature was not banned"
        # exactness throughout: every tick delivered to its window's key
        sent = np.asarray(engine.arena_for("HopGrain").state["sent"])
        rows = engine.arena_for("HopGrain").resolve_rows(src)
        np.testing.assert_array_equal(sent[rows], T)
        total = 0
        for w in range(T // W):
            _, c = _lww_state(engine, [100 + w])
            total += int(c[0])
        assert total == n * T

    run(main())


def test_clustered_ban_for_remote_keys(run):
    """On a clustered silo a steady pattern whose key set is not entirely
    ring-owned must never fuse — a fused window would freeze remote keys
    into a local program.  (Simulates a stale/bypassed ownership split: a
    BatchInjector constructed directly instead of via make_injector.)"""

    async def main():
        from orleans_tpu.tensor.engine import BatchInjector
        from orleans_tpu.testing.cluster import TestingCluster

        cluster = TestingCluster(n_silos=2)
        await cluster.start()
        try:
            s0 = cluster.silos[0]
            engine = s0.tensor_engine
            engine.config.auto_fusion_ticks = 3
            keys = np.arange(64, dtype=np.int64)
            _, remote = s0.vector_router.partition("LwwGrain", keys)
            assert remote, "test setup: expected a split key set"
            T = 12
            inj = BatchInjector(engine, "LwwGrain", "put", keys)
            for t in range(T):
                inj.inject({"v": np.full(len(keys), t + 1, np.int32)})
                await engine.drain_queues()
            await cluster.quiesce_engines()

            assert engine.autofuser.windows_run == 0
            assert engine.autofuser._disabled, \
                "mixed-ownership signature was not banned"
            # delivery stayed exact through the unfused path
            arena = engine.arenas["LwwGrain"]
            rows, found = arena.lookup_rows(keys)
            assert found.all()
            counts = np.asarray(arena.state["count"])[rows]
            np.testing.assert_array_equal(counts, T)
        finally:
            await cluster.stop()

    run(main())


def test_idle_flush_drains_partial_window(run):
    """With the engine LOOP running, a partially-filled window drains by
    itself after the idle grace — no explicit flush() needed."""

    async def main():
        n = 16
        keys = np.arange(n, dtype=np.int64)
        engine = TensorEngine(config=_cfg(
            auto_fusion_window=16, auto_fusion_idle_flush=0.05,
            tick_interval=0.001))
        engine.start()
        try:
            inj = engine.make_injector("LwwGrain", "put", keys)
            T = 8
            for t in range(T):
                inj.inject({"v": np.full(n, t + 1, np.int32)})
                await asyncio.sleep(0.005)
            # wait for engagement + buffering + idle grace to elapse
            deadline = asyncio.get_running_loop().time() + 5.0
            while asyncio.get_running_loop().time() < deadline:
                _, count = _lww_state(engine, keys)
                if (count == T).all():
                    break
                await asyncio.sleep(0.02)
            value, count = _lww_state(engine, keys)
            np.testing.assert_array_equal(count, T)
            np.testing.assert_array_equal(value, T)
            assert not engine.autofuser.has_buffer()
        finally:
            await engine.stop()

    run(main())


def test_periodic_checkpoint_fires_inside_fused_steady_state(run):
    """checkpoint_every_ticks must hold its bounded-loss promise while
    auto-fusion is engaged: fused windows advance the tick clock, so the
    cadence fires at window boundaries too — without any explicit
    checkpoint() call."""

    async def main():
        from orleans_tpu.tensor.persistence import MemoryVectorStore

        store = MemoryVectorStore()
        engine = TensorEngine(
            config=_cfg(auto_fusion_window=4), store=store)
        engine.config.checkpoint_every_ticks = 8
        n, T = 16, 32
        keys = np.arange(n, dtype=np.int64)
        inj = engine.make_injector("LwwGrain", "put", keys)
        for t in range(T):
            inj.inject({"v": np.full(n, t + 1, np.int32)})
            await engine.drain_queues()
        await engine.flush()
        assert engine.autofuser.ticks_fused > 0  # fusion really engaged
        stored = store.read_many("LwwGrain", keys.tolist())
        assert len(stored) == n, "cadence never checkpointed under fusion"
        # the stored counts lag live state by at most the cadence
        live = np.asarray(engine.arenas["LwwGrain"].state["count"])
        rows, _ = engine.arenas["LwwGrain"].lookup_rows(keys)
        for k in keys:
            lag = int(live[rows[int(k)]]) - int(stored[int(k)]["count"])
            assert 0 <= lag <= 8, lag

    run(main())


def test_chirper_autofuses_with_fanout(run):
    """Auto-fusion engages on a pattern with a REGISTERED FAN-OUT (the
    CSR expansion runs inside the compiled window) and matches the
    unfused engine's delivery counts exactly."""

    async def main():
        from samples.chirper import build_follow_graph, run_chirper_load

        n_accounts, T = 2000, 24
        fan1 = build_follow_graph(n_accounts, 8.0, seed=3)
        plain = TensorEngine(config=TensorEngineConfig(auto_fusion_ticks=0))
        await run_chirper_load(plain, n_accounts=n_accounts, n_ticks=T,
                               fanout=fan1)

        fan2 = build_follow_graph(n_accounts, 8.0, seed=3)
        auto = TensorEngine(config=_cfg(auto_fusion_ticks=4))
        stats = await run_chirper_load(auto, n_accounts=n_accounts,
                                       n_ticks=T, fanout=fan2)
        assert auto.autofuser.ticks_fused > 0, \
            "fan-out pattern never engaged"

        keys = np.arange(n_accounts, dtype=np.int64)
        a_ref = plain.arena_for("ChirperAccount")
        a_auto = auto.arena_for("ChirperAccount")
        rows_ref = a_ref.resolve_rows(keys)
        rows_auto = a_auto.resolve_rows(keys)
        for col in ("received", "published"):
            np.testing.assert_array_equal(
                np.asarray(a_auto.state[col])[rows_auto],
                np.asarray(a_ref.state[col])[rows_ref],
                err_msg=f"ChirperAccount.{col} diverged under autofuse")

    run(main())


def test_gpstracker_autofuses_with_gated_emits(run):
    """Auto-fusion on GPSTracker: movement-gated emits (mask-varying
    per tick) fuse and match the unfused engine's notifier counts."""

    async def main():
        from samples.gpstracker import N_NOTIFIERS, run_gps_load

        n_devices, T = 2000, 24
        notifiers = np.arange(N_NOTIFIERS, dtype=np.int64)
        plain = TensorEngine(config=TensorEngineConfig(auto_fusion_ticks=0))
        # pre-activate the notifier tier in BOTH engines: cold-start
        # redelivery coalesces several ticks' emits into one application,
        # which is exact for counts but makes the per-row "ticks with
        # traffic" column schedule-dependent — steady state is what the
        # parity claim is about
        plain.arena_for("PushNotifierGrain").resolve_rows(notifiers)
        s_ref = await run_gps_load(plain, n_devices=n_devices, n_ticks=T,
                                   seed=5)

        auto = TensorEngine(config=_cfg(auto_fusion_ticks=4))
        auto.arena_for("PushNotifierGrain").resolve_rows(notifiers)
        s_auto = await run_gps_load(auto, n_devices=n_devices, n_ticks=T,
                                    seed=5)
        assert auto.autofuser.ticks_fused > 0, \
            "gps pattern never engaged"
        # same seed → identical movement → identical notification counts
        assert s_auto["notified"] == s_ref["notified"]
        for type_name in ("DeviceGrain", "PushNotifierGrain"):
            a_ref = plain.arena_for(type_name)
            a_auto = auto.arena_for(type_name)
            kr = a_ref.keys()
            rr, _ = a_ref.lookup_rows(kr)
            ra, found = a_auto.lookup_rows(kr)
            assert found.all()
            for col in a_ref.state:
                np.testing.assert_allclose(
                    np.asarray(a_auto.state[col])[ra],
                    np.asarray(a_ref.state[col])[rr], rtol=1e-5,
                    err_msg=f"{type_name}.{col} diverged under autofuse")

    run(main())


def test_two_concurrent_patterns_fuse_together(run):
    """A tick carrying TWO steady streams (presence heartbeats AND lww
    puts) compiles into ONE multi-pattern window; both streams' totals
    match independent unfused engines exactly."""

    async def main():
        import samples.presence  # registers presence grains

        n, T = 512, 24
        keys = np.arange(n, dtype=np.int64)
        games = (keys % 8).astype(np.int32)

        def drive(engine):
            inj_p = engine.make_injector("PresenceGrain", "heartbeat",
                                         keys)
            inj_l = engine.make_injector("LwwGrain", "put", keys)
            g_d = jnp.asarray(games)
            s_d = jnp.ones(n, jnp.float32)
            return inj_p, inj_l, g_d, s_d

        plain = TensorEngine(config=TensorEngineConfig(auto_fusion_ticks=0))
        inj_p, inj_l, g_d, s_d = drive(plain)
        for t in range(T):
            inj_p.inject({"game": g_d, "score": s_d,
                          "tick": np.int32(t + 1)})
            inj_l.inject({"v": np.full(n, t + 1, np.int32)})
            await plain.drain_queues()
        await plain.flush()

        auto = TensorEngine(config=_cfg(auto_fusion_window=4))
        inj_p, inj_l, g_d, s_d = drive(auto)
        for t in range(T):
            inj_p.inject({"game": g_d, "score": s_d,
                          "tick": np.int32(t + 1)})
            inj_l.inject({"v": np.full(n, t + 1, np.int32)})
            await auto.drain_queues()
        await auto.flush()

        af = auto.autofuser
        assert af.ticks_fused > 0, "two-stream steady state never fused"
        assert len(af._programs) >= 1
        prog = next(iter(af._programs.values()))
        assert len(prog.sources) == 2, \
            "expected ONE program applying BOTH streams per tick"

        for type_name in ("PresenceGrain", "GameGrain", "LwwGrain"):
            a_ref = plain.arena_for(type_name)
            a_auto = auto.arena_for(type_name)
            kr = a_ref.keys()
            rr, _ = a_ref.lookup_rows(kr)
            ra, found = a_auto.lookup_rows(kr)
            assert found.all()
            for col in a_ref.state:
                np.testing.assert_allclose(
                    np.asarray(a_auto.state[col])[ra],
                    np.asarray(a_ref.state[col])[rr], rtol=1e-5,
                    err_msg=f"{type_name}.{col} diverged (2-pattern)")

    run(main())


def test_pattern_set_change_breaks_and_replays(run):
    """One of two fused streams stopping is a pattern break: buffered
    ticks of BOTH streams replay in order before the new shape runs."""

    async def main():
        n = 64
        keys = np.arange(n, dtype=np.int64)
        engine = TensorEngine(config=_cfg(auto_fusion_window=8))
        inj_a = engine.make_injector("LwwGrain", "put", keys)
        inj_b = engine.make_injector("HopGrain", "send", keys)
        engine.arena_for("LwwGrain").reserve(n + 8)
        dst0 = np.zeros(n, np.int32)

        T = 10
        for t in range(T):
            inj_a.inject({"v": np.full(n, t + 1, np.int32)})
            inj_b.inject({"dst": dst0, "v": np.full(n, 100 + t, np.int32)})
            await engine.drain_queues()
        assert engine.autofuser.has_buffer(), \
            "test setup: expected a partially-filled 2-stream window"

        # stream B stops: the 1-stream tick is a different composite
        # signature — buffered 2-stream ticks must apply FIRST
        for t in range(T, T + 3):
            inj_a.inject({"v": np.full(n, t + 1, np.int32)})
            await engine.drain_queues()
        await engine.flush()

        value, count = _lww_state(engine, keys)
        # LwwGrain saw T puts + 3 more puts + T hop deliveries to key 0
        np.testing.assert_array_equal(count[1:], T + 3)
        np.testing.assert_array_equal(value[1:], T + 3)  # order held
        sent = np.asarray(engine.arena_for("HopGrain").state["sent"])
        rows = engine.arena_for("HopGrain").resolve_rows(keys)
        np.testing.assert_array_equal(sent[rows], T)

    run(main())


def test_out_of_band_repack_settles_clean_chain(run):
    """A direct arena call that moves rows (reserve → grow) while a
    verification chain is outstanding settles the chain FIRST
    (GrainArena._settle_owner_chain) — exactness survives a mid-run
    repack with no rollback when the chain was clean."""

    async def main():
        n, T = 32, 24
        keys = np.arange(n, dtype=np.int64)
        engine = TensorEngine(config=_cfg(auto_fusion_verify_windows=8))
        inj = engine.make_injector("LwwGrain", "put", keys)
        arena = engine.arena_for("LwwGrain")

        repacked = False
        for t in range(T):
            inj.inject({"v": np.full(n, t + 1, np.int32)})
            await engine.drain_queues()
            if not repacked and engine.autofuser._unverified:
                gen0 = arena.generation
                arena.reserve(arena.capacity * 4)  # out-of-band row move
                assert arena.generation > gen0, "reserve did not repack"
                assert not engine.autofuser._unverified, \
                    "row move left the verification chain outstanding"
                repacked = True
        assert repacked, "test setup: never saw an unverified chain"
        await engine.flush()

        af = engine.autofuser
        assert af.windows_run > 0
        assert af.windows_rolled_back == 0
        value, count = _lww_state(engine, keys)
        np.testing.assert_array_equal(count, T)      # exact delivery
        np.testing.assert_array_equal(value, T)      # order held
        assert engine.messages_processed == n * T

    run(main())


def test_out_of_band_repack_with_dirty_chain_replays_exactly(run):
    """The previously-lossy path (r4 code 2914): a chain carrying misses
    (a cold-destination window) hits an out-of-band arena repack.  The
    row move settles the chain first — rollback + unfused replay happen
    AT THE REPACK, while the snapshot is still restorable — so nothing
    is lost and the cold key activates."""

    async def main():
        n, T = 32, 24
        src = np.arange(n, dtype=np.int64)
        engine = TensorEngine(config=_cfg(auto_fusion_max_rollbacks=100,
                                          auto_fusion_verify_windows=8))
        hop = engine.arena_for("HopGrain")
        hop.reserve(n)
        engine.arena_for("LwwGrain").reserve(n + 64)
        inj = engine.make_injector("HopGrain", "send", src)

        cold_tick = 14  # lands in the 4th fused window of the chain
        repacked = False
        for t in range(T):
            dst = np.full(n, 5000 if t == cold_tick else 0, np.int32)
            inj.inject({"dst": dst, "v": np.full(n, t + 1, np.int32)})
            await engine.drain_queues()
            if (not repacked and t > cold_tick
                    and engine.autofuser.windows_run >= 4
                    and engine.autofuser._unverified):
                # the chain now carries the cold tick's misses on device;
                # move rows out-of-band BEFORE any settle reads them
                hop.reserve(hop.capacity * 4)
                repacked = True
        assert repacked, "test setup: dirty chain never outstanding"
        await engine.flush()

        af = engine.autofuser
        assert af.windows_rolled_back >= 1, \
            "the repack-time settle did not roll the dirty chain back"
        sent = np.asarray(engine.arena_for("HopGrain").state["sent"])
        rows = engine.arena_for("HopGrain").resolve_rows(src)
        np.testing.assert_array_equal(sent[rows], T)  # every tick applied
        value0, count0 = _lww_state(engine, [0])
        valuec, countc = _lww_state(engine, [5000])
        assert int(count0[0]) == n * (T - 1)
        assert int(countc[0]) == n  # the cold key's deliveries landed

    run(main())


def test_twitter_autofuses_with_inject_only_loader(run):
    """The TwitterSentiment dispatcher-pool pattern autofuses
    TRANSPARENTLY: the loader only calls inject() on the fixed pool with
    per-tick (hashtag, score) slab args, and the engine compiles the
    dispatch → hashtag fan-in → counter chain itself — totals match the
    unfused engine exactly."""

    async def main():
        from samples.twitter_sentiment import (  # noqa: F401 — registers
            COUNTER_KEY,
            HashtagGrain,
            TweetCounterGrain,
            TweetDispatcherGrain,
            _zipf_payloads,
        )

        n_tweets, n_tags, T = 1_000, 200, 24
        m = n_tweets * 2
        tag_keys, payloads = _zipf_payloads(n_tags, m, T, 1.4, 5)
        pool = np.arange(8, dtype=np.int64)

        async def drive(engine):
            engine.arena_for("TweetDispatcherGrain").reserve(len(pool))
            engine.arena_for("HashtagGrain").reserve(n_tags)
            engine.arena_for("HashtagGrain").resolve_rows(tag_keys)
            engine.arena_for("TweetCounterGrain").resolve_rows(
                np.asarray([COUNTER_KEY], dtype=np.int64))
            inj = engine.make_injector("TweetDispatcherGrain", "dispatch",
                                       pool)
            for t in range(T):
                keys_t, scores_t = payloads[t]
                inj.inject({"keys": keys_t.astype(np.int32),
                            "score": scores_t})
                await engine.drain_queues()
            await engine.flush()

        plain = TensorEngine(config=TensorEngineConfig(auto_fusion_ticks=0))
        await drive(plain)
        auto = TensorEngine(config=_cfg(auto_fusion_ticks=3,
                                        auto_fusion_window=4))
        await drive(auto)
        assert auto.autofuser.windows_run > 0, "twitter never autofused"
        assert auto.autofuser.windows_rolled_back == 0

        a_ref = plain.arena_for("HashtagGrain")
        a_auto = auto.arena_for("HashtagGrain")
        rows_ref = a_ref.resolve_rows(tag_keys)
        rows_auto = a_auto.resolve_rows(tag_keys)
        for col in ("total", "positive", "negative", "counted",
                    "last_score"):
            np.testing.assert_array_equal(
                np.asarray(a_auto.state[col])[rows_auto],
                np.asarray(a_ref.state[col])[rows_ref],
                err_msg=f"HashtagGrain.{col} diverged under autofuse")
        c_ref = plain.arena_for("TweetCounterGrain").read_row(COUNTER_KEY)
        c_auto = auto.arena_for("TweetCounterGrain").read_row(COUNTER_KEY)
        assert int(c_ref["hashtags"]) == int(c_auto["hashtags"])
        assert plain.messages_processed == auto.messages_processed

    run(main())
