"""Per-activation state machine, mailbox, and turn gate.

Parity: reference ActivationData (reference: src/OrleansRuntime/Catalog/
ActivationData.cs:42 — waiting-message list :473, EnqueueMessage :487,
overload check :522, Running record :411) plus the single-threaded turn
guarantee the reference enforces with its two-level scheduler
(reference: src/OrleansRuntime/Scheduler/WorkItemGroup.cs:36).

Execution-model mapping: the reference pins each activation to a
WorkItemGroup drained by a worker-pool thread; here each silo runs one
asyncio event loop, each *turn* is an asyncio task, and this class is the
admission gate that decides whether an arriving request starts a turn now
or waits — which is precisely the reference's reentrancy logic
(reference: Dispatcher.ActivationMayAcceptRequest/CanInterleave :316,:329).
Single-threadedness is structural (one event loop), so the gate only has to
enforce *logical* turn exclusivity: one non-interleaving request in flight
per activation.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from enum import Enum
from typing import Any, Awaitable, Callable, Deque, Dict, List, Optional

from orleans_tpu.core.grain import GrainClassInfo, MethodInfo
from orleans_tpu.ids import ActivationAddress, ActivationId, GrainId, SiloAddress
from orleans_tpu.runtime.messaging import Message, RejectionType


def _observe_turn(t: "asyncio.Task") -> None:
    """Mark a finished turn task's exception as retrieved.

    Failures already reach the caller through the response message; this
    only silences asyncio's "exception was never retrieved" reporting.  A
    non-graceful silo stop cancels in-flight turns, and ``Task.exception()``
    raises on a cancelled task, so that case must be skipped."""
    if not t.cancelled():
        t.exception()


class ActivationState(Enum):
    """(reference: ActivationState.cs)"""

    CREATE = "create"
    ACTIVATING = "activating"
    VALID = "valid"
    DEACTIVATING = "deactivating"
    INVALID = "invalid"


class GrainTimer:
    """Volatile per-activation timer (reference: GrainTimer.cs:31).

    Ticks are delivered as turns through the activation's admission gate, so
    a timer callback never runs concurrently with a request turn — matching
    the reference, which schedules ticks on the activation's task scheduler.
    """

    def __init__(self, activation: "ActivationData",
                 callback: Callable[..., Awaitable[None]],
                 due: float, period: Optional[float], state: Any) -> None:
        import inspect
        self._activation = activation
        takes_state = len(inspect.signature(callback).parameters) >= 1
        self._fire = (lambda: callback(state)) if takes_state else (lambda: callback())
        self._due = due
        self._period = period
        self._task: Optional[asyncio.Task] = None
        self._disposed = False

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        try:
            await asyncio.sleep(self._due)
            while not self._disposed:
                # ACTIVATING is fine (timers registered in on_activate);
                # only a dying/dead activation stops the timer
                if self._activation.state in (ActivationState.DEACTIVATING,
                                              ActivationState.INVALID):
                    break
                await self._activation.run_closure_turn(self._fire)
                if self._period is None:
                    break
                await asyncio.sleep(self._period)
        except asyncio.CancelledError:
            pass

    def dispose(self) -> None:
        self._disposed = True
        if self._task is not None:
            self._task.cancel()


class ActivationData:
    """One activation: grain instance + mailbox + gate + collector metadata."""

    # Overload limit (reference: ActivationData.CheckOverloaded :522 driven
    # by LimitManager 'MaxEnqueuedRequests').
    DEFAULT_MAX_ENQUEUED = 5000

    def __init__(self, grain_id: GrainId, activation_id: ActivationId,
                 silo: SiloAddress, class_info: GrainClassInfo,
                 runtime: Any) -> None:
        self.grain_id = grain_id
        self.activation_id = activation_id
        self.address = ActivationAddress(silo, grain_id, activation_id)
        self.class_info = class_info
        self.runtime = runtime  # InsideRuntimeClient
        self.grain_instance: Any = None
        self.state = ActivationState.CREATE

        # mailbox + gate
        self.waiting: Deque[tuple[Message, Callable[[Message], Awaitable[None]]]] = deque()
        self.running: Dict[int, Message] = {}
        self._closure_waiters: Deque[tuple[asyncio.Future, Callable]] = deque()
        self.max_enqueued = self.DEFAULT_MAX_ENQUEUED

        # collector metadata (reference: ActivationData.CollectionTicket)
        self.last_use = time.monotonic()
        self.keep_alive_until = 0.0
        self._deactivate_on_idle = False
        self.deactivation_task: Optional[asyncio.Task] = None

        self.timers: List[GrainTimer] = []
        self.logger = runtime.logger.child(str(grain_id)) if runtime else None
        self.on_destroyed: List[Callable[[], None]] = []

    # -- admission gate (reference: Dispatcher.cs:316,:329) -----------------

    def may_interleave(self, msg: Message) -> bool:
        if self.class_info.reentrant:
            return True
        if msg.is_always_interleave:
            return True
        if msg.is_read_only and all(m.is_read_only for m in self.running.values()):
            return True
        return False

    def can_start_turn(self, msg: Message) -> bool:
        if not self.running:
            return True
        return self.may_interleave(msg)

    def check_overloaded(self) -> Optional[str]:
        """(reference: ActivationData.CheckOverloaded :522)"""
        n = len(self.waiting)
        if n > self.max_enqueued:
            return (f"activation {self.address} overloaded: {n} enqueued "
                    f"(limit {self.max_enqueued})")
        return None

    def enqueue_or_start(self, msg: Message,
                         invoke: Callable[[Message], Awaitable[None]]) -> Optional[str]:
        """Either start a turn for ``msg`` now or queue it.

        Returns an overload description if the message must be rejected
        (reference: Dispatcher.HandleIncomingRequest :375 + EnqueueMessage
        :487)."""
        self.last_use = time.monotonic()
        if self.state == ActivationState.VALID and self.can_start_turn(msg):
            self._start_turn(msg, invoke)
            return None
        overload = self.check_overloaded()
        if overload is not None:
            return overload
        self.waiting.append((msg, invoke))
        return None

    def _start_turn(self, msg: Message,
                    invoke: Callable[[Message], Awaitable[None]]) -> None:
        self.running[msg.id] = msg
        loop = asyncio.get_running_loop()
        task = loop.create_task(self._run_turn(msg, invoke))
        task.add_done_callback(_observe_turn)  # outcome travels via response

    async def _run_turn(self, msg: Message,
                        invoke: Callable[[Message], Awaitable[None]]) -> None:
        try:
            await invoke(msg)
        finally:
            self.running.pop(msg.id, None)
            self.last_use = time.monotonic()
            self._pump()

    def _pump(self) -> None:
        """After a turn ends: admit queued closures then queued messages
        (reference: ActivationData 'RunOnInactive'/waiting pump)."""
        while self._closure_waiters and not self.running:
            fut, token = self._closure_waiters.popleft()
            if not fut.done():
                # Reserve the gate for the closure *before* waking it so no
                # message sneaks in between set_result and its resumption.
                self.running[id(token)] = token  # type: ignore[index]
                fut.set_result(None)
                return
        while self.waiting:
            msg, invoke = self.waiting[0]
            if self.state == ActivationState.VALID and self.can_start_turn(msg):
                self.waiting.popleft()
                self._start_turn(msg, invoke)
                if not self.may_interleave(msg):
                    break
            else:
                break
        if (self._deactivate_on_idle and not self.running and not self.waiting
                and self.state == ActivationState.VALID):
            self.runtime.catalog.schedule_deactivation(self)

    # -- closure turns (timers, system work on the activation's context) ----

    async def run_closure_turn(self, fn: Callable[[], Awaitable[None]]) -> None:
        """Run ``fn`` as a turn respecting the gate (used by timers).

        Reference analog: ClosureWorkItem queued to the activation's
        WorkItemGroup (reference: ClosureWorkItem.cs)."""
        if self.state not in (ActivationState.VALID, ActivationState.ACTIVATING):
            return
        token = object()
        if self.running:
            fut = asyncio.get_running_loop().create_future()
            self._closure_waiters.append((fut, token))
            await fut  # _pump reserves the gate under id(token) before waking us
        else:
            self.running[id(token)] = token  # type: ignore[index]
        try:
            await fn()
        finally:
            self.running.pop(id(token), None)
            self.last_use = time.monotonic()
            self._pump()

    # -- timers -------------------------------------------------------------

    def register_timer(self, callback, due: float, period: Optional[float],
                       state: Any) -> GrainTimer:
        timer = GrainTimer(self, callback, due, period, state)
        self.timers.append(timer)
        timer.start()
        return timer

    def stop_timers(self) -> None:
        for t in self.timers:
            t.dispose()
        self.timers.clear()

    # -- collection (reference: Grain.DeactivateOnIdle :218) ----------------

    def deactivate_on_idle(self) -> None:
        self._deactivate_on_idle = True
        if not self.running and not self.waiting:
            self.runtime.catalog.schedule_deactivation(self)

    def delay_deactivation(self, seconds: float) -> None:
        self.keep_alive_until = max(self.keep_alive_until,
                                    time.monotonic() + seconds)

    def is_collectible(self, age_limit: float, now: float) -> bool:
        return (self.state == ActivationState.VALID
                and not self.running and not self.waiting
                and now >= self.keep_alive_until
                and now - self.last_use >= age_limit)

    def __repr__(self) -> str:
        return (f"Activation({self.grain_id} {self.activation_id} "
                f"{self.state.value} run={len(self.running)} "
                f"wait={len(self.waiting)})")
