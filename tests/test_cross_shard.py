"""Device-resident cross-shard routing (tensor/exchange.py).

Runs on the conftest-forced 8-device virtual CPU mesh and exercises the
REAL exchange path: bucket-by-destination-shard + lax.all_to_all inside
the compiled program, overflow redelivery with original inject stamps,
the fused-window threading, and the directory/arena agreement the whole
design rests on ("the directory IS the sharding map").
"""

import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from orleans_tpu.tensor import TensorEngine
from orleans_tpu.tensor.arena import shard_of_keys
from orleans_tpu.tensor.exchange import (
    exchangeable_args,
    ladder_ceil,
    pow2ceil,
)

from samples.routing import (
    SINK_BASE,
    RouteSink,     # noqa: F401 — registers the vector grains
    RouteSource,   # noqa: F401
    build_ratio_destinations,
    run_routing_load,
)

N_DEV = 8


def _mesh(n: int = N_DEV) -> Mesh:
    devices = jax.devices("cpu")
    assert len(devices) >= n, "conftest must force 8 host devices"
    return Mesh(np.array(devices[:n]), ("grains",))


def _engine(**kw) -> TensorEngine:
    e = TensorEngine(mesh=_mesh(), **kw)
    e.config.auto_fusion_ticks = 0  # tests opt in explicitly
    # the virtual CPU mesh disengages the structured path by default
    # (identity mode — config.exchange_structured "auto"); these suites
    # exist to prove the STRUCTURED machinery, so they pin it on
    e.config.exchange_structured = "always"
    return e


def _sink_state(engine, n_sinks: int):
    arena = engine.arena_for("RouteSink")
    sinks = np.arange(SINK_BASE, SINK_BASE + n_sinks, dtype=np.int64)
    rows, found = arena.lookup_rows(sinks)
    assert found.all()
    return (np.asarray(arena.state["total"])[rows],
            np.asarray(arena.state["received"])[rows])


# ---------------------------------------------------------------------------
# exchange kernel unit level
# ---------------------------------------------------------------------------

def test_exchange_delivery_set_and_locality():
    """The exchange preserves the (row, payload) delivery multiset
    exactly (minus counted drops) and every received lane's row belongs
    to the shard block of the position it landed in."""
    engine = _engine(initial_capacity=16 * N_DEV)
    arena = engine.arena_for("RouteSink")
    arena.resolve_rows(np.arange(SINK_BASE, SINK_BASE + 100,
                                 dtype=np.int64))
    cap = arena.capacity
    rng = np.random.default_rng(0)
    m = 100
    rows = rng.integers(0, cap, m).astype(np.int32)
    mask = np.ones(m, bool)
    mask[::7] = False
    v = rng.integers(1, 9, m).astype(np.float32)
    r2, a2, m2, dropped, stats = engine.exchange.dispatch(
        arena, jnp.asarray(rows), {"v": jnp.asarray(v),
                                   "t": np.float32(3.0)},
        jnp.asarray(mask))
    r2h, vh, m2h, dh, sh = map(np.asarray, (r2, a2["v"], m2, dropped,
                                            stats))
    valid_in = mask & (rows >= 0)
    assert int(sh[2]) == int(valid_in.sum()) - int(dh.sum())
    sent = collections.Counter(
        zip(rows[valid_in & ~dh].tolist(),
            v[valid_in & ~dh].tolist()))
    got = collections.Counter(zip(r2h[m2h].tolist(), vh[m2h].tolist()))
    assert sent == got
    # locality: the received lane's row lives in the block of the shard
    # that received it — the step kernel's scatter is shard-local
    per_shard = len(r2h) // N_DEV
    pos_shard = np.arange(len(r2h)) // per_shard
    assert ((r2h[m2h] // arena.shard_capacity) == pos_shard[m2h]).all()
    # scalar leaves bypass the exchange untouched
    assert a2["t"] == np.float32(3.0)


def test_exchange_plan_ladder_and_clamp():
    """Plan contract: widths the plane itself produced (exchange
    outputs, aligned layouts — registered transport widths) keep their
    exact per-shard split (re-quantizing would shift lanes out of
    their home chunks); everything else — including organic batches
    that merely happen to be n-divisible — quantizes onto the {2^k} ∪
    {3·2^(k-1)} ladder, so the compile set stays O(log) under drifting
    population.  An unmeasured site falls back to the worst-case cap
    formula; a measured site uses its quantized grant.  (Host-aligned
    batches never reach plan(): the fused build skips their exchange
    entirely.)"""
    engine = _engine(initial_capacity=16 * N_DEV)
    xch = engine.exchange
    for m in (1, 100, 4096, 100_000):
        L, cap = xch.plan(m)
        assert L == ladder_ceil(-(-m // N_DEV)) >= -(-m // N_DEV)
        # fallback (unmeasured): worst-case formula, clamped to L
        assert cap == pow2ceil(cap) and cap <= L
        assert cap >= min(L, engine.config.exchange_pad_quantum)
    # a registered transport width (n·544 is no ladder rung) keeps its
    # exact split; the same width unregistered would re-quantize
    assert xch.plan(8 * 544)[0] == ladder_ceil(544) != 544
    xch.note_transport_width(8 * 544)
    assert xch.plan(8 * 544)[0] == 544
    # a measured site uses its ladder-quantized grant (headroom 1.5
    # over the observed per-destination peak), clamped to L
    site = ("RouteSink", "recv")
    xch.observe_need(site, np.array([40, 3, 0, 0, 0, 0, 0, 0]))
    want = ladder_ceil(int(np.ceil(40 * engine.config.exchange_headroom)))
    assert xch.plan(4096, site=site) == (512, want)
    assert xch.plan(8, site=site) == (1, 1)  # clamp: cap ≤ L
    # zero demand quantizes to cap 0 — the classification-only fast path
    site0 = ("RouteSink", "quiet")
    xch.observe_need(site0, np.zeros(N_DEV, np.int64))
    assert xch.plan(4096, site=site0) == (512, 0)
    # the occupancy toggle is live: off → every site uses the fallback
    engine.config.exchange_occupancy_sizing = False
    L, cap = xch.plan(4096, site=site)
    assert cap >= min(L, engine.config.exchange_pad_quantum)


def test_slab_style_args_are_not_exchangeable():
    """Handlers consuming a whole buffer per tick (leaf leading dim !=
    lane count — the twitter dispatcher shape) must keep the legacy
    path: permuting rows away from the buffer would corrupt them."""
    assert exchangeable_args({"v": np.zeros(8), "s": np.float32(1)}, 8)
    assert not exchangeable_args({"slab": np.zeros(64)}, 8)


# ---------------------------------------------------------------------------
# engine integration: exactness across the ratio sweep
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("ratio", [0.0, 0.5, 0.9])
def test_routing_exact_vs_exchange_off(run, ratio):
    """Exchange ON must produce bit-identical sink state to the
    implicit-collective baseline at every cross-shard ratio (integer
    payloads through seg_sum: no float-order escape hatch)."""

    async def main():
        e_on = _engine(initial_capacity=1024)
        st_on = await run_routing_load(e_on, 512, 256, ratio, n_ticks=4)
        e_off = _engine(initial_capacity=1024)
        e_off.config.cross_shard_exchange = False
        st_off = await run_routing_load(e_off, 512, 256, ratio,
                                        n_ticks=4)
        assert st_on["total_ticks"] == st_off["total_ticks"]
        t_on, r_on = _sink_state(e_on, 256)
        t_off, r_off = _sink_state(e_off, 256)
        np.testing.assert_array_equal(t_on, t_off)
        np.testing.assert_array_equal(r_on, r_off)
        assert r_on.sum() == 512 * st_on["total_ticks"]
        xs = e_on.snapshot()["exchange"]
        assert xs["exchanges_run"] > 0 and xs["dropped_msgs"] == 0
        assert e_off.snapshot()["exchange"]["exchanges_run"] == 0
        if ratio > 0:
            assert xs["cross_shard_msgs"] > 0

    run(main())


def test_cross_shard_count_matches_constructed_ratio(run):
    """The stats the exchange reports reconcile with the analytically
    constructed traffic: sink deliveries cross shards exactly at the
    requested ratio (sources land on their own shard post-exchange, so
    the delivery leg's crossings are ratio * lanes per tick)."""

    async def main():
        n_src, n_sink, ratio, ticks = 512, 256, 0.5, 4
        e = _engine(initial_capacity=1024)
        await run_routing_load(e, n_src, n_sink, ratio, n_ticks=ticks,
                               warm_ticks=0)
        xs = e.snapshot()["exchange"]
        # two exchanged legs per tick: the source injection (whose
        # crossings depend on the injection layout) and the sink
        # delivery (whose crossings are EXACTLY the constructed ratio —
        # post-exchange, every emit lane sits on its source's home
        # shard).  The total is source-leg + ratio * lanes per tick.
        src = np.arange(n_src, dtype=np.int64)
        rows, _ = e.arena_for("RouteSource").lookup_rows(src)
        lane_shard = np.arange(n_src) // -(-n_src // N_DEV)
        src_cross = int((shard_of_keys(src, N_DEV) != lane_shard).sum())
        sink_cross = int(round(ratio * n_src))
        assert xs["cross_shard_msgs"] == (src_cross + sink_cross) * ticks
        assert xs["delivered_msgs"] == 2 * n_src * ticks
        assert xs["dropped_msgs"] == 0

    run(main())


# ---------------------------------------------------------------------------
# overflow redelivery + latency-ledger stamps
# ---------------------------------------------------------------------------

def test_overflow_redelivers_exactly_with_original_stamp(run):
    """Max-skew traffic (every message to ONE sink) with a deliberately
    tiny bucket: lanes overflow, redeliver over later ticks, and nothing
    is lost — and the device latency ledger records the redelivered
    lanes with their ORIGINAL inject stamp (nonzero tick deltas)."""

    async def main():
        e = _engine(initial_capacity=1024)
        e.config.exchange_pad_quantum = 2
        e.config.exchange_capacity_factor = 0.25
        src = np.arange(256, dtype=np.int64)
        e.arena_for("RouteSource").reserve(256)
        e.arena_for("RouteSink").reserve(64)
        e.arena_for("RouteSource").resolve_rows(src)
        e.arena_for("RouteSink").resolve_rows(
            np.arange(64, dtype=np.int64))
        inj = e.make_injector("RouteSource", "send", src)
        dst = jnp.asarray(np.zeros(256, np.int32))
        v = jnp.asarray(np.ones(256, np.float32))
        for t in range(3):
            inj.inject({"dst": dst, "v": v, "tick": np.int32(t)})
            await e.drain_queues()
        await e.flush()
        xs = e.snapshot()["exchange"]
        assert xs["dropped_msgs"] > 0 and xs["redeliveries"] > 0
        row = e.arena_for("RouteSink").read_row(0)
        assert int(row["received"]) == 256 * 3  # nothing lost
        led = e.ledger.snapshot()
        sink = led["RouteSink.recv"]
        assert sink["total"] == 256 * 3  # counted once each
        # redelivered lanes completed ticks after their stamp: buckets
        # beyond "same tick" must be populated
        assert sum(sink["counts"][1:]) > 0, sink

    run(main())


def test_checkpoint_defers_while_exchange_checks_parked(run):
    """Review-fix regression: a periodic checkpoint with exchange
    overflow redeliveries still parked would persist subscriber effects
    without their source update — the write defers one tick (the checks
    drain and requeue) and lands after the redeliveries apply."""
    from orleans_tpu.tensor import MemoryVectorStore
    from orleans_tpu.tensor.engine import _ExchangeCheck

    async def main():
        e = TensorEngine(mesh=_mesh(), initial_capacity=64,
                         store=MemoryVectorStore())
        e.config.auto_fusion_ticks = 0
        e.config.checkpoint_every_ticks = 1
        arena = e.arena_for("RouteSink")
        arena.resolve_rows(np.arange(SINK_BASE, SINK_BASE + 8,
                                     dtype=np.int64))
        e.tick_number = 5
        keys = jnp.asarray(
            np.arange(SINK_BASE, SINK_BASE + 4).astype(np.int32))
        e._exchange_checks.append(_ExchangeCheck(
            type_name="RouteSink", method="recv", keys=keys,
            args={"v": jnp.ones(4, jnp.float32),
                  "count": jnp.ones(4, jnp.int32)},
            dropped=jnp.asarray(np.array([True, False, False, False])),
            stats=jnp.asarray(np.array([1, 1, 3], np.int32)),
            inject_tick=2))
        assert e.maybe_periodic_checkpoint() == 0.0  # deferred
        assert not e._exchange_checks                # drained…
        redelivery = e.queues[("RouteSink", "recv")]
        assert redelivery and redelivery[0].inject_tick == 2  # …requeued
        await e.flush()  # redelivery applies (ticks checkpoint en route)
        assert e._last_checkpoint_tick > 0

    run(main())


def test_host_batch_not_misattributed_cross_shard(run):
    """Review-fix regression: a host-key batch for a method previously
    seen only through the exchange is organic traffic (host batches
    never exchange by design) — not a cross_shard toggle event."""

    async def main():
        e = _engine(initial_capacity=1024)
        await run_routing_load(e, 256, 128, 0.5, n_ticks=2,
                               warm_ticks=0)
        before = e.compile_tracker.by_cause.get("cross_shard", 0)
        e.send_batch("RouteSink", "recv",
                     np.arange(SINK_BASE, SINK_BASE + 16,
                               dtype=np.int64),
                     {"v": np.ones(16, np.float32),
                      "count": np.ones(16, np.int32)})
        await e.flush()
        assert e.compile_tracker.by_cause.get("cross_shard", 0) == before

    run(main())


def test_exchange_accounting_invariant(run):
    """The chaos-plane checker: parked checks drained at quiescence and
    counters internally consistent."""
    from orleans_tpu.chaos.invariants import check_exchange_accounting

    async def main():
        e = _engine(initial_capacity=1024)
        await run_routing_load(e, 256, 128, 0.5, n_ticks=3)
        report = check_exchange_accounting(e)
        assert report["ok"] and report["delivered_msgs"] > 0

    run(main())


# ---------------------------------------------------------------------------
# fused windows + autofuse
# ---------------------------------------------------------------------------

def test_fused_window_exchange_exact(run):
    """The exchange threads through the fused lax.scan: a fused run over
    the mesh matches the unfused exchange-off baseline exactly."""

    async def main():
        e_f = _engine(initial_capacity=1024)
        st_f = await run_routing_load(e_f, 512, 256, 0.5, n_ticks=4,
                                      fused_window=2)
        e_off = _engine(initial_capacity=1024)
        e_off.config.cross_shard_exchange = False
        st_o = await run_routing_load(e_off, 512, 256, 0.5, n_ticks=4,
                                      warm_ticks=2)
        t_f, r_f = _sink_state(e_f, 256)
        t_o, r_o = _sink_state(e_off, 256)
        # warm schedules differ (the fused path re-plans its bucket
        # caps across two warm windows), so per-tick state compares by
        # cross-multiplication — integer payloads, exact
        tf, to = st_f["total_ticks"], st_o["total_ticks"]
        np.testing.assert_array_equal(t_f * to, t_o * tf)
        np.testing.assert_array_equal(r_f * to, r_o * tf)

    run(main())


def test_fused_exchange_toggle_retraces_with_cause(run):
    """A live cross_shard_exchange toggle re-traces the fused program
    (cause config_toggle) instead of silently running the stale plan."""

    async def main():
        import jax.numpy as jnp

        e = _engine(initial_capacity=1024)
        src = np.arange(128, dtype=np.int64)
        e.arena_for("RouteSource").resolve_rows(src)
        e.arena_for("RouteSink").resolve_rows(
            np.arange(SINK_BASE, SINK_BASE + 64, dtype=np.int64))
        dst = build_ratio_destinations(
            src, np.arange(SINK_BASE, SINK_BASE + 64, dtype=np.int64),
            N_DEV, 0.5, seed=0)
        prog = e.fuse_ticks("RouteSource", "send", src)
        static = {"dst": jnp.asarray(dst.astype(np.int32)),
                  "v": jnp.ones(128, jnp.float32)}
        prog.run({"tick": jnp.arange(2, dtype=jnp.int32)},
                 static_args=static)
        assert prog.verify() == 0
        assert prog._exchange_on is True
        before = e.compile_tracker.by_cause.get("config_toggle", 0)
        e.config.cross_shard_exchange = False
        prog.run({"tick": jnp.arange(2, dtype=jnp.int32)},
                 static_args=static)
        assert prog.verify() == 0
        assert prog._exchange_on is False
        assert e.compile_tracker.by_cause["config_toggle"] == before + 1

    run(main())


def test_autofuse_engages_over_exchange(run):
    """Transparent auto-fusion on the mesh: the steady routing pattern
    engages, runs exchanged windows, and stays exact."""

    async def main():
        e = _engine(initial_capacity=1024)
        e.config.auto_fusion_ticks = 3
        e.config.auto_fusion_window = 4
        stats = await run_routing_load(e, 256, 128, 0.5, n_ticks=16,
                                       warm_ticks=0)
        assert e.autofuser.ticks_fused > 0, stats
        assert e.autofuser.windows_rolled_back == 0
        _t, received = _sink_state(e, 128)
        assert received.sum() == 256 * 16

    run(main())


# ---------------------------------------------------------------------------
# compile-cause + phase accounting
# ---------------------------------------------------------------------------

def test_live_toggle_records_cross_shard_cause(run):
    """Flipping the exchange re-specializes a seen (type, method, m)
    step — attributed as cause 'cross_shard', not organic shape churn."""

    async def main():
        e = _engine(initial_capacity=1024)
        e.config.cross_shard_exchange = False
        await run_routing_load(e, 256, 128, 0.5, n_ticks=2,
                               warm_ticks=0)
        assert e.compile_tracker.by_cause.get("cross_shard", 0) == 0
        e.config.cross_shard_exchange = True
        await run_routing_load(e, 256, 128, 0.5, n_ticks=2,
                               warm_ticks=0)
        assert e.compile_tracker.by_cause["cross_shard"] > 0

    run(main())


def test_exchange_phase_reconciles(run):
    """The exchange is its own tick phase; phase sums still reconcile
    with tick wall time (no double-counted stage)."""

    async def main():
        e = _engine(initial_capacity=1024)
        await run_routing_load(e, 256, 128, 0.5, n_ticks=4)
        prof = e.profiler
        assert prof.phase_seconds["exchange"] > 0.0
        assert prof.overrun_ticks == 0
        snap = prof.snapshot()
        assert "exchange" in snap["phase_seconds"]

    run(main())


# ---------------------------------------------------------------------------
# satellite: directory/arena agreement property test
# ---------------------------------------------------------------------------

def test_directory_arena_shard_agreement(run):
    """THE sharding-map claim, enforced: for random keys, the ring's
    device-granularity helper, the arena's row-block placement, and the
    exchange's rows//shard_capacity bucketing all agree — across
    growth (repack) and a mesh reshard."""
    from orleans_tpu.runtime.ring import device_shard_of_keys

    async def main():
        rng = np.random.default_rng(7)
        e = _engine(initial_capacity=2 * N_DEV)  # tiny: forces growth
        arena = e.arena_for("RouteSink")
        keys = np.unique(rng.integers(0, 2**31 - 2, 500,
                                      dtype=np.int64))

        def check(n_shards: int) -> None:
            rows, found = arena.lookup_rows(keys)
            assert found.all()
            got = rows // arena.shard_capacity
            want = shard_of_keys(keys, n_shards)
            np.testing.assert_array_equal(got, want)
            np.testing.assert_array_equal(
                want, device_shard_of_keys(keys, n_shards))

        arena.resolve_rows(keys[:50])   # initial block
        arena.resolve_rows(keys)        # forces several growths
        check(N_DEV)
        # growth again after more activations
        more = np.unique(rng.integers(2**20, 2**31 - 2, 1000,
                                      dtype=np.int64))
        arena.resolve_rows(more)
        check(N_DEV)
        # mesh reshard 8 → 4: same function at the new granularity
        await e.reshard(_mesh(4))
        check(4)

    run(main())


# ---------------------------------------------------------------------------
# satellite: chaos — mesh reshard mid-traffic × eviction epochs
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_mesh_reshard_mid_traffic(run):
    """The chaos scenario the issue names: reshard the mesh 8→4→8 while
    routing traffic flows, evict idle sinks mid-run (eviction epochs ×
    exchange), and assert the mesh invariants — single activation,
    home-block placement, exchange accounting, and exact end-to-end
    conservation (no message lost or doubled)."""
    from orleans_tpu.chaos.invariants import (
        check_exchange_accounting,
        check_mesh_single_activation,
    )
    from orleans_tpu.tensor import MemoryVectorStore

    async def main():
        store = MemoryVectorStore()
        e = TensorEngine(mesh=_mesh(), initial_capacity=1024,
                         store=store)
        e.config.auto_fusion_ticks = 0
        e.config.exchange_structured = "always"  # exercise the machinery
        n_src, n_sink = 256, 128
        src = np.arange(n_src, dtype=np.int64)
        sinks = np.arange(SINK_BASE, SINK_BASE + n_sink, dtype=np.int64)
        dst = build_ratio_destinations(src, sinks, N_DEV, 0.5, seed=3)
        e.arena_for("RouteSource").resolve_rows(src)
        e.arena_for("RouteSink").resolve_rows(sinks)
        inj = e.make_injector("RouteSource", "send", src)
        dst_d = jnp.asarray(dst.astype(np.int32))
        v = jnp.asarray(np.ones(n_src, np.float32))
        ticks = 0

        async def burst(n: int) -> None:
            nonlocal ticks
            for _ in range(n):
                inj.inject({"dst": dst_d, "v": v,
                            "tick": np.int32(ticks)})
                await e.drain_queues()
                ticks += 1

        await burst(3)
        await e.reshard(_mesh(4))          # mid-traffic shrink
        inj = e.make_injector("RouteSource", "send", src)
        await burst(3)
        # eviction epoch churn: evict EVERYTHING idle (write-back to the
        # store), then keep routing — sinks re-activate from storage
        await e.flush()
        evicted = e.collect_idle(max_idle_ticks=0)
        assert evicted > 0
        await burst(3)
        await e.reshard(_mesh(N_DEV))      # grow back
        inj = e.make_injector("RouteSource", "send", src)
        await burst(3)
        await e.flush()

        check_mesh_single_activation(e)
        check_exchange_accounting(e)
        # sinks with no post-eviction traffic live only in the store —
        # re-activation loads their state back (Catalog stage-2 analog)
        e.arena_for("RouteSink").resolve_rows(sinks)
        check_mesh_single_activation(e)
        _total, received = _sink_state(e, n_sink)
        assert received.sum() == n_src * 12  # every tick, exactly once

    run(main())


# ---------------------------------------------------------------------------
# satellite: metrics + dashboard plumbing
# ---------------------------------------------------------------------------

def test_route_metrics_declared_and_dashboard_row():
    from orleans_tpu.dashboard import render_text, view_from_snapshots
    from orleans_tpu.metrics import CATALOG, MetricsRegistry

    for name in ("route.cross_shard_msgs", "route.delivered_msgs",
                 "route.exchange_dropped", "route.exchanges",
                 "route.exchange_s", "route.exchange_util",
                 "route.exchange_overlap_s", "route.exchange_cap",
                 "arena.shard_occupancy"):
        assert name in CATALOG, name
    reg = MetricsRegistry(source="s1")
    reg.apply("route.cross_shard_msgs", 100.0, None)
    reg.apply("route.delivered_msgs", 150.0, None)
    reg.apply("route.exchanges", 4.0, None)
    reg.apply("route.exchange_dropped", 2.0, None)
    reg.apply("route.exchange_s", 0.5, None)
    reg.apply("route.exchange_overlap_s", 0.25, None)
    reg.gauge("route.exchange_util").set(0.75)
    reg.gauge("route.exchange_cap", {"shard": "3"}).set(96.0)
    view = view_from_snapshots([reg.snapshot()])
    xs = view["cluster"]["cross_shard"]
    assert xs["exchanged_messages"] == 100
    assert xs["delivered_messages"] == 150
    assert xs["dropped_redelivered"] == 2
    # utilization + overlap + occupancy caps ride the row (the
    # occupancy-sizing satellite contract)
    assert xs["bucket_utilization"] == 0.75
    assert xs["overlap_seconds"] == 0.25
    assert xs["caps"] == {"3": 96.0}
    text = render_text(view)
    assert "cross-shard (on device)" in text
    assert "util 0.75" in text


def test_shard_occupancy_gauge(run):
    async def main():
        e = _engine(initial_capacity=16 * N_DEV)
        arena = e.arena_for("RouteSink")
        arena.resolve_rows(np.arange(200, dtype=np.int64))
        occ = arena.shard_occupancy()
        assert occ.sum() == 200 and len(occ) == N_DEV
        expected = np.bincount(shard_of_keys(
            np.arange(200, dtype=np.int64), N_DEV), minlength=N_DEV)
        np.testing.assert_array_equal(occ, expected)

    run(main())


# ---------------------------------------------------------------------------
# satellite: perfgate multichip artifact family
# ---------------------------------------------------------------------------

def test_perfgate_multichip_family(tmp_path):
    import json

    from orleans_tpu.perfgate import newest_bench_artifact, run_gate

    # opaque legacy rounds are skipped, never treated as regression-free
    (tmp_path / "MULTICHIP_r05.json").write_text(json.dumps(
        {"n_devices": 8, "rc": 0, "ok": True, "tail": ""}))
    structured = {"workload": "multichip", "n_devices": 8,
                  "aggregate_msgs_per_sec": 1000.0,
                  "exchange": {"dropped_msgs": 0}}
    (tmp_path / "MULTICHIP_BENCH.json").write_text(
        json.dumps(structured))
    found = newest_bench_artifact(str(tmp_path), family="multichip")
    assert found is not None
    assert found[0].endswith("MULTICHIP_BENCH.json")

    baseline = {"source": "test",
                "multichip_metrics": {
                    "aggregate": {"path": "aggregate_msgs_per_sec",
                                  "value": 900.0, "tolerance": 0.3,
                                  "direction": "higher"},
                    "dropped": {"path": "exchange.dropped_msgs",
                                "value": 0.0, "tolerance": 0.0,
                                "direction": "lower"}}}
    bp = tmp_path / "PERF_BASELINE.json"
    bp.write_text(json.dumps(baseline))
    verdict = run_gate(str(bp), family="multichip")
    assert verdict["status"] == "pass", verdict
    # a driver-wrapper structured round outranks the bench fallback
    (tmp_path / "MULTICHIP_r06.json").write_text(json.dumps(
        {"parsed": {**structured, "aggregate_msgs_per_sec": 50.0}}))
    verdict = run_gate(str(bp), family="multichip")
    assert verdict["status"] == "fail"
    assert verdict["artifact"].endswith("MULTICHIP_r06.json")

    # the repo's own baseline declares the multichip family
    repo_baseline = json.loads(
        open("PERF_BASELINE.json").read())
    assert repo_baseline.get("multichip_metrics"), \
        "PERF_BASELINE.json must carry multichip tolerance bands"
    # the never-regress contract is gated with flag semantics: fused
    # exchange-on dropping below exchange-off can never pass again
    beats = repo_baseline["multichip_metrics"].get(
        "multichip_exchange_on_beats_off_at_50")
    assert beats and beats["direction"] == "flag", beats


# ---------------------------------------------------------------------------
# occupancy-sized caps: estimator, churn property, re-quantization cause
# ---------------------------------------------------------------------------

def test_estimator_grows_immediately_shrinks_with_patience():
    """Cap grants move on the quantized ladder: up the moment demand
    overflows (undersized caps cost a redelivery EVERY tick), down only
    after exchange_shrink_patience calm drains (a noisy steady state
    must not flap compiles)."""
    engine = _engine(initial_capacity=16 * N_DEV)
    xch = engine.exchange
    engine.config.exchange_headroom = 1.5
    engine.config.exchange_shrink_patience = 3
    site = ("RouteSink", "recv")
    v0 = xch.cap_version
    # first observation grants immediately
    xch.observe_need(site, np.array([20] + [0] * (N_DEV - 1)))
    g1 = xch.grant_for(site)
    assert g1 == ladder_ceil(int(np.ceil(20 * 1.5)))
    assert xch.cap_version == v0 + 1
    # growth is immediate
    xch.observe_need(site, np.array([200] + [0] * (N_DEV - 1)))
    g2 = xch.grant_for(site)
    assert g2 == ladder_ceil(int(np.ceil(200 * 1.5))) > g1
    assert xch.cap_version == v0 + 2
    # calm traffic: no shrink before patience drains
    for i in range(2):
        xch.observe_need(site, np.array([10] + [0] * (N_DEV - 1)))
        assert xch.grant_for(site) == g2, f"shrank after {i + 1} obs"
    # the patience-th calm drain shrinks to the windowed peak
    xch.observe_need(site, np.array([10] + [0] * (N_DEV - 1)))
    assert xch.grant_for(site) == ladder_ceil(int(np.ceil(10 * 1.5)))
    assert xch.cap_version == v0 + 3
    # per-shard cap gauges quantize the all-time peak per destination
    caps = xch.cap_gauges()
    assert caps[0] == ladder_ceil(int(np.ceil(200 * 1.5)))
    assert caps[1] == 0


@pytest.mark.parametrize("per_dest", ["never", "always"])
def test_undersized_estimate_parks_and_redelivers_under_churn(run,
                                                              per_dest):
    """THE safety property of occupancy sizing: a stale/undersized cap
    estimate may only ever park-and-redeliver — never drop, never
    double-deliver — across traffic shifts, arena growth, mesh
    reshards, and eviction-epoch bumps.  Verified by an exact host
    mirror of every delivery across randomized churn rounds.
    Parametrized over BOTH exchange bodies: the legacy max-over-dest
    cap and the per-destination grant vector — an undersized/stale
    per-dest grant must obey the identical conservation contract."""

    async def main():
        from orleans_tpu.tensor import MemoryVectorStore

        e = TensorEngine(mesh=_mesh(), initial_capacity=1024,
                         store=MemoryVectorStore())
        e.config.auto_fusion_ticks = 0
        e.config.exchange_structured = "always"
        e.config.exchange_per_dest = per_dest
        e.config.exchange_shrink_patience = 1  # shrink eagerly: the
        # estimate goes stale the moment traffic shifts back up
        n_src = 256
        src = np.arange(n_src, dtype=np.int64)
        sinks = list(range(SINK_BASE, SINK_BASE + 64))
        e.arena_for("RouteSource").resolve_rows(src)
        e.arena_for("RouteSink").resolve_rows(
            np.asarray(sinks, dtype=np.int64))
        mirror: dict = {}
        dropped_seen = 0
        tick = 0
        for rnd in range(8):
            # alternate tiny and huge cross ratios so the sized cap is
            # undersized on every upswing
            ratio = [0.0, 0.9][rnd % 2]
            sink_arr = np.asarray(sinks, dtype=np.int64)
            dst = build_ratio_destinations(src, sink_arr, e.n_shards,
                                           ratio, seed=rnd)
            inj = e.make_injector("RouteSource", "send", src)
            for _ in range(2):
                inj.inject({"dst": jnp.asarray(dst.astype(np.int32)),
                            "v": jnp.asarray(
                                np.ones(n_src, np.float32)),
                            "tick": np.int32(tick)})
                await e.drain_queues()
                tick += 1
                for d in dst:
                    mirror[int(d)] = mirror.get(int(d), 0) + 1
            await e.flush()
            dropped_seen = max(dropped_seen,
                               e.exchange.dropped_msgs)
            # churn between rounds: grow the sink set, bump eviction
            # epochs, and reshard the mesh mid-sequence
            if rnd == 2:
                sinks += list(range(SINK_BASE + 1000,
                                    SINK_BASE + 1000 + 512))
                e.arena_for("RouteSink").resolve_rows(
                    np.asarray(sinks, dtype=np.int64))
            if rnd == 4:
                # eviction-epoch bump: everything idle writes back to
                # the store and re-activates on the next delivery
                evicted = e.collect_idle(max_idle_ticks=0)
                assert evicted > 0
            if rnd == 5:
                await e.reshard(_mesh(4))
            if rnd == 6:
                await e.reshard(_mesh(N_DEV))
        # exact conservation: every injected delivery landed exactly
        # once, through however many parks/redeliveries it took
        arena = e.arena_for("RouteSink")
        keys = np.asarray(sorted(mirror), dtype=np.int64)
        # evicted-but-quiet sinks live only in the store — re-activate
        # (loads written-back state) before reading
        arena.resolve_rows(keys)
        rows, found = arena.lookup_rows(keys)
        assert found.all()
        got = np.asarray(arena.state["received"])[rows]
        want = np.asarray([mirror[int(k)] for k in keys])
        np.testing.assert_array_equal(got, want)
        # the interesting path actually ran: at least one upswing
        # overflowed the stale cap into a parked redelivery
        assert dropped_seen > 0
        assert e.exchange.redeliveries > 0

    run(main())


def test_cap_requantization_retraces_with_recorded_cause(run):
    """A cap re-quantization must surface as ONE cause-coded re-trace
    (bucket_growth) — never a silent recompile, and never a per-tick
    compile storm in steady state."""

    async def main():
        e = _engine(initial_capacity=1024)
        src = np.arange(512, dtype=np.int64)
        sinks = np.arange(SINK_BASE, SINK_BASE + 256, dtype=np.int64)
        e.arena_for("RouteSource").resolve_rows(src)
        e.arena_for("RouteSink").resolve_rows(sinks)
        dst = build_ratio_destinations(src, sinks, N_DEV, 0.5, seed=1)
        prog = e.fuse_ticks("RouteSource", "send", src)
        static = {"dst": jnp.asarray(dst.astype(np.int32)),
                  "v": jnp.asarray(np.ones(512, np.float32))}

        def win(t0):
            return {"tick": jnp.arange(2, dtype=jnp.int32) + t0}

        prog.run(win(0), static_args=static)   # fallback worst-case cap
        assert prog.verify() == 0              # folds measured demand
        causes0 = dict(e.compile_tracker.by_cause)
        prog.run(win(2), static_args=static)   # re-traces at tight cap
        assert prog.verify() == 0
        causes1 = dict(e.compile_tracker.by_cause)
        assert causes1["bucket_growth"] == causes0.get(
            "bucket_growth", 0) + 1, (causes0, causes1)
        # steady state: no further compiles, same program
        total = e.compile_tracker.total
        for i in range(3):
            prog.run(win(4 + 2 * i), static_args=static)
        assert prog.verify() == 0
        assert e.compile_tracker.total == total
        # the unfused dispatch records a re-quantization the same way:
        # same (L, shard_capacity, leaves) shape under a NEW cap
        xch = e.exchange
        arena = e.arena_for("RouteSink")
        rows = jnp.asarray(np.zeros(512, np.int32))
        mask = jnp.ones(512, bool)
        site = ("RouteSink", "probe_site")
        xch.observe_need(site, np.array([4] + [0] * (N_DEV - 1)))
        xch.dispatch(arena, rows, {"v": jnp.zeros(512)}, mask,
                     site=site)
        before = e.compile_tracker.by_cause.get("bucket_growth", 0)
        xch.observe_need(site, np.array([300] + [0] * (N_DEV - 1)))
        xch.dispatch(arena, rows, {"v": jnp.zeros(512)}, mask,
                     site=site)
        assert e.compile_tracker.by_cause["bucket_growth"] \
            == before + 1

    run(main())


# ---------------------------------------------------------------------------
# packed cross-lanes: host alignment + identity engagement + overlap
# ---------------------------------------------------------------------------

def test_fused_source_alignment_packs_and_skips_exchange(run):
    """A fused source with a static key set is packed home-shard-local
    at build (align_plan): the source leg traces NO exchange at all,
    the sink leg still exchanges, and the result is exact vs an
    unaligned window."""

    async def main():
        src = np.arange(512, dtype=np.int64)
        sinks = np.arange(SINK_BASE, SINK_BASE + 256, dtype=np.int64)
        dst = None
        results = {}
        for align in (True, False):
            e = _engine(initial_capacity=1024)
            e.config.exchange_align_sources = align
            e.arena_for("RouteSource").resolve_rows(src)
            e.arena_for("RouteSink").resolve_rows(sinks)
            if dst is None:
                dst = build_ratio_destinations(src, sinks, N_DEV, 0.5,
                                               seed=2)
            prog = e.fuse_ticks("RouteSource", "send", src)
            static = {"dst": jnp.asarray(dst.astype(np.int32)),
                      "v": jnp.asarray(np.ones(512, np.float32))}
            prog.run({"tick": jnp.arange(4, dtype=jnp.int32)},
                     static_args=static)
            assert prog.verify() == 0
            if align:
                assert prog._align[0] is not None
                # the aligned source leg skips the exchange entirely;
                # the sink (emit) leg still runs it
                assert "RouteSource.send" not in prog._exchange_sites
                assert "RouteSink.recv" in prog._exchange_sites
                # the packed layout really is home-shard-local
                al = prog._align[0]
                rows_a = np.asarray(al["rows"])
                La = len(rows_a) // N_DEV
                chunk = np.arange(len(rows_a)) // La
                cap_shard = e.arena_for("RouteSource").shard_capacity
                live = rows_a >= 0
                assert (rows_a[live] // cap_shard
                        == chunk[live]).all()
            else:
                assert prog._align[0] is None
            results[align] = _sink_state(e, 256)
        np.testing.assert_array_equal(results[True][0],
                                      results[False][0])
        np.testing.assert_array_equal(results[True][1],
                                      results[False][1])

    run(main())


def test_auto_mode_disengages_on_virtual_mesh_and_probes(run):
    """config.exchange_structured='auto' on a host-virtual CPU mesh:
    the structured path never runs (identity — delivery rides implicit
    collectives, bit-exact vs exchange-off), while the sampled probe
    still reports true cross traffic and demand."""

    async def main():
        e = TensorEngine(mesh=_mesh(), initial_capacity=1024)
        e.config.auto_fusion_ticks = 0
        e.config.exchange_probe_interval = 2
        assert not e.exchange.engaged()
        st = await run_routing_load(e, 512, 256, 0.5, n_ticks=4)
        assert st["messages_per_sec"] > 0
        xs = e.snapshot()["exchange"]
        # nothing structured ran …
        assert xs["exchanges_run"] == 0
        assert xs["dropped_msgs"] == 0
        # … yet the probe measured the real cross traffic and demand
        assert xs["cross_shard_msgs"] > 0
        assert any(v["peak_need"] and max(v["peak_need"]) > 0
                   for v in xs["sites"].values())
        # exact vs the exchange-off replay
        e_off = TensorEngine(mesh=_mesh(), initial_capacity=1024)
        e_off.config.auto_fusion_ticks = 0
        e_off.config.cross_shard_exchange = False
        await run_routing_load(e_off, 512, 256, 0.5, n_ticks=4)
        t_on, r_on = _sink_state(e, 256)
        t_off, r_off = _sink_state(e_off, 256)
        np.testing.assert_array_equal(t_on, t_off)
        np.testing.assert_array_equal(r_on, r_off)

    run(main())


def test_pre_exchange_overlap_credit(run):
    """Exchange overlap, unfused path: injector batches with cached
    resolutions pre-dispatch their exchange at round start; the
    consuming group collects the result and the credit (the wall the
    device had to hide the all_to_all in) accumulates — with delivery
    still exact."""

    async def main():
        e = _engine(initial_capacity=1024)
        assert e.config.exchange_overlap
        st = await run_routing_load(e, 512, 256, 0.5, n_ticks=6)
        assert st["messages_per_sec"] > 0
        xs = e.exchange
        assert xs.overlap_hits > 0
        assert xs.overlap_seconds >= 0.0
        assert e.snapshot()["exchange"]["overlap_seconds"] \
            == round(xs.overlap_seconds, 6)
        # exactness unchanged by the pre-dispatch path
        e_off = _engine(initial_capacity=1024)
        e_off.config.cross_shard_exchange = False
        await run_routing_load(e_off, 512, 256, 0.5, n_ticks=6)
        np.testing.assert_array_equal(_sink_state(e, 256)[1],
                                      _sink_state(e_off, 256)[1])

    run(main())


@pytest.mark.slow
def test_multichip_bench_tier_publishes_contract(run):
    """The structured multichip tier at plumbing scale: the artifact
    carries the sweep, exactness at every ratio, per-shard balance, the
    A/B toggles, and an embedded perfgate verdict — the fields the
    driver's MULTICHIP rounds become trackable through.  Full smoke:
    ``python bench.py --workload multichip --smoke``."""
    import bench

    stats = run(bench._multichip_tier(smoke=False,
                                      sizes=(1024, 512, 4, 2)))
    assert stats["workload"] == "multichip"
    assert stats["exact_all_ratios"], stats["sweep"]
    assert set(stats["sweep"]) == {"r0", "r10", "r50", "r90"}
    for s in stats["sweep"].values():
        assert s["exact_vs_unfused_replay"]
        assert s["structured_exact_vs_unfused_replay"]
        assert s["exchange_dropped"] == 0
        assert len(s["per_shard_sink_occupancy"]) == 8
        # the never-regress pair + the occupancy telemetry ride every
        # sweep row
        assert s["exchange_off_fused_msgs_per_sec"] > 0
        assert 0 < s["bucket_utilization"] <= 1.0
        assert "exchange_overlap_s" in s
        assert isinstance(s["exchange_caps"], dict)
    # the structured segment measures real cross traffic at 50%
    assert stats["sweep"]["r50"]["cross_shard_msgs"] > 0
    # headline = fused exchange-on only; the old any-engine max is the
    # secondary field and can only be ≥ it
    assert stats["aggregate_msgs_per_sec"] > 0
    assert stats["aggregate_best_any_msgs_per_sec"] \
        >= stats["aggregate_msgs_per_sec"]
    assert "fused exchange-on" in stats["aggregate_def"].lower() \
        or "FUSED EXCHANGE-ON" in stats["aggregate_def"]
    assert stats["throughput_point"]["msgs_per_sec"] > 0
    assert "exchange_speedup_at_50" in stats
    assert "exchange_on_beats_off_at_50" in stats
    attr = stats["exchange_attribution"]
    assert "worst_case_cap_padding" in attr
    assert "backend_engagement" in attr
    assert attr["backend_engagement"][
        "structured_unfused_msgs_per_sec_at_50"] > 0
    assert stats["host_slab_reference"]["total_msgs_per_sec"] > 0
    assert stats["perfgate"]["family"] == "multichip"
