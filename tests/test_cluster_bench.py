"""The clustered bench tier (bench.py --workload cluster): plumbing test.

Runs the bench's cluster runner in-process at tiny sizes and pins the
published contract: cross-silo ``msgs_per_sec``, per-link ``bytes_sent``,
``slab_merge_ratio`` > 1 under aggregation, exact delivery, and the
receiver-compile A/B direction (aggregation ⇒ no more compiles than the
un-aggregated run).  The full smoke invocation is
``python bench.py --workload cluster --smoke``.
"""

import pytest

import bench


@pytest.mark.cluster
def test_cluster_bench_tier_publishes_contract_fields(run):
    stats = run(bench._cluster_presence(
        n_players=1_000, n_games=10, n_ticks=4, aggregate=True,
        warm_ticks=4))
    # the acceptance contract: these exact fields, with a live merge
    for key in ("msgs_per_sec", "links", "slab_merge_ratio",
                "receiver_compiles", "bytes_sent"):
        assert key in stats, key
    assert stats["msgs_per_sec"] > 0
    assert stats["slab_merge_ratio"] > 1.0, stats
    assert stats["delivery_exact"], stats
    assert stats["bytes_sent"] > 0
    assert any(link["bytes_sent"] > 0 for link in stats["links"].values())


@pytest.mark.cluster
@pytest.mark.slow
def test_cluster_bench_aggregation_reduces_receiver_compiles(run):
    """The A/B the tentpole exists for: with sender aggregation the
    receivers compile fewer step programs than with raw fragment churn."""
    agg = run(bench._cluster_presence(
        n_players=1_000, n_games=10, n_ticks=6, aggregate=True,
        warm_ticks=4))
    raw = run(bench._cluster_presence(
        n_players=1_000, n_games=10, n_ticks=6, aggregate=False,
        warm_ticks=4))
    assert agg["receiver_compiles"] < raw["receiver_compiles"], (agg, raw)
    assert raw["slab_merge_ratio"] == 1.0
