"""Cross-shard routing workload — the multichip bench's ratio sweep.

A deliberately minimal two-type pipeline whose ONE tunable is the
fraction of traffic that crosses mesh shards: ``RouteSource.send``
updates the source row and emits one message per lane to a
``RouteSink`` key chosen so that exactly ``cross_ratio`` of the
destinations live in a DIFFERENT shard block than their source (the
shared shard-of-key hash — tensor/arena.shard_of_keys — makes the
construction exact, not statistical).  Both kernels combine with
``seg_sum``, so delivery is order-free and the exchange's lane
permutation cannot perturb results: state equality against an
exchange-off replay is exact (integer-valued float payloads — no
float-reassociation noise either).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from orleans_tpu.core.grain import batched_method, commutative
from orleans_tpu.tensor import (
    Batch,
    Emit,
    VectorGrain,
    field,
    seg_sum,
    vector_grain,
)
from orleans_tpu.tensor.vector_grain import scatter_add_rows
from orleans_tpu.tensor.arena import shard_of_keys

#: sink keys start here (disjoint from the source key space so the two
#: arenas never alias); bench/test readers derive the sink set from it
SINK_BASE = 1 << 20


def sink_keys(n_sinks: int) -> np.ndarray:
    return np.arange(SINK_BASE, SINK_BASE + n_sinks, dtype=np.int64)


@vector_grain
class RouteSource(VectorGrain):
    """Per-producer state: counts its own sends."""

    sent = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    def send(state, batch: Batch, n_rows: int):
        rows, args = batch.rows, batch.args
        ones = jnp.ones(rows.shape[0], jnp.int32)
        state = {**state,
                 "sent": scatter_add_rows(state["sent"], rows, ones)}
        emit = Emit(interface="RouteSink", method="recv",
                    keys=args["dst"],
                    args={"v": args["v"], "count": ones},
                    mask=batch.mask)
        return state, None, (emit,)


@vector_grain
class RouteSink(VectorGrain):
    """Per-consumer aggregate (order-free fan-in).

    ``recv`` is declared ``@commutative``: both columns are pure sums,
    so a hot sink may be promoted to replica rows (hot-grain
    replication) and the fold is exact — this is what lets the
    rebalance bench's single-hot-grain tier recover."""

    total = field(jnp.float32, 0.0)
    received = field(jnp.int32, 0)

    @batched_method
    @staticmethod
    @commutative
    def recv(state, batch: Batch, n_rows: int):
        rows, args = batch.rows, batch.args
        return {**state,
                "total": state["total"]
                + seg_sum(args["v"], rows, n_rows),
                "received": state["received"]
                + seg_sum(args["count"], rows, n_rows)}


def build_ratio_destinations(sources: np.ndarray, sinks: np.ndarray,
                             n_shards: int, cross_ratio: float,
                             seed: int = 0) -> np.ndarray:
    """One destination sink key per source, with EXACTLY
    ``round(cross_ratio * n)`` of them in a different shard than their
    source (by the canonical shard-of-key hash).  Requires every shard
    to hold at least one sink — size ``sinks`` generously."""
    rng = np.random.default_rng(seed)
    src_shard = shard_of_keys(sources, n_shards)
    sink_shard = shard_of_keys(sinks, n_shards)
    by_shard = [sinks[sink_shard == s] for s in range(n_shards)]
    if any(len(b) == 0 for b in by_shard):
        raise ValueError("every shard needs at least one sink key")
    n = len(sources)
    cross = np.zeros(n, dtype=bool)
    n_cross = int(round(cross_ratio * n))
    cross[rng.choice(n, size=n_cross, replace=False)] = True
    dst = np.empty(n, dtype=np.int64)
    for s in range(n_shards):
        mine = src_shard == s
        # same-shard picks come from the source's own block; cross picks
        # from a uniformly random OTHER block
        local_pool = by_shard[s]
        idx = np.nonzero(mine & ~cross)[0]
        dst[idx] = local_pool[rng.integers(0, len(local_pool), len(idx))]
        idx = np.nonzero(mine & cross)[0]
        if len(idx):
            others = rng.integers(0, n_shards - 1, len(idx))
            others = others + (others >= s)
            for o in range(n_shards):
                sel = idx[others == o]
                if len(sel):
                    pool = by_shard[o]
                    dst[sel] = pool[rng.integers(0, len(pool), len(sel))]
    return dst


async def run_routing_load(engine, n_sources: int, n_sinks: int,
                           cross_ratio: float, n_ticks: int = 10,
                           seed: int = 0, warm_ticks: int = 2,
                           fused_window: int = 0
                           ) -> Dict[str, float]:
    """Drive ``n_ticks`` of the routing pipeline at a fixed cross-shard
    ratio; returns stats (2 logical messages per source per tick: the
    source send + the sink delivery).  ``fused_window > 0`` runs the
    steady state through ``engine.fuse_ticks`` windows of that length
    (exactness asserted via the window miss counter); 0 drives the
    unfused tick loop through a cached injector."""
    import jax as _jax

    sources = np.arange(n_sources, dtype=np.int64)
    sinks = sink_keys(n_sinks)
    dst = build_ratio_destinations(sources, sinks, engine.n_shards,
                                   cross_ratio, seed=seed)
    rng = np.random.default_rng(seed + 1)
    # integer-valued floats: seg_sum order cannot perturb the total
    values = rng.integers(1, 8, n_sources).astype(np.float32)

    engine.arena_for("RouteSource").reserve(n_sources)
    engine.arena_for("RouteSink").reserve(n_sinks)
    engine.arena_for("RouteSource").resolve_rows(sources)
    engine.arena_for("RouteSink").resolve_rows(sinks)

    sink_arena = engine.arena_for("RouteSink")
    dst_d = jnp.asarray(dst.astype(np.int32))
    values_d = jnp.asarray(values)

    if fused_window > 0:
        from orleans_tpu.tensor.fused import plan_windows
        window, n_windows, n_ticks = plan_windows(fused_window, n_ticks)
        prog = engine.fuse_ticks("RouteSource", "send", sources)
        static = {"dst": dst_d, "v": values_d}
        # warm window 1: compile outside the timed segment — runs at
        # the worst-case FALLBACK bucket caps (no demand observed yet)
        prog.run({"tick": jnp.arange(window, dtype=jnp.int32)},
                 static_args=static)
        _jax.block_until_ready(sink_arena.state["total"])
        # verify() folds the window's measured bucket demand into the
        # occupancy estimators; warm window 2 then RE-TRACES at the
        # tight caps (cause bucket_growth), still outside the timed
        # segment — the steady state below runs the occupancy-sized
        # program from its first tick
        misses = prog.verify()
        prog.run({"tick": jnp.arange(window, dtype=jnp.int32) + window},
                 static_args=static)
        _jax.block_until_ready(sink_arena.state["total"])
        misses += prog.verify()
        if misses:
            raise RuntimeError(
                f"fused routing warm-up missed {misses} deliveries")
        compiles0 = engine.compile_count()
        live0, pad0 = _exchange_lanes(engine)
        t0 = time.perf_counter()
        for w in range(n_windows):
            prog.run({"tick": jnp.arange(window, dtype=jnp.int32)
                      + (w + 2) * window}, static_args=static)
        _jax.block_until_ready(sink_arena.state["total"])
        elapsed = time.perf_counter() - t0
        misses = prog.verify()
        if misses:
            raise RuntimeError(
                f"fused routing window missed {misses} deliveries")
        if engine.compile_count() != compiles0:
            raise RuntimeError(
                "fused routing steady state recompiled mid-run "
                "(cap re-quantization must settle in warm-up)")
        engine_kind = "fused"
    else:
        injector = engine.make_injector("RouteSource", "send", sources)

        def args_for(t: int):
            return {"dst": dst_d, "v": values_d, "tick": np.int32(t)}

        warm_total = warm_ticks
        for t in range(warm_ticks):
            injector.inject(args_for(t))
            await engine.drain_queues()
        await engine.flush()
        if warm_ticks > 0:
            # the flush drained the parked exchange stats — the
            # occupancy estimators size the steady-state caps from
            # them; one more warm tick then compiles the re-quantized
            # programs outside the timed segment
            injector.inject(args_for(warm_ticks))
            await engine.drain_queues()
            await engine.flush()
            warm_total += 1
        _jax.block_until_ready(sink_arena.state["total"])
        live0, pad0 = _exchange_lanes(engine)
        t0 = time.perf_counter()
        for t in range(n_ticks):
            injector.inject(args_for(warm_total + t))
            await engine.drain_queues()
        await engine.flush()
        _jax.block_until_ready(sink_arena.state["total"])
        elapsed = time.perf_counter() - t0
        engine_kind = "unfused"

    messages = 2 * n_sources * n_ticks
    xs = engine.snapshot().get("exchange") or {}
    live1, pad1 = _exchange_lanes(engine)
    # STEADY-STATE utilization: the timed segment only — the warm
    # phase deliberately runs worst-case caps while demand is being
    # measured, and folding it in would understate what the occupancy
    # sizing achieves (the cumulative number stays in the snapshot)
    steady_util = round((live1 - live0) / (pad1 - pad0), 4) \
        if pad1 > pad0 else xs.get("bucket_utilization")
    return {
        "sources": n_sources,
        "sinks": n_sinks,
        "cross_ratio": cross_ratio,
        "ticks": n_ticks,
        # warm + timed — the denominator for per-tick state oracles
        # (sink counts accumulate across BOTH phases)
        "total_ticks": n_ticks + (2 * window if fused_window > 0
                                  else warm_total),
        "seconds": elapsed,
        "messages": messages,
        "messages_per_sec": messages / elapsed,
        "engine": engine_kind,
        "bucket_utilization": steady_util,
        "exchange_overlap_s": xs.get("overlap_seconds"),
        "exchange_caps": xs.get("sites"),
    }


def _exchange_lanes(engine) -> Tuple[int, int]:
    xch = getattr(engine, "exchange", None)
    if xch is None:
        return 0, 0
    return xch.live_lanes, xch.padded_lanes


def expected_sink_state(sources: np.ndarray, dst: np.ndarray,
                        values: np.ndarray, sinks: np.ndarray,
                        n_ticks: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side ground truth: (total float64, received int64) per sink
    key, for exactness assertions against any engine configuration."""
    order = np.searchsorted(sinks, dst)
    total = np.zeros(len(sinks), np.float64)
    np.add.at(total, order, values.astype(np.float64))
    received = np.zeros(len(sinks), np.int64)
    np.add.at(received, order, 1)
    return total * n_ticks, received * n_ticks
