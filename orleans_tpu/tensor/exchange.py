"""ShardExchange: on-device cross-shard message routing over the mesh.

The arena is mesh-sharded (the directory's consistent-hash assignment IS
the shard-block map — arena.py, runtime/ring.py), but until now a batch's
scatter into rows owned by OTHER shards was left to XLA's implicit
collectives: every `state.at[rows].set` over a sharded column turns into
unstructured gather/scatter communication, re-planned per kernel.  This
module makes the cross-shard hop an EXPLICIT, structured exchange — the
device analog of the cross-silo slab path (tensor/router.py), so the
8-device mesh runs as one logical cluster with host transport reserved
for true cross-process hops:

1. **bucket** — each shard classifies its slice of the batch by
   destination shard (``rows // shard_capacity``; identical to the
   directory's `shard_of_keys` hash by construction — the agreement is
   property-tested) and packs messages into a ``[n_shards, cap]`` send
   buffer, ``cap`` pow2-padded so compile count stays O(log n) under
   varying load;
2. **exchange** — ONE ``lax.all_to_all`` over the mesh axis moves every
   bucket to its owner (inside the compiled program: the fused window
   threads this through its ``lax.scan``);
3. **fold** — the received lanes carry rows that are all shard-local, so
   the existing step kernel's scatter/segment-sum applies them without
   further communication.

Exactness across the bounded buckets: a lane that does not fit its
bucket (``cap`` overflow under skew) is never silently lost — the
send side computes a per-lane ``dropped`` mask, the engine parks it like
an optimistic miss-check, and the dropped lanes re-deliver next tick
through the exact same path with their ORIGINAL ``inject_tick`` stamp
(the latency ledger therefore includes the redelivery wait, same
contract as the miss path).  Inside a fused window the dropped count
folds into the window's miss counter instead: a nonzero count fails
``verify()`` and the auto-fuser rolls back and replays unfused —
transparency never costs exactness.

Ordering caveat (same as host-batch padding): the exchange permutes lane
order within a (type, method) batch.  Delivery SETS are preserved
exactly; handlers that resolve duplicate-row writes by lane order
(``scatter_rows`` with duplicate destinations) are order-sensitive and
should combine with ``seg_*`` instead — the contract vector_grain.py
already states for fan-in.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec


def pow2ceil(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


class ShardExchange:
    """Per-engine exchange plane: builds and caches the jitted exchange
    programs (one per (batch size, capacity, shard layout) — batch sizes
    are stable in steady state, and ``cap`` is pow2-padded) and holds the
    device-side stat accumulators the engine drains at quiescence.

    ``capacity_factor`` sizes the per-(src, dst) bucket relative to the
    uniform share ``L / n_shards``: 2.0 tolerates 2x destination skew
    before any lane overflows into redelivery.  ``pad_quantum`` floors
    the bucket so tiny batches don't churn compiles."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self.mesh = engine.mesh
        self.axis = engine.config.mesh_axis
        self.n_shards = engine.n_shards
        # cumulative stats (folded from device at drain points)
        self.exchanges_run = 0
        self.cross_shard_msgs = 0
        self.delivered_msgs = 0
        self.dropped_msgs = 0
        self.redeliveries = 0
        self.exchange_seconds = 0.0
        self._jit_cache: Dict[Tuple[int, int, int], Any] = {}

    def adopt_stats(self, prev: "Optional[ShardExchange]") -> None:
        """Carry cumulative counters across a mesh reshard (the engine
        rebuilds the exchange; the perf trajectory must not reset)."""
        if prev is None:
            return
        self.exchanges_run = prev.exchanges_run
        self.cross_shard_msgs = prev.cross_shard_msgs
        self.delivered_msgs = prev.delivered_msgs
        self.dropped_msgs = prev.dropped_msgs
        self.redeliveries = prev.redeliveries
        self.exchange_seconds = prev.exchange_seconds

    # -- planning ------------------------------------------------------------

    def plan(self, m: int) -> Tuple[int, int]:
        """(per-shard lanes L, per-(src,dst) bucket cap) for an m-lane
        batch.  Both pow2 so the compile set under varying batch sizes is
        O(log n); cap is clamped to L (a bucket can never need more than
        one shard's whole slice)."""
        n = self.n_shards
        cfg = self.engine.config
        L = pow2ceil(-(-m // n))
        cap = min(L, pow2ceil(max(
            int(cfg.exchange_pad_quantum),
            int(L / n * cfg.exchange_capacity_factor))))
        return L, cap

    # -- the per-shard program (pure jax; traced into jit or a fused scan) ---

    def _traced(self, rows, leaves: List[Any], mask, shard_capacity: int,
                L: int, cap: int):
        """The exchange body at padded size ``n * L``: returns
        ``(recv_rows, recv_leaves, recv_mask, dropped, stats)`` where
        ``dropped`` is a bool[n*L] mask in INPUT lane order (slice back
        to m) and ``stats`` is an int32[3] (cross_shard, dropped,
        delivered) summed over shards."""
        from jax.experimental.shard_map import shard_map

        n = self.n_shards
        axis = self.axis
        m_pad = n * L
        W = pow2ceil(L + n * cap)  # output lanes per shard

        def pad_to(x, fill):
            if x.shape[0] == m_pad:
                return x
            widths = [(0, m_pad - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, widths, constant_values=fill)

        rows = pad_to(jnp.asarray(rows, jnp.int32), -1)
        mask = pad_to(jnp.asarray(mask, bool), False)
        leaves = [pad_to(jnp.asarray(x), 0) for x in leaves]

        def per_shard(rows_l, mask_l, *leaves_l):
            my = jax.lax.axis_index(axis)
            valid = mask_l & (rows_l >= 0)
            # destination shard straight from the row-block layout — the
            # same function as the directory's shard_of_keys (arena rows
            # are allocated in the key's home block; property-tested)
            dest = jnp.where(valid, rows_l // shard_capacity, n)
            # lanes already home stay IN PLACE (first L output lanes):
            # the all_to_all carries only cross-shard traffic, so its
            # volume — and the bucket pressure `cap` must absorb —
            # scales with the cross-shard ratio, not the batch size
            local = valid & (dest == my)
            sdest_in = jnp.where(valid & ~local, dest, n)
            order = jnp.argsort(sdest_in)  # ties keep relative order
            sdest = sdest_in[order]
            start = jnp.searchsorted(sdest,
                                     jnp.arange(n, dtype=sdest.dtype))
            pos = jnp.arange(L) - start[jnp.clip(sdest, 0, n - 1)]
            fits = (sdest < n) & (pos < cap)
            # out-of-range slot + mode="drop": invalid/overflow lanes
            # scatter nowhere
            slot = jnp.where(fits, sdest * cap + pos, n * cap)
            send_rows = jnp.full(n * cap, -1, jnp.int32) \
                .at[slot].set(rows_l[order], mode="drop")

            def bucket(leaf):
                s = leaf[order]
                out = jnp.zeros((n * cap,) + s.shape[1:], s.dtype)
                return out.at[slot].set(s, mode="drop")

            send_leaves = [bucket(x) for x in leaves_l]

            def a2a(x):
                r = jax.lax.all_to_all(
                    x.reshape((n, cap) + x.shape[1:]), axis,
                    split_axis=0, concat_axis=0)
                return r.reshape((n * cap,) + x.shape[2:])

            # output per-shard width pads to pow2: a DOWNSTREAM exchange
            # (the emit leg of this batch) re-slices the global output
            # into pow2 per-shard runs, and only a pow2 width keeps
            # those slices aligned with THIS exchange's shard boundaries
            # — misaligned slices would re-cross lanes that are already
            # home (correct but wasteful; the accounting test pins it)
            tail = W - (L + n * cap)
            recv_rows = jnp.concatenate(
                [jnp.where(local, rows_l, -1), a2a(send_rows),
                 jnp.full(tail, -1, jnp.int32)])
            recv_leaves = [
                jnp.concatenate(
                    [x, a2a(s),
                     jnp.zeros((tail,) + x.shape[1:], x.dtype)])
                for x, s in zip(leaves_l, send_leaves)]
            recv_mask = recv_rows >= 0
            # dropped mask back in input lane order
            dropped_sorted = (sdest < n) & (pos >= cap)
            dropped_l = jnp.zeros(L, bool).at[order].set(dropped_sorted)
            n_dropped = jnp.sum(dropped_sorted.astype(jnp.int32))
            stats = jnp.stack([
                jnp.sum((valid & ~local).astype(jnp.int32)),
                n_dropped,
                jnp.sum(valid.astype(jnp.int32)) - n_dropped,
            ])[None, :]  # [1, 3]: per-shard partial, summed outside
            return (recv_rows, recv_mask, dropped_l, stats, *recv_leaves)

        P = PartitionSpec
        sharded = P(axis)
        out_specs = (sharded, sharded, sharded, sharded) \
            + (sharded,) * len(leaves)
        fn = shard_map(per_shard, mesh=self.mesh,
                       in_specs=(sharded, sharded) + (sharded,) * len(leaves),
                       out_specs=out_specs, check_rep=False)
        recv_rows, recv_mask, dropped, stats, *recv_leaves = fn(
            rows, mask, *leaves)
        return (recv_rows, recv_leaves, recv_mask, dropped,
                jnp.sum(stats, axis=0))

    # -- fused-path entry (called under an active trace) ---------------------

    def apply_traced(self, shard_capacity: int, rows, args: Any, mask):
        """Exchange inside a fused window trace: returns
        ``(rows2, args2, mask2, dropped_count)`` — the dropped count
        folds into the window's device-side miss counter so a capacity
        overflow fails ``verify()`` (rollback + unfused replay) instead
        of losing lanes.  A group whose args are not lane-aligned (slab
        -style handlers consuming a whole buffer per tick, e.g. the
        twitter dispatcher) passes through untouched — permuting rows
        away from such args would break the handler's row↔buffer
        correspondence."""
        m = rows.shape[0]
        if not exchangeable_args(args, m):
            return rows, args, mask, jnp.int32(0)
        L, cap = self.plan(m)
        leaves, treedef, scalar_ix = _split_leaves(args, m)
        rows2, leaves2, mask2, _dropped, stats = self._traced(
            rows, leaves, mask, shard_capacity, L, cap)
        args2 = _join_leaves(treedef, scalar_ix, leaves2)
        return rows2, args2, mask2, stats[1]

    # -- unfused-path entry (jitted dispatch; stats parked on device) --------

    def dispatch(self, arena, rows, args: Any, mask):
        """One async exchange dispatch for an unfused batch.  Returns
        ``(rows2, args2, mask2, dropped_mask, stats)`` with the dropped
        mask and the int32[3] stats still ON DEVICE — the engine parks
        them (like a miss-check) and reads everything in one batched
        transfer at the next quiescence point."""
        t0 = time.perf_counter()
        m = int(rows.shape[0])
        shard_capacity = int(arena.shard_capacity)
        L, cap = self.plan(m)
        leaves, treedef, scalar_ix = _split_leaves(args, m)
        key = (L, cap, shard_capacity, len(leaves))
        fn = self._jit_cache.get(key)
        if fn is None:
            def call(rows, mask, *leaves):
                return self._traced(rows, list(leaves), mask,
                                    shard_capacity, L, cap)
            fn = jax.jit(call)
            self._jit_cache[key] = fn
        rows2, leaves2, mask2, dropped, stats = fn(
            jnp.asarray(rows), mask, *leaves)
        args2 = _join_leaves(treedef, scalar_ix, leaves2)
        self.exchanges_run += 1
        self.exchange_seconds += time.perf_counter() - t0
        return rows2, args2, mask2, dropped[:m], stats

    def fold_stats(self, stats_host: np.ndarray) -> None:
        """Accumulate one drained [3] stats vector."""
        self.cross_shard_msgs += int(stats_host[0])
        self.dropped_msgs += int(stats_host[1])
        self.delivered_msgs += int(stats_host[2])

    def snapshot(self) -> Dict[str, Any]:
        return {
            "n_shards": self.n_shards,
            "exchanges_run": self.exchanges_run,
            "cross_shard_msgs": self.cross_shard_msgs,
            "delivered_msgs": self.delivered_msgs,
            "dropped_msgs": self.dropped_msgs,
            "redeliveries": self.redeliveries,
            "exchange_seconds": round(self.exchange_seconds, 6),
            "compiled_programs": len(self._jit_cache),
        }


def exchangeable_args(args: Any, m: int) -> bool:
    """True when every non-scalar arg leaf is lane-aligned ([m, ...]) —
    the precondition for permuting lanes.  Slab-style handlers (args
    consumed as a whole buffer, not per lane) fail this and keep the
    legacy path."""
    return all(np.ndim(leaf) == 0 or np.shape(leaf)[0] == m
               for leaf in jax.tree_util.tree_leaves(args))


def _split_leaves(args: Any, m: int):
    """Flatten an args pytree into (exchangeable [m, ...] leaves,
    treedef, scalar positions).  Scalar leaves broadcast in the kernels
    and are uniform across lanes, so they bypass the exchange."""
    flat, treedef = jax.tree_util.tree_flatten(args)
    leaves: List[Any] = []
    scalar_ix: Dict[int, Any] = {}
    for i, leaf in enumerate(flat):
        if np.ndim(leaf) == 0:
            scalar_ix[i] = leaf
        else:
            if np.shape(leaf)[0] != m:
                raise ValueError(
                    f"exchange: arg leaf {i} has leading dim "
                    f"{np.shape(leaf)[0]}, batch has {m} lanes")
            leaves.append(leaf)
    return leaves, treedef, scalar_ix


def _join_leaves(treedef, scalar_ix: Dict[int, Any],
                 leaves: List[Any]) -> Any:
    flat: List[Any] = []
    it = iter(leaves)
    for i in range(treedef.num_leaves):
        flat.append(scalar_ix[i] if i in scalar_ix else next(it))
    return jax.tree_util.tree_unflatten(treedef, flat)
