"""In-process multi-silo test cluster.

Parity: reference TestingSiloHost (reference: src/OrleansTestingHost/
TestingSiloHost.cs:58 — Primary+Secondary in AppDomains, client attached
in-process, StartAdditionalSilos :235, KillSilo :334 hard-kill,
RestartSilo :347) plus its shared in-process store so MemoryStorage
survives topology changes (reference: Silo.cs:217-221,
HierarchicalKeyStore.cs:33).

Here "AppDomain" isolation becomes: silos on one event loop joined by an
InProcTransport fabric (wire-fidelity serialization on every hop) and one
shared InMemoryMembershipTable — the same trust boundaries, minus threads.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Dict, List, Optional, Tuple

from orleans_tpu.config import SiloConfig
from orleans_tpu.core.factory import GrainFactory
from orleans_tpu.providers.memory_storage import MemoryStorage
from orleans_tpu.runtime.membership import InMemoryMembershipTable
from orleans_tpu.runtime.reminders import InMemoryReminderTable
from orleans_tpu.runtime.silo import Silo
from orleans_tpu.runtime.transport import InProcTransport


class TestingCluster:

    __test__ = False  # not a pytest collection target

    def __init__(self, n_silos: int = 2,
                 config_factory: Optional[Callable[[str], SiloConfig]] = None,
                 wire_fidelity: bool = True,
                 silo_setup: Optional[Callable[[Silo], None]] = None,
                 transport: str = "inproc",
                 table_service: bool = False,
                 table_service_address: Optional[Tuple[str, int]] = None
                 ) -> None:
        self.n_initial = n_silos
        self.config_factory = config_factory or self._default_config
        # per-silo wiring hook (providers etc.) run before silo.start()
        self.silo_setup = silo_setup
        # "inproc": wire-fidelity in-memory fabric (fast default);
        # "tcp": real sockets between silos on this loop — the DCN path
        # (framing, TTL rebase, connect failure, queue bounds) under the
        # same kill/restart suite (reference: the AppDomain test cluster
        # still spoke real TCP between silos)
        self.transport = transport
        if transport == "tcp":
            from orleans_tpu.runtime.transport import TcpFabric
            self.fabric = TcpFabric()
        else:
            self.fabric = InProcTransport(wire_fidelity=wire_fidelity)
        self.table = InMemoryMembershipTable()
        # shared durable reminder store (reference: TestingSiloHost's
        # ReminderTableGrain / shared in-proc stores)
        self.reminder_table = InMemoryReminderTable()
        # table_service=True: silos reach the system tables over TCP via
        # a TableServiceServer started by start() — the "no shared disk"
        # cluster formation mode (plugins/table_service.py; reference:
        # ZooKeeper/SQL membership table deployments)
        self._use_table_service = table_service
        self.table_service = None
        # external table service (e.g. a `python -m
        # orleans_tpu.plugins.table_service` process): silos connect to
        # this address instead of an in-process server started by start()
        self._table_service_address = table_service_address
        self._remote_tables: List = []
        self.storage_backing = MemoryStorage.shared_backing()
        # durable pub/sub state so stream subscriptions survive the death
        # of the silo hosting a rendezvous grain (reference: the test
        # clusters' "PubSubStore" provider block)
        self.pubsub_backing = MemoryStorage.shared_backing()
        self.silos: List[Silo] = []
        self._counter = 0

    @staticmethod
    def _default_config(name: str) -> SiloConfig:
        cfg = SiloConfig(name=name)
        # fast liveness for tests (reference: TestingSiloHost liveness
        # config with shortened probe/vote timings)
        cfg.liveness.probe_period = 0.1
        cfg.liveness.probe_timeout = 0.1
        cfg.liveness.num_missed_probes_limit = 2
        cfg.liveness.table_refresh_timeout = 0.2
        cfg.liveness.iam_alive_table_publish = 0.5
        return cfg

    # ================= lifecycle ==========================================

    async def start(self) -> "TestingCluster":
        if self._use_table_service and self.table_service is None:
            from orleans_tpu.plugins.table_service import TableServiceServer
            self.table_service = await TableServiceServer(
                membership_table=self.table,
                reminder_table=self.reminder_table).start()
        for _ in range(self.n_initial):
            await self.start_additional_silo()
        return self

    async def start_additional_silo(self, name: Optional[str] = None) -> Silo:
        """(reference: TestingSiloHost.StartAdditionalSilos :235)"""
        if name is None:
            self._counter += 1
            name = f"silo{self._counter}"
        host, port = None, 0
        if self.transport == "tcp":
            host, port = self.fabric.host, self.fabric.reserve()
        membership_table, reminder_table = self.table, self.reminder_table
        if self.table_service is not None \
                or self._table_service_address is not None:
            from orleans_tpu.plugins.table_service import (
                RemoteMembershipTable,
                RemoteReminderTable,
            )
            ts_host, ts_port = (self._table_service_address
                                or self.table_service.address)
            membership_table = RemoteMembershipTable(ts_host, ts_port)
            reminder_table = RemoteReminderTable(ts_host, ts_port)
            self._remote_tables += [membership_table, reminder_table]
        silo = Silo(
            config=self.config_factory(name),
            storage_providers={
                "Default": MemoryStorage(self.storage_backing),
                "PubSubStore": MemoryStorage(self.pubsub_backing),
            },
            fabric=self.fabric,
            membership_table=membership_table,
            reminder_table=reminder_table,
            host=host, port=port,
        )
        if self.silo_setup is not None:
            self.silo_setup(silo)
        await silo.start()
        self.silos.append(silo)
        # let membership settle (gossip + view refresh)
        await asyncio.sleep(0)
        return silo

    def kill_silo(self, silo: Silo) -> None:
        """Hard kill — no goodbye, no handoff; peers must detect it
        (reference: TestingSiloHost.KillSilo :334)."""
        silo.kill()
        if silo in self.silos:
            self.silos.remove(silo)

    async def stop_silo(self, silo: Silo) -> None:
        """Graceful single-silo shutdown."""
        await silo.stop()
        if silo in self.silos:
            self.silos.remove(silo)

    async def restart_silo(self, silo: Silo) -> Silo:
        """Kill + start a fresh incarnation at the same endpoint — new
        generation, so the membership protocol declares the old one dead
        (reference: TestingSiloHost.RestartSilo :347)."""
        self.kill_silo(silo)
        return await self.start_additional_silo(name=silo.name)

    async def stop(self) -> None:
        for silo in list(reversed(self.silos)):
            await silo.stop()
        self.silos.clear()
        for t in self._remote_tables:
            t.close()
        self._remote_tables.clear()
        if self.table_service is not None:
            self.table_service.close()
            self.table_service = None

    # ================= client =============================================

    def attach_client(self, silo_index: int = 0) -> GrainFactory:
        """In-process client bound to one silo (reference: TestingSiloHost
        initializes GrainClient against the primary gateway)."""
        return self.silos[silo_index].attach_client()

    # ================= convenience ========================================

    async def quiesce_engines(self, rounds: int = 300,
                              poll: float = 0.01) -> None:
        """Quiesce the cluster's tensor data plane: flush every silo's
        engine until no engine processes anything new — slabs may still
        be in flight between silos after any single engine drains
        (tensor/router.py), so stability must be observed cluster-wide."""
        last, stable = -1, 0
        for _ in range(rounds):
            for silo in self.silos:
                if silo.tensor_engine is not None:
                    await silo.tensor_engine.flush()
            await asyncio.sleep(poll)
            total = sum(s.tensor_engine.messages_processed
                        for s in self.silos
                        if s.tensor_engine is not None)
            if total == last:
                stable += 1
                if stable >= 3:
                    return
            else:
                stable = 0
            last = total
        raise TimeoutError("tensor data plane did not quiesce")

    async def wait_for_liveness_convergence(self, timeout: float = 10.0) -> None:
        """Wait until every live silo's view equals exactly the live set —
        in particular, killed silos must have been DECLARED dead by every
        survivor (merely agreeing while all still believe a corpse is
        active is not convergence)."""
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            expected = frozenset(s.address for s in self.silos)
            if all(frozenset(s.active_silos()) == expected
                   for s in self.silos):
                return
            if asyncio.get_running_loop().time() > deadline:
                views = [frozenset(s.active_silos()) for s in self.silos]
                raise TimeoutError(
                    f"liveness did not converge: {views} != {expected}")
            await asyncio.sleep(0.05)

    def collect_timeline(self, reference: str = "",
                         out_dir: Optional[str] = None):
        """In-process timeline collection: merge every live silo's
        per-silo span/lifecycle/metrics log onto one clock
        (orleans_tpu/timeline.py).  In-process silos share one
        ``time.monotonic()``, so the merge is exact even before any
        clock probe has run.  ``out_dir`` additionally writes
        ``TIMELINE.json`` + the Perfetto export there."""
        from orleans_tpu.timeline import merge_timelines, write_artifacts
        exports = [s.spans.timeline.export() for s in self.silos
                   if s.spans.timeline is not None]
        merged = merge_timelines(exports, reference=reference)
        if out_dir is not None:
            write_artifacts(merged, out_dir)
        return merged

    def total_activations(self) -> int:
        return sum(len(s.catalog.directory) for s in self.silos)

    def find_silo_hosting(self, grain_id) -> Optional[Silo]:
        for s in self.silos:
            if s.catalog.directory.by_grain.get(grain_id):
                return s
        return None
