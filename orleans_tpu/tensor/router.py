"""VectorRouter: the cross-silo batched vector data plane.

The reference crosses the silo boundary one message at a time through a
dedicated sender thread that batch-serializes whatever is queued
(reference: src/OrleansRuntime/Messaging/OutgoingMessageSender.cs:128-176);
the north star demands the inverse discipline — batches stay batches across
the boundary.  When a vector batch's keys hash to a remote silo's arena,
the router partitions the batch by ring owner, serializes each partition as
ONE (keys, args) slab through the codec (first-class ndarray tokens), ships
it over the silo transport, and the peer injects it into its engine as a
batch — never through the per-message host path.

Single-activation enforcement (reference: Catalog.cs:533-563 duplicate-
activation race; LocalGrainDirectory.cs:510): a vector grain's arena row
may exist ONLY on its ring owner.  Every entry point — host batches, the
per-message dispatcher bridge, optimistic device-miss activation — derives
ownership from the same vectorized ring hash (hashing.ring_hash_int_keys ==
GrainId.ring_hash bit-for-bit), so "which silo owns this key" has exactly
one answer everywhere.  On ring change, rows whose keys are no longer owned
are written back and evicted (the arena half of directory handoff,
reference: GrainDirectoryHandoffManager.cs:141); the new owner re-activates
them from the store on first touch.

Fan-out contract: DeviceFanout subscription graphs are owner-local state.
A slab ships *pre-expansion* messages and the owner expands them through
its own CSR — registering a remote key's subscriptions on a non-owner silo
would double-deliver and is a configuration error.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import jax
import numpy as np

from orleans_tpu.hashing import ring_hash_int_keys
from orleans_tpu.ids import GrainCategory, SiloAddress


def _gather_args(args: Any, idx: np.ndarray) -> Any:
    """Take rows ``idx`` of every array leaf (scalar leaves broadcast)."""
    return jax.tree_util.tree_map(
        lambda a: a if np.ndim(a) == 0 else np.asarray(a)[idx], args)


@jax.jit
def _gather_args_dev(args: Any, idx) -> Any:
    """Device-side partition gather (scalar leaves pass through) — keeps
    the local slice of a device-resident payload on device and shrinks
    the remote slices BEFORE they cross to the host."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: a if jnp.ndim(a) == 0 else jnp.take(a, idx, axis=0),
        args)


def _host_args(args: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, args)


def _merge_fragments(frags: List[Tuple[np.ndarray, Any]]
                     ) -> Tuple[np.ndarray, Any]:
    """Concatenate per-destination slab fragments into one (keys, args)
    slab; scalar leaves broadcast to their fragment's row count first
    (same discipline as engine._coalesce_host_batches)."""
    keys = np.concatenate([k for k, _ in frags])

    def cat(*leaves):
        return np.concatenate(
            [np.broadcast_to(np.asarray(x),
                             (len(frags[i][0]),) + np.shape(x)[1:])
             if np.ndim(x) == 0 else np.asarray(x)
             for i, x in enumerate(leaves)])

    args = jax.tree_util.tree_map(cat, *(a for _, a in frags))
    return keys, args


def _send_release(silo, target: SiloAddress, digest: Tuple[str, ...]) -> None:
    """One-way handoff_release to a peer's vector_router target."""
    from orleans_tpu.ids import GrainId, SystemTargetCodes
    from orleans_tpu.runtime.messaging import Category, Direction, Message
    silo.message_center.send_message(Message(
        category=Category.SYSTEM,
        direction=Direction.ONE_WAY,
        sending_silo=silo.address,
        sending_grain=silo.client_grain_id,
        target_silo=target,
        target_grain=GrainId.system_target(
            int(SystemTargetCodes.VECTOR_ROUTER)),
        method_name="handoff_release",
        args=(list(digest), silo.address),
    ))


class VectorRouter:
    """One per clustered silo; registered as the ``vector_router`` system
    target so peers can address slabs to it."""

    def __init__(self, silo) -> None:
        self.silo = silo
        self.engine = silo.tensor_engine
        self.engine.router = self
        # owner tables cache, keyed by (ring.version, type_code) — the ring
        # invalidates by version bump on membership change
        self._my_index_cache: Tuple[int, int] = (-2, -2)
        self.slabs_shipped = 0
        self.messages_shipped = 0
        self.slabs_received = 0
        self.messages_received = 0
        self.slabs_requeued = 0
        self.messages_dropped = 0
        self.slab_retry_limit = 8
        self._retry_tasks: Set[asyncio.Task] = set()
        # -- sender-side slab aggregation ---------------------------------
        # fragments produced within one drain cycle (one synchronous burst
        # of the event loop) accumulate per (target, type, method) and
        # flush as ONE merged slab, so the receiver sees a handful of
        # stable-bucketed batch sizes instead of N compile-churning ones
        # (the sender-side analog of engine._coalesce_host_batches; the
        # reference batch-drains its per-destination send queues in
        # SocketSender/SiloMessageSender rather than writing singly).
        # Toggle (config.tensor.slab_aggregation) kept for A/B measurement
        # — bench.py --workload cluster publishes both sides.
        self.aggregate_slabs = bool(getattr(
            silo.config.tensor, "slab_aggregation", True))
        self._pending_slabs: Dict[Tuple, List[Tuple[np.ndarray, Any]]] = {}
        self._flush_scheduled = False
        self.slab_fragments = 0   # ship_slab calls (pre-merge)
        self.slab_frames = 0      # one-way frames actually sent (post-merge)
        self.slab_bounces = 0     # frames the transport bounced back to us
        # recurring-slab injector cache (see _inject_local)
        self._slab_injectors: Dict[Tuple, Any] = {}
        self._slab_key_counts: Dict[Tuple, int] = {}
        # -- placement overrides (live migration across silos) -------------
        # type_name → {key: SiloAddress}: keys the rebalance plane moved
        # OFF their ring-hash owner.  partition() applies them after the
        # hash, so every entry point (host batches, miss activation,
        # slab arrivals) gets the same one answer — the directory's
        # "exception table" for migrated vector grains.  Scoped to the
        # current membership VIEW: any ring change clears them (keys
        # re-home by hash; the handoff migration moves state to match).
        self._placement: Dict[str, Dict[int, SiloAddress]] = {}
        self._placement_arrays_cache: Dict[str, Tuple] = {}
        self.grains_migrated_out = 0
        self.grains_adopted = 0
        self.adopt_conflicts = 0
        # -- handoff fence (ordering for ownership moves) ------------------
        # A ring change moves key ranges between silos, but old and new
        # owners process the change at independent times: the new owner's
        # first-touch store READ could precede the old owner's write-back,
        # silently losing state (the race the reference's
        # GrainDirectoryHandoffManager transfer protocol closes).  Fence:
        # after processing a change (write-back + evict done), each silo
        # broadcasts handoff_release(view-digest) to its peers; a silo
        # defers ACTIVATION of unseen keys until every alive peer has
        # released the current view (or the fence times out — a dead/
        # stalled peer must not wedge the cluster; its loss window is the
        # documented checkpoint cadence).
        self._fence_version = -1
        self._barrier_digest: Tuple[str, ...] = ()
        self._awaiting: Set[SiloAddress] = set()
        self._acks: Dict[SiloAddress, Tuple[str, ...]] = {}
        self._handoff_deadline = 0.0
        self.handoff_timeout = getattr(silo.config.tensor,
                                       "handoff_fence_timeout", 2.0)
        # arm/broadcast on EVERY ring change, even before the silo is
        # ACTIVE (a joining silo must release its peers — it holds no
        # rows, so its release is trivially true; eviction for active
        # silos already ran: the silo's own ring subscription precedes
        # this one, so on_ring_changed's write-back happens first).
        # A SHUTTING_DOWN silo's release is also sound: its ranges move
        # only at membership leave, and graceful stop checkpoints the
        # arenas BEFORE the leave (silo.py stop ordering), so any range
        # a peer gains from it is already durable; mid-shutdown ring
        # changes caused by THIRD silos move no ranges away from it.
        silo.ring.subscribe(lambda *_: self._arm_fence())

    # ================= ownership ==========================================

    def _my_index(self, members: List[SiloAddress]) -> int:
        version = self.silo.ring.version
        cached_version, idx = self._my_index_cache
        if cached_version != version:
            try:
                idx = members.index(self.silo.address)
            except ValueError:
                idx = -1  # non-hosting observer: owns nothing
            self._my_index_cache = (version, idx)
        return idx

    def partition(self, type_name: str, keys: np.ndarray
                  ) -> Tuple[np.ndarray, Dict[SiloAddress, np.ndarray]]:
        """Split ``keys`` (int64[n]) by ring owner.

        Returns ``(local_mask bool[n], {owner: index_array})`` where the
        index arrays cover exactly the non-local entries.  Single-member
        rings short-circuit to all-local (zero hashing cost)."""
        ring = self.silo.ring
        keys = np.asarray(keys, dtype=np.int64)
        ov = self._placement.get(type_name)
        if len(ring._members) <= 1 and self._my_index(ring.members) == 0 \
                and not ov:
            return np.ones(len(keys), dtype=bool), {}
        from orleans_tpu.tensor.vector_grain import vector_type
        info = vector_type(type_name)
        points = ring_hash_int_keys(info.type_code, keys,
                                    category=int(GrainCategory.GRAIN))
        owner_idx, members = ring.owners_of_hashes(points)
        my = self._my_index(members)
        if ov:
            # live-migration overrides beat the hash (the directory's
            # exception table): one vectorized membership test over the
            # small pinned set, then per-hit rewrites
            pk, pt = self._placement_arrays(type_name)
            idx = np.minimum(np.searchsorted(pk, keys), len(pk) - 1)
            hits = np.nonzero(pk[idx] == keys)[0]
            if len(hits):
                members = list(members)
                midx = {m: i for i, m in enumerate(members)}
                owner_idx = owner_idx.copy()
                for i in hits:
                    t = pt[int(idx[i])]
                    j = midx.get(t)
                    if j is None:
                        members.append(t)
                        j = len(members) - 1
                        midx[t] = j
                    owner_idx[i] = j
                my = midx.get(self.silo.address, -1)
        local_mask = owner_idx == my
        remote: Dict[SiloAddress, np.ndarray] = {}
        if not local_mask.all():
            for o in np.unique(owner_idx[~local_mask]):
                if o < 0:
                    continue
                remote[members[int(o)]] = np.nonzero(owner_idx == o)[0]
        return local_mask, remote

    def _placement_arrays(self, type_name: str) -> Tuple:
        """Sorted (keys int64[], targets list) mirror of one type's
        placement overrides, cached until the override set mutates."""
        cached = self._placement_arrays_cache.get(type_name)
        ov = self._placement.get(type_name, {})
        if cached is not None and cached[2] == len(ov):
            return cached[0], cached[1]
        pk = np.fromiter(ov.keys(), dtype=np.int64, count=len(ov))
        order = np.argsort(pk)
        pk = pk[order]
        vals = list(ov.values())
        pt = [vals[int(i)] for i in order]
        self._placement_arrays_cache[type_name] = (pk, pt, len(ov))
        return pk, pt

    def register_placement(self, type_name: str, keys: np.ndarray,
                           target: SiloAddress) -> None:
        """Record live-migration placement overrides (idempotent; the
        broadcast applies them on every silo so ownership has one
        answer everywhere)."""
        ov = self._placement.setdefault(type_name, {})
        for k in np.asarray(keys, dtype=np.int64).tolist():
            ov[int(k)] = target
        self._placement_arrays_cache.pop(type_name, None)

    def owns_key(self, type_name: str, key: int) -> bool:
        local, _ = self.partition(type_name,
                                  np.asarray([key], dtype=np.int64))
        return bool(local[0])

    # ================= handoff fence ======================================

    def _view_digest(self) -> Tuple[str, ...]:
        return tuple(sorted(str(m) for m in self.silo.ring.members))

    def _arm_fence(self) -> None:
        """Ring changed: broadcast our release (write-back for this change
        is already durable — the silo's eviction subscription runs before
        this one) and start awaiting the peers' releases."""
        ring = self.silo.ring
        self._fence_version = ring.version
        digest = self._view_digest()
        self._barrier_digest = digest
        peers = [m for m in ring.members if m != self.silo.address]
        self._awaiting = {p for p in peers if self._acks.get(p) != digest}
        self._handoff_deadline = time.monotonic() + self.handoff_timeout
        for p in peers:
            _send_release(self.silo, p, digest)

    async def handoff_release(self, digest, sender: SiloAddress) -> None:
        """Peer finished its write-back for the membership view ``digest``
        — unseen keys in ranges we gained from it are now safe to
        activate from the store."""
        digest = tuple(digest)
        self._acks[sender] = digest
        if digest == self._barrier_digest:
            self._awaiting.discard(sender)

    def handoff_settled(self) -> bool:
        """True when first-touch activation is safe: every alive peer has
        released the current membership view (their write-back for any
        range we gained is durable).  The engine defers unseen-key
        activation while this is False; traffic to already-active rows is
        unaffected."""
        if self._fence_version != self.silo.ring.version:
            self._arm_fence()
        if not self._awaiting:
            return True
        if time.monotonic() >= self._handoff_deadline:
            self.silo.logger.warn(
                f"handoff fence timed out awaiting release from "
                f"{[str(p) for p in self._awaiting]} — proceeding "
                f"(their write-back may still be in flight)", code=2912)
            self._awaiting.clear()
            return True
        alive = set(self.silo.active_silos())
        self._awaiting = {p for p in self._awaiting if p in alive}
        return not self._awaiting

    # ================= send side ==========================================

    def route_batch(self, type_name: str, method: str, keys: np.ndarray,
                    args: Any, want_results: bool = False
                    ) -> Optional[asyncio.Future]:
        """Cluster-level send_batch: local partition enqueues on this
        silo's engine, each remote partition ships as one slab."""
        keys = np.asarray(keys, dtype=np.int64)
        local_mask, remote = self.partition(type_name, keys)
        if not remote:
            return self.engine.enqueue_local_batch(
                type_name, method, keys, args, want_results=want_results)
        args_h = _host_args(args)
        if not want_results:
            if local_mask.any():
                lidx = np.nonzero(local_mask)[0]
                self.engine.enqueue_local_batch(
                    type_name, method, keys[lidx], _gather_args(args_h, lidx))
            for target, idx in remote.items():
                self.ship_slab(target, type_name, method, keys[idx],
                               _gather_args(args_h, idx))
            return None
        return asyncio.get_running_loop().create_task(
            self._route_with_results(type_name, method, keys, args_h,
                                     local_mask, remote))

    async def _route_with_results(self, type_name: str, method: str,
                                  keys: np.ndarray, args_h: Any,
                                  local_mask: np.ndarray,
                                  remote: Dict[SiloAddress, np.ndarray],
                                  hops: int = 0) -> Any:
        """Scatter a want_results batch, await all partitions, reassemble
        the result pytree in the caller's original message order."""
        if remote and hops > self.silo.max_forward_count:
            # diverged ring views could bounce a slab between silos
            # forever — bound the hop chain like any forwarded request
            # (reference: Dispatcher.TryForwardRequest :474)
            raise RuntimeError(
                f"vector slab for {type_name} exceeded max forward count "
                f"({hops} hops; ring views diverged?)")
        parts: List[Tuple[np.ndarray, Any]] = []  # (index array, awaitable)
        if local_mask.any():
            lidx = np.nonzero(local_mask)[0]
            fut = self.engine.enqueue_local_batch(
                type_name, method, keys[lidx], _gather_args(args_h, lidx),
                want_results=True)
            parts.append((lidx, fut))
        for target, idx in remote.items():
            self.messages_shipped += len(idx)
            self.slabs_shipped += 1
            coro = self.silo.system_rpc(
                target, "vector_router", "call_slab",
                (type_name, method, keys[idx], _gather_args(args_h, idx),
                 hops + 1))
            parts.append((idx, coro))
        results = await asyncio.gather(*(p[1] for p in parts))
        if all(r is None for r in results):
            return None
        n = len(keys)

        def scatter(*leaves):
            out = None
            for (idx, _), leaf in zip(parts, leaves):
                if leaf is None:
                    continue
                leaf = np.asarray(leaf)
                if out is None:
                    out = np.zeros((n,) + leaf.shape[1:], dtype=leaf.dtype)
                out[idx] = leaf
            return out

        # all non-None parts share one handler → one tree structure
        first = next(r for r in results if r is not None)
        leaves_per_part = []
        treedef = jax.tree_util.tree_structure(first)
        for r in results:
            if r is None:
                leaves_per_part.append(
                    [None] * treedef.num_leaves)
            else:
                leaves_per_part.append(jax.tree_util.tree_leaves(r))
        combined = [scatter(*[lp[i] for lp in leaves_per_part])
                    for i in range(treedef.num_leaves)]
        return jax.tree_util.tree_unflatten(treedef, combined)

    def ship_slab(self, target: SiloAddress, type_name: str, method: str,
                  keys: np.ndarray, args: Any, hops: int = 0,
                  retries: int = 0) -> None:
        """One (keys, args) slab fragment bound for ``target``'s router
        (the batched silo boundary; never per-message send_one).

        With aggregation on (default), fragments accumulate per
        (target, type, method, hops, retries) and flush as ONE merged
        frame at the end of the current drain cycle; with it off every
        fragment is its own frame.  ``retries`` rides the wire so the
        backoff budget accumulates across silos — a slab ping-ponging
        between diverged ring views still hits the drop limit instead of
        circulating forever."""
        keys = np.asarray(keys, dtype=np.int64)
        self.slab_fragments += 1
        self.messages_shipped += len(keys)
        if not self.aggregate_slabs:
            self._ship_frame(target, type_name, method, keys,
                             _host_args(args), hops, retries)
            return
        bucket = (target, type_name, method, int(hops), int(retries))
        self._pending_slabs.setdefault(bucket, []).append(
            (keys, _host_args(args)))
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self.flush_slabs)

    def flush_slabs(self) -> None:
        """End-of-drain-cycle flush: one merged frame per pending
        (destination, type, method) bucket."""
        self._flush_scheduled = False
        pending, self._pending_slabs = self._pending_slabs, {}
        for (target, type_name, method, hops, retries), frags \
                in pending.items():
            if len(frags) == 1:
                keys, args = frags[0]
            else:
                try:
                    keys, args = _merge_fragments(frags)
                except Exception:  # noqa: BLE001 — mismatched arg trees
                    # cannot merge (should not happen within one (type,
                    # method)); ship unmerged rather than lose payload
                    for keys, args in frags:
                        self._ship_frame(target, type_name, method, keys,
                                         args, hops, retries)
                    continue
            self._ship_frame(target, type_name, method, keys, args,
                             hops, retries)

    def _ship_frame(self, target: SiloAddress, type_name: str, method: str,
                    keys: np.ndarray, args: Any, hops: int,
                    retries: int) -> None:
        from orleans_tpu.ids import GrainId, SystemTargetCodes
        from orleans_tpu.runtime.messaging import (
            Category,
            Direction,
            Message,
            SLAB_METHOD,
        )
        self.slabs_shipped += 1
        self.slab_frames += 1
        msg = Message(
            category=Category.APPLICATION,
            direction=Direction.ONE_WAY,
            sending_silo=self.silo.address,
            sending_grain=self.silo.client_grain_id,
            target_silo=target,
            target_grain=GrainId.system_target(
                int(SystemTargetCodes.VECTOR_ROUTER)),
            method_name=SLAB_METHOD,
            args=(type_name, method, keys, args, hops, retries),
        )
        self.silo.message_center.send_message(msg)

    def reinject_bounced(self, msg, reason: str) -> None:
        """The transport bounced a slab frame back (link down, byte/count
        queue overflow): park the payload and retry with backoff instead
        of dropping it — a transient link failure redelivers; only the
        retry budget's exhaustion loses messages (and that is logged)."""
        type_name, method, keys, args = msg.args[:4]
        retries = int(msg.args[5]) if len(msg.args) > 5 else 0
        self.slab_bounces += 1
        self.silo.logger.warn(
            f"slab frame for {type_name} to {msg.target_silo} bounced "
            f"({reason}) — re-injecting with backoff", code=2914)
        self._backoff_reinject(type_name, method,
                               np.asarray(keys, dtype=np.int64), args,
                               retries)

    def make_injector(self, type_name: str, method: str, keys: np.ndarray):
        """Cluster-aware steady-state injector: resolves the ownership
        split once per ring version; every inject() is one local enqueue
        + one slab per remote owner."""
        return ClusterInjector(self, type_name, method,
                               np.asarray(keys, dtype=np.int64))

    # ================= receive side (system target) =======================

    async def inject_slab(self, type_name: str, method: str,
                          keys: np.ndarray, args: Any, hops: int = 0,
                          retries: int = 0, _recount: bool = True) -> None:
        """Peer slab arrival: verify ownership (the ring may have moved
        while the slab was in flight) and enqueue the owned part; forward
        strays with a bounded hop count (reference: MaxForwardCount,
        Dispatcher.TryForwardRequest :474).  A slab that exhausts its hop
        budget is NOT dropped: diverged ring views converge within a
        membership refresh, so the holder parks it and re-injects with
        backoff (the batched analog of the reference's resend-with-
        backoff; only the retry budget's exhaustion loses messages, and
        that is logged as an error)."""
        keys = np.asarray(keys, dtype=np.int64)
        if _recount:  # local backoff re-entries must not double-count
            self.slabs_received += 1
            self.messages_received += len(keys)
        local_mask, remote = self.partition(type_name, keys)
        if local_mask.any():
            idx = np.nonzero(local_mask)[0]
            self._inject_local(type_name, method, keys[idx],
                               _gather_args(args, idx))
            self.engine._wake_up()
        for target, idx in remote.items():
            if hops + 1 > self.silo.max_forward_count:
                self._backoff_reinject(type_name, method, keys[idx],
                                       _gather_args(args, idx), retries)
                continue
            self.ship_slab(target, type_name, method, keys[idx],
                           _gather_args(args, idx), hops=hops + 1,
                           retries=retries)

    def _inject_local(self, type_name: str, method: str,
                      keys: np.ndarray, args: Any) -> None:
        """Enqueue a slab's locally-owned partition.

        Steady cross-silo traffic repeats the same key set every slab
        (the sender's ClusterInjector split is cached), but each arrival
        deserializes to FRESH arrays — so the receiving engine would
        re-resolve rows per slab and its auto-fuser would never see a
        stable pattern (its signature keys on the key array's identity).
        Cache a BatchInjector per recurring (type, method, keys) slab
        shape: repeats ride the cached-row fast path AND present a
        stable identity, so the RECEIVING silo's steady state fuses just
        like the sender's (north star: batches stay batches across the
        boundary, including the compiled tier)."""
        digest = (type_name, method, len(keys),
                  hash(keys.tobytes()))
        cached = self._slab_injectors.get(digest)
        if cached is not None and np.array_equal(cached.keys, keys):
            # LRU touch: insertion order doubles as recency order
            self._slab_injectors[digest] = self._slab_injectors.pop(digest)
            cached.inject(args)
            return
        count = self._slab_key_counts.get(digest, 0) + 1
        if digest not in self._slab_key_counts \
                and len(self._slab_key_counts) >= 1024:
            # churny, never-recurring shapes must not grow this without
            # bound; recurring shapes re-accumulate in 3 arrivals
            self._slab_key_counts.clear()
        self._slab_key_counts[digest] = count
        if count >= 3:  # recurring slab shape: build the cached edge
            from orleans_tpu.tensor.engine import BatchInjector
            inj = BatchInjector(self.engine, type_name, method, keys)
            self._slab_injectors[digest] = inj
            self._slab_key_counts.pop(digest, None)
            while len(self._slab_injectors) > 64:
                # least-recently-used falls off; hot shapes were touched
                # to the end above, so they survive
                self._slab_injectors.pop(next(iter(self._slab_injectors)))
            inj.inject(args)
            return
        self.engine.enqueue_local_batch(type_name, method, keys, args)

    def _backoff_reinject(self, type_name: str, method: str,
                          keys: np.ndarray, args: Any, retries: int) -> None:
        """Over-forwarded slab: park it and retry with a fresh hop budget
        once ring views have had time to converge."""
        if retries >= self.slab_retry_limit:
            self.messages_dropped += len(keys)
            self.silo.logger.error(
                f"dropping {len(keys)}-message slab for {type_name} after "
                f"{retries} backoff retries: ring views never converged",
                code=2910)
            return
        self.slabs_requeued += 1
        delay = min(0.05 * (2 ** retries), 1.0)

        async def retry() -> None:
            await asyncio.sleep(delay)
            from orleans_tpu.runtime.silo import SiloStatus
            if self.silo.status == SiloStatus.DEAD:
                return
            await self.inject_slab(type_name, method, keys, args,
                                   hops=0, retries=retries + 1,
                                   _recount=False)

        # hold a strong reference: asyncio keeps only weak refs to tasks,
        # and this task is the sole holder of the parked slab's data
        task = asyncio.get_running_loop().create_task(retry())
        self._retry_tasks.add(task)
        task.add_done_callback(self._retry_tasks.discard)

    async def call_slab(self, type_name: str, method: str,
                        keys: np.ndarray, args: Any, hops: int = 1) -> Any:
        """Request/response slab (want_results path).  Re-partitions on
        arrival (ring may have moved) with the hop chain bounded — never
        an unbounded bounce between silos with diverged views."""
        self.slabs_received += 1
        self.messages_received += len(keys)
        keys = np.asarray(keys, dtype=np.int64)
        local_mask, remote = self.partition(type_name, keys)
        self.engine._wake_up()
        return await self._route_with_results(
            type_name, method, keys, _host_args(args), local_mask, remote,
            hops=hops)

    # ================= live migration (cross-silo) ========================

    def _ship_adopt(self, target: SiloAddress, type_name: str,
                    keys: np.ndarray,
                    columns: Dict[str, np.ndarray],
                    timers=None) -> None:
        """One-way adopt_grains frame: a migrated partition's state slab
        (key column + every state column, the same columnar shape the
        checkpoint drain writes) plus any armed device timers detached
        from the movers (transport-plain payload, relative remaining
        ticks).  Sent on the same link as (and therefore FIFO-before)
        any later handoff release, so a peer's first-touch miss after
        the release finds the keys already adopted."""
        from orleans_tpu.ids import GrainId, SystemTargetCodes
        from orleans_tpu.runtime.messaging import (
            Category,
            Direction,
            Message,
        )
        self.silo.message_center.send_message(Message(
            category=Category.SYSTEM,
            direction=Direction.ONE_WAY,
            sending_silo=self.silo.address,
            sending_grain=self.silo.client_grain_id,
            target_silo=target,
            target_grain=GrainId.system_target(
                int(SystemTargetCodes.VECTOR_ROUTER)),
            method_name="adopt_grains",
            args=(type_name, np.asarray(keys, dtype=np.int64),
                  {n: np.asarray(c) for n, c in columns.items()},
                  self.silo.address, timers),
        ))

    async def adopt_grains(self, type_name: str, keys, columns,
                           sender: SiloAddress, timers=None) -> int:
        """Receive a live-migrated partition: register the placement
        override (this silo now OWNS these keys — the one-answer
        contract) and land the pushed state at freshly allocated rows.
        First-writer-wins on keys already live here (the
        register_single discipline; counted as adopt_conflicts).  The
        store is bypassed — a migration is a MOVE, not a re-activation:
        reading persisted rows underneath the pushed state would
        resurrect the old owner's last write-back over its final
        state."""
        keys = np.asarray(keys, dtype=np.int64)
        eng = self.engine
        arena = eng.arena_for(type_name)
        self.register_placement(type_name, keys, self.silo.address)
        _rows, found = arena.lookup_rows(keys)
        conflicts = int(found.sum())
        fresh = ~found
        n = int(fresh.sum())
        if n:
            fidx = np.nonzero(fresh)[0]
            store = arena.store
            arena.store = None
            try:
                arena._activate_keys(keys[fidx])
            finally:
                arena.store = store
            rows, ok = arena.lookup_rows(keys[fidx])
            assert ok.all()
            arena.scatter_restore(
                rows.astype(np.int64),
                {name: np.asarray(col)[fidx]
                 for name, col in columns.items()},
                np.zeros(n, dtype=np.int32))
            # adopted rows stamp THIS engine's clock: the sender's tick
            # counter is meaningless here, and "just migrated" is
            # exactly "just touched" for the idle collector
            arena.last_use_tick[rows] = eng.tick_number
            eng.migrations += 1
            eng.grains_migrated += n
        if timers:
            # armed timers move WITH their grain (Orleans: a reminder
            # survives migration): re-armed at the local clock, recorded
            # as arm ops for this silo's next checkpoint cut
            eng.timers.adopt_keys(type_name, timers)
        self.grains_adopted += n
        self.adopt_conflicts += conflicts
        eng._wake_up()
        # coverage report: the sender declares the move successful only
        # when adopted + already-live accounts for EVERY key (a
        # tensor-less stub's 0/0 must read as failure, never success)
        return {"adopted": n, "live": conflicts}

    async def place_keys(self, type_name: str, keys,
                         target: SiloAddress) -> bool:
        """Peer notification of a live migration: route these keys to
        ``target`` from now on (until the next ring change re-homes
        them by hash)."""
        self.register_placement(type_name, np.asarray(keys, np.int64),
                                target)
        return True

    async def migrate_keys_out(self, type_name: str, keys: np.ndarray,
                               target: SiloAddress) -> int:
        """Batched live migration of resident grains to a PEER silo:
        deactivate-with-state-handoff → reactivate on the target.

        Ordering closes the lost-update race without a stop-the-world
        fence: (1) the SOURCE registers the override and, in ONE
        synchronous block (no await — no tick can interleave), gathers
        the movers' columns and evicts their rows WITHOUT write-back —
        from this instant the keys are live NOWHERE, so no state can
        diverge from the gathered slab; local/in-flight messages to
        them miss and re-route through the override (a slab reaching
        the target early bounces on its hop budget until adoption —
        the diverged-ring-view backoff machinery, not a new protocol).
        (2) the TARGET adopts override+state atomically (one rpc).
        (3) remaining peers learn the override; late learners just pay
        a forward hop.  Returns grains moved."""
        eng = self.engine
        arena = eng.arenas.get(type_name)
        if arena is None or target == self.silo.address:
            return 0
        keys = np.unique(np.asarray(keys, dtype=np.int64))
        rows, found = arena.lookup_rows(keys)
        keys, rows = keys[found], rows[found].astype(np.int64)
        if len(keys) == 0:
            return 0
        # ---- the synchronous no-divergence block ----
        self.register_placement(type_name, keys, target)
        columns = arena.rows_to_host(rows)
        # detach armed device timers inside the same block: from this
        # instant the source cannot fire them, and in-flight fires to
        # the movers miss and re-route through the override like any
        # other message — no deadline is ever stranded or doubled
        timers = eng.timers.export_keys(type_name, keys)
        arena.evict_keys(keys, write_back=False)
        # ---------------------------------------------
        # Adoption outcome trichotomy.  A RETURNED rpc is definitive:
        # adopted+live covering every key = success; anything else
        # (e.g. a tensor-less stub's 0/0) = the target provably did NOT
        # adopt → retract + re-land, no split possible.  An EXCEPTION
        # is AMBIGUOUS (a timeout may race a late adoption), so it
        # retries the idempotent adopt (already-live keys count as
        # covered); if every attempt raises, the override is KEPT and
        # the slab goes through the store when one is attached —
        # re-landing locally after an ambiguous send is the one path
        # that could mint a second live copy, so it never happens.
        reply = None
        for _attempt in range(3):
            try:
                reply = await self.silo.system_rpc(
                    target, "vector_router", "adopt_grains",
                    (type_name, keys, columns, self.silo.address,
                     timers))
                break
            except Exception:
                reply = None
        covered = (reply.get("adopted", 0) + reply.get("live", 0)) \
            if isinstance(reply, dict) else -1
        if reply is not None and covered != len(keys):
            # definitive non-adoption: retract the override and re-land
            # the state HERE (the gathered slab is still the only copy)
            ov = self._placement.get(type_name, {})
            for k in keys.tolist():
                ov.pop(int(k), None)
            self._placement_arrays_cache.pop(type_name, None)
            store = arena.store
            arena.store = None
            try:
                arena._activate_keys(keys)
            finally:
                arena.store = store
            back, ok = arena.lookup_rows(keys)
            assert ok.all()
            arena.scatter_restore(back.astype(np.int64), columns,
                                  np.zeros(len(keys), dtype=np.int32))
            arena.last_use_tick[back] = eng.tick_number
            if timers:
                # the movers' timers re-land here with their state
                eng.timers.adopt_keys(type_name, timers)
            self.silo.logger.warn(
                f"migration of {len(keys)} {type_name} grains to "
                f"{target} refused at adoption ({covered}/{len(keys)} "
                f"covered) — retracted locally", code=2931)
            return 0
        if reply is None:
            # ambiguous: the target may yet adopt.  Route stays pointed
            # at it; the store write below is the durable net (a target
            # that never adopts serves the keys from first-touch store
            # reads after the next ring change re-homes them).
            if arena.store is not None:
                arena.store.write_many_columnar(type_name,
                                                keys.tolist(), columns)
            self.silo.logger.warn(
                f"migration of {len(keys)} {type_name} grains to "
                f"{target}: adoption rpc failed after retries — "
                f"override kept (re-landing could double-activate); "
                f"state {'written through the store' if arena.store is not None else 'IN LIMBO until the target adopts or the next ring change'}",
                code=2932)
            return 0
        peers = [m for m in self.silo.active_silos()
                 if m not in (self.silo.address, target)]
        if peers:
            await asyncio.gather(
                *(self.silo.system_rpc(p, "vector_router", "place_keys",
                                       (type_name, keys, target),
                                       timeout=5.0) for p in peers),
                return_exceptions=True)
        eng.migrations += 1
        eng.grains_migrated += len(keys)
        self.grains_migrated_out += len(keys)
        return len(keys)

    async def drain_migrate_out(self) -> int:
        """Elastic scale-IN: migrate every resident grain to its
        POST-LEAVE ring owner before this silo says goodbye.  Survivors
        adopt the state directly (no first-touch store miss; state
        survives even storeless).  Owners are computed on a ring copy
        without this silo — the same construction the survivors' rings
        converge to once the leave lands, at which point their
        ring-change clear re-homes the adopted keys by hash with zero
        movement."""
        from orleans_tpu.runtime.ring import VirtualBucketsRing
        from orleans_tpu.tensor.vector_grain import vector_type
        peers = [m for m in self.silo.ring.members
                 if m != self.silo.address
                 and self.silo.is_silo_alive(m)]
        if not peers:
            return 0
        post = VirtualBucketsRing(
            peers[0], self.silo.config.directory.buckets_per_silo)
        for m in peers[1:]:
            post.add_silo(m)
        total = 0
        for type_name, arena in self.engine.arenas.items():
            keys = arena.keys()
            if len(keys) == 0:
                continue
            info = vector_type(type_name)
            points = ring_hash_int_keys(
                info.type_code, keys, category=int(GrainCategory.GRAIN))
            owner_idx, members = post.owners_of_hashes(points)
            for o in np.unique(owner_idx):
                if o < 0:
                    continue
                sel = np.nonzero(owner_idx == o)[0]
                rows, found = arena.lookup_rows(keys[sel])
                assert found.all()
                self._ship_adopt(members[int(o)], type_name, keys[sel],
                                 arena.rows_to_host(
                                     rows.astype(np.int64)),
                                 timers=self.engine.timers.export_keys(
                                     type_name, keys[sel]))
                total += len(sel)
            # no write-back: the graceful-stop checkpoint (before this)
            # is the durable net; the pushed slabs are the live copy
            arena.evict_keys(keys, write_back=False)
        self.grains_migrated_out += total
        if total:
            self.silo.logger.info(
                f"drain: migrated {total} resident grains to "
                f"{len(peers)} survivors")
        return total

    # ================= handoff (ring change) ==============================

    def on_ring_changed(self) -> None:
        """Arena half of directory handoff (reference:
        GrainDirectoryHandoffManager.cs:141): rows whose keys this silo
        no longer owns MIGRATE to their new owner — one columnar gather
        + one adopt_grains slab per destination, sent BEFORE this
        silo's fence release on the same links (FIFO: the new owner
        adopts before its first-touch misses unfence) — then evict.
        With a store attached the write-back still runs as the durable
        net under the push (equal state either way; a lost one-way
        adopt frame degrades to the old evict-and-miss path, never to
        loss).  ``rebalance.handoff_migration=False`` restores the pure
        evict-and-miss handoff (the A/B baseline)."""
        # placement overrides are scoped to the membership view: keys
        # re-home by hash and the push below moves state to match
        if self._placement:
            self._placement.clear()
            self._placement_arrays_cache.clear()
        migrate = getattr(self.silo.config, "rebalance", None)
        migrate = migrate is not None and migrate.handoff_migration
        for type_name, arena in self.engine.arenas.items():
            keys = arena.keys()
            if len(keys) == 0:
                continue
            local_mask, remote = self.partition(type_name, keys)
            stray = keys[~local_mask]
            if not len(stray):
                continue
            if migrate:
                for target, ridx in remote.items():
                    rows, found = arena.lookup_rows(keys[ridx])
                    assert found.all()
                    self._ship_adopt(target, type_name, keys[ridx],
                                     arena.rows_to_host(
                                         rows.astype(np.int64)),
                                     timers=self.engine.timers
                                     .export_keys(type_name, keys[ridx]))
                self.engine.migrations += 1
                self.engine.grains_migrated += len(stray)
                self.grains_migrated_out += len(stray)
            evicted = arena.evict_keys(stray)
            if arena.store is None and not migrate:
                # eviction preserves single-activation either way, but
                # without a store or a push the rows' state cannot
                # follow them — same contract as the reference's
                # storage-less grains (deactivation discards state),
                # surfaced loudly
                self.silo.logger.warn(
                    f"handoff: evicted {evicted} {type_name} rows "
                    "WITHOUT write-back (no VectorStore attached) — "
                    "their state restarts from field defaults on the "
                    "new owner", code=2911)
            else:
                self.silo.logger.info(
                    f"handoff: {'migrated' if migrate else 'evicted'} "
                    f"{evicted} {type_name} rows no longer owned here")

    def snapshot(self) -> Dict[str, Any]:
        return {
            "slabs_shipped": self.slabs_shipped,
            "messages_shipped": self.messages_shipped,
            "slabs_received": self.slabs_received,
            "messages_received": self.messages_received,
            "slabs_requeued": self.slabs_requeued,
            "messages_dropped": self.messages_dropped,
            "slab_fragments": self.slab_fragments,
            "slab_frames": self.slab_frames,
            "slab_bounces": self.slab_bounces,
            # live migration across silos (placement overrides +
            # adopt_grains state slabs)
            "grains_migrated_out": self.grains_migrated_out,
            "grains_adopted": self.grains_adopted,
            "adopt_conflicts": self.adopt_conflicts,
            # > 1 means sender aggregation is doing its job (fragments
            # merged per destination per drain cycle) — THE health
            # indicator for the cross-silo data plane
            "slab_merge_ratio": round(
                self.slab_fragments / self.slab_frames, 3)
            if self.slab_frames else 0.0,
        }


class HandoffFenceStub:
    """The 'vector_router' system target for a clustered silo WITHOUT a
    tensor engine: it owns no vector rows, so its write-back for any ring
    change is trivially complete — but peers' handoff fences still await
    its release.  The stub broadcasts releases so mixed clusters (tensor
    + non-tensor silos) settle in one RTT instead of stalling every ring
    change to the fence timeout."""

    def __init__(self, silo) -> None:
        self.silo = silo
        silo.ring.subscribe(lambda *_: self._broadcast())

    def _view_digest(self):
        return tuple(sorted(str(m) for m in self.silo.ring.members))

    def _broadcast(self) -> None:
        digest = self._view_digest()
        for p in self.silo.ring.members:
            if p != self.silo.address:
                _send_release(self.silo, p, digest)

    async def handoff_release(self, digest, sender) -> None:
        pass  # no fence here: nothing ever defers activation

    async def inject_slab(self, type_name: str, method: str,
                          keys, args, hops: int = 0, retries: int = 0,
                          _recount: bool = True) -> None:
        self.silo.logger.error(
            f"dropping {len(keys)}-message slab for {type_name}: this "
            f"silo has no tensor engine (ring misconfiguration — "
            f"non-tensor silos should not own vector key ranges)",
            code=2913)

    async def adopt_grains(self, type_name: str, keys, columns,
                           sender, timers=None):
        self.silo.logger.error(
            f"dropping {len(keys)}-grain migration slab for "
            f"{type_name}: this silo has no tensor engine (ring "
            f"misconfiguration — non-tensor silos should not own "
            f"vector key ranges)", code=2913)
        return {"adopted": 0, "live": 0}

    async def place_keys(self, type_name: str, keys, target) -> bool:
        return True  # nothing routes from here; nothing to override


class ClusterInjector:
    """Steady-state cluster injector: the ownership split of a stable key
    set is computed once per ring version; each ``inject`` is one local
    enqueue plus one pre-gathered slab per remote owner (the cross-silo
    analog of BatchInjector's cached-row fast path).  A membership change
    invalidates the split — injecting through a stale split would
    re-activate keys the handoff just evicted."""

    def __init__(self, router: VectorRouter, type_name: str, method: str,
                 keys: np.ndarray) -> None:
        self.router = router
        self.type_name = type_name
        self.method = method
        self.keys = keys
        self.n = len(keys)
        self._ring_version = -1
        # overlapped h2d (BatchInjector.stage parity): the staged slab
        # for the next inject(); the all-local fast path forwards the
        # staging to the wrapped BatchInjector so the device copy rides
        # under the current tick's compute
        self._staged: Optional[Any] = None
        self._rebuild()

    def _rebuild(self) -> None:
        import jax.numpy as jnp

        self._ring_version = self.router.silo.ring.version
        local_mask, remote = self.router.partition(self.type_name,
                                                   self.keys)
        self._all_local = not remote
        self._local_idx = np.nonzero(local_mask)[0]
        self._local_idx_dev = jnp.asarray(self._local_idx.astype(np.int32))
        self._remote = [(target, idx,
                         jnp.asarray(idx.astype(np.int32)))
                        for target, idx in remote.items()]
        self._local = None
        if len(self._local_idx):
            from orleans_tpu.tensor.engine import BatchInjector
            self._local = BatchInjector(
                self.router.engine, self.type_name, self.method,
                self.keys if self._all_local
                else self.keys[self._local_idx])

    def stage(self, args: Any) -> Any:
        """Overlapped h2d, the BatchInjector.stage contract: start the
        next injection's device copy now.  On the all-local fast path
        (single-owner key set — every single-silo cluster) the wrapped
        BatchInjector stages for real; split key sets keep the payload
        host-side and partition it at inject as before."""
        self._staged = args
        if self._all_local and self._local is not None \
                and self._ring_version == self.router.silo.ring.version:
            self._local.stage(args)
        return args

    def inject(self, args: Any = None, want_results: bool = False
               ) -> Optional[asyncio.Future]:
        if args is None:
            args, self._staged = self._staged, None
            if args is None:
                raise ValueError("inject() with no args needs a staged "
                                 "slab — call stage(args) first")
            if self._all_local and not want_results \
                    and self._ring_version \
                    == self.router.silo.ring.version \
                    and self._local is not None \
                    and self._local._staged is not None:
                # consume the device-staged slab zero-copy
                return self._local.inject()
        else:
            self._staged = None  # an explicit injection supersedes
        if self._ring_version != self.router.silo.ring.version:
            self._rebuild()
        if self._all_local and not want_results:
            return self._local.inject(args)  # zero-copy fast path
        if want_results:
            # results need order reassembly — reuse the routed path
            return self.router.route_batch(self.type_name, self.method,
                                           self.keys, args,
                                           want_results=True)
        if any(isinstance(leaf, jax.Array)
               for leaf in jax.tree_util.tree_leaves(args)):
            # device payloads: gather partitions ON DEVICE — the local
            # slice never touches the host, remote slices cross at their
            # partition size, not the full payload's
            if self._local is not None:
                self._local.inject(_gather_args_dev(args,
                                                    self._local_idx_dev))
            for target, idx, idx_dev in self._remote:
                self.router.ship_slab(
                    target, self.type_name, self.method, self.keys[idx],
                    jax.device_get(_gather_args_dev(args, idx_dev)))
            return None
        args_h = _host_args(args)
        if self._local is not None:
            self._local.inject(_gather_args(args_h, self._local_idx))
        for target, idx, _ in self._remote:
            self.router.ship_slab(target, self.type_name, self.method,
                                  self.keys[idx], _gather_args(args_h, idx))
        return None
