"""End-to-end HelloWorld: the PR1 slice (reference: Samples/HelloWorld;
test analog: Tester/HelloWorldTests pattern via TestingSiloHost)."""

import asyncio

from orleans_tpu.runtime.silo import Silo
from samples.helloworld import IHello


def test_hello_end_to_end(run):
    async def main():
        silo = Silo(name="s1")
        await silo.start()
        try:
            factory = silo.attach_client()
            hello = factory.get_grain(IHello, 0)
            reply = await hello.say_hello("Good morning, my friend!")
            assert reply == "You said: 'Good morning, my friend!', I say: Hello!"
            # same logical grain → same activation (single-activation)
            assert len(silo.catalog.directory) == 1
            await hello.say_hello("again")
            assert len(silo.catalog.directory) == 1
            # different key → different activation
            other = factory.get_grain(IHello, 1)
            await other.say_hello("hi")
            assert len(silo.catalog.directory) == 2
        finally:
            await silo.stop()
        assert len(silo.catalog.directory) == 0  # graceful stop deactivated all

    run(main())


def test_many_grains_concurrent(run):
    async def main():
        silo = Silo(name="s1")
        await silo.start()
        try:
            factory = silo.attach_client()
            refs = [factory.get_grain(IHello, i) for i in range(200)]
            replies = await asyncio.gather(
                *(r.say_hello(str(i)) for i, r in enumerate(refs)))
            assert len(replies) == 200
            assert all("I say: Hello!" in r for r in replies)
            assert len(silo.catalog.directory) == 200
        finally:
            await silo.stop()

    run(main())
