"""SQL (sqlite) membership + reminder tables.

Parity: reference SQL system stores (reference: src/OrleansSQLUtils/
SqlMembershipTable.cs:34, SqlReminderTable.cs:31, and the
CreateOrleansTables_SqlServer.sql DDL).  Contracts match the in-memory
tables exactly (orleans_tpu/runtime/membership.py InMemoryMembershipTable;
orleans_tpu/runtime/reminders.py InMemoryReminderTable), so the membership
oracle and reminder service run unchanged over either backend — the same
pluggability the reference gets from IMembershipTable/IReminderTable.

CAS discipline: membership rows carry integer etags and the whole table a
version (reference: TableVersion, IMembershipTable.cs:133); every
insert/update is a compare-and-swap on both.  Reminder rows carry string
etags; remove requires the current one.
"""

from __future__ import annotations

import sqlite3
import uuid
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

from orleans_tpu.codec import default_manager as codec
from orleans_tpu.ids import GrainId
from orleans_tpu.runtime.membership import CasConflictError, MembershipEntry
from orleans_tpu.runtime.reminders import ReminderEntry, ReminderTable

codec.register(MembershipEntry, name="orleans.MembershipEntry")

_MEMBERSHIP_SCHEMA = """
CREATE TABLE IF NOT EXISTS membership (
    silo_key TEXT PRIMARY KEY,
    etag     INTEGER NOT NULL,
    entry    BLOB NOT NULL
);
CREATE TABLE IF NOT EXISTS membership_version (
    id      INTEGER PRIMARY KEY CHECK (id = 0),
    version INTEGER NOT NULL
);
INSERT OR IGNORE INTO membership_version (id, version) VALUES (0, 0);
"""

_REMINDER_SCHEMA = """
CREATE TABLE IF NOT EXISTS reminders (
    grain_key TEXT NOT NULL,
    name      TEXT NOT NULL,
    etag      TEXT NOT NULL,
    entry     BLOB NOT NULL,
    PRIMARY KEY (grain_key, name)
);
"""


class SqliteMembershipTable:
    """Drop-in for InMemoryMembershipTable over sqlite
    (reference: SqlMembershipTable.cs:34)."""

    def __init__(self, path: str = ":memory:") -> None:
        self._conn = sqlite3.connect(path)
        self._conn.executescript(_MEMBERSHIP_SCHEMA)
        self._conn.commit()
        self.write_count = 0

    def close(self) -> None:
        self._conn.close()

    def _version(self) -> int:
        return self._conn.execute(
            "SELECT version FROM membership_version WHERE id=0"
        ).fetchone()[0]

    def _bump_version(self, expected: int) -> None:
        cur = self._conn.execute(
            "UPDATE membership_version SET version=version+1 "
            "WHERE id=0 AND version=?", (expected,))
        if cur.rowcount == 0:
            raise CasConflictError("table version moved")

    async def read_all(self) -> Tuple[
            Dict, int]:
        rows = self._conn.execute(
            "SELECT etag, entry FROM membership").fetchall()
        snap = {}
        for etag, blob in rows:
            entry: MembershipEntry = codec.deserialize(blob)
            snap[entry.silo] = (entry, etag)
        return snap, self._version()

    async def insert_row(self, entry: MembershipEntry,
                         table_version: int) -> None:
        self._bump_version(table_version)
        try:
            self._conn.execute(
                "INSERT INTO membership (silo_key, etag, entry) "
                "VALUES (?, 0, ?)",
                (str(entry.silo), codec.serialize(entry)))
        except sqlite3.IntegrityError:
            self._conn.rollback()
            raise CasConflictError("row exists")
        self._conn.commit()
        self.write_count += 1

    async def update_row(self, entry: MembershipEntry, etag: int,
                         table_version: int) -> None:
        self._bump_version(table_version)
        cur = self._conn.execute(
            "UPDATE membership SET etag=?, entry=? "
            "WHERE silo_key=? AND etag=?",
            (etag + 1, codec.serialize(entry), str(entry.silo), etag))
        if cur.rowcount == 0:
            self._conn.rollback()
            raise CasConflictError("row etag moved")
        self._conn.commit()
        self.write_count += 1

    async def update_iam_alive(self, silo, when: float) -> None:
        """Heartbeat column — no CAS (reference: UpdateIAmAlive)."""
        row = self._conn.execute(
            "SELECT etag, entry FROM membership WHERE silo_key=?",
            (str(silo),)).fetchone()
        if row is None:
            return
        etag, blob = row
        entry: MembershipEntry = codec.deserialize(blob)
        entry.iam_alive_time = when
        self._conn.execute(
            "UPDATE membership SET entry=? WHERE silo_key=?",
            (codec.serialize(entry), str(silo)))
        self._conn.commit()


class SqliteReminderTable(ReminderTable):
    """Drop-in for InMemoryReminderTable over sqlite
    (reference: SqlReminderTable.cs:31)."""

    def __init__(self, path: str = ":memory:") -> None:
        self._conn = sqlite3.connect(path)
        self._conn.executescript(_REMINDER_SCHEMA)
        self._conn.commit()

    def close(self) -> None:
        self._conn.close()

    def _next_etag(self) -> str:
        # uuid, not a counter: a counter resets on process restart, so a
        # stale etag held from a previous process could wrongly match a
        # newer row and defeat the CAS discipline
        return uuid.uuid4().hex

    async def read_row(self, grain_id: GrainId,
                       name: str) -> Optional[ReminderEntry]:
        row = self._conn.execute(
            "SELECT entry FROM reminders WHERE grain_key=? AND name=?",
            (str(grain_id), name)).fetchone()
        return codec.deserialize(row[0]) if row is not None else None

    async def read_rows(self, grain_id: GrainId) -> List[ReminderEntry]:
        rows = self._conn.execute(
            "SELECT entry FROM reminders WHERE grain_key=?",
            (str(grain_id),)).fetchall()
        return [codec.deserialize(b) for (b,) in rows]

    async def read_all(self) -> List[ReminderEntry]:
        rows = self._conn.execute("SELECT entry FROM reminders").fetchall()
        return [codec.deserialize(b) for (b,) in rows]

    async def upsert_row(self, entry: ReminderEntry) -> str:
        etag = self._next_etag()
        stored = replace(entry, etag=etag)
        self._conn.execute(
            "INSERT INTO reminders (grain_key, name, etag, entry) "
            "VALUES (?,?,?,?) "
            "ON CONFLICT (grain_key, name) DO UPDATE SET etag=?, entry=?",
            (str(entry.grain_id), entry.name, etag, codec.serialize(stored),
             etag, codec.serialize(stored)))
        self._conn.commit()
        return etag

    async def remove_row(self, grain_id: GrainId, name: str,
                         etag: str) -> bool:
        cur = self._conn.execute(
            "DELETE FROM reminders WHERE grain_key=? AND name=? AND etag=?",
            (str(grain_id), name, etag))
        self._conn.commit()
        return cur.rowcount > 0
