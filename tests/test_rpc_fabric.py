"""Batched silo→silo fabric (orleans_tpu/runtime/rpc.py RpcFabric +
codec.encode_fabric_frame/decode_fabric_frame).

Covers the PR's contracts: fabric frame codec roundtrip, cross-silo
batched-vs-per-message reply bit-exactness (with the fabric actually
engaged), per-call TTL rebase + forward_count inside ONE frame (the
expired member dead-letters with its hop count, its frame-mate
delivers), per-sender FIFO across the silo→silo coalescer under
interleaved methods, sampled-trace continuity through a batched frame
on BOTH silos, bounce-on-death (no stranded callers), and the counted
per-message fallback for frame-ineligible traffic.
"""

import asyncio
import time

import numpy as np
import pytest

from orleans_tpu import Grain, grain_interface
from orleans_tpu.codec import (
    FABRIC_NO_TTL,
    FABRIC_RESULT_ERROR,
    FABRIC_RESULT_OK,
    FABRIC_RESULT_REJECTION,
    FabricCallsSection,
    FabricResultsSection,
    decode_fabric_frame,
    default_manager as codec,
    encode_fabric_frame,
)
from orleans_tpu.config import SiloConfig
from orleans_tpu.core.context import RequestContext
from orleans_tpu.core.grain import grain_class
from orleans_tpu.ids import GrainId, SiloAddress
from orleans_tpu.runtime.messaging import (
    Category,
    Direction,
    Message,
    RejectionType,
    ResponseKind,
)
from orleans_tpu.runtime.runtime_client import CallbackData, RejectionError
from orleans_tpu.spans import TRACE_KEY
from orleans_tpu.testing import TestingCluster

from samples.helloworld import IHello

pytestmark = pytest.mark.rpc

HELLO = "You said: '{0}', I say: Hello!"


@grain_interface
class IFabricRecorder:
    async def mark(self, tag: str) -> str: ...
    async def mark_b(self, tag: str) -> str: ...


@grain_class
class FabricRecorderGrain(Grain, IFabricRecorder):
    """Appends every invocation to a class-level log so tests can assert
    cross-frame execution order on the EXECUTING silo."""

    log: list = []

    async def mark(self, tag: str) -> str:
        FabricRecorderGrain.log.append(("mark", int(self.grain_id.n1), tag))
        return tag

    async def mark_b(self, tag: str) -> str:
        FabricRecorderGrain.log.append(("mark_b", int(self.grain_id.n1), tag))
        return tag


@grain_interface
class IFabricCtx:
    async def who(self) -> dict: ...


@grain_class
class FabricCtxGrain(Grain, IFabricCtx):
    async def who(self) -> dict:
        t = RequestContext.get(TRACE_KEY)
        return {"trace_id": t.get("trace_id") if t else None,
                "sampled": bool(t and t.get("sampled"))}


async def _key_hosted_on(cluster, silo, iface, start: int = 0,
                         method: str = None) -> int:
    """Activate candidate grains until one lands on ``silo`` (default
    placement is hash-based, so the host follows the key)."""
    factory = cluster.silos[0].attach_client()
    for key in range(start, start + 64):
        ref = factory.get_grain(iface, key)
        m = getattr(ref, method or "who")
        await (m("probe") if method else m())
        if cluster.find_silo_hosting(ref.grain_id) is silo:
            return key
    raise AssertionError("no key hashed to the target silo in 64 tries")


# ===========================================================================
# fabric frame codec (pure)
# ===========================================================================

def test_fabric_frame_codec_roundtrip():
    """Mixed calls/results sections with trace columns, TTL sentinels,
    ndarray args and a rejection result survive encode→decode exactly
    (the wire contract every cross-silo frame rides)."""
    origin = SiloAddress("silo-a", 0, 1)
    g1 = GrainId.from_int(7001, 11)
    g2 = GrainId.from_int(7001, 12)
    idents = [(origin, g1), g2]
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    calls = FabricCallsSection(
        7001, "poke", False,
        keys=[11, 2 ** 63 + 5], msg_ids=[101, 102],
        ttls=[29.5, FABRIC_NO_TTL], forward_counts=[0, 3],
        senders=[0, 0], trace_ids=[12345, 0], span_ids=[77, 0],
        args_list=[("x", arr), ({"k": 1},)])
    ones = FabricCallsSection(
        7002, "fire", True,
        keys=[1, 2, 3], msg_ids=[201, 202, 203],
        ttls=[FABRIC_NO_TTL] * 3, forward_counts=[0, 0, 0],
        senders=[0, 0, 0], trace_ids=None, span_ids=None,
        common_args=(0.5,))
    results = FabricResultsSection(
        msg_ids=[55, 56, 57],
        statuses=[FABRIC_RESULT_OK, FABRIC_RESULT_ERROR,
                  FABRIC_RESULT_REJECTION],
        rejections=[0, 0, int(RejectionType.EXPIRED)],
        targets=[1, 1, 1], trace_ids=None, span_ids=None,
        values=[arr * 2, ValueError("boom"), "expired in rpc ingress"])
    segments = encode_fabric_frame(codec, origin, idents,
                                   [calls, ones, results])
    payload = b"".join(bytes(s) for s in segments)
    frame = decode_fabric_frame(codec, payload)

    assert frame.origin == origin
    assert frame.idents[0] == (origin, g1) and frame.idents[1] == g2
    c, o, r = frame.sections
    assert isinstance(c, FabricCallsSection) and not c.one_way
    assert (c.type_code, c.method_name, c.n) == (7001, "poke", 2)
    assert list(c.keys) == [11, 2 ** 63 + 5]
    assert list(c.msg_ids) == [101, 102]
    assert c.ttls[0] == pytest.approx(29.5) and c.ttls[1] == FABRIC_NO_TTL
    assert list(c.forward_counts) == [0, 3]
    assert list(c.trace_ids) == [12345, 0]
    assert c.args_list[0][0] == "x"
    np.testing.assert_array_equal(c.args_list[0][1], arr)
    assert c.args_list[1] == ({"k": 1},)

    assert o.one_way and o.common_args == (0.5,) and o.trace_ids is None
    assert list(o.keys) == [1, 2, 3]

    assert isinstance(r, FabricResultsSection) and r.n == 3
    assert list(r.statuses) == [FABRIC_RESULT_OK, FABRIC_RESULT_ERROR,
                                FABRIC_RESULT_REJECTION]
    np.testing.assert_array_equal(r.values[0], arr * 2)
    assert isinstance(r.values[1], ValueError)
    assert r.values[2] == "expired in rpc ingress"
    assert int(r.rejections[2]) == int(RejectionType.EXPIRED)


# ===========================================================================
# cross-silo end-to-end
# ===========================================================================

def test_cross_silo_batched_vs_per_message_bit_exact(run):
    """Warm cross-silo traffic rides coalesced frames (frames/calls
    counted on the sender, results batched on the return path) and the
    replies are bit-exact against the per-message arm (fabric off via
    live config reload)."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            factory = cluster.silos[0].attach_client()
            refs = [factory.get_grain(IHello, 52000 + i) for i in range(48)]
            await asyncio.gather(*(r.say_hello("warm") for r in refs))

            s0, s1 = cluster.silos
            before = s0.rpc_fabric.snapshot()
            batched = await asyncio.gather(
                *(r.say_hello(f"m{i % 5}") for i, r in enumerate(refs)))
            after = s0.rpc_fabric.snapshot()
            # the fabric actually engaged: coalesced frames out, and the
            # coalescing is real (more members than frames)
            assert after["frames_sent"] > before["frames_sent"]
            assert after["calls_sent"] > before["calls_sent"]
            members = (after["calls_sent"] - before["calls_sent"]
                       + after["results_sent"] - before["results_sent"])
            frames = after["frames_sent"] - before["frames_sent"]
            assert members > frames
            assert s1.rpc_fabric.snapshot()["results_sent"] > 0

            # A/B: same calls with the fabric disabled LIVE on both silos
            for s in cluster.silos:
                s.update_config({"rpc": {"fabric_enabled": False}})
            frames_frozen = s0.rpc_fabric.snapshot()["frames_sent"]
            unbatched = await asyncio.gather(
                *(r.say_hello(f"m{i % 5}") for i, r in enumerate(refs)))
            assert s0.rpc_fabric.snapshot()["frames_sent"] == frames_frozen
            assert batched == unbatched
            assert batched[3] == HELLO.format("m3")
        finally:
            await cluster.stop()

    run(main())


def test_forwarded_ttl_and_forward_count_in_one_frame(run):
    """THE satellite regression: two forwarded requests ride ONE fabric
    frame with 30s and 0s remaining TTL.  The receiving silo rebases
    each deadline PER CALL on its own clock: the live one executes and
    replies, the expired one dead-letters (reason=expired) carrying its
    forward_count, and its caller gets the non-retryable EXPIRED
    rejection."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            s0, s1 = cluster.silos
            key = await _key_hosted_on(cluster, s1, IHello, start=53000,
                                       method="say_hello")
            factory = s0.attach_client()
            gid = factory.get_grain(IHello, key).grain_id
            loop = asyncio.get_running_loop()
            rc = s0.runtime_client

            def forwarded(ttl: float, fwd: int, tag: str):
                msg = Message(
                    category=Category.APPLICATION,
                    direction=Direction.REQUEST,
                    sending_silo=s0.address,
                    sending_grain=s0.client_grain_id,
                    target_silo=s1.address, target_grain=gid,
                    method_name="say_hello", args=(tag,),
                    forward_count=fwd,
                    expiration=time.monotonic() + ttl)
                fut = loop.create_future()
                rc.callbacks[msg.id] = CallbackData(future=fut, message=msg)
                return msg, fut

            live_msg, live_fut = forwarded(30.0, 2, "alive")
            dead_msg, dead_fut = forwarded(0.0, 3, "late")
            frames_before = s0.rpc_fabric.snapshot()["frames_sent"]
            dl_before = s1.dead_letters.by_reason.get("expired", 0)
            # both sends land in the same egress ring before any await —
            # they MUST ship as one frame
            s0.message_center.send_message(live_msg)
            s0.message_center.send_message(dead_msg)

            assert await asyncio.wait_for(live_fut, 10) == \
                HELLO.format("alive")
            with pytest.raises(RejectionError) as ei:
                await asyncio.wait_for(dead_fut, 10)
            assert ei.value.rejection == RejectionType.EXPIRED

            assert s0.rpc_fabric.snapshot()["frames_sent"] == \
                frames_before + 1
            # the dead-letter on the EXECUTING silo carries the hop
            # count the frame column delivered (fwd=3 in the record)
            assert s1.dead_letters.by_reason.get("expired", 0) == \
                dl_before + 1
            entry = [e for e in s1.dead_letters.entries
                     if e["reason"] == "expired"
                     and e["method"] == "say_hello"][-1]
            assert "fwd=3" in entry["message"]
        finally:
            await cluster.stop()

    run(main())


def test_per_sender_fifo_across_fabric_interleaved(run):
    """A sender's calls to a remote grain execute in submission order
    even when they alternate between (type, method) sections inside the
    coalesced frames — the egress section builder applies the same
    per-sender floor discipline as the invoke-window builder, and the
    receiving coalescer replays sections in frame order."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            s0, s1 = cluster.silos
            key = await _key_hosted_on(cluster, s1, IFabricRecorder,
                                       start=54000, method="mark")
            factory = s0.attach_client()
            ref = factory.get_grain(IFabricRecorder, key)
            FabricRecorderGrain.log.clear()
            calls_before = s0.rpc_fabric.snapshot()["calls_sent"]
            out = await asyncio.gather(*(
                (ref.mark if i % 2 == 0 else ref.mark_b)(f"t{i}")
                for i in range(24)))
            assert out == [f"t{i}" for i in range(24)]
            # this sender's tags executed strictly in submission order,
            # across alternating method sections
            tags = [t for (_m, k, t) in FabricRecorderGrain.log
                    if k == key]
            assert tags == [f"t{i}" for i in range(24)]
            assert s0.rpc_fabric.snapshot()["calls_sent"] > calls_before
        finally:
            await cluster.stop()

    run(main())


def test_trace_continuity_through_fabric(run):
    """A sampled cross-silo call keeps ONE trace id through the batched
    frame: the window-link span lands on BOTH silos, and sampling never
    causes a fabric fallback (the trace rides a frame column)."""

    async def main():
        def cfg(name):
            c = SiloConfig(name=name)
            c.tracing.sample_rate = 1.0
            return c

        cluster = await TestingCluster(n_silos=2,
                                       config_factory=cfg).start()
        try:
            s0, s1 = cluster.silos
            key = await _key_hosted_on(cluster, s1, IFabricCtx,
                                       start=55000)
            factory = s0.attach_client()
            ref = factory.get_grain(IFabricCtx, key)
            await ref.who()  # warm: no placement traffic in the window
            f_before = s0.rpc_fabric.snapshot()["fallbacks"]
            frames_before = s0.rpc_fabric.snapshot()["frames_sent"]
            # pin the trace identity so BOTH silos' ledgers can be
            # queried by it (fast turns carry the trace on the _Call,
            # not in the grain-visible RequestContext)
            tid = 0x5EED_FAB1
            RequestContext.set(TRACE_KEY, {"trace_id": tid,
                                           "span_id": "", "sampled": True})
            try:
                await ref.who()
            finally:
                RequestContext.clear()
            await s0.rpc_fabric.wait_idle()
            # the sampled call rode the fabric — no sampling-attributable
            # fallback, and a frame actually shipped
            assert s0.rpc_fabric.snapshot()["fallbacks"] == f_before
            assert s0.rpc_fabric.snapshot()["frames_sent"] > frames_before
            kinds0 = {s.kind for s in s0.spans.flight.spans
                      if s.trace_id == tid}
            kinds1 = {s.kind for s in s1.spans.flight.spans
                      if s.trace_id == tid}
            # the window-link event ties the member trace to the batched
            # window span on BOTH sides of the fabric
            assert "rpc.window.link" in kinds0
            assert "rpc.window.link" in kinds1
        finally:
            await cluster.stop()

    run(main())


# ===========================================================================
# failure paths
# ===========================================================================

def test_fabric_bounce_fails_members_immediately(run):
    """A destination declared dead mid-flush fails every ringed member
    NOW: requests re-enter the resend machinery as TRANSIENT rejections
    (re-addressed and answered — no caller waits out its deadline),
    and the bounce is counted."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            s0, s1 = cluster.silos
            key = await _key_hosted_on(cluster, s1, IHello, start=56000,
                                       method="say_hello")
            factory = s0.attach_client()
            gid = factory.get_grain(IHello, key).grain_id
            loop = asyncio.get_running_loop()
            rc = s0.runtime_client
            futs = []
            for i in range(4):
                msg = Message(
                    category=Category.APPLICATION,
                    direction=Direction.REQUEST,
                    sending_silo=s0.address,
                    sending_grain=s0.client_grain_id,
                    target_silo=s1.address, target_grain=gid,
                    method_name="say_hello", args=(f"b{i}",))
                fut = loop.create_future()
                rc.callbacks[msg.id] = CallbackData(future=fut, message=msg)
                s0.message_center.send_message(msg)
                futs.append(fut)
            assert s0.rpc_fabric.pending() == 4
            # the silo-death hook fires before the flush task drains
            s0.rpc_fabric.fail_destination(s1.address, "silo declared dead")
            assert s0.rpc_fabric.pending() == 0
            assert s0.rpc_fabric.snapshot()["bounced"] == 4
            # no stranded callers: every future resolves promptly (the
            # TRANSIENT rejection re-addresses onto the live directory
            # entry and the calls complete)
            out = await asyncio.wait_for(asyncio.gather(*futs), 10)
            assert out == [HELLO.format(f"b{i}") for i in range(4)]
            assert s0.metrics.requests_resent >= 4
        finally:
            await cluster.stop()

    run(main())


def test_fabric_fallback_counted_never_silent(run):
    """Frame-ineligible remote traffic (rich request context, string
    keys, call chains) stays on the per-message path, still works, and
    is COUNTED as a fabric fallback."""

    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        try:
            s0, s1 = cluster.silos
            key = await _key_hosted_on(cluster, s1, IFabricCtx,
                                       start=57000)
            factory = s0.attach_client()
            ref = factory.get_grain(IFabricCtx, key)
            await ref.who()  # warm
            f_before = s0.rpc_fabric.snapshot()["fallbacks"]
            # a non-trace request context key makes the call ineligible
            # for the frame's trace-only context column
            RequestContext.set("tenant", "acme")
            try:
                got = await ref.who()
            finally:
                RequestContext.clear()
            assert got["trace_id"] is None or got is not None
            assert s0.rpc_fabric.snapshot()["fallbacks"] > f_before

            # direct eligibility checks for shapes with no frame column
            fab = s0.rpc_fabric
            real_gid = ref.grain_id
            base = dict(
                category=Category.APPLICATION, direction=Direction.REQUEST,
                sending_silo=s0.address, sending_grain=s0.client_grain_id,
                target_silo=s1.address,
                target_grain=real_gid,
                method_name="who")
            assert fab._eligible(Message(**base))
            # unregistered method names can't resolve through the frame's
            # invoke tables — sender keeps them per-message
            assert not fab._eligible(Message(**{
                **base, "method_name": "poke"}))
            assert not fab._eligible(Message(**{
                **base, "call_chain": (GrainId.from_int(9901, 6),)}))
            assert not fab._eligible(Message(**{
                **base, "request_context": {"tenant": "acme"}}))
            assert not fab._eligible(Message(**{**base, "target_grain":
                GrainId.from_string(real_gid.type_code, "string-key")}))
            assert not fab._eligible(Message(**{
                **base, "is_new_placement": True}))
            resp = Message(category=Category.APPLICATION,
                           direction=Direction.RESPONSE,
                           target_silo=s1.address,
                           target_grain=GrainId.from_string(9901, "string-key"),
                           response_kind=ResponseKind.SUCCESS, result=1)
            # responses correlate by id — even string-keyed reply-to
            # identities ride the frame's ident table
            assert fab._eligible(resp)
        finally:
            await cluster.stop()

    run(main())
