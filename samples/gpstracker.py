"""GPSTracker sample — movement-gated notification pipeline.

Parity: reference Samples/GPSTracker — DeviceGrain receives position
messages, computes speed from the previous fix, and forwards a velocity
message to the PushNotifierGrain ONLY when the position changed
(reference: Samples/GPSTracker/GPSTracker.GrainImplementation/
DeviceGrain.cs:37 ProcessMessage — change check :44, GetSpeed :64;
PushNotifierGrain.cs:39 — a [StatelessWorker] that batches messages and
flushes on a timer).

TPU-native shape: every device is a vector-grain row; one tick's position
fixes arrive as a dense tensor, the change-gate and the equirectangular
speed formula vectorize on the VPU, and the conditional forward is an
``Emit`` mask — messages for unmoved devices simply never materialize.
The notifier tier is a small set of rows addressed by ``device % n``, the
batched analog of the stateless-worker pool, and its per-row fan-in is
the batching the reference does with a timer + queue.
"""

from __future__ import annotations

import time
from typing import Dict

import jax.numpy as jnp
import numpy as np

from orleans_tpu.core.grain import batched_method
from orleans_tpu.tensor import (
    Batch,
    Emit,
    VectorGrain,
    field,
    scatter_rows,
    seg_sum,
    vector_grain,
)

EARTH_R = 6371.0 * 1000.0  # meters (reference: DeviceGrain.cs:70)
N_NOTIFIERS = 8            # notifier pool width (stateless-worker analog)


@vector_grain
class DeviceGrain(VectorGrain):
    """Per-device last-fix state (reference: DeviceGrain.cs:37)."""

    lat = field(jnp.float32, 0.0)
    lon = field(jnp.float32, 0.0)
    ts = field(jnp.float32, -1.0)         # -1 = no fix yet
    speed = field(jnp.float32, 0.0)
    moves = field(jnp.int32, 0)           # fixes that changed position

    @batched_method
    @staticmethod
    def process_message(state, batch: Batch, n_rows: int):
        rows, args = batch.rows, batch.args
        safe = jnp.where(rows >= 0, rows, 0)
        lat = jnp.asarray(args["lat"], jnp.float32)
        lon = jnp.asarray(args["lon"], jnp.float32)
        ts = jnp.asarray(args["ts"], jnp.float32)
        dev = jnp.asarray(args["device"], jnp.int32)

        prev_lat = state["lat"][safe]
        prev_lon = state["lon"][safe]
        prev_ts = state["ts"][safe]
        first = prev_ts < 0.0
        moved = (first | (prev_lat != lat) | (prev_lon != lon)) & batch.mask

        # equirectangular speed (reference: GetSpeed, DeviceGrain.cs:64)
        x = (lon - prev_lon) * jnp.cos(jnp.deg2rad((lat + prev_lat) * 0.5))
        y = lat - prev_lat
        dist = jnp.sqrt(x * x + y * y) * jnp.deg2rad(1.0) * EARTH_R
        dt = ts - prev_ts
        speed = jnp.where(first | (dt <= 0.0), 0.0, dist / jnp.maximum(dt,
                                                                       1e-6))

        state = {
            **state,
            "lat": scatter_rows(state["lat"], rows, lat),
            "lon": scatter_rows(state["lon"], rows, lon),
            "ts": scatter_rows(state["ts"], rows, ts),
            "speed": scatter_rows(state["speed"], rows, speed),
            "moves": state["moves"] + seg_sum(
                jnp.asarray(moved, jnp.int32), rows, n_rows),
        }
        emit = Emit(
            interface="PushNotifierGrain", method="send_message",
            keys=dev % N_NOTIFIERS,
            args={"speed": speed, "one": jnp.asarray(moved, jnp.int32)},
            mask=moved)
        return state, None, (emit,)


@vector_grain
class PushNotifierGrain(VectorGrain):
    """Notification batcher tier (reference: PushNotifierGrain.cs:39 —
    [StatelessWorker] queue + 100ms flush timer; here a tick IS the
    batch window, so the queue is the per-row segment fan-in)."""

    forwarded = field(jnp.int32, 0)       # velocity messages absorbed
    batches = field(jnp.int32, 0)         # ticks this row saw traffic
    speed_sum = field(jnp.float32, 0.0)

    @batched_method
    @staticmethod
    def send_message(state, batch: Batch, n_rows: int):
        rows, args = batch.rows, batch.args
        count = seg_sum(jnp.asarray(args["one"], jnp.int32), rows, n_rows)
        return {
            **state,
            "forwarded": state["forwarded"] + count,
            "batches": state["batches"] + jnp.asarray(count > 0, jnp.int32),
            "speed_sum": state["speed_sum"]
            + seg_sum(jnp.asarray(args["speed"], jnp.float32), rows, n_rows),
        }


async def run_gps_load(engine, n_devices: int = 100_000, n_ticks: int = 10,
                       move_fraction: float = 0.7,
                       seed: int = 0) -> Dict[str, float]:
    """Each tick every device reports a fix; ``move_fraction`` of them
    moved (the reference's FakeDeviceGateway moves devices around
    Redmond).  Unmoved fixes update state but emit nothing."""
    import jax as _jax

    rng = np.random.default_rng(seed)
    devices = np.arange(n_devices, dtype=np.int64)
    engine.arena_for("DeviceGrain").reserve(n_devices)
    engine.arena_for("PushNotifierGrain").reserve(N_NOTIFIERS)
    injector = engine.make_injector("DeviceGrain", "process_message",
                                    devices)

    lat = 47.6 + rng.random(n_devices, dtype=np.float32) * 0.1
    lon = -122.1 + rng.random(n_devices, dtype=np.float32) * 0.1
    dev_i32 = jnp.asarray(devices.astype(np.int32))

    # notification count = measured notifier DELTA, not a prediction —
    # correct whether the engine is cold (first fixes all notify) or warm
    arena = engine.arena_for("PushNotifierGrain")
    forwarded_before = int(np.asarray(arena.state["forwarded"]).sum()) \
        if arena.live_count else 0
    ts_base = float(engine.tick_number)  # keep timestamps monotone on re-runs

    t0 = time.perf_counter()
    for t in range(n_ticks):
        moving = rng.random(n_devices) < move_fraction
        lat = lat + np.where(moving, 1e-4, 0.0).astype(np.float32)
        injector.inject({
            "lat": jnp.asarray(lat), "lon": jnp.asarray(lon),
            "ts": jnp.full(n_devices, ts_base + t + 1, jnp.float32),
            "device": dev_i32,
        })
        await engine.drain_queues()
    await engine.flush()
    _jax.block_until_ready(arena.state["forwarded"])
    elapsed = time.perf_counter() - t0

    moved_total = int(np.asarray(arena.state["forwarded"]).sum()) \
        - forwarded_before
    messages = n_devices * n_ticks + moved_total
    return {
        "devices": n_devices,
        "ticks": n_ticks,
        "seconds": elapsed,
        "messages": messages,
        "messages_per_sec": messages / elapsed,
        "notified": moved_total,
    }


async def run_gps_load_fused(engine, n_devices: int = 100_000,
                             n_ticks: int = 10, move_fraction: float = 0.7,
                             window: int = 10, seed: int = 0,
                             measure_latency: bool = False
                             ) -> Dict[str, float]:
    """GPS through the FUSED tick path: the per-fix kernel, the movement
    gate (emit mask), and the notifier fan-in compile into one program
    per window.  Positions genuinely vary per tick, so lat/ts ride as
    scanned [T, m] leaves while lon/device ids (static here) close over
    the scan."""
    import jax as _jax

    rng = np.random.default_rng(seed)
    devices = np.arange(n_devices, dtype=np.int64)
    engine.arena_for("DeviceGrain").reserve(n_devices)
    engine.arena_for("PushNotifierGrain").reserve(N_NOTIFIERS)
    engine.arena_for("PushNotifierGrain").resolve_rows(
        np.arange(N_NOTIFIERS, dtype=np.int64))
    prog = engine.fuse_ticks("DeviceGrain", "process_message", devices)

    lat0 = (47.6 + rng.random(n_devices, dtype=np.float32) * 0.1)
    lon = -122.1 + rng.random(n_devices, dtype=np.float32) * 0.1
    static = {"lon": jnp.asarray(lon),
              "device": jnp.asarray(devices.astype(np.int32))}

    from orleans_tpu.tensor.fused import plan_windows
    if measure_latency:
        window = 1
    window, n_windows, n_ticks = plan_windows(window, n_ticks)

    # position cursor carries ACROSS windows: device tracks continue where
    # the previous window left them (restarting from lat0 would teleport
    # devices backward at window boundaries and corrupt the moved gate)
    lat_cursor = lat0.copy()
    w_rng = np.random.default_rng(seed + 1)

    def window_args(base: int):
        nonlocal lat_cursor
        lats = np.empty((window, n_devices), np.float32)
        for t in range(window):
            moving = w_rng.random(n_devices) < move_fraction
            lat_cursor = lat_cursor + np.where(moving, 1e-4,
                                               0.0).astype(np.float32)
            lats[t] = lat_cursor
        ts = (np.arange(window, dtype=np.float32)[:, None]
              + np.float32(base * window + 1))
        return {"lat": jnp.asarray(lats),
                "ts": jnp.broadcast_to(jnp.asarray(ts), (window, n_devices))}

    prog.run(window_args(0), static_args=static)  # untimed warm window
    notif = engine.arena_for("PushNotifierGrain")
    _jax.block_until_ready(notif.state["forwarded"])
    forwarded_before = int(np.asarray(notif.state["forwarded"]).sum())

    windows = [window_args(w + 1) for w in range(n_windows)]
    _jax.block_until_ready(windows)
    tick_durations = []
    t0 = time.perf_counter()
    for stacked in windows:
        w0 = time.perf_counter()
        prog.run(stacked, static_args=static)
        if measure_latency:
            _jax.block_until_ready(notif.state["forwarded"])
            tick_durations.append(time.perf_counter() - w0)
    _jax.block_until_ready(notif.state["forwarded"])
    elapsed = time.perf_counter() - t0
    assert prog.verify() == 0, "fused window touched unactivated grains"

    forwarded = int(np.asarray(notif.state["forwarded"]).sum())
    # same units as run_gps_load: fixes injected + notifications delivered,
    # counting only the TIMED windows
    messages = n_devices * n_ticks + (forwarded - forwarded_before)
    stats: Dict[str, float] = {
        "devices": n_devices, "ticks": n_ticks, "seconds": elapsed,
        "messages": messages,
        "messages_per_sec": messages / elapsed,
        "forwarded_total": forwarded,
        "engine": "fused",
    }
    if tick_durations:
        d = np.asarray(tick_durations)
        stats["tick_p50_seconds"] = float(np.percentile(d, 50))
        stats["tick_p99_seconds"] = float(np.percentile(d, 99))
    return stats
