"""Stream tests (reference analog: Tester/StreamingTests/* —
SMSStreamingTests, PersistentStreamingTests, ImplicitSubscritionTests,
DelayedQueueRebalancingTests)."""

from __future__ import annotations

import asyncio

from orleans_tpu import Grain, grain_interface
from orleans_tpu.core.grain import grain_class
from orleans_tpu.runtime.silo import Silo
from orleans_tpu.streams import (
    InMemoryQueueAdapter,
    PersistentStreamProvider,
    SimpleMessageStreamProvider,
    implicit_stream_subscription,
)
from orleans_tpu.testing.cluster import TestingCluster


@grain_interface
class IStreamProducerGrain:
    async def produce(self, provider: str, ns: str, key, items: list): ...
    async def finish(self, provider: str, ns: str, key): ...


@grain_class
class StreamProducerGrain(Grain, IStreamProducerGrain):
    async def produce(self, provider, ns, key, items):
        stream = self.get_stream(provider, ns, key)
        await stream.on_next_batch(items)

    async def finish(self, provider, ns, key):
        await self.get_stream(provider, ns, key).on_completed()


@grain_interface
class IStreamConsumerGrain:
    async def join(self, provider: str, ns: str, key): ...
    async def leave(self): ...
    async def received(self) -> list: ...
    async def completed(self) -> bool: ...


@grain_class
class StreamConsumerGrain(Grain, IStreamConsumerGrain):
    def __init__(self) -> None:
        self.items = []
        self.done = False
        self.handle = None

    async def join(self, provider, ns, key):
        stream = self.get_stream(provider, ns, key)

        async def on_next(item, seq):
            self.items.append((item, seq))

        async def on_completed():
            self.done = True

        # resume an existing durable subscription if one survives in
        # pub/sub (the reference's resume-on-activate pattern), else
        # subscribe fresh
        existing = await stream.get_all_subscription_handles()
        if existing:
            self.handle = await existing[0].resume(
                on_next, on_completed=on_completed)
        else:
            self.handle = await stream.subscribe(on_next,
                                                 on_completed=on_completed)

    async def leave(self):
        if self.handle is not None:
            await self.handle.unsubscribe()
            self.handle = None

    async def received(self):
        return list(self.items)

    async def completed(self):
        return self.done


@grain_interface
class IImplicitConsumerGrain:
    async def seen(self) -> list: ...


@implicit_stream_subscription("implicit-ns")
@grain_class
class ImplicitConsumerGrain(Grain, IImplicitConsumerGrain):
    """(reference: [ImplicitStreamSubscription] grains)"""

    def __init__(self) -> None:
        self.items = []

    async def on_stream_item(self, stream_id, item, seq):
        self.items.append(item)

    async def seen(self):
        return list(self.items)


async def _sms_silo():
    silo = Silo(name="streams")
    silo.add_stream_provider("sms", SimpleMessageStreamProvider())
    await silo.start()
    return silo


def test_sms_fanout_and_unsubscribe(run):
    async def go():
        silo = await _sms_silo()
        try:
            f = silo.attach_client()
            producer = f.get_grain(IStreamProducerGrain, 1)
            c1 = f.get_grain(IStreamConsumerGrain, 1)
            c2 = f.get_grain(IStreamConsumerGrain, 2)
            await c1.join("sms", "chat", 7)
            await c2.join("sms", "chat", 7)
            await producer.produce("sms", "chat", 7, ["a", "b"])
            assert [i for i, _ in await c1.received()] == ["a", "b"]
            assert [i for i, _ in await c2.received()] == ["a", "b"]
            # sequence numbers are the producer's monotone counter
            assert [s for _, s in await c1.received()] == [0, 1]

            await c2.leave()
            await producer.produce("sms", "chat", 7, ["c"])
            assert [i for i, _ in await c1.received()] == ["a", "b", "c"]
            assert [i for i, _ in await c2.received()] == ["a", "b"]

            await producer.finish("sms", "chat", 7)
            assert await c1.completed() is True
            assert await c2.completed() is False
        finally:
            await silo.stop(graceful=False)

    run(go())


def test_sms_late_subscriber_reaches_cached_producer(run):
    """A consumer subscribing AFTER the producer's first produce must still
    receive subsequent items: pub/sub pushes the updated consumer view to
    registered producers (reference: IStreamProducerExtension.AddSubscriber
    push keeping the producer cache current)."""

    async def go():
        silo = await _sms_silo()
        try:
            f = silo.attach_client()
            producer = f.get_grain(IStreamProducerGrain, 9)
            c1 = f.get_grain(IStreamConsumerGrain, 91)
            await c1.join("sms", "chat", 70)
            await producer.produce("sms", "chat", 70, ["first"])  # seeds cache
            c2 = f.get_grain(IStreamConsumerGrain, 92)
            await c2.join("sms", "chat", 70)
            # pubsub's push to the producer is one-way; let it land
            await asyncio.sleep(0.05)
            await producer.produce("sms", "chat", 70, ["second"])
            assert [i for i, _ in await c1.received()] == ["first", "second"]
            assert [i for i, _ in await c2.received()] == ["second"]
        finally:
            await silo.stop(graceful=False)

    run(go())


def test_sms_client_producer(run):
    """Clients (non-grain contexts) can produce to a stream."""

    async def go():
        silo = await _sms_silo()
        try:
            f = silo.attach_client()
            c = f.get_grain(IStreamConsumerGrain, 10)
            await c.join("sms", "chat", 99)
            stream = silo.stream_provider("sms").get_stream("chat", 99)
            await stream.on_next("hello")
            assert [i for i, _ in await c.received()] == ["hello"]
        finally:
            await silo.stop(graceful=False)

    run(go())


def test_sms_implicit_subscription(run):
    async def go():
        silo = await _sms_silo()
        try:
            f = silo.attach_client()
            producer = f.get_grain(IStreamProducerGrain, 2)
            # stream key 42 → ImplicitConsumerGrain key 42, auto-subscribed
            await producer.produce("sms", "implicit-ns", 42, ["x", "y"])
            consumer = f.get_grain(IImplicitConsumerGrain, 42)
            assert await consumer.seen() == ["x", "y"]
        finally:
            await silo.stop(graceful=False)

    run(go())


def test_persistent_stream_delivery(run):
    async def go():
        silo = Silo(name="pstreams")
        silo.add_stream_provider("pq", PersistentStreamProvider(
            InMemoryQueueAdapter(n_queues=4), pull_period=0.01,
            consumer_cache_ttl=0.0))
        await silo.start()
        try:
            f = silo.attach_client()
            c = f.get_grain(IStreamConsumerGrain, 20)
            await c.join("pq", "events", 5)
            producer = f.get_grain(IStreamProducerGrain, 3)
            await producer.produce("pq", "events", 5, [1, 2, 3])

            async def until_delivered():
                while len(await c.received()) < 3:
                    await asyncio.sleep(0.01)

            await asyncio.wait_for(until_delivered(), timeout=5.0)
            items = await c.received()
            assert [i for i, _ in items] == [1, 2, 3]
            # queue-assigned seqs are monotone
            seqs = [s for _, s in items]
            assert seqs == sorted(seqs)

            await producer.finish("pq", "events", 5)

            async def until_done():
                while not await c.completed():
                    await asyncio.sleep(0.01)

            await asyncio.wait_for(until_done(), timeout=5.0)
        finally:
            await silo.stop(graceful=False)

    run(go())


def test_persistent_stream_multi_silo_and_rebalance(run):
    """Queues spread across silos by the ring balancer; killing a silo
    hands its queues (and their cursor) to survivors
    (reference analog: DelayedQueueRebalancingTests)."""

    async def go():
        backing = InMemoryQueueAdapter.shared_backing()

        def setup(silo):
            silo.add_stream_provider("pq", PersistentStreamProvider(
                InMemoryQueueAdapter(n_queues=8, backing=backing),
                pull_period=0.01, consumer_cache_ttl=0.0))

        cluster = TestingCluster(n_silos=3, silo_setup=setup)
        await cluster.start()
        try:
            await cluster.wait_for_liveness_convergence()
            # every queue owned by exactly one agent cluster-wide
            owned = [q for s in cluster.silos
                     for q in s.stream_provider("pq").manager.agents]
            assert sorted(owned) == list(range(8)), owned
            by_silo = {s.name: list(s.stream_provider("pq").manager.agents)
                       for s in cluster.silos}
            assert sum(1 for v in by_silo.values() if v) >= 2, by_silo

            f = cluster.attach_client(0)
            c = f.get_grain(IStreamConsumerGrain, 30)
            await c.join("pq", "events", "k1")
            producer = f.get_grain(IStreamProducerGrain, 4)
            await producer.produce("pq", "events", "k1", list(range(5)))

            async def until(n):
                while len(await c.received()) < n:
                    await asyncio.sleep(0.01)

            await asyncio.wait_for(until(5), timeout=5.0)

            # kill the silo that owns the stream's queue
            provider0 = cluster.silos[0].stream_provider("pq")
            qid = provider0.mapper.queue_for(
                provider0.get_stream("events", "k1").stream_id)
            owner = next(s for s in cluster.silos
                         if qid in s.stream_provider("pq").manager.agents)
            cluster.kill_silo(owner)
            await cluster.wait_for_liveness_convergence()

            # re-join from a surviving client: if the consumer activation
            # died with the silo, join() resumes the durable subscription
            # on the new activation (the reference's resume-on-activate
            # pattern); if it didn't die, join() finds the handle already
            # resumed and is a no-op re-resume
            f = cluster.attach_client(0)
            c = f.get_grain(IStreamConsumerGrain, 30)
            await c.join("pq", "events", "k1")

            # a survivor adopts the queue and resumes from the cursor
            async def adopted():
                while not any(qid in s.stream_provider("pq").manager.agents
                              for s in cluster.silos):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(adopted(), timeout=5.0)
            before = len(await c.received())
            await producer.produce("pq", "events", "k1", [100, 101])

            async def more():
                while len(await c.received()) < before + 2:
                    await asyncio.sleep(0.01)

            await asyncio.wait_for(more(), timeout=5.0)
            items = [i for i, _ in await c.received()]
            assert items[-2:] == [100, 101]
        finally:
            await cluster.stop()

    run(go())


def test_pubsub_state_survives_rendezvous_silo_death(run):
    """The rendezvous grain's subscription state is written through the
    PubSubStore provider, so when the silo hosting it is hard-killed the
    re-activated rendezvous still knows the consumers and queued events
    keep flowing (reference: PubSubRendezvousGrain's persisted State via
    the PubSubStore storage provider)."""

    async def go():
        from orleans_tpu.core.factory import factory
        from orleans_tpu.streams.pubsub import IPubSubRendezvous

        backing = InMemoryQueueAdapter.shared_backing()

        def setup(silo):
            silo.add_stream_provider("pq", PersistentStreamProvider(
                InMemoryQueueAdapter(n_queues=8, backing=backing),
                pull_period=0.01, consumer_cache_ttl=0.0))

        cluster = TestingCluster(n_silos=3, silo_setup=setup)
        await cluster.start()
        try:
            await cluster.wait_for_liveness_convergence()
            f = cluster.attach_client(0)
            c = f.get_grain(IStreamConsumerGrain, 31)
            await c.join("pq", "events", "k2")
            producer = f.get_grain(IStreamProducerGrain, 6)
            await producer.produce("pq", "events", "k2", ["a"])

            async def until(n):
                while len(await c.received()) < n:
                    await asyncio.sleep(0.01)

            await asyncio.wait_for(until(1), timeout=5.0)

            # find and kill the silo hosting the rendezvous grain
            stream_id = cluster.silos[0].stream_provider(
                "pq").get_stream("events", "k2").stream_id
            pubsub_id = factory.get_grain(
                IPubSubRendezvous, stream_id.pubsub_key()).grain_id
            host = next(s for s in cluster.silos
                        if s.catalog.directory.by_grain.get(pubsub_id))
            cluster.kill_silo(host)
            await cluster.wait_for_liveness_convergence()
            # resume-on-activate (no-op if the consumer survived the kill)
            f = cluster.attach_client(0)
            c = f.get_grain(IStreamConsumerGrain, 31)
            await c.join("pq", "events", "k2")

            before = len(await c.received())
            await producer.produce("pq", "events", "k2", ["b", "c"])
            await asyncio.wait_for(until(before + 2), timeout=10.0)
            items = [i for i, _ in await c.received()]
            assert items[-2:] == ["b", "c"]
        finally:
            await cluster.stop()

    run(go())


def test_consumer_resumes_after_deactivation(run):
    """Durable subscription state lives in pub/sub; a reactivated consumer
    without a resumed handle surfaces the unresumed-delivery fault unless
    it re-subscribes (reference: resume-on-activate pattern)."""

    async def go():
        silo = await _sms_silo()
        try:
            f = silo.attach_client()
            c = f.get_grain(IStreamConsumerGrain, 40)
            await c.join("sms", "chat", 123)
            producer = f.get_grain(IStreamProducerGrain, 5)
            await producer.produce("sms", "chat", 123, ["pre"])

            # deactivate the consumer; its subscription survives in pubsub
            act = silo.catalog.directory.by_grain[c.grain_id][0]
            await silo.catalog._deactivate(act)

            # delivery now faults (unresumed subscription, no implicit
            # handler) and the producer — not fire-and-forget — sees it
            try:
                await producer.produce("sms", "chat", 123, ["lost"])
                raise AssertionError("expected unresumed-delivery fault")
            except Exception as exc:
                assert "not resumed" in str(exc)

            # the consumer re-subscribes (resume path) and flow continues
            await c.join("sms", "chat", 123)
            await producer.produce("sms", "chat", 123, ["post"])
            items = [i for i, _ in await c.received()]
            assert "post" in items
        finally:
            await silo.stop(graceful=False)

    run(go())


def test_pubsub_conflict_replays_delta_only(run):
    """On an etag write conflict the rendezvous adopts the winner's state
    and replays only its own delta — additions survive, and removals are
    not resurrected by the loser's stale view."""

    async def main():
        from orleans_tpu.streams.pubsub import PubSubRendezvousGrain

        class FakeBridge:
            def __init__(self):
                self.durable = {"producers": {"P-other"},
                                "consumer_subs": {7: _handle(7)}}
                self.state = None
                self.fail_next = False

            async def read_state(self):
                self.state = {k: (set(v) if isinstance(v, set) else dict(v))
                              for k, v in self.durable.items()}

            async def write_state(self):
                from orleans_tpu.runtime.storage import InconsistentStateError
                if self.fail_next:
                    self.fail_next = False
                    raise InconsistentStateError("etag", None)
                self.durable = {"producers": set(self.state["producers"]),
                                "consumer_subs":
                                    dict(self.state["consumer_subs"])}

        def _handle(sub_id):
            class H:
                subscription_id = sub_id
                consumer = f"C{sub_id}"
                stream_id = None
            return H()

        g = PubSubRendezvousGrain.__new__(PubSubRendezvousGrain)
        g.producers = {"P-other", "P-mine"}
        g.consumer_subs = {7: _handle(7)}
        g._bridge = FakeBridge()

        # removal delta under conflict: 7 must stay removed even though
        # the winner's durable state still contains it
        g.consumer_subs.pop(7)
        g._bridge.fail_next = True
        await g._save(("remove_consumer", _handle(7)))
        assert 7 not in g._bridge.durable["consumer_subs"]
        # and the winner's producer set was preserved (not overwritten by
        # our stale view): P-mine was never durably written before the
        # conflict, so only the delta semantics keep the winner's P-other
        assert "P-other" in g._bridge.durable["producers"]

        # addition delta under conflict survives alongside winner's data
        g._bridge.durable["consumer_subs"] = {9: _handle(9)}
        g.consumer_subs[8] = _handle(8)
        g._bridge.fail_next = True
        await g._save(("add_consumer", _handle(8)))
        assert set(g._bridge.durable["consumer_subs"]) == {8, 9}

    run(main())


def test_persistent_stream_over_sqlite_queue(run, tmp_path):
    """The durable queue adapter (AzureQueueAdapter analog) runs the same
    delivery discipline as in-memory, and events survive a 'process
    restart' — a fresh adapter over the same db resumes undelivered
    events from the durable cursor."""

    async def go():
        from orleans_tpu.plugins.sqlite_queue import SqliteQueueAdapter
        from orleans_tpu.providers.memory_storage import MemoryStorage
        from orleans_tpu.streams.pubsub import PUBSUB_STORE

        db = str(tmp_path / "queues.db")
        # durable subscriptions: without a PubSubStore, a subscription
        # dies with its silo and a restarted agent correctly acks events
        # into the void (reference: PubSubStore provider block)
        pubsub_backing = MemoryStorage.shared_backing()
        silo = Silo(name="pstreams-sqlite", storage_providers={
            PUBSUB_STORE: MemoryStorage(pubsub_backing)})
        silo.add_stream_provider("pq", PersistentStreamProvider(
            SqliteQueueAdapter(path=db, n_queues=4), pull_period=0.01,
            consumer_cache_ttl=0.0))
        await silo.start()
        try:
            f = silo.attach_client()
            c = f.get_grain(IStreamConsumerGrain, 60)
            await c.join("pq", "devents", 9)
            producer = f.get_grain(IStreamProducerGrain, 61)
            await producer.produce("pq", "devents", 9, ["a", "b", "c"])

            async def until(n):
                while len(await c.received()) < n:
                    await asyncio.sleep(0.01)

            await asyncio.wait_for(until(3), timeout=5.0)
            items = await c.received()
            assert [i for i, _ in items] == ["a", "b", "c"]
        finally:
            await silo.stop(graceful=False)

        # restart simulation: write events with one adapter+no consumer,
        # then a FRESH adapter over the same file delivers them
        adapter = SqliteQueueAdapter(path=db, n_queues=4)
        from orleans_tpu.streams.core import StreamId
        sid = StreamId(provider="pq", namespace="devents", key=9)
        from orleans_tpu.streams.persistent import (
            HashRingStreamQueueMapper,
            QueueMessage,
        )
        q = HashRingStreamQueueMapper(4).queue_for(sid)
        await adapter.queue_message(q, QueueMessage(stream_id=sid,
                                                   item="post-crash", seq=0))
        adapter.close()

        silo2 = Silo(name="pstreams-sqlite-2", storage_providers={
            PUBSUB_STORE: MemoryStorage(pubsub_backing)})
        silo2.add_stream_provider("pq", PersistentStreamProvider(
            SqliteQueueAdapter(path=db, n_queues=4), pull_period=0.01,
            consumer_cache_ttl=0.0))
        await silo2.start()
        try:
            f2 = silo2.attach_client()
            c2 = f2.get_grain(IStreamConsumerGrain, 60)
            # the subscription is durable in the PubSubStore; the fresh
            # activation RESUMES it (join takes the resume path via
            # get_all_subscription_handles — the reference's
            # resume-on-activate pattern; an unresumed handle faults)
            await c2.join("pq", "devents", 9)

            async def until2():
                items = await c2.received()
                return any(i == "post-crash" for i, _ in items)

            deadline = asyncio.get_running_loop().time() + 5
            while not await until2():
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.02)
        finally:
            await silo2.stop(graceful=False)

    run(go())


@grain_interface
class IRewindConsumerGrain:
    async def join_from(self, provider: str, ns: str, key, from_seq: int): ...
    async def received(self) -> list: ...


@grain_class
class RewindConsumerGrain(Grain, IRewindConsumerGrain):
    def __init__(self) -> None:
        self.items = []

    async def join_from(self, provider, ns, key, from_seq):
        stream = self.get_stream(provider, ns, key)
        async def on_next(item, seq):
            self.items.append((item, seq))
        await stream.subscribe(on_next, from_seq=from_seq)

    async def received(self):
        return list(self.items)


def test_rewind_token_replays_retained_events(run):
    """A subscription carrying a sequence token (reference:
    StreamSequenceToken) receives RETAINED events from that seq even
    though they were produced, delivered and acked before it existed."""

    async def go():
        silo = Silo(name="rewind")
        silo.add_stream_provider("pq", PersistentStreamProvider(
            InMemoryQueueAdapter(n_queues=2), pull_period=0.01,
            consumer_cache_ttl=0.0))
        await silo.start()
        try:
            f = silo.attach_client()
            # early consumer drives delivery + ack of the first events
            c1 = f.get_grain(IStreamConsumerGrain, 70)
            await c1.join("pq", "history", 3)
            producer = f.get_grain(IStreamProducerGrain, 71)
            await producer.produce("pq", "history", 3, ["e0", "e1", "e2"])

            async def until(c, n):
                while len(await c.received()) < n:
                    await asyncio.sleep(0.01)

            await asyncio.wait_for(until(c1, 3), timeout=5.0)
            seqs = dict((i, s) for i, s in await c1.received())

            # late consumer rewinds to e1's sequence on an IDLE stream:
            # the subscription poke triggers replay without new traffic
            c2 = f.get_grain(IRewindConsumerGrain, 72)
            await c2.join_from("pq", "history", 3, seqs["e1"])
            await asyncio.wait_for(until(c2, 2), timeout=5.0)
            got = [i for i, _ in await c2.received()]
            assert got == ["e1", "e2"], got
            assert [s for _, s in await c2.received()] \
                == [seqs["e1"], seqs["e2"]]
            # live traffic still flows to the rewound sub afterwards
            await producer.produce("pq", "history", 3, ["e3"])
            await asyncio.wait_for(until(c2, 3), timeout=5.0)
            got = [i for i, _ in await c2.received()]
            assert got == ["e1", "e2", "e3"], got
            assert "e0" not in got  # before the token
        finally:
            await silo.stop(graceful=False)

    run(go())


def test_rewind_token_on_sqlite_queue(run, tmp_path):
    async def go():
        from orleans_tpu.plugins.sqlite_queue import SqliteQueueAdapter

        silo = Silo(name="rewind-sqlite")
        silo.add_stream_provider("pq", PersistentStreamProvider(
            SqliteQueueAdapter(path=str(tmp_path / "rw.db"), n_queues=2),
            pull_period=0.01, consumer_cache_ttl=0.0))
        await silo.start()
        try:
            f = silo.attach_client()
            c1 = f.get_grain(IStreamConsumerGrain, 75)
            await c1.join("pq", "dhistory", 4)
            producer = f.get_grain(IStreamProducerGrain, 76)
            await producer.produce("pq", "dhistory", 4, ["a", "b"])

            async def until(c, n):
                while len(await c.received()) < n:
                    await asyncio.sleep(0.01)

            await asyncio.wait_for(until(c1, 2), timeout=5.0)
            c2 = f.get_grain(IRewindConsumerGrain, 77)
            await c2.join_from("pq", "dhistory", 4, 0)
            await producer.produce("pq", "dhistory", 4, ["c"])
            await asyncio.wait_for(until(c2, 3), timeout=5.0)
            got = [i for i, _ in await c2.received()]
            assert got[:2] == ["a", "b"], got
        finally:
            await silo.stop(graceful=False)

    run(go())
