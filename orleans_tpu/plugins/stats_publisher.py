"""Statistics publishers: periodic export of the silo metrics snapshot.

Parity: reference statistics publication backends (reference:
src/OrleansSQLUtils/SqlStatisticsPublisher.cs; Azure analogs
StatsTableDataManager / SiloMetricsTableDataManager; periodic driver
LogStatistics.cs:33,52).  A publisher receives the flattened
``SiloMetrics.snapshot()`` dict at each reporting interval.
"""

from __future__ import annotations

import json
import sqlite3
import time
from typing import Dict, List, Tuple

from orleans_tpu.tracing import TraceLogger


class StatisticsPublisher:
    """Contract (reference: IStatisticsPublisher / ISiloMetricsDataPublisher
    — Init + ReportStats/ReportMetrics)."""

    async def init(self, silo_name: str) -> None:  # noqa: B027 — optional
        pass

    async def report(self, silo_name: str,
                     stats: Dict[str, float]) -> None:
        raise NotImplementedError

    async def close(self) -> None:  # noqa: B027 — optional hook
        pass


class LogStatisticsPublisher(StatisticsPublisher):
    """Dump the snapshot to the trace log (reference: LogStatistics.cs:52
    'DumpCounters' periodic log dump)."""

    def __init__(self, logger: TraceLogger | None = None) -> None:
        self.logger = logger or TraceLogger("stats")

    async def report(self, silo_name: str,
                     stats: Dict[str, float]) -> None:
        self.logger.info(f"stats {silo_name}: "
                         + json.dumps(stats, sort_keys=True, default=float))


class SqliteStatisticsPublisher(StatisticsPublisher):
    """Append snapshots to a sqlite table — the SQL stats backend analog
    (reference: SqlStatisticsPublisher.cs, CreateOrleansTables DDL's
    OrleansStatisticsTable)."""

    _SCHEMA = """
    CREATE TABLE IF NOT EXISTS silo_statistics (
        id        INTEGER PRIMARY KEY AUTOINCREMENT,
        time      REAL NOT NULL,
        silo_name TEXT NOT NULL,
        stat_name TEXT NOT NULL,
        value     REAL NOT NULL
    );
    """

    def __init__(self, path: str = ":memory:") -> None:
        self._conn = sqlite3.connect(path)
        self._conn.executescript(self._SCHEMA)
        self._conn.commit()

    async def report(self, silo_name: str,
                     stats: Dict[str, float]) -> None:
        now = time.time()
        self._conn.executemany(
            "INSERT INTO silo_statistics (time, silo_name, stat_name, value) "
            "VALUES (?,?,?,?)",
            [(now, silo_name, k, float(v)) for k, v in stats.items()
             if isinstance(v, (int, float))])
        self._conn.commit()

    def rows(self, silo_name: str | None = None
             ) -> List[Tuple[float, str, str, float]]:
        """Read back published rows (test/ops surface)."""
        if silo_name is None:
            cur = self._conn.execute(
                "SELECT time, silo_name, stat_name, value "
                "FROM silo_statistics ORDER BY id")
        else:
            cur = self._conn.execute(
                "SELECT time, silo_name, stat_name, value "
                "FROM silo_statistics WHERE silo_name=? ORDER BY id",
                (silo_name,))
        return cur.fetchall()

    async def close(self) -> None:
        self._conn.close()
