"""Async + host-side utility layer (reference: src/Orleans/Async/*.cs)."""

from orleans_tpu.utils.async_utils import (
    INFINITE_RETRIES,
    AsyncLock,
    AsyncPipeline,
    AsyncSerialExecutor,
    BatchedContinuationQueue,
    ExponentialBackoff,
    FixedBackoff,
    MultiCompletionSource,
    execute_with_retries,
)

__all__ = [
    "INFINITE_RETRIES",
    "AsyncLock",
    "AsyncPipeline",
    "AsyncSerialExecutor",
    "BatchedContinuationQueue",
    "ExponentialBackoff",
    "FixedBackoff",
    "MultiCompletionSource",
    "execute_with_retries",
]
