"""Out-of-cluster client + gateway + observer tests.

Reference analogs: Tester/ObserverTests, ClientAddressableTests, gateway
connection handling in MembershipTests.
"""

import asyncio

import pytest

from orleans_tpu.client import GrainClient
from orleans_tpu.runtime.runtime_client import RejectionError
from orleans_tpu.testing import TestingCluster
from orleans_tpu import Grain, grain_interface, one_way
from orleans_tpu.core.grain import grain_class

from tests.fixture_grains import ICounterGrain, IFailingGrain


@grain_interface
class IObserverCallback:
    @one_way
    async def on_event(self, value: int): ...


@grain_interface
class IPublisher:
    async def subscribe(self, observer): ...
    async def publish(self, value: int): ...


@grain_class
class PublisherGrain(Grain, IPublisher):
    def __init__(self) -> None:
        self.subscribers = []

    async def subscribe(self, observer):
        self.subscribers.append(observer)

    async def publish(self, value: int):
        for ref in self.subscribers:
            await ref.on_event(value)


class LocalObserver:
    """Client-side plain object exposed via create_object_reference."""

    def __init__(self) -> None:
        self.events = []

    async def on_event(self, value: int):
        self.events.append(value)


def test_client_roundtrip_and_errors(run):
    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        client = None
        try:
            await cluster.wait_for_liveness_convergence()
            client = await GrainClient().connect(*cluster.silos)
            counter = client.get_grain(ICounterGrain, 7)
            assert await counter.add(3) == 3
            assert await counter.add(4) == 7
            failing = client.get_grain(IFailingGrain, 1)
            with pytest.raises(ValueError, match="kaboom"):
                await failing.boom()
        finally:
            if client:
                await client.close()
            await cluster.stop()

    run(main())


def test_observer_notifications(run):
    async def main():
        cluster = await TestingCluster(n_silos=2).start()
        client = None
        try:
            await cluster.wait_for_liveness_convergence()
            client = await GrainClient().connect(cluster.silos[0])
            observer = LocalObserver()
            obs_ref = await client.create_object_reference(
                IObserverCallback, observer)
            pub = client.get_grain(IPublisher, 1)
            await pub.subscribe(obs_ref)
            await pub.publish(41)
            await pub.publish(42)
            # one-way delivery: give the pump a moment
            await asyncio.sleep(0.1)
            assert observer.events == [41, 42]
        finally:
            if client:
                await client.close()
            await cluster.stop()

    run(main())


def test_client_disconnect_breaks_calls(run):
    async def main():
        cluster = await TestingCluster(n_silos=1).start()
        try:
            client = await GrainClient().connect(cluster.silos[0])
            counter = client.get_grain(ICounterGrain, 9)
            assert await counter.add(1) == 1
            await client.close()
            with pytest.raises((RejectionError, RuntimeError)):
                await counter.add(1)
        finally:
            await cluster.stop()

    run(main())


def test_client_vector_grain_via_gateway(run):
    """A remote client can call tensor-path grains through the gateway."""

    async def main():
        import numpy as np

        from tests.test_tensor_engine import AccumGrain  # noqa: F401 — registers

        cluster = await TestingCluster(n_silos=1).start()
        client = None
        try:
            client = await GrainClient().connect(cluster.silos[0])
            ref = client.get_grain("AccumGrain", 123)
            res = await ref.add({"v": np.float32(5.0)})
            assert float(res["echo"]) == 10.0
        finally:
            if client:
                await client.close()
            await cluster.stop()

    run(main())
